"""XLA cost-model attribution: kernel-class costs of compiled executables.

The repo already reads two numbers off a compiled executable — total
FLOPs (``profiler.compiled_flops``, feeding MFU) and collective bytes
(``parallel.tp.hlo_collectives``, feeding the TP floor gate). Both are
single scalars over a whole step; neither can answer "where does the
step's time GO?" — the question ROADMAP items 4/5 gate on (the MoE
rung's 52.4% routing overhead is one opaque number, and the int8
dequant epilogue has no kernel-level attribution at all).

This module is the missing middle layer. It walks the **compiled** HLO
text (post-fusion — the instructions the hardware actually runs, each
carrying the JAX scope in its ``metadata={op_name=...}``), classifies
every instruction into a kernel class:

- ``attention``   — dots/softmax under an attention/flash scope
- ``dense_matmul``— every other dot/convolution (MLP, QKV/O projections,
                    expert FFNs, the LM head)
- ``moe_dispatch``— router matmul, top-k, one-hot/sort/gather under a
                    MoE scope on the way INTO the experts
- ``moe_combine`` — the weighted scatter/einsum back OUT of the experts
- ``collective``  — all-reduce / all-gather / reduce-scatter /
                    all-to-all / collective-permute (ICI traffic)
- ``quant_dequant``— int8<->float converts + their scale multiplies
- ``elementwise`` — everything else (LN, residuals, optimizer math)

and estimates per-instruction FLOPs and bytes from the instruction
shapes (the ``hlo_collectives`` technique, generalized). The per-class
sums are then **rescaled so they agree with XLA's own
``cost_analysis()`` totals** for the executable — the cost model
supplies the authoritative magnitudes, the HLO walk supplies the
attribution. :func:`roofline` converts class costs into estimated
device time + a compute/HBM/ICI-bound placement against the BASELINE.md
roofline constants (197 TFLOP/s bf16 peak, the measured ~260 GB/s HBM
envelope) so a class's placement says WHICH ceiling it sits under.

Everything here is AOT and side-effect-free: callers lower+compile
abstract shapes (no device allocation, nothing executed), so analysis
can run on a background thread off the serving hot path —
``observability.anatomy`` does exactly that.
"""
from __future__ import annotations

import os
import re
from typing import Dict, Optional

#: classification targets, in display order
KERNEL_CLASSES = (
    "attention", "dense_matmul", "moe_dispatch", "moe_combine",
    "collective", "quant_dequant", "elementwise",
)

# BASELINE.md roofline constants (v5e slice): bf16 peak per chip, the
# MEASURED HBM envelope (~260 GB/s of the 819 GB/s spec — the number
# the decode rung's total_bw_frac is normalized against), and one ICI
# link direction. Env-overridable like profiler.PDT_TPU_PEAK_FLOPS so
# a different slice reuses the machinery without a code edit.
DEFAULT_PEAK_FLOPS = 197e12
DEFAULT_HBM_BYTES_S = 260e9
DEFAULT_ICI_BYTES_S = 45e9

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8e4m3fn": 1,
    "f8e5m2": 1, "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2,
    "u16": 2, "s8": 1, "u8": 1, "pred": 1,
}

_COLLECTIVE_OPS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute",
)

# one HLO instruction: `%name = f32[8,128]{1,0} dot(f32[8,64] %a, ...)`
# (tuple-typed results match on their first element, same as
# parallel.tp.hlo_collectives — a weight, not an exact byte count)
_INSTR_RE = re.compile(
    r"=\s*\(?\s*(\w+)\[([0-9,]*)\][^=]*?\s"
    r"([a-z][a-z0-9\-]*)(?:-start)?\(")
_OPERAND_RE = re.compile(r"(\w+)\[([0-9,]*)\](?:\{[0-9,]*\})? %")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")

# scope keyword tables, matched case-insensitively against the
# op_name metadata (the flax module path survives compilation):
# moe wins over attention wins over the opcode fallback, and within a
# moe scope the combine-side keywords are checked first (the combine
# einsum's scope also contains the block name the dispatch shares)
_ATTN_PAT = re.compile(r"attn|attention|flash|softmax", re.I)
_MOE_PAT = re.compile(r"moe|expert|router|gshard", re.I)
_MOE_COMBINE_PAT = re.compile(
    r"combine|unsort|scatter_out|weighted_sum|sec,ecd", re.I)
_MOE_EXPERT_MM_PAT = re.compile(
    # param names (wi/wo), module names, and the expert einsum
    # equations themselves — flax puts the equation in the scope
    # (`moe/ecd,edf->ecf/dot_general`), and those [E,C,d]x[E,d,f]
    # batched matmuls are the expert WORK, not routing
    r"wi|wo|mlp|ffn|expert_m|ecd,edf|ecf,efd", re.I)
_QUANT_PAT = re.compile(r"quant|dequant", re.I)


def _numel(dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d.strip():
            n *= int(d)
    return n


def classify_instruction(opcode: str, op_name: str) -> str:
    """Kernel class of one HLO instruction from its opcode + the JAX
    scope carried in its ``op_name`` metadata."""
    if opcode in _COLLECTIVE_OPS:
        return "collective"
    if _QUANT_PAT.search(op_name):
        return "quant_dequant"
    if _MOE_PAT.search(op_name):
        if _MOE_COMBINE_PAT.search(op_name):
            return "moe_combine"
        if opcode in ("dot", "convolution") \
                and _MOE_EXPERT_MM_PAT.search(op_name):
            # the expert FFN matmuls are the WORK, not the routing —
            # matched-active-FLOPs accounting keeps them dense_matmul
            return "dense_matmul"
        return "moe_dispatch"
    if _ATTN_PAT.search(op_name):
        return "attention"
    if opcode in ("dot", "convolution"):
        return "dense_matmul"
    return "elementwise"


def parse_hlo_classes(hlo: str) -> Dict[str, dict]:
    """Walk compiled HLO text into per-class FLOP/byte/count estimates.

    Per instruction: bytes = (operand + result elements) x dtype
    width; FLOPs = 2 x result elements x contraction length for dots
    (contraction parsed from ``lhs_contracting_dims`` against the first
    operand's shape), result elements otherwise. These are WEIGHTS for
    attribution — :func:`executable_class_costs` rescales them against
    ``cost_analysis()`` so the totals are XLA's own."""
    out: Dict[str, dict] = {
        c: {"flops": 0.0, "bytes": 0.0, "count": 0}
        for c in KERNEL_CLASSES
    }
    for line in hlo.splitlines():
        m = _INSTR_RE.search(line)
        if m is None:
            continue
        dtype, dims, opcode = m.groups()
        if opcode in ("parameter", "constant", "tuple",
                      "get-tuple-element", "bitcast",
                      # container ops: their cost IS their body's cost,
                      # and the body's instructions are walked too —
                      # counting both would double-attribute
                      "fusion", "call", "while", "conditional"):
            continue
        res_elems = _numel(dims)
        nbytes = res_elems * _DTYPE_BYTES.get(dtype, 4)
        operands = _OPERAND_RE.findall(line[m.end():])
        for odt, odims in operands:
            nbytes += _numel(odims) * _DTYPE_BYTES.get(odt, 4)
        flops = float(res_elems)
        if opcode in ("dot", "convolution"):
            contract = 1
            cm = _CONTRACT_RE.search(line)
            if cm and operands:
                lhs_dims = [int(d) for d in operands[0][1].split(",")
                            if d.strip()]
                for idx in cm.group(1).split(","):
                    if idx.strip() and int(idx) < len(lhs_dims):
                        contract *= lhs_dims[int(idx)]
            flops = 2.0 * res_elems * max(contract, 1)
        name_m = _OPNAME_RE.search(line)
        cls = classify_instruction(
            opcode, name_m.group(1) if name_m else "")
        out[cls]["flops"] += flops
        out[cls]["bytes"] += nbytes
        out[cls]["count"] += 1
    return out


def cost_totals(compiled) -> dict:
    """XLA ``cost_analysis()`` totals of a compiled executable,
    tolerant of the list-of-dict shape older jax returns (the
    ``profiler.executable_flops`` convention). Empty dict when the
    backend doesn't report."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        out = {}
        if cost.get("flops"):
            out["flops"] = float(cost["flops"])
        if cost.get("bytes accessed"):
            out["bytes"] = float(cost["bytes accessed"])
        return out
    except Exception:  # noqa: BLE001 — absent on some backends
        return {}


def executable_class_costs(compiled) -> dict:
    """Per-kernel-class FLOPs/bytes for one compiled executable:
    the HLO-walk attribution of :func:`parse_hlo_classes`, rescaled
    per dimension so the class sums equal the executable's own
    ``cost_analysis()`` totals (when the backend reports them — the
    raw HLO estimates stand otherwise). Returns::

        {"classes": {cls: {"flops", "bytes", "count", "frac_flops"}},
         "total_flops", "total_bytes", "collective_bytes",
         "instructions"}
    """
    classes = parse_hlo_classes(compiled.as_text())
    totals = cost_totals(compiled)
    est_flops = sum(c["flops"] for c in classes.values())
    est_bytes = sum(c["bytes"] for c in classes.values())
    flops_scale = (totals["flops"] / est_flops
                   if totals.get("flops") and est_flops > 0 else 1.0)
    bytes_scale = (totals["bytes"] / est_bytes
                   if totals.get("bytes") and est_bytes > 0 else 1.0)
    out_classes = {}
    for cls, c in classes.items():
        out_classes[cls] = {
            "flops": c["flops"] * flops_scale,
            "bytes": c["bytes"] * bytes_scale,
            "count": c["count"],
        }
    total_flops = sum(c["flops"] for c in out_classes.values())
    for c in out_classes.values():
        c["frac_flops"] = (c["flops"] / total_flops
                           if total_flops > 0 else 0.0)
    return {
        "classes": out_classes,
        "total_flops": total_flops,
        "total_bytes": sum(c["bytes"] for c in out_classes.values()),
        "collective_bytes": out_classes["collective"]["bytes"],
        "instructions": sum(c["count"] for c in out_classes.values()),
    }


def analyze_jitted(jitted_fn, *args, **kwargs) -> dict:
    """AOT lower+compile ``jitted_fn`` for the given (abstract or
    concrete) args and return :func:`executable_class_costs`. Like
    ``profiler.compiled_flops`` this is a one-shot startup/background
    call, NOT a hot-loop call — it pays a compile."""
    return executable_class_costs(
        jitted_fn.lower(*args, **kwargs).compile())


def abstractify(tree):
    """Concrete arg tree -> ShapeDtypeStruct tree carrying shardings
    (the ``parallel.tp._decode_step_hlo`` technique), so an analysis
    thread never holds references to live (donatable) buffers."""
    import jax

    def leaf(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is None or dtype is None:
            return x
        sharding = getattr(x, "sharding", None)
        try:
            return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)
        except (TypeError, ValueError):
            return jax.ShapeDtypeStruct(shape, dtype)

    return jax.tree.map(leaf, tree)


def roofline_constants(peak_flops: Optional[float] = None,
                       hbm_bytes_s: Optional[float] = None,
                       ici_bytes_s: Optional[float] = None) -> dict:
    """Resolve the roofline triple: explicit args > env overrides >
    the detected chip's peak (profiler table) > BASELINE.md defaults."""
    if peak_flops is None:
        env = os.environ.get("PDT_TPU_PEAK_FLOPS")
        if env:
            peak_flops = float(env)
        else:
            try:
                from .profiler import peak_flops_per_device
                peak_flops = peak_flops_per_device()
            except Exception:  # noqa: BLE001
                peak_flops = None
        if peak_flops is None:
            peak_flops = DEFAULT_PEAK_FLOPS
    if hbm_bytes_s is None:
        hbm_bytes_s = float(
            os.environ.get("PDT_HBM_BYTES_S", DEFAULT_HBM_BYTES_S))
    if ici_bytes_s is None:
        ici_bytes_s = float(
            os.environ.get("PDT_ICI_BYTES_S", DEFAULT_ICI_BYTES_S))
    return {"peak_flops": float(peak_flops),
            "hbm_bytes_s": float(hbm_bytes_s),
            "ici_bytes_s": float(ici_bytes_s)}


def roofline(costs: dict, peak_flops: Optional[float] = None,
             hbm_bytes_s: Optional[float] = None,
             ici_bytes_s: Optional[float] = None) -> dict:
    """Roofline placement per kernel class: estimated device time is
    ``max(flops/peak, bytes/hbm)`` (``bytes/ici`` for the collective
    class), and the class is bound by whichever ceiling wins. Returns
    ``{"classes": {cls: {est_time_s, frac_time, bound, ...}},
    "est_step_time_s", constants...}`` — fractions of the MODELED
    time; ``anatomy`` marries them to measured wall time."""
    k = roofline_constants(peak_flops, hbm_bytes_s, ici_bytes_s)
    out_classes = {}
    for cls, c in costs["classes"].items():
        t_compute = c["flops"] / k["peak_flops"]
        if cls == "collective":
            t_mem = c["bytes"] / k["ici_bytes_s"]
            bound = "ici" if t_mem >= t_compute else "compute"
        else:
            t_mem = c["bytes"] / k["hbm_bytes_s"]
            bound = "hbm" if t_mem >= t_compute else "compute"
        out_classes[cls] = {
            **c,
            "est_time_s": max(t_compute, t_mem),
            "bound": bound,
        }
    total = sum(c["est_time_s"] for c in out_classes.values())
    for c in out_classes.values():
        c["frac_time"] = (c["est_time_s"] / total if total > 0 else 0.0)
    return {
        "classes": out_classes,
        "est_step_time_s": total,
        **k,
    }
