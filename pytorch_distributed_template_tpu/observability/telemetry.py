"""Flight recorder: one structured record per training step.

MegaScale-style per-step telemetry as a first-class subsystem: the
trainer (and bench.py) feed one record per step into a bounded
in-memory ring buffer, and process 0 appends each record as a JSON
line to ``<run_dir>/telemetry.jsonl``. A wedged or crashed run leaves
its last ``capacity`` steps on disk and in the watchdog's stall dump
(utils/watchdog.py) instead of evaporating; a healthy run leaves a
machine-parseable timeline that tooling (bench.py, sweeps, dashboards)
reads back without scraping logs.

Record schema (all optional except ``v``/``step``/``t``):

    {"v": 1, "step": 0, "t": <unix seconds>,
     "wall_ms": ..., "data_wait_ms": ...,
     "loss": ..., "grad_norm": ..., "lr": ...,
     "examples": ..., "tokens": ...,
     "steps_per_sec": ..., "examples_per_sec": ..., "tokens_per_sec": ...,
     "mfu": ...,
     "compile_events": [{"event": ..., "dur_ms": ...}, ...],
     "host_rss_mb": ..., "devices": {"0": {"bytes_in_use": ...,
                                           "peak_bytes_in_use": ...}}}

Memory fields attach every ``memory_every`` records (host RSS is a
/proc read, device HBM a ``memory_stats()`` call per device — cheap,
but not per-step cheap on big slices). Compile events come from a
``jax.monitoring`` duration listener installed once per process: any
jit/pjit compilation that happened since the previous record rides
along on the next one, so recompilation storms are visible in the
timeline instead of silently halving throughput.
"""
from __future__ import annotations

import atexit
import collections
import json
import os
import threading
import time
import weakref
from pathlib import Path
from typing import Optional

SCHEMA_VERSION = 1

# File-backed recorders register here so ONE atexit hook fsyncs every
# live JSONL tail on interpreter exit — normal return, sys.exit, and
# unhandled exceptions all run atexit, so a crashing run keeps its last
# ring of records on disk without every caller remembering to flush().
# (os._exit and SIGKILL bypass atexit; the watchdog's stall-path flush
# covers the wedged-then-killed case.) WeakSet: registration must not
# keep closed recorders alive.
_live_recorders: "weakref.WeakSet" = weakref.WeakSet()
_atexit_lock = threading.Lock()
_atexit_installed = False


def _flush_live_recorders() -> None:
    for rec in list(_live_recorders):
        try:
            rec.flush()
        except Exception:  # noqa: BLE001 — exit hooks must never raise
            pass


def _register_for_atexit(recorder) -> None:
    global _atexit_installed
    _live_recorders.add(recorder)
    with _atexit_lock:
        if not _atexit_installed:
            _atexit_installed = True
            atexit.register(_flush_live_recorders)

# ---------------------------------------------------------------------------
# host / device memory probes
# ---------------------------------------------------------------------------


def host_rss_bytes() -> Optional[int]:
    """Resident set size of this process, or None when unknowable."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        import resource
        import sys

        # ru_maxrss is KB on linux, bytes on macOS; prefer /proc above,
        # this is the portable fallback (peak, not current)
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return rss if sys.platform == "darwin" else rss * 1024
    except Exception:
        return None


def device_memory_stats() -> dict:
    """Per-device HBM stats from ``Device.memory_stats()``.

    ``{device_index: {"bytes_in_use": ..., "peak_bytes_in_use": ...}}``;
    empty on backends that don't report (CPU returns None)."""
    out: dict = {}
    try:
        import jax

        for d in jax.local_devices():
            stats = None
            try:
                stats = d.memory_stats()
            except Exception:
                pass
            if not stats:
                continue
            out[str(d.id)] = {
                k: int(v) for k, v in stats.items()
                if k in ("bytes_in_use", "peak_bytes_in_use",
                         "bytes_limit", "num_allocs")
            }
    except Exception:
        pass
    return out


# ---------------------------------------------------------------------------
# compile-event capture (process-wide, installed once)
# ---------------------------------------------------------------------------

_compile_lock = threading.Lock()
_compile_events: "collections.deque" = collections.deque(maxlen=256)
_compile_listener_installed = False
# persistent-compilation-cache counters (utils/compile_cache wires the
# cache itself; these count process lifetime hits/misses/requests —
# /metrics and the bench warm_start rung read them). A "miss" IS a real
# XLA compile; a "hit" is an executable deserialized from the cache dir.
_cache_counters = {"hits": 0, "misses": 0, "requests": 0}


def _install_compile_listener() -> None:
    """Register ``jax.monitoring`` listeners recording every compilation
    event (durations) and every persistent-cache hit/miss (plain
    events). Idempotent; silently absent on jax builds without the
    monitoring API."""
    global _compile_listener_installed
    with _compile_lock:
        if _compile_listener_installed:
            return
        _compile_listener_installed = True
    try:
        from jax import monitoring

        def _listen(event: str, duration: float, **kw) -> None:
            # real compilation work only (XLA backend compile + MLIR
            # lowering); the /jax/core/compile/jaxpr_trace_duration
            # events fire per traced sub-jaxpr and spam hundreds of
            # sub-ms entries on the first step
            if "compil" in event and "trace_duration" not in event:
                with _compile_lock:
                    _compile_events.append(
                        {"event": event,
                         "dur_ms": round(duration * 1e3, 3)}
                    )

        def _listen_plain(event: str, **kw) -> None:
            # cache hit/miss ride the per-step records too (a miss next
            # to a backend_compile duration says the compile was real;
            # a hit says it was a disk read) — note the
            # backend_compile_duration event fires EITHER WAY in jax
            # (it wraps compile_or_get_cached), so these events are the
            # only honest new-compile signal when the cache is on
            if not event.startswith("/jax/compilation_cache/"):
                return
            key = event.rsplit("/", 1)[-1]
            with _compile_lock:
                if key == "cache_hits":
                    _cache_counters["hits"] += 1
                    _compile_events.append({"event": event})
                elif key == "cache_misses":
                    _cache_counters["misses"] += 1
                    _compile_events.append({"event": event})
                elif key == "compile_requests_use_cache":
                    _cache_counters["requests"] += 1

        monitoring.register_event_duration_secs_listener(_listen)
        monitoring.register_event_listener(_listen_plain)
    except Exception:
        pass


def drain_compile_events() -> list:
    """Compilation events since the last drain (process-wide)."""
    with _compile_lock:
        out = list(_compile_events)
        _compile_events.clear()
    return out


def compile_cache_stats() -> dict:
    """Process-lifetime persistent-compilation-cache counters.

    ``misses`` counts real XLA compiles (cache enabled but no entry),
    ``hits`` counts executables loaded from the cache dir instead of
    compiled. All zero when the cache was never enabled (the listener
    only sees events jax emits, and jax emits none without a cache
    dir). Consumers: serve.py ``GET /metrics`` and bench.py's
    ``warm_start`` rung."""
    try:
        import jax

        cache_dir = jax.config.jax_compilation_cache_dir
    except Exception:
        cache_dir = None
    with _compile_lock:
        counters = dict(_cache_counters)
    return {
        "enabled": bool(cache_dir),
        "dir": cache_dir,
        **counters,
    }


# ---------------------------------------------------------------------------
# the recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Bounded per-step record ring + JSONL writer.

    :param run_dir: directory for ``telemetry.jsonl``; None disables the
        file (ring buffer only — e.g. non-main processes, tests).
    :param capacity: ring size; the watchdog stall dump and
        ``aggregates()`` see at most this many trailing records.
    :param memory_every: attach host RSS + device HBM stats to every
        N-th record (0 disables the memory fields entirely).
    :param filename: JSONL file name inside ``run_dir``.

    Thread-safe: the serving/bench paths record from worker threads.
    """

    def __init__(self, run_dir=None, capacity: int = 512,
                 memory_every: int = 16,
                 filename: str = "telemetry.jsonl"):
        self.capacity = int(capacity)
        self.memory_every = int(memory_every)
        self.ring: "collections.deque" = collections.deque(
            maxlen=self.capacity
        )
        # _lock guards ONLY the ring + counter (never held across I/O or
        # device probes): the watchdog's stall dump reads the ring from
        # its monitor thread, and a wedged file write or memory_stats()
        # call — exactly the stalls it diagnoses — must not deadlock it.
        # _io_lock serializes the JSONL file.
        self._lock = threading.Lock()
        self._io_lock = threading.Lock()
        self._n = 0
        self._file = None
        self.path = None
        if run_dir is not None:
            self.path = Path(run_dir) / filename
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self._file = open(self.path, "a", buffering=1)  # line-buffered
            _register_for_atexit(self)
        _install_compile_listener()

    # -- write ---------------------------------------------------------------

    def record(self, step: int, **fields) -> dict:
        """Append one step record; returns the full record as written.

        Non-finite floats are nulled (strict-JSON consumers choke on
        NaN/Infinity); None-valued fields are dropped."""
        rec = {"v": SCHEMA_VERSION, "step": int(step),
               "t": round(time.time(), 3)}
        for k, v in fields.items():
            if v is None:
                continue
            if (not isinstance(v, (bool, int, float, str, bytes))
                    and hasattr(v, "item")):
                # numpy/jax scalars: unwrap to builtins so the
                # non-finite nulling below sees them and json.dumps
                # never chokes on a caller's un-converted scalar
                try:
                    v = v.item()
                except Exception:
                    pass
            if isinstance(v, float) and (v != v or v in (float("inf"),
                                                         float("-inf"))):
                v = None
            rec[k] = v
        compile_events = drain_compile_events()
        if compile_events:
            # EXTEND a caller-provided list rather than replace it: a
            # deferred record (trainer sync-free logging) drains at
            # enqueue time so its own compile rides under its own step,
            # and anything arriving before the flush still lands here
            rec["compile_events"] = (
                list(rec.get("compile_events") or []) + compile_events
            )
        with self._lock:
            self._n += 1
            attach_memory = (
                self.memory_every
                and (self._n - 1) % self.memory_every == 0
            )
        if attach_memory:  # probes run OUTSIDE the ring lock (see init)
            rss = host_rss_bytes()
            if rss:
                rec["host_rss_mb"] = round(rss / 2**20, 1)
            devices = device_memory_stats()
            if devices:
                rec["devices"] = devices
        with self._lock:
            self.ring.append(rec)
        with self._io_lock:
            if self._file is not None:
                try:
                    # default=repr: one exotic caller field must not
                    # void the line (same policy as SpanRecorder.dump)
                    self._file.write(json.dumps(rec, default=repr) + "\n")
                except (OSError, ValueError, TypeError):
                    pass  # a full disk must never kill the step loop
        return rec

    # -- read ----------------------------------------------------------------

    def last(self, n: Optional[int] = None) -> list:
        """The trailing ``n`` records (all buffered when None)."""
        with self._lock:
            records = list(self.ring)
        return records if n is None else records[-int(n):]

    def aggregates(self) -> dict:
        """Throughput over the buffered window, computed from the
        records themselves (the numbers bench.py reports): steps/s from
        summed ``wall_ms``, tokens/s and examples/s from the summed
        ``tokens``/``examples`` fields over the same wall time."""
        records = self.last()
        timed = [r for r in records if r.get("wall_ms")]
        if not timed:
            return {"steps": len(records)}
        wall_s = sum(r["wall_ms"] for r in timed) / 1e3
        out = {
            "steps": len(timed),
            "wall_s": round(wall_s, 3),
            "steps_per_sec": round(len(timed) / wall_s, 4),
        }
        tokens = sum(r.get("tokens", 0) for r in timed)
        if tokens:
            out["tokens_per_sec"] = round(tokens / wall_s, 1)
        examples = sum(r.get("examples", 0) for r in timed)
        if examples:
            out["examples_per_sec"] = round(examples / wall_s, 1)
        waits = [r["data_wait_ms"] for r in timed
                 if r.get("data_wait_ms") is not None]
        if waits:
            out["data_wait_ms_mean"] = round(sum(waits) / len(waits), 3)
        losses = [r["loss"] for r in timed if r.get("loss") is not None]
        if losses:
            out["last_loss"] = losses[-1]
        return out

    # -- lifecycle -----------------------------------------------------------

    def flush(self) -> None:
        with self._io_lock:
            if self._file is not None:
                try:
                    self._file.flush()
                    os.fsync(self._file.fileno())
                except (OSError, ValueError):
                    pass

    def close(self) -> None:
        with self._io_lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_jsonl(path) -> list:
    """Load a telemetry JSONL file back into a list of records —
    the round-trip consumers (tests, dashboards, bench) use."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return records
