"""Numerics forensics: per-step health summary + anomaly detection.

The flight recorder (telemetry.py) writes WHAT happened every step; this
module decides whether it was HEALTHY and, when it wasn't, freezes the
evidence before it scrolls out of the ring. Three pieces:

- **In-graph summary** (``health_summary_*`` helpers, called from
  ``engine/steps.make_train_step(health=True)``): a handful of scalar
  reductions compiled INTO the train step — per-example loss, global
  grad/update norms, and non-finite element counts for the gradients
  (per top-level parameter group, so a dump says *which* module
  produced the NaN) and the post-update parameters. The host never
  syncs on them: the trainer defers the fetch by one step (the same
  sync-free pattern as its log-window metrics), so detection rides the
  dispatch pipeline instead of stalling it.

- **Anomaly detector** (``EwmaDetector`` + ``HealthMonitor``): hard
  triggers on any non-finite count or non-finite loss, soft triggers on
  EWMA z-scores of loss and grad-norm. Soft triggers hold their fire
  for ``warmup_steps`` observations so the compile step / early
  optimization transient can't false-alarm. On firing, process 0 writes
  ``<log_dir>/anomaly_<step>.json``: the offending step's summary, the
  detector state, the trailing flight-recorder records, the active
  spans, and (when the trainer passes it) the epoch/batch index — then
  flushes the recorder so the JSONL tail survives whatever happens
  next. Firing can also (configurably) pause best-checkpoint promotion
  for the epoch, so a poisoned metric can't crown ``model_best``.

- **Process-wide counters** (``health_counters()``): ``anomaly_total``,
  ``straggler_windows_total``, ``profile_captures_total``, and
  ``last_anomaly_step`` — read by serve.py's ``GET /metrics`` /
  ``/healthz`` and ridden onto log-step recorder records.

Config (``trainer.health`` in the experiment JSON, all optional)::

    "health": {"enabled": true, "ewma_alpha": 0.05, "z_threshold": 8.0,
               "warmup_steps": 20, "dump_last_n": 32, "max_dumps": 8,
               "cooldown_steps": 25, "pause_best_promotion": false}
"""
from __future__ import annotations

import collections
import json
import math
import threading
import time
from pathlib import Path
from typing import Dict, Optional

# ---------------------------------------------------------------------------
# process-wide health counters (serve /metrics + recorder piggyback)
# ---------------------------------------------------------------------------

_counter_lock = threading.Lock()
_counters: dict = {
    "anomaly_total": 0,
    "straggler_windows_total": 0,
    "profile_captures_total": 0,
    "last_anomaly_step": None,
}


def health_counters() -> dict:
    """Snapshot of the process-lifetime health counters."""
    with _counter_lock:
        return dict(_counters)


def bump_counter(name: str, n: int = 1) -> None:
    with _counter_lock:
        _counters[name] = int(_counters.get(name) or 0) + n


def note_anomaly(step: int) -> None:
    with _counter_lock:
        _counters["anomaly_total"] += 1
        _counters["last_anomaly_step"] = int(step)


def reset_counters() -> None:
    """Test hook: counters are process-global."""
    with _counter_lock:
        _counters.update(anomaly_total=0, straggler_windows_total=0,
                         profile_captures_total=0, last_anomaly_step=None)


# ---------------------------------------------------------------------------
# in-graph summary helpers (traced inside the jitted train step)
# ---------------------------------------------------------------------------


def _group_items(tree):
    """Top-level (group_name, subtree) pairs of a param/grad pytree;
    the whole tree under ``"all"`` when it isn't a mapping."""
    if hasattr(tree, "items"):
        return sorted(tree.items())
    return [("all", tree)]


def nonfinite_total(tree):
    """Count of non-finite elements across all inexact leaves (traced)."""
    import jax
    import jax.numpy as jnp

    total = jnp.zeros((), jnp.float32)
    for leaf in jax.tree.leaves(tree):
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
            total = total + jnp.sum(
                ~jnp.isfinite(leaf)
            ).astype(jnp.float32)
    return total


def nonfinite_by_group(tree) -> Dict[str, object]:
    """Non-finite element counts per top-level parameter group (traced).

    Group = a top-level key of the params dict (``Dense_0``,
    ``TransformerBlock_3``, ...), so an anomaly dump attributes the NaN
    to a module instead of just saying "somewhere". The total non-finite
    grad count is the sum of these — computed host-side, not as a
    second full-tree pass."""
    return {name: nonfinite_total(sub) for name, sub in _group_items(tree)}


def health_layout(params) -> list:
    """Field order of the packed summary vector — the host-side mirror
    of ``pack_health_summary``. One [K] vector instead of K scalar
    outputs: the summary rides the step's output pytree as a single
    tiny transfer, which is what keeps the per-step overhead inside the
    dispatch shadow even on hosts where per-buffer costs dominate."""
    return ["loss", "grad_norm", "update_norm", "nonfinite_params"] + [
        f"nonfinite/{name}" for name, _ in _group_items(params)
    ]


def pack_health_summary(loss, grad_norm, update_norm, grads,
                        new_params):
    """Build the packed summary vector (traced); order matches
    ``health_layout``.

    The non-finite COUNT passes (full-tree elementwise scans) hide
    behind a ``lax.cond`` keyed on the scalars already in hand: when
    loss and both norms are finite, every count is provably zero — a
    non-finite element anywhere makes the corresponding norm non-finite
    (NaN propagates through the squared sum; inf squares to inf), and a
    param can only go non-finite through a non-finite update — so the
    cheap branch returns the TRUE value. Steady-state per-step cost is
    therefore three scalar ``isfinite`` checks; the expensive scans run
    only on the steps that are about to be dumped anyway.
    ``grads`` must be the PRE-CLIP gradients from the SAME point in
    the dataflow as ``grad_norm`` (post-normalize, post-freeze): a NaN
    global norm makes the clip scale NaN and would smear one bad leaf
    over every group, destroying the per-module attribution the dump
    exists for — and the fast-path proof above only holds when the
    counted tree is the one the norm was computed on.
    """
    import jax
    import jax.numpy as jnp

    loss = jnp.asarray(loss).astype(jnp.float32)
    grad_norm = jnp.asarray(grad_norm).astype(jnp.float32)
    update_norm = jnp.asarray(update_norm).astype(jnp.float32)
    names = sorted(name for name, _ in _group_items(grads))

    def count_branch(_):
        gc = nonfinite_by_group(grads)
        return jnp.stack([nonfinite_total(new_params)]
                         + [gc[n] for n in names])

    def zero_branch(_):
        return jnp.zeros((len(names) + 1,), jnp.float32)

    all_finite = (jnp.isfinite(loss) & jnp.isfinite(grad_norm)
                  & jnp.isfinite(update_norm))
    counts = jax.lax.cond(all_finite, zero_branch, count_branch, None)
    return jnp.concatenate(
        [jnp.stack([loss, grad_norm, update_norm]), counts]
    )


def health_metric_keys(params) -> list:
    """The metric key(s) ``make_train_step(health=True)`` adds — for
    out-sharding declarations and for stripping the health entry out of
    the epoch accumulator. (One packed vector under ``"health"``.)"""
    return ["health"]


def unpack_health_summary(vec, layout: list) -> dict:
    """Packed vector -> named summary dict; derives the total
    ``nonfinite_grads`` from the per-group counts."""
    import numpy as np

    flat = np.asarray(vec, np.float64).reshape(-1)
    summary = {name: float(v) for name, v in zip(layout, flat)}
    summary["nonfinite_grads"] = float(sum(
        v for k, v in summary.items() if k.startswith("nonfinite/")
    ))
    return summary


# ---------------------------------------------------------------------------
# host-side detection
# ---------------------------------------------------------------------------


class EwmaDetector:
    """EWMA mean/variance z-score detector for one scalar series.

    ``update(x)`` returns the z-score of ``x`` against the series'
    exponentially-weighted history, or None while warming up (fewer than
    ``warmup`` finite observations) or when ``x`` is non-finite (the
    hard-trigger path owns that case). The deviation floor
    (``1e-8 + floor_frac * |mean|``) keeps a near-constant series (e.g.
    a converged loss) from turning sub-percent jitter into huge
    z-scores.

    One-sided by default: the monitored series (loss, grad norm) are
    "bigger is worse" — a healthy training run's steadily DECREASING
    loss must never fire, so downward deviations score 0.
    """

    def __init__(self, alpha: float = 0.05, warmup: int = 20,
                 floor_frac: float = 0.02, one_sided: bool = True):
        self.one_sided = bool(one_sided)
        self.alpha = float(alpha)
        self.warmup = int(warmup)
        self.floor_frac = float(floor_frac)
        self.mean = 0.0
        self.var = 0.0
        self.n = 0

    def update(self, x) -> Optional[float]:
        if x is None or not math.isfinite(float(x)):
            return None
        x = float(x)
        z = None
        if self.n >= self.warmup:
            dev = (x - self.mean) if self.one_sided else abs(x - self.mean)
            std = math.sqrt(max(self.var, 0.0))
            floor = 1e-8 + self.floor_frac * abs(self.mean)
            z = max(dev, 0.0) / max(std, floor)
        if self.n == 0:
            self.mean, self.var = x, 0.0
        else:
            a = self.alpha
            delta = x - self.mean
            self.mean += a * delta
            self.var = (1.0 - a) * (self.var + a * delta * delta)
        self.n += 1
        return z

    def state(self) -> dict:
        return {"mean": self.mean, "std": math.sqrt(max(self.var, 0.0)),
                "n": self.n}


class HealthMonitor:
    """Consumes per-step health summaries; dumps forensics on anomaly.

    :param cfg: the ``trainer.health`` config dict (see module doc).
    :param recorder: optional ``FlightRecorder`` — its trailing records
        go into the anomaly bundle and it is flushed after a dump.
    :param spans: optional ``SpanRecorder`` — active spans at dump time.
    :param log_dir: where ``anomaly_<step>.json`` lands (None: no file,
        e.g. non-main processes — detection/counters still run).

    ``enqueue(step, device_metrics)`` defers the device fetch by one
    step: the entry observed at step N was dispatched at step N-1, whose
    buffers resolved while step N dispatched — so consuming the summary
    never blocks the pipeline on the step just issued. ``drain()`` at
    epoch end observes the final pending entry.
    """

    def __init__(self, cfg: Optional[dict] = None, recorder=None,
                 spans=None, log_dir=None, layout=None):
        cfg = dict(cfg or {})
        self.layout = list(layout) if layout is not None else None
        self.enabled = bool(cfg.get("enabled", True))
        self.z_threshold = float(cfg.get("z_threshold", 8.0))
        self.dump_last_n = int(cfg.get("dump_last_n", 32))
        self.max_dumps = int(cfg.get("max_dumps", 8))
        self.cooldown_steps = int(cfg.get("cooldown_steps", 25))
        self.pause_best_promotion = bool(
            cfg.get("pause_best_promotion", False)
        )
        alpha = float(cfg.get("ewma_alpha", 0.05))
        warmup = int(cfg.get("warmup_steps", 20))
        self.detectors = {
            "loss": EwmaDetector(alpha, warmup),
            # grad norm legitimately swings several-x during normal
            # training (schedule phases, batch composition — measured
            # 0.6 -> 4.3 on the bench TinyLM). floor_frac=1.0 makes the
            # z-score count multiples of the running MEAN, so the soft
            # trigger needs an ~order-of-magnitude explosion
            # (> (1 + z_threshold) x EWMA), not a few sigmas of a
            # quiet stretch
            "grad_norm": EwmaDetector(alpha, warmup, floor_frac=1.0),
        }
        self.recorder = recorder
        self.spans = spans
        self.log_dir = Path(log_dir) if log_dir is not None else None
        self.cfg = cfg
        self.anomalies = 0          # fires this process
        self.dumps_written = 0
        self.last_anomaly_step: Optional[int] = None
        self._last_dump_step: Optional[int] = None
        self._last_note_step: Optional[int] = None
        self._epoch_anomaly = False
        self._pending: "collections.deque" = collections.deque()

    # -- deferred per-step feed ---------------------------------------------

    def enqueue(self, step: int, device_metrics: dict,
                meta: Optional[dict] = None) -> None:
        """Queue this step's (still on-device) health scalars; observe
        the previously queued step (its buffers have resolved)."""
        if not self.enabled:
            return
        self._pending.append((step, device_metrics, meta))
        while len(self._pending) > 1:
            self._observe_device(*self._pending.popleft())

    def drain(self) -> None:
        """Observe anything still pending (epoch end)."""
        while self._pending:
            self._observe_device(*self._pending.popleft())

    def _observe_device(self, step, device_metrics, meta) -> None:
        try:
            import jax

            fetched = jax.device_get(device_metrics)
            if self.layout is not None and "health" in fetched:
                summary = unpack_health_summary(fetched["health"],
                                                self.layout)
            else:  # pre-unpacked scalar dicts (tests, custom feeds)
                summary = {k.replace("health/", "", 1): float(v)
                           for k, v in fetched.items()}
        except Exception:  # noqa: BLE001 — diagnostics must not crash
            return
        self.observe(step, summary, meta=meta)

    # -- detection -----------------------------------------------------------

    def observe(self, step: int, summary: dict,
                meta: Optional[dict] = None) -> Optional[dict]:
        """Run the detectors over one step's summary; returns the
        anomaly dict when one fired (also written to disk), else None.

        ``summary`` keys: ``loss``, ``grad_norm``, ``update_norm``,
        ``nonfinite_grads``, ``nonfinite_params``, and per-group
        ``nonfinite/<group>`` counts (all plain floats).
        """
        if not self.enabled:
            return None
        reasons = []
        loss = summary.get("loss")
        if loss is not None and not math.isfinite(float(loss)):
            reasons.append({"kind": "nonfinite_loss", "value": repr(loss)})
        for key in ("grad_norm", "update_norm"):
            # hard trigger, not EWMA (the detector skips non-finite
            # inputs and this path owns them): a norm can overflow f32
            # to inf from FINITE elements (squares sum past ~3.4e38),
            # in which case grad clipping silently zeroes every update
            # — loss stays finite, counts stay 0, and without this
            # check the run stalls with the health layer all-clear
            v = summary.get(key)
            if v is not None and not math.isfinite(float(v)):
                reasons.append({"kind": f"nonfinite_{key}",
                                "value": repr(v)})
        for key in ("nonfinite_grads", "nonfinite_params"):
            v = summary.get(key)
            if v is not None and (not math.isfinite(float(v))
                                  or float(v) > 0):
                reasons.append({"kind": key, "count": float(v)})
        zscores = {}
        for name, det in self.detectors.items():
            z = det.update(summary.get(name))
            if z is not None:
                zscores[name] = round(z, 2)
                if z > self.z_threshold:
                    reasons.append({
                        "kind": f"{name}_zscore", "z": round(z, 2),
                        "value": summary.get(name),
                        "ewma": det.state(),
                    })
        if not reasons:
            return None
        return self._fire(step, summary, reasons, zscores, meta)

    def _fire(self, step, summary, reasons, zscores, meta) -> dict:
        self.anomalies += 1
        self.last_anomaly_step = int(step)
        self._epoch_anomaly = True
        note_anomaly(step)
        anomaly = {
            "v": 1,
            "step": int(step),
            "t": round(time.time(), 3),
            "reasons": reasons,
            "summary": summary,
            "zscores": zscores,
            "detector": {k: d.state() for k, d in self.detectors.items()},
            "config": self.cfg,
        }
        if meta:
            anomaly.update(meta)
        if self.spans is not None:
            try:
                anomaly["active_spans"] = self.spans.active_spans()
            except Exception:  # noqa: BLE001
                pass
        if self.recorder is not None:
            try:
                anomaly["last_records"] = self.recorder.last(
                    self.dump_last_n
                )
            except Exception:  # noqa: BLE001
                pass
        # the process's time-series window (ISSUE 14): the anomaly
        # bundle carries the trailing trend, not just the offending
        # instant — present only when a store is registered (the
        # serving paths register one; bare training runs don't)
        try:
            from .timeseries import default_store

            ts = default_store()
            if ts is not None:
                anomaly["timeseries_window"] = ts.points(
                    last_n=self.dump_last_n)
        except Exception:  # noqa: BLE001
            pass
        self._write_dump(step, anomaly)
        # timeline note + tail fsync rate-limited by the SAME cooldown
        # as the dumps: a persistent NaN streak under skip_nonfinite
        # fires every step for the rest of the run, and an fsync per
        # hot-loop step (ms-to-tens-of-ms on networked filesystems)
        # would tax exactly the run the user asked to keep going.
        # Counters still count every fire.
        note_ok = (self._last_note_step is None
                   or step - self._last_note_step >= self.cooldown_steps)
        if self.recorder is not None and note_ok:
            self._last_note_step = int(step)
            try:
                # the anomaly becomes a timeline event, and the JSONL
                # tail is forced to disk — a crash right after a NaN
                # must not lose the records that explain it
                self.recorder.record(
                    step, event="anomaly",
                    reasons=json.dumps([r["kind"] for r in reasons]),
                )
                self.recorder.flush()
            except Exception:  # noqa: BLE001
                pass
        return anomaly

    def _write_dump(self, step, anomaly) -> None:
        if self.log_dir is None:
            return
        if self.dumps_written >= self.max_dumps:
            return
        if (self._last_dump_step is not None
                and step - self._last_dump_step < self.cooldown_steps):
            return  # a NaN streak fires per step; don't flood the dir
        try:
            self.log_dir.mkdir(parents=True, exist_ok=True)
            path = self.log_dir / f"anomaly_{int(step)}.json"
            path.write_text(json.dumps(anomaly, default=repr))
            self.dumps_written += 1
            self._last_dump_step = int(step)
            anomaly["dump_path"] = str(path)
        except Exception:  # noqa: BLE001 — a full disk must not kill
            pass           # the run the dump is diagnosing

    # -- checkpoint-promotion gate -------------------------------------------

    def promotion_allowed(self) -> bool:
        """False while ``pause_best_promotion`` is set and the current
        epoch saw an anomaly — a poisoned metric must not crown
        ``model_best``."""
        return not (self.pause_best_promotion and self._epoch_anomaly)

    def epoch_start(self) -> None:
        self._epoch_anomaly = False
