"""Hierarchical logging configured from JSON dictConfig.

Parity with /root/reference/logger/logger.py:7-22 and
logger/logger_config.json: console handler at DEBUG with bare messages plus a
rotating ``info.log`` file handler (INFO, timestamps, 10 MiB x 20 backups)
whose path is rewritten into the run directory.
"""
from __future__ import annotations

import logging
import logging.config
from pathlib import Path

from ..utils.util import read_json

DEFAULT_CONFIG = Path(__file__).parent / "logger_config.json"


def setup_logging(save_dir, log_config=DEFAULT_CONFIG,
                  default_level=logging.INFO) -> None:
    """Setup logging configuration, rewriting file-handler paths into
    ``save_dir``. Falls back to ``basicConfig`` when the JSON is missing
    (reference parity, logger/logger.py:20-22)."""
    log_config = Path(log_config)
    if log_config.is_file():
        config = read_json(log_config)
        for handler in config.get("handlers", {}).values():
            if "filename" in handler:
                handler["filename"] = str(Path(save_dir) / handler["filename"])
        logging.config.dictConfig(config)
    else:
        print(f"logging config {log_config} missing; "
              "falling back to basicConfig")
        logging.basicConfig(level=default_level)
