"""Per-stage service-time models + goodput accounting (ISSUE 14).

The stitched request timelines (observability/reqtrace.py) decompose
every request into non-overlapping segments, but PR 8 only ever
*summarized* them (p50/p99 per segment). The discrete-event simulator
and any scale-up policy (ROADMAP item 5) need the actual measured
**distributions** — Splitwise and DistServe both built their
phase-split and provisioning decisions on exactly this input. This
module extracts them and freezes the result as a **versioned
``service_model.json``**, the simulator's input contract:

- per segment (admit / decode / scheduler_queue / ...), a log-spaced
  histogram over SHARED global bin edges (two models compare
  bin-to-bin) plus exact p50/p90/p99 from the raw samples (via THE
  package percentile convention, utils/promtext.percentile);
- the same, split per **route class** — ``(admit mode: warm / cold /
  paged) × (stream / unary) × (prompt-length bucket)`` — because a
  warm pointer-update admit and a cold 512-token prefill are
  different random variables and a simulator that pools them
  reproduces neither;
- **coverage**: the attributed fraction of stitched request wall
  time, so a consumer knows how much latency the model explains (the
  CI gate holds it ≥ 0.9).

:func:`drift_report` compares two models per-segment with a relative
tolerance — the distribution-level regression gate behind
``telemetry_report --drift`` (a p99 shift in ``admit`` fails CI even
when aggregate tok/s held).

:class:`GoodputMeter` is the fleet-wide goodput ledger: raw tokens vs
SERVED tokens (error / cancelled / deadline-truncated tokens
excluded) vs SLO-compliant tokens, with per-tenant shares — the
"useful work per second" number an autoscaler optimizes, scraped on
the router's ``/metrics``.

Stdlib-only: the fleet router imports this and must stay jax-free.
"""
from __future__ import annotations

import bisect
import json
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

from ..utils.promtext import percentile as _pctl
from . import reqtrace

SERVICE_MODEL_VERSION = 1
SERVICE_MODEL_FILENAME = "service_model.json"

#: shared log-spaced bin edges (seconds): 100 µs .. 1000 s, 8 bins per
#: decade. Global and versioned WITH the model so histograms from two
#: runs align bin-to-bin — drift comparison and simulator sampling
#: never need to rebin.
LOG_EDGES_S = tuple(round(10.0 ** (e / 8.0), 9)
                    for e in range(-32, 25))


def hist_counts(values) -> List[int]:
    """Counts per LOG_EDGES_S bin (+1 overflow bin at the end;
    values below the first edge land in bin 0)."""
    counts = [0] * (len(LOG_EDGES_S) + 1)
    for v in values:
        counts[bisect.bisect_left(LOG_EDGES_S, float(v))] += 1
    return counts


def _seg_stats(values: List[float]) -> dict:
    vals = sorted(float(v) for v in values)
    return {
        "count": len(vals),
        "mean_s": round(sum(vals) / len(vals), 6),
        "p50_s": round(_pctl(vals, 0.50), 6),
        "p90_s": round(_pctl(vals, 0.90), 6),
        "p99_s": round(_pctl(vals, 0.99), 6),
        "max_s": round(vals[-1], 6),
        "hist_counts": hist_counts(vals),
    }


def _by_rid(spans: List[dict]) -> Dict[str, List[dict]]:
    out: Dict[str, List[dict]] = {}
    for s in spans:
        rid = s.get("rid")
        if rid:
            out.setdefault(rid, []).append(s)
    return out


def prompt_len_bucket(n: int) -> int:
    """Power-of-two prompt-length bucket (the admit ladder's own
    shape discipline): 0, 1..32 -> 32, 33..64 -> 64, ..."""
    n = int(n)
    if n <= 0:
        return 0
    b = 32
    while b < n:
        b <<= 1
    return b


def route_class(recs: List[dict]) -> str:
    """One request's route class from its raw span records:
    ``<admit mode>|<stream|unary>|b<prompt bucket>``. The admit span
    (continuous engine) carries ``mode`` (warm/cold/paged) and the
    admission ``bucket``; the replica's ``http`` span carries the
    ``stream`` flag. Missing spans degrade to ``"?"`` fields — the
    class still groups consistently."""
    mode, bucket = "?", 0
    http_stream = req_stream = None
    for r in recs:
        attrs = r.get("attrs") or {}
        name = r.get("name")
        if name == "admit":
            mode = str(attrs.get("mode", mode))
            try:
                bucket = int(attrs.get("bucket", bucket) or 0)
            except (TypeError, ValueError):
                pass
        elif name == "queue_wait" and not bucket:
            # fallback: older admit spans (pre-ISSUE 14 paged path)
            # carry the bucket only on the queue_wait span
            try:
                bucket = int(attrs.get("bucket", 0) or 0)
            except (TypeError, ValueError):
                pass
        elif name == "http" and "stream" in attrs:
            http_stream = bool(attrs.get("stream"))
        elif name == "request" and "stream" in attrs:
            req_stream = bool(attrs.get("stream"))
    # the replica's handler span is closest to the wire truth; the
    # router's request span covers direct-vs-fleet gaps
    stream = http_stream if http_stream is not None else req_stream
    return (f"{mode}|{'stream' if stream else 'unary'}"
            f"|b{prompt_len_bucket(bucket)}")


def build_service_model(spans: List[dict],
                        client_e2e_by_rid: Optional[dict] = None,
                        stitched_only: bool = True) -> dict:
    """Stitch ``spans`` and fold every request's segment values into
    the versioned model (see module doc). ``stitched_only`` keeps
    single-process orphans out of the distributions (their segments
    are partial by construction); direct-to-replica runs pass False.
    """
    report = reqtrace.stitch_spans(
        spans, client_e2e_by_rid=client_e2e_by_rid)
    recs_by_rid = _by_rid(spans)
    seg_values: Dict[str, List[float]] = {}
    class_values: Dict[str, Dict[str, List[float]]] = {}
    used = 0
    wall_s = attributed_s = 0.0
    for row in report["requests"]:
        if stitched_only and not row.get("stitched"):
            continue
        if row.get("e2e_s") is None:
            continue
        used += 1
        wall_s += float(row["e2e_s"])
        attributed_s += float(row.get("attributed_s", 0.0))
        cls = route_class(recs_by_rid.get(row["rid"], ()))
        for name, v in row["segments"].items():
            seg_values.setdefault(name, []).append(float(v))
            class_values.setdefault(name, {}).setdefault(
                cls, []).append(float(v))
    segments = {}
    for name in sorted(seg_values):
        entry = _seg_stats(seg_values[name])
        entry["classes"] = {
            cls: _seg_stats(vals)
            for cls, vals in sorted(class_values[name].items())}
        segments[name] = entry
    return {
        "version": SERVICE_MODEL_VERSION,
        "generated_t": round(time.time(), 3),
        "edges_s": list(LOG_EDGES_S),
        "counts": {
            "requests": report["counts"]["requests"],
            "stitched": report["counts"]["stitched"],
            "modeled": used,
        },
        "coverage": {
            "stitched_wall_s": round(wall_s, 6),
            "attributed_s": round(attributed_s, 6),
            "frac": (round(attributed_s / wall_s, 4)
                     if wall_s > 0 else None),
        },
        "segments": segments,
    }


def write_service_model(model: dict, path) -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(model, indent=2) + "\n")
    return path


def load_service_model(path) -> dict:
    model = json.loads(Path(path).read_text())
    if not isinstance(model, dict) or "segments" not in model:
        raise ValueError(f"{path}: not a service_model.json")
    return model


def drift_report(current: dict, baseline: dict,
                 tolerance: float = 0.25,
                 quantiles=("p50_s", "p99_s"),
                 min_count: int = 3) -> dict:
    """Per-segment distribution drift between two service models.

    For every segment present in either model (with at least
    ``min_count`` samples on the side that has it), each gated
    quantile must sit within ``tolerance`` RELATIVE shift of the
    baseline (both directions — a segment getting 10x *faster* is as
    much a behavior change as 10x slower, and usually means the
    measurement broke). A segment present on one side only is a
    shift. Returns ``{"compared": [...], "shifts": [...],
    "tolerance": ...}``; callers exit nonzero on any shift. A model
    compared against itself passes at tolerance 0 (shift requires a
    STRICT tolerance exceedance)."""
    shifts: List[dict] = []
    compared: List[dict] = []
    if current.get("version") != baseline.get("version"):
        shifts.append({"segment": "<model>", "kind": "version",
                       "current": current.get("version"),
                       "baseline": baseline.get("version")})
    cur_segs = current.get("segments") or {}
    base_segs = baseline.get("segments") or {}
    for name in sorted(set(cur_segs) | set(base_segs)):
        c, b = cur_segs.get(name), base_segs.get(name)
        if c is None or b is None:
            present = c if c is not None else b
            if int(present.get("count", 0)) >= min_count:
                shifts.append({
                    "segment": name, "kind": "missing",
                    "side": "baseline" if c is not None
                    else "current"})
            continue
        if (int(c.get("count", 0)) < min_count
                or int(b.get("count", 0)) < min_count):
            continue                     # too thin to judge either way
        for q in quantiles:
            cv, bv = c.get(q), b.get(q)
            if cv is None or bv is None:
                continue
            rel = abs(float(cv) - float(bv)) / max(abs(float(bv)),
                                                   1e-6)
            row = {"segment": name, "quantile": q,
                   "current": cv, "baseline": bv,
                   "rel_shift": round(rel, 4)}
            compared.append(row)
            if rel > tolerance:
                shifts.append({**row, "kind": "shift"})
    return {"compared": compared, "shifts": shifts,
            "tolerance": tolerance}


# ---------------------------------------------------------------------------
# goodput accounting
# ---------------------------------------------------------------------------


class GoodputMeter:
    """Fleet-wide goodput ledger (the router's ``/metrics`` view).

    Three nested token counters, each a subset of the last:

    - ``raw_tokens_total`` — every generated token that crossed the
      wire, whatever became of its request;
    - ``served_tokens_total`` — tokens of requests that completed
      normally: **error / cancelled / deadline-truncated tokens are
      excluded** (the engine burned chip time on them, but nobody got
      the answer they asked for — counting them would reward
      truncation);
    - ``goodput_tokens_total`` — served tokens that ALSO met the
      configured SLO thresholds (== served when no SLO is armed).

    Plus ``deadline_goodput_tokens_total`` (served tokens of
    deadline-carrying requests — the budget was feasible AND met) and
    per-tenant raw/good shares. Rates are over the meter's lifetime
    since its first observation; ``goodput ≤ served ≤ raw`` holds by
    construction and the serve_fleet rung gates it.
    """

    #: outcomes whose tokens count as SERVED (the router's _generate
    #: outcome vocabulary; the plain serve.py path passes "ok")
    SERVED_OUTCOMES = ("proxied", "done", "ok")

    def __init__(self, ttft_s: Optional[float] = None,
                 e2e_s: Optional[float] = None):
        self.ttft_s = float(ttft_s) if ttft_s else None
        self.e2e_s = float(e2e_s) if e2e_s else None
        self._lock = threading.Lock()
        self._t0: Optional[float] = None
        self._c = {"raw_tokens_total": 0, "served_tokens_total": 0,
                   "goodput_tokens_total": 0,
                   "deadline_goodput_tokens_total": 0}
        self._tenants: Dict[str, dict] = {}

    def set_slo(self, ttft_s: Optional[float],
                e2e_s: Optional[float]) -> None:
        self.ttft_s = float(ttft_s) if ttft_s else None
        self.e2e_s = float(e2e_s) if e2e_s else None

    def observe(self, tokens: int, outcome: str = "proxied",
                e2e_s: Optional[float] = None,
                ttft_s: Optional[float] = None,
                tenant: str = "default",
                had_deadline: bool = False) -> None:
        tokens = max(int(tokens or 0), 0)
        served = outcome in self.SERVED_OUTCOMES
        slo_ok = served
        if slo_ok and self.ttft_s is not None and ttft_s is not None \
                and ttft_s > self.ttft_s:
            slo_ok = False
        if slo_ok and self.e2e_s is not None and e2e_s is not None \
                and e2e_s > self.e2e_s:
            slo_ok = False
        with self._lock:
            if self._t0 is None:
                self._t0 = time.monotonic()
            self._c["raw_tokens_total"] += tokens
            t = self._tenants.setdefault(
                str(tenant)[:64], {"raw_tokens": 0, "good_tokens": 0})
            t["raw_tokens"] += tokens
            if served:
                self._c["served_tokens_total"] += tokens
                if had_deadline:
                    # a SERVED deadline-carrying request met its
                    # budget by definition (expiry would have
                    # classified it "deadline") — the feasible tier
                    # is a subset of SERVED, not of the SLO tier
                    self._c["deadline_goodput_tokens_total"] += tokens
            if slo_ok:
                self._c["goodput_tokens_total"] += tokens
                t["good_tokens"] += tokens

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._c)
            elapsed = (time.monotonic() - self._t0
                       if self._t0 is not None else 0.0)
            tenants = {k: dict(v) for k, v in self._tenants.items()}
        out["goodput_frac"] = round(
            out["goodput_tokens_total"]
            / max(out["raw_tokens_total"], 1), 4)
        if elapsed > 0:
            out["raw_tok_s"] = round(
                out["raw_tokens_total"] / elapsed, 2)
            out["goodput_tok_s"] = round(
                out["goodput_tokens_total"] / elapsed, 2)
        for t in tenants.values():
            t["goodput_frac"] = round(
                t["good_tokens"] / max(t["raw_tokens"], 1), 4)
        out["goodput_tenants"] = tenants    # JSON-only (nested)
        return out
