"""Cross-host stats aggregation + straggler detection.

On a multi-host job, process 0's ``telemetry.jsonl`` only sees its own
step loop — a slow host (thermal throttling, a sick NIC, a noisy
neighbor stealing its data-loader cores) is invisible until it drags
every collective down, and then it is indistinguishable from "the model
got slower". MegaScale-style straggler hunting needs each host's view
side by side.

``CrossHostAggregator`` piggybacks a tiny fixed-shape per-host stats
vector — mean step wall ms, mean data-wait ms, host RSS MB, and the
per-device HBM high-water MB — on a host collective
(``multihost_utils.process_allgather``, the same DCN path the
preemption consensus uses) once per log window. Every host computes the
same aggregate deterministically; process 0 attaches it to the
window's flight-recorder record::

    "hosts": {"0": {"wall_ms": 101.2, "data_wait_ms": 0.4, ...},
              "1": {"wall_ms": 163.0, ...}},
    "straggler": true, "straggler_hosts": [1], "wall_spread": 1.61

A host is flagged a straggler when its mean step wall time exceeds the
cross-host median by ``threshold`` (default 1.25x). Flagged windows
bump the process-wide ``straggler_windows_total`` counter
(health.health_counters — served by ``GET /metrics``).

Single-host the exchange degrades to a local no-collective snapshot
(``hosts`` has one entry, never a straggler), so the code path is
identical in tests and production.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from .health import bump_counter
from .telemetry import device_memory_stats, host_rss_bytes

# fixed per-host vector layout (version the layout, not the wire)
_FIELDS = ("wall_ms", "data_wait_ms", "rss_mb", "hbm_peak_mb")


def local_stats_vector(records: List[dict]) -> np.ndarray:
    """This host's stats vector over a window of recorder records.

    Records carrying ``compile_events`` are excluded: the compile
    step's wall time lands in DIFFERENT hosts' rings asymmetrically
    (process 0 defers its log-step records by one window; other hosts
    record every step immediately), and a 30s compile in one host's
    window mean but not another's reads as a 7x "straggler" on the
    first window of every multi-host run."""
    timed = [r for r in records
             if r.get("wall_ms") and not r.get("compile_events")]
    wall = (sum(r["wall_ms"] for r in timed) / len(timed)) if timed else 0.0
    waits = [r["data_wait_ms"] for r in timed
             if r.get("data_wait_ms") is not None]
    wait = (sum(waits) / len(waits)) if waits else 0.0
    rss = host_rss_bytes() or 0
    hbm_peak = 0
    for stats in device_memory_stats().values():
        hbm_peak = max(hbm_peak, int(stats.get("peak_bytes_in_use", 0)))
    return np.array([wall, wait, rss / 2**20, hbm_peak / 2**20],
                    np.float32)


def aggregate(host_vectors: np.ndarray, threshold: float = 1.25) -> dict:
    """Pure aggregation of the gathered ``[P, len(_FIELDS)]`` matrix —
    deterministic on every host (all inputs are the gathered matrix)."""
    host_vectors = np.asarray(host_vectors, np.float64).reshape(
        -1, len(_FIELDS)
    )
    hosts = {
        str(i): {f: round(float(v), 3) for f, v in zip(_FIELDS, row)}
        for i, row in enumerate(host_vectors)
    }
    walls = host_vectors[:, 0]
    out = {"hosts": hosts}
    median = float(np.median(walls))
    # every host must have a measured window (wall > 0): a host whose
    # records were all compile-filtered would drag the median down and
    # flag its healthy peers
    if median > 0 and all(w > 0 for w in walls):
        stragglers = [
            i for i, w in enumerate(walls) if w > threshold * median
        ]
        out["wall_spread"] = round(float(walls.max()) / median, 3)
        if stragglers:
            out["straggler"] = True
            out["straggler_hosts"] = stragglers
    return out


class CrossHostAggregator:
    """Per-log-window host stats exchange (see module doc).

    :param cfg: ``trainer.telemetry.crosshost`` dict: ``enabled``
        (default: auto — on iff multi-host), ``threshold`` (1.25).
    :param is_main: whether this process attaches/counts (process 0).
    """

    def __init__(self, cfg: Optional[dict] = None, is_main: bool = True):
        cfg = dict(cfg or {})
        self.threshold = float(cfg.get("threshold", 1.25))
        self.is_main = bool(is_main)
        enabled = cfg.get("enabled")
        if enabled is None:
            try:
                from ..parallel import dist

                enabled = dist.process_count() > 1
            except Exception:  # noqa: BLE001
                enabled = False
        self.enabled = bool(enabled)
        self.windows = 0
        self.straggler_windows = 0

    def should_exchange(self, batch_idx: int, log_step: int) -> bool:
        """Deterministic per-host condition — every host must reach the
        collective at the same batch or the gather deadlocks."""
        return (self.enabled and log_step > 0 and batch_idx > 0
                and batch_idx % log_step == 0)

    def exchange(self, records: List[dict]) -> Optional[dict]:
        """Gather every host's window vector; return the aggregate
        (identical on all hosts), or None on collective failure."""
        vec = local_stats_vector(records)
        try:
            from ..parallel import dist

            if dist.process_count() > 1:
                from jax.experimental import multihost_utils

                gathered = np.asarray(
                    multihost_utils.process_allgather(vec)
                )
            else:
                gathered = vec[None]
        except Exception:  # noqa: BLE001 — observability must not kill
            return None    # the step loop on a flaky DCN gather
        agg = aggregate(gathered, threshold=self.threshold)
        self.windows += 1
        if agg.get("straggler"):
            self.straggler_windows += 1
            if self.is_main:
                bump_counter("straggler_windows_total")
        return agg
