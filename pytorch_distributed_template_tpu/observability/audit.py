"""Sampled shadow-replay token-integrity auditor (ISSUE 18).

The serving stack's correctness story rests on ONE invariant: every
optimized path — paged warm admits, int8-KV, spill/ship/promote,
speculative decode, ring layouts — is token-identical to the cold
no-pool reference (greedy bit-exact; sampled exact under the request's
own seed). Tier-1 tests and bench gates enforce it at build time;
NOTHING enforced it on live traffic, where a stale adopted page or a
torn promote would serve wrong tokens invisibly. This module audits it
continuously:

- :class:`ShadowAuditor` samples COMPLETED requests — stratified by
  their serve-path fingerprint (reqtrace.path_fingerprint), so rare
  paths (ring wraps, tier promotes, shipped imports) get a coverage
  floor instead of drowning under the uniform majority — and replays
  prompt + sampling config + seed through a caller-supplied cold
  reference closure, comparing token ids EXACTLY.
- The replay runs on a background worker, OFF the scheduler hot path:
  completions ``offer()`` into a bounded queue; a full queue drops
  (counted), never blocks.
- Any mismatch increments ``token_divergence_total`` (and the
  per-fingerprint family), writes a bounded ``divergence_<rid>.json``
  bundle (both token streams, first-divergence index, the request's
  fingerprint + its reqtrace timeline) under the same max-dumps +
  cooldown discipline as the SLO watcher's slow-request dumps, and
  flips :meth:`healthy` — serve.py degrades ``/healthz`` on it so the
  fleet poller surfaces the replica.

Layout discipline: the reference closure MUST decode through the same
KV layout as the serving path. warm==cold is exact per layout;
int8-vs-f32 is a documented tolerance (PR 15), so a cross-layout
reference would false-positive on healthy traffic. serve.py builds
the closure from the serving model itself — and for an int8-KV POOL
the reference gets its own private pool too, because pool pages and
the contiguous no-pool cache quantize at different granularities
(pool-cold is the exact peer of pool-warm; no-pool int8 is not —
tests/test_audit.py pins both directions).

Stdlib-only; jax enters only through the injected ``reference_fn``.
"""
from __future__ import annotations

import json
import logging
import queue as queue_mod
import threading
import time
from pathlib import Path
from typing import Callable, List, Optional

logger = logging.getLogger(__name__)

#: terminal classifications eligible for replay: a truncated request
#: (cancelled / deadline) stopped at an absorb boundary the reference
#: cannot reproduce, so comparing it would false-positive on healthy
#: traffic
AUDITABLE_OUTCOMES = ("length", "stop")


def first_divergence(a, b) -> int:
    """Index of the first position where two token streams differ
    (length difference counts); -1 when identical."""
    a, b = list(a), list(b)
    for i, (x, y) in enumerate(zip(a, b)):
        if int(x) != int(y):
            return i
    return -1 if len(a) == len(b) else min(len(a), len(b))


class ShadowAuditor:
    """Stratified shadow-replay worker over completed requests.

    ``reference_fn(record) -> list[int]`` replays the record's prompt +
    sampling config through the cold no-pool path and returns the
    token ids the reference produced (the serving layer owns how —
    typically a second GenerationService sharing model/params with no
    prefix cache). It runs on THIS auditor's worker thread and may
    take seconds; that is the design (the queue bounds the backlog).

    Sampling is deterministic (no RNG): per fingerprint, the first
    ``floor`` completions always audit — the coverage floor that keeps
    a 1%-of-traffic ring-wrap path covered — and after the floor a
    systematic 1-in-``round(1/sample_rate)`` of that fingerprint's
    completions audits, so coverage per path is exact and testable.
    """

    def __init__(self, reference_fn: Callable[[dict], List[int]],
                 sample_rate: float = 0.05, floor: int = 4,
                 queue_max: int = 64, dump_dir=None, tracer=None,
                 tsdb=None, max_dumps: int = 8,
                 cooldown_s: float = 30.0):
        self.reference_fn = reference_fn
        self.sample_rate = max(0.0, min(1.0, float(sample_rate)))
        self.floor = max(0, int(floor))
        self.dump_dir = Path(dump_dir) if dump_dir is not None else None
        self.tracer = tracer
        self._tsdb = tsdb
        self.max_dumps = int(max_dumps)
        self.cooldown_s = float(cooldown_s)
        self._last_dump_t: Optional[float] = None
        self._lock = threading.Lock()
        self._q: "queue_mod.Queue" = queue_mod.Queue(
            maxsize=max(1, int(queue_max)))
        # fingerprint -> completions seen / audited (coverage report)
        self._seen: dict = {}
        self._audited: dict = {}
        self._divergent: dict = {}
        self._c = {"audit_sampled_total": 0, "audit_matched_total": 0,
                   "token_divergence_total": 0,
                   "audit_dropped_total": 0, "audit_skipped_total": 0,
                   "audit_error_total": 0, "audit_dumps_written": 0}
        self._closed = False
        self._idle = threading.Event()
        self._idle.set()
        self._thread = threading.Thread(
            target=self._worker, daemon=True, name="shadow-audit")
        self._thread.start()

    # ---- completion-side API (hot path: must never block) -----------

    def offer(self, record: dict) -> bool:
        """One completed request's audit candidacy. ``record`` needs
        ``serve_path`` plus everything a replay takes: ``prompt_ids``,
        ``max_new_tokens``, ``temperature``, ``top_k``, ``top_p``,
        ``seed``, ``stop``, and the served ``ids`` (+ ``rid``,
        ``stop_reason``). Returns True when enqueued for replay."""
        if self._closed:
            return False
        if record.get("stop_reason", "length") not in AUDITABLE_OUTCOMES:
            with self._lock:
                self._c["audit_skipped_total"] += 1
            return False
        fp = str(record.get("serve_path") or "")
        if not fp:
            with self._lock:
                self._c["audit_skipped_total"] += 1
            return False
        with self._lock:
            n = self._seen.get(fp, 0)
            self._seen[fp] = n + 1
            if not self._take(n):
                return False
        try:
            self._q.put_nowait(dict(record))
            return True
        except queue_mod.Full:
            with self._lock:
                self._c["audit_dropped_total"] += 1
            return False

    def _take(self, n: int) -> bool:
        """Deterministic stratified pick for the ``n``-th completion of
        a fingerprint (0-based): everything under the floor, then
        systematic 1-in-k."""
        if n < self.floor:
            return True
        if self.sample_rate <= 0.0:
            return False
        k = max(1, round(1.0 / self.sample_rate))
        return (n - self.floor) % k == 0

    # ---- worker -----------------------------------------------------

    def _worker(self) -> None:
        while True:
            rec = self._q.get()
            if rec is None:
                return
            self._idle.clear()
            try:
                self._audit_one(rec)
            except Exception:  # noqa: BLE001 — the auditor must never
                # take the server down; an errored replay is counted,
                # not raised
                logger.exception("shadow audit error (rid=%s)",
                                 rec.get("rid"))
                with self._lock:
                    self._c["audit_error_total"] += 1
            finally:
                if self._q.empty():
                    self._idle.set()

    def _audit_one(self, rec: dict) -> None:
        fp = str(rec.get("serve_path") or "")
        replay = [int(t) for t in (self.reference_fn(rec) or ())]
        served = [int(t) for t in (rec.get("ids") or ())]
        div = first_divergence(served, replay)
        counters = None
        with self._lock:
            self._c["audit_sampled_total"] += 1
            self._audited[fp] = self._audited.get(fp, 0) + 1
            if div < 0:
                self._c["audit_matched_total"] += 1
            else:
                self._c["token_divergence_total"] += 1
                self._divergent[fp] = self._divergent.get(fp, 0) + 1
            if self._tsdb is not None:
                counters = {
                    "audit_sampled_total": self._c[
                        "audit_sampled_total"],
                    "token_divergence_total": self._c[
                        "token_divergence_total"]}
        if counters is not None:
            # verdict counters ride the TimeSeriesStore so stall /
            # anomaly dumps carry the audit trend alongside goodput
            self._tsdb.observe(counters=counters)
        if div < 0:
            return
        logger.error(
            "TOKEN DIVERGENCE rid=%s fingerprint=%s first_index=%d "
            "(served %d tokens, replay %d)", rec.get("rid"), fp, div,
            len(served), len(replay))
        self._maybe_dump(rec, fp, served, replay, div)

    def _maybe_dump(self, rec, fp, served, replay, div) -> None:
        if self.dump_dir is None:
            return
        now = time.monotonic()
        with self._lock:
            if self._c["audit_dumps_written"] >= self.max_dumps:
                return
            if (self._last_dump_t is not None
                    and now - self._last_dump_t < self.cooldown_s):
                return
            self._c["audit_dumps_written"] += 1
            self._last_dump_t = now
        rid = str(rec.get("rid") or "unknown")
        payload = {
            "rid": rid,
            "fingerprint": fp,
            "first_divergence": div,
            "served_ids": served,
            "replay_ids": replay,
            "prompt_ids": list(rec.get("prompt_ids") or ()),
            "sampling": {
                k: rec.get(k) for k in
                ("max_new_tokens", "temperature", "top_k", "top_p",
                 "seed", "stop")},
            "stop_reason": rec.get("stop_reason"),
        }
        if self.tracer is not None:
            # the request's pool/page event timeline (admit mode, kv
            # adoptions, tier promotes) — the forensic half of the
            # bundle: WHICH event put the wrong bytes in reach
            payload["timeline"] = self.tracer.timeline(rid)
        try:
            self.dump_dir.mkdir(parents=True, exist_ok=True)
            path = Path(self.dump_dir) / f"divergence_{rid}.json"
            path.write_text(json.dumps(payload, indent=2,
                                       default=repr))
            logger.error("divergence bundle written: %s", path)
        except OSError:
            logger.exception("divergence bundle write failed")

    # ---- observability ----------------------------------------------

    def healthy(self) -> bool:
        """False once any replay diverged — serve.py degrades
        ``/healthz`` on it so the fleet poller surfaces the replica."""
        with self._lock:
            return self._c["token_divergence_total"] == 0

    def stats(self) -> dict:
        """Flat counters + queue gauge for /metrics."""
        with self._lock:
            out = dict(self._c)
        out["audit_queue_depth"] = self._q.qsize()
        return out

    def coverage(self) -> dict:
        """fingerprint -> {seen, audited, divergent} (the coverage
        report the serve_audit rung and the fleet dashboard read)."""
        with self._lock:
            fps = set(self._seen) | set(self._audited)
            return {fp: {"seen": self._seen.get(fp, 0),
                         "audited": self._audited.get(fp, 0),
                         "divergent": self._divergent.get(fp, 0)}
                    for fp in sorted(fps)}

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Block until the queue is empty and the worker idles (tests
        and the serve_audit rung use this to read final verdicts)."""
        deadline = time.monotonic() + float(timeout_s)
        while time.monotonic() < deadline:
            if self._q.empty() and self._idle.is_set():
                return True
            time.sleep(0.01)
        return False

    def close(self) -> None:
        self._closed = True
        self._q.put(None)
