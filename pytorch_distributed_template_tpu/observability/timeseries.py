"""Bounded fixed-interval time-series ring for the serving fleet.

The fleet already *scrapes* rich signals — the poller reads every
replica's ``/metrics?format=json`` each sweep and the slot scheduler
records a telemetry line per chunk — but until this module everything
except the latest snapshot was discarded: ``/metrics`` answers "what
is the queue depth NOW", never "what has it been doing for the last
two minutes", which is exactly the question an autoscaling policy (and
the operator staring at a brownout) needs answered. ROADMAP item 5
(autoscale + time-compressed simulation) is blocked on this layer.

:class:`TimeSeriesStore` turns a stream of ``observe(counters,
gauges)`` calls into fixed-interval *points*:

- **counters** (monotonic, ``*_total`` by convention) are delta'd
  against the previous observation with the same reset-correction
  discipline as ``fleet/replicas.absorb_counters`` (a drop means the
  process restarted: the new value IS the delta) and emitted as
  per-second **rates** (``tokens_generated_total`` →
  ``tokens_generated_per_s``) over the actually-covered span — an
  idle stretch between observations widens the denominator instead of
  fabricating a spike;
- **gauges** are sampled (last write in the interval wins);
- each completed interval appends ONE point to a bounded in-memory
  ring (the query API below) and ONE JSON line to ``timeseries.jsonl``
  (line-buffered, torn tails skipped on load — the FlightRecorder
  discipline), so a crash keeps the trend that explains it and an
  offline consumer replays the whole run.

Feeders: the fleet poller calls ``observe`` once per health sweep
(fleet aggregates + admission depths), and the continuous engine once
per absorbed chunk (tokens/admissions/queue/pool). The recorder-side
cost is gated < 2% by the ``quick_timeseries`` bench rung.

A process-wide default store (:func:`set_default_store`) lets the
watchdog's ``stall_dump.json`` and the health layer's
``anomaly_<step>.json`` attach the last window of points to their
forensic bundles — a dump then carries the *trend* into the incident,
not just the instant.

Stdlib-only: the fleet router imports this and must stay jax-free.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from ..utils.promtext import percentile as _pctl

TIMESERIES_FILENAME = "timeseries.jsonl"

# process-wide default store for forensic dumps (watchdog / health):
# registered by whoever builds the store (serve.py, the fleet CLI)
_default_store: Optional["TimeSeriesStore"] = None
_default_lock = threading.Lock()


def set_default_store(store: Optional["TimeSeriesStore"]) -> None:
    """Register (or clear, with None) the process's dump-context
    store. The watchdog and health layers read it best-effort — a
    process without one simply dumps without trend context."""
    global _default_store
    with _default_lock:
        _default_store = store


def default_store() -> Optional["TimeSeriesStore"]:
    with _default_lock:
        return _default_store


def rate_name(counter: str) -> str:
    """``tokens_generated_total`` -> ``tokens_generated_per_s`` (a
    counter without the ``_total`` suffix still gets ``_per_s``)."""
    base = counter[:-len("_total")] if counter.endswith("_total") \
        else counter
    return f"{base}_per_s"


class TimeSeriesStore:
    """Fixed-interval ring of rate/gauge points with JSONL persistence.

    :param path: ``timeseries.jsonl`` destination (None = ring only —
        tests, overhead benches).
    :param interval_s: point width; observations landing in the same
        interval fold into one point.
    :param window: ring capacity in points (the query API and the
        forensic dumps see at most this much history).
    :param process: stamped on the file's anchor line (stitch-side
        provenance, mirroring ``RequestTracer``).

    Thread-safe: the poller, the scheduler thread, and ``/metrics``
    scrapes may interleave. The lock is never held across file I/O of
    a *read* path; point emission (one small JSON line per interval)
    writes under it — bounded, line-buffered, and rarer than the
    observations by construction.
    """

    def __init__(self, path=None, interval_s: float = 1.0,
                 window: int = 720, process: str = "serve"):
        self.interval_s = max(float(interval_s), 1e-3)
        self.window = int(window)
        self.process = str(process)
        self._lock = threading.Lock()
        self._points: "deque" = deque(maxlen=self.window)
        self._last_raw: Dict[str, float] = {}
        self._acc: Dict[str, float] = {}      # per-bucket counter deltas
        self._gauges: Dict[str, float] = {}   # per-bucket last samples
        self._bucket_id: Optional[int] = None
        self._span = 0.0          # seconds of history the bucket covers
        self._prev_obs_t: Optional[float] = None
        self.points_written = 0
        self._file = None
        self.path = None
        if path is not None:
            self.path = Path(path)
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self._file = open(self.path, "a", buffering=1)
            self._write_line({"anchor": 1, "proc": self.process,
                              "interval_s": self.interval_s,
                              "epoch": round(time.time(), 6)})

    # -- internals ----------------------------------------------------------

    def _write_line(self, rec: dict) -> None:
        if self._file is None:
            return
        try:
            self._file.write(json.dumps(rec, default=repr) + "\n")
        except (OSError, ValueError):
            pass                  # a full disk must not stall the feed

    def _emit_locked(self, t_end: float) -> None:
        """Close the open bucket into one point (caller holds lock)."""
        if self._bucket_id is None:
            return
        point: dict = {"t": round(t_end, 3),
                       "span_s": round(self._span, 3)}
        if self._span > 1e-9:
            for name, delta in self._acc.items():
                point[rate_name(name)] = round(
                    max(delta, 0.0) / self._span, 4)
        # a first-ever bucket has no covered span: counter history up
        # to it is startup state, not a rate — gauges still emit
        point.update({k: v for k, v in self._gauges.items()})
        self._points.append(point)
        self.points_written += 1
        self._write_line(point)
        self._acc = {}
        self._gauges = {}
        self._span = 0.0
        self._bucket_id = None

    # -- feeding ------------------------------------------------------------

    def observe(self, counters: Optional[dict] = None,
                gauges: Optional[dict] = None,
                t: Optional[float] = None) -> None:
        """Absorb one scrape / one chunk record.

        ``counters`` are cumulative monotonic values (reset-corrected
        deltas feed the rates); ``gauges`` are sampled as-is. ``t``
        defaults to ``time.time()`` — tests pin it to drive interval
        boundaries deterministically."""
        t = time.time() if t is None else float(t)
        with self._lock:
            bid = int(t // self.interval_s)
            if self._bucket_id is not None and bid != self._bucket_id:
                self._emit_locked(
                    (self._bucket_id + 1) * self.interval_s)
            if self._bucket_id is None:
                self._bucket_id = bid
            if self._prev_obs_t is not None and t > self._prev_obs_t:
                self._span += t - self._prev_obs_t
            self._prev_obs_t = t
            for name, v in (counters or {}).items():
                if isinstance(v, bool) or not isinstance(
                        v, (int, float)):
                    continue
                last = self._last_raw.get(name)
                if last is not None:
                    # reset correction (fleet/replicas discipline): a
                    # counter below its last value means the source
                    # restarted — the new value IS the delta since
                    # reset. The FIRST sighting only sets the
                    # baseline: its value is pre-store history, and
                    # charging it to one interval would fabricate a
                    # rate spike on attach.
                    self._acc[name] = self._acc.get(name, 0.0) + (
                        (v - last) if v >= last else float(v))
                self._last_raw[name] = float(v)
            for name, v in (gauges or {}).items():
                if isinstance(v, bool) or not isinstance(
                        v, (int, float)):
                    continue
                self._gauges[name] = float(v)

    def observe_flat(self, metrics: dict,
                     t: Optional[float] = None) -> None:
        """Absorb a flat ``/metrics``-shaped dict: ``*_total`` keys
        are counters, other scalar numerics are gauges, histogram
        snapshots / nested dicts / bools / strings are skipped."""
        counters, gauges = {}, {}
        for k, v in (metrics or {}).items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            (counters if k.endswith("_total") else gauges)[k] = v
        self.observe(counters=counters, gauges=gauges, t=t)

    def flush(self, t: Optional[float] = None) -> None:
        """Emit the partially-filled bucket (drain/shutdown path) and
        force the JSONL tail to disk."""
        t = time.time() if t is None else float(t)
        with self._lock:
            self._emit_locked(t)
            if self._file is not None:
                try:
                    self._file.flush()
                except (OSError, ValueError):
                    pass

    def close(self) -> None:
        self.flush()
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None

    # -- querying -----------------------------------------------------------

    def points(self, last_n: Optional[int] = None) -> List[dict]:
        """The trailing ``last_n`` points (all buffered when None)."""
        with self._lock:
            pts = list(self._points)
        return pts if last_n is None else pts[-int(last_n):]

    def series_names(self) -> List[str]:
        names: set = set()
        for p in self.points():
            names.update(k for k in p if k not in ("t", "span_s"))
        return sorted(names)

    def series(self, name: str,
               last_n: Optional[int] = None) -> List[Tuple[float,
                                                           float]]:
        """``[(t, value), ...]`` for one metric over the window."""
        return [(p["t"], p[name]) for p in self.points(last_n)
                if name in p]

    def latest(self, name: str) -> Optional[float]:
        for p in reversed(self.points()):
            if name in p:
                return p[name]
        return None

    def quantile(self, name: str, q: float,
                 last_n: Optional[int] = None) -> Optional[float]:
        """Window quantile via THE package percentile convention
        (utils/promtext.percentile — linear interpolation)."""
        vals = sorted(v for _, v in self.series(name, last_n))
        return _pctl(vals, q)

    def summary(self, last_n: Optional[int] = None) -> dict:
        """Per-series p50/p99/last over the window — the compact form
        the dashboard and the dump consumers embed."""
        out: dict = {"points": len(self.points(last_n))}
        for name in self.series_names():
            vals = sorted(v for _, v in self.series(name, last_n))
            if not vals:
                continue
            out[name] = {
                "last": self.latest(name),
                "p50": round(_pctl(vals, 0.5), 4),
                "p99": round(_pctl(vals, 0.99), 4),
            }
        return out


def load_timeseries(path) -> List[dict]:
    """Read a ``timeseries.jsonl`` back into points (anchor lines and
    torn tails skipped) — the offline analyzer's loader."""
    points: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and "anchor" not in rec:
                    points.append(rec)
    except OSError:
        pass
    return points
