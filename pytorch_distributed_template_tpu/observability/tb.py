"""Duck-typed TensorBoard writer with graceful degradation.

Parity with /root/reference/logger/visualization.py: tries real TensorBoard
backends in order, no-ops cleanly when disabled or missing, auto-tags scalars
as ``tag/mode`` for train/valid separation, and emits a ``steps_per_sec``
throughput scalar from wall-clock deltas in ``set_step``
(visualization.py:40-48).

Fixed vs reference: non-TB attribute access raised ``TypeError`` there
(``object.__getattr__(name)`` wrong arity, visualization.py:70); here it
raises a proper ``AttributeError``.
"""
from __future__ import annotations

import importlib
from datetime import datetime


class TensorboardWriter:
    TB_MODULES = ["torch.utils.tensorboard", "tensorboardX"]

    TB_WRITER_FTNS = {
        "add_scalar", "add_scalars", "add_image", "add_images", "add_audio",
        "add_text", "add_histogram", "add_pr_curve", "add_embedding",
    }
    TAG_MODE_EXCEPTIONS = {"add_histogram", "add_embedding"}

    def __init__(self, log_dir, logger, enabled: bool):
        self.writer = None
        self.selected_module = ""

        if enabled:
            log_dir = str(log_dir)
            succeeded = False
            for module in self.TB_MODULES:
                try:
                    self.writer = importlib.import_module(module).SummaryWriter(log_dir)
                    self.selected_module = module
                    succeeded = True
                    break
                except ImportError:
                    succeeded = False

            if not succeeded:
                logger.warning(
                    "Warning: visualization (Tensorboard) is configured to use, "
                    "but currently not installed on this machine. Please install "
                    "TensorBoard (tensorboard or tensorboardX) to use it, or turn "
                    "off the option in the config file (trainer.tensorboard)."
                )

        self.step = 0
        self.mode = ""
        self.timer = datetime.now()

    def set_step(self, step, mode="train") -> None:
        self.mode = mode
        self.step = step
        if step == 0:
            self.timer = datetime.now()
        else:
            duration = datetime.now() - self.timer
            self.add_scalar("steps_per_sec", 1 / max(duration.total_seconds(), 1e-12))
            self.timer = datetime.now()

    def __getattr__(self, name):
        """Return a wrapped TB method (tagging ``tag/mode``), a no-op when TB
        is disabled, or raise AttributeError for unknown names."""
        if name in self.TB_WRITER_FTNS:
            add_data = getattr(self.writer, name, None)

            def wrapper(tag, data, *args, **kwargs):
                if add_data is not None:
                    if name not in self.TAG_MODE_EXCEPTIONS and self.mode:
                        tag = f"{tag}/{self.mode}"
                    # global_step as a keyword: its positional slot differs
                    # across TB methods (the reference passed it positionally
                    # and corrupted add_pr_curve/add_embedding arguments).
                    kwargs.setdefault("global_step", self.step)
                    add_data(tag, data, *args, **kwargs)

            return wrapper
        # Pass through other real writer attributes (e.g. flush, close).
        if self.writer is not None and hasattr(self.writer, name):
            return getattr(self.writer, name)
        if name in ("flush", "close"):
            return lambda *a, **k: None
        raise AttributeError(f"type object '{type(self).__name__}' has no attribute '{name}'")
