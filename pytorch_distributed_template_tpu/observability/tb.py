"""TensorBoard facade: explicit methods, graceful degradation.

Covers the role of /root/reference/logger/visualization.py (mode-suffixed
scalar tags for train/valid separation, a ``steps_per_sec`` scalar derived
from ``set_step`` wall-clock deltas, silent no-op when TB is disabled or
not installed) with an explicit-method design rather than a duck-typed
``__getattr__`` wrapper: every supported ``add_*`` is a real method, so
typos raise immediately, signatures are inspectable, and ``add_embedding``
takes TensorBoard's actual argument order (the wrapper design forced
``(tag, data)`` first, which does not match ``SummaryWriter.add_embedding``).
"""
from __future__ import annotations

import importlib
import time


class TensorboardWriter:
    """Rank-0 metrics sink. All methods no-op when ``enabled`` is False or
    no TB backend imports, so call sites never need to guard."""

    TB_MODULES = ("torch.utils.tensorboard", "tensorboardX")

    def __init__(self, log_dir, logger, enabled: bool):
        self.writer = None
        self.selected_module = ""
        if enabled:
            for module in self.TB_MODULES:
                try:
                    self.writer = importlib.import_module(
                        module
                    ).SummaryWriter(str(log_dir))
                    self.selected_module = module
                    break
                except ImportError:
                    continue
            if self.writer is None:
                logger.warning(
                    "trainer.tensorboard is enabled but no backend could "
                    "be imported (tried %s); metrics will not be recorded. "
                    "Install tensorboard/tensorboardX or set "
                    "trainer.tensorboard to false.",
                    ", ".join(self.TB_MODULES),
                )
        self.step = 0
        self.mode = ""
        self._last_step_time = time.monotonic()

    def set_step(self, step: int, mode: str = "train") -> None:
        """Advance the global step; non-zero steps also record the
        wall-clock-derived ``steps_per_sec`` scalar."""
        self.mode = mode
        self.step = step
        now = time.monotonic()
        if step == 0:
            self._last_step_time = now
        else:
            self.add_scalar(
                "steps_per_sec", 1.0 / max(now - self._last_step_time, 1e-12)
            )
            self._last_step_time = now

    def _emit(self, method: str, tag: str, *args, mode_tag: bool = True,
              **kwargs):
        if self.writer is None:
            return
        fn = getattr(self.writer, method, None)
        if fn is None:  # backend lacks this method (old tensorboardX etc.)
            return
        if mode_tag and self.mode:
            tag = f"{tag}/{self.mode}"
        # global_step always as a keyword: its positional slot differs
        # across TB methods.
        kwargs.setdefault("global_step", self.step)
        fn(tag, *args, **kwargs)

    # -- scalars / text ---------------------------------------------------
    def add_scalar(self, tag, value, **kwargs):
        self._emit("add_scalar", tag, value, **kwargs)

    def add_scalars(self, tag, value_dict, **kwargs):
        self._emit("add_scalars", tag, value_dict, **kwargs)

    def add_text(self, tag, text, **kwargs):
        self._emit("add_text", tag, text, **kwargs)

    # -- media ------------------------------------------------------------
    def add_image(self, tag, img, **kwargs):
        self._emit("add_image", tag, img, **kwargs)

    def add_images(self, tag, imgs, **kwargs):
        self._emit("add_images", tag, imgs, **kwargs)

    def add_audio(self, tag, snd, **kwargs):
        self._emit("add_audio", tag, snd, **kwargs)

    # -- distributions (tags stay global: the same weights are logged from
    # train and valid phases and must land in one chart) -------------------
    def add_histogram(self, tag, values, **kwargs):
        self._emit("add_histogram", tag, values, mode_tag=False, **kwargs)

    def add_pr_curve(self, tag, labels, predictions, **kwargs):
        self._emit("add_pr_curve", tag, labels, predictions, **kwargs)

    def add_embedding(self, mat, metadata=None, label_img=None,
                      tag="default", **kwargs):
        if self.writer is None:
            return
        fn = getattr(self.writer, "add_embedding", None)
        if fn is None:
            return
        kwargs.setdefault("global_step", self.step)
        fn(mat, metadata=metadata, label_img=label_img, tag=tag, **kwargs)

    # -- lifecycle --------------------------------------------------------
    def flush(self):
        if self.writer is not None:
            self.writer.flush()

    def close(self):
        if self.writer is not None:
            self.writer.close()
