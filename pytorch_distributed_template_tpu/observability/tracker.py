"""Running-average metric tracking.

Parity with the reference's pandas-backed ``MetricTracker``
(/root/reference/utils/util.py:46-67): per-key total/count/average, optional
TensorBoard write on every update. Implemented over a plain dict instead of a
pandas DataFrame — same semantics, no per-step DataFrame indexing cost in the
hot loop (the reference pays a pandas ``.at`` lookup per batch).
"""
from __future__ import annotations


class MetricTracker:
    def __init__(self, *keys, writer=None):
        self.writer = writer
        self._data = {k: [0.0, 0, 0.0] for k in keys}  # total, count, average

    def reset(self) -> None:
        for k in self._data:
            self._data[k] = [0.0, 0, 0.0]

    def update(self, key, value, n: int = 1) -> None:
        if key not in self._data:
            self._data[key] = [0.0, 0, 0.0]
        if self.writer is not None:
            self.writer.add_scalar(key, value)
        total, count, _ = self._data[key]
        total += float(value) * n
        count += n
        self._data[key] = [total, count, total / count]

    def avg(self, key) -> float:
        return self._data[key][2]

    def count(self, key) -> int:
        return self._data[key][1]

    def result(self) -> dict:
        return {k: v[2] for k, v in self._data.items()}

    def keys(self):
        return list(self._data)
