"""Profiling: throughput, MFU, and on-demand XLA trace capture.

The reference's only performance instrumentation is a ``steps_per_sec``
TensorBoard scalar derived from wall-clock deltas between logging calls
(/root/reference/logger/visualization.py:40-48). This module supplies the
TPU-native tier promised in SURVEY.md §5 "Tracing / profiling":

- ``ThroughputMeter``: honest steps/sec + examples/sec over timing windows
  (the reference's number was really *logging-calls*/sec — kept for TB
  parity in ``TensorboardWriter.set_step``, while this meter feeds the real
  values).
- ``compiled_flops``: cost analysis of the *compiled* XLA executable — the
  exact FLOPs the hardware will run (post-fusion), not an analytic estimate.
- ``mfu``: model FLOPs utilization against the chip's peak, with a device
  table for TPU generations (override via config or
  ``PDT_TPU_PEAK_FLOPS``).
- ``TraceCapture``: a step-windowed ``jax.profiler`` trace (view in
  TensorBoard's profile plugin) — start/stop driven by the trainer's step
  counter so the capture covers steady-state steps, not compilation.
"""
from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Optional

import jax

# Peak dense bf16/fp16 FLOPs per *chip*, by device_kind substring (lowercase,
# first match wins; order matters: "v5 lite" before "v5"). Public numbers
# from the TPU generation announcements.
PEAK_FLOPS_TABLE = (
    ("v6 lite", 918e12),
    ("v6e", 918e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4 lite", 137e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops_per_device(device=None) -> Optional[float]:
    """Peak FLOPs/s for one device, or None when unknown (e.g. CPU)."""
    env = os.environ.get("PDT_TPU_PEAK_FLOPS")
    if env:
        return float(env)
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, val in PEAK_FLOPS_TABLE:
        if key in kind:
            return val
    return None


def executable_flops(compiled) -> Optional[float]:
    """FLOPs of one invocation of an already-compiled executable, from
    XLA's cost analysis (post-fusion). Returns None when the backend
    doesn't report."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        flops = cost.get("flops")
        return float(flops) if flops else None
    except Exception:
        return None


def compiled_flops(jitted_fn, *args, **kwargs) -> Optional[float]:
    """FLOPs of one invocation, from XLA's cost analysis of the compiled
    executable (post-fusion). Returns None when the backend doesn't report.

    Note: this runs an AOT lower+compile of ``jitted_fn`` for the given
    shapes; call it once at startup (compilation is cached per shape on most
    backends, but do not put this in the hot loop).
    """
    try:
        return executable_flops(jitted_fn.lower(*args, **kwargs).compile())
    except Exception:
        return None


def mfu(flops_per_step: Optional[float], steps_per_sec: float,
        peak_per_device: Optional[float] = None) -> Optional[float]:
    """Model FLOPs utilization in [0, 1]; None when peak/flops unknown.

    ``flops_per_step`` is the *per-device* figure: under SPMD partitioning,
    ``cost_analysis`` on the compiled executable reports the partitioned
    per-device module (on one device that equals the whole program), so it
    is compared against a single device's peak.
    """
    if not flops_per_step or not steps_per_sec:
        return None
    if peak_per_device is None:
        peak_per_device = peak_flops_per_device()
    if peak_per_device is None:
        return None
    return (flops_per_step * steps_per_sec) / peak_per_device


class ThroughputMeter:
    """Windowed steps/sec + examples/sec.

    ``update(n_examples)`` once per step; ``rate()`` returns the rates since
    the last ``rate()``/``reset()`` call and opens a new window. The first
    window of an epoch includes compilation unless ``reset`` is called after
    the first step (the trainer does).
    """

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self._t0 = time.perf_counter()
        self._steps = 0
        self._examples = 0

    def update(self, n_examples: int = 0) -> None:
        self._steps += 1
        self._examples += int(n_examples)

    def rate(self) -> dict:
        dt = max(time.perf_counter() - self._t0, 1e-9)
        out = {
            "steps_per_sec": self._steps / dt,
            "examples_per_sec": self._examples / dt,
        }
        self.reset()
        return out


class TraceCapture:
    """Step-windowed ``jax.profiler`` trace into ``<log_dir>/profile``.

    :param log_dir: run log dir; traces land in its ``profile/`` subdir.
    :param start_step: first step included in the capture (global step).
    :param num_steps: how many steps to capture (0: nothing scheduled —
        but ``request()`` can still arm a capture at runtime).

    Call ``before_step(step)`` / ``after_step(step)`` around each train
    step; idempotent and a no-op while no window is armed.

    ``request(n)`` arms an ON-DEMAND n-step capture starting at the next
    step — signal-handler-safe (it only assigns one attribute), which is
    how ``train.py`` wires it to SIGUSR2: profile a live run exactly
    when it misbehaves, no restart, no config edit. Each completed
    capture bumps the process-wide ``profile_captures_total`` counter
    (observability/health) and, when a recorder is attached, lands an
    ``event: "profile_capture"`` record on the telemetry timeline.
    """

    def __init__(self, log_dir, start_step: int = 10, num_steps: int = 0):
        self.dir = str(Path(log_dir) / "profile")
        self.start_step = int(start_step)
        self.num_steps = int(num_steps)
        self._active = False
        self._done = self.num_steps <= 0
        self._requested: Optional[int] = None
        self.captures = 0
        self.recorder = None

    def attach_recorder(self, recorder) -> None:
        """Optional FlightRecorder that capture completions get noted on."""
        self.recorder = recorder

    def request(self, num_steps: int = 5) -> None:
        """Arm an on-demand capture of ``num_steps`` steps starting at
        the next ``before_step``. Safe from signal handlers / other
        threads (single attribute write); ignored while a capture is
        already in flight — a second SIGUSR2 during a slow capture must
        not latch a surprise extra trace for after it closes."""
        if self._active:
            return
        self._requested = max(int(num_steps), 1)

    def before_step(self, step: int) -> None:
        if self._active:
            return
        if self._requested is not None:
            # runtime trigger: re-arm regardless of the config-scheduled
            # window having been consumed
            self.num_steps = self._requested
            self._requested = None
            self._done = False
            self.start_step = step
        if not self._done and step >= self.start_step:
            Path(self.dir).mkdir(parents=True, exist_ok=True)
            jax.profiler.start_trace(self.dir)
            self._active = True
            self._until = step + self.num_steps

    def after_step(self, step: int, sync=None) -> None:
        """``sync``: step outputs to ``block_until_ready`` before stopping —
        steps are dispatched asynchronously, so without it the trace would
        close while the captured steps still run on device."""
        if self._active and step + 1 >= self._until:
            if sync is not None:
                jax.block_until_ready(sync)
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
            self._note_capture(step)

    def _note_capture(self, step: int) -> None:
        self.captures += 1
        try:
            from .health import bump_counter

            bump_counter("profile_captures_total")
        except Exception:  # noqa: BLE001
            pass
        if self.recorder is not None:
            try:
                self.recorder.record(
                    step, event="profile_capture",
                    profile_dir=self.dir, profile_steps=self.num_steps,
                )
            except Exception:  # noqa: BLE001
                pass

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
            self._note_capture(self.start_step + self.num_steps)


def install_sigusr2(trace: TraceCapture, default_steps: int = 5) -> bool:
    """SIGUSR2 -> ``trace.request(n)``: on-demand profiling of a live
    training run (``kill -USR2 <pid>``). ``PDT_PROFILE_STEPS`` overrides
    the window length. Returns False on platforms without SIGUSR2 or
    when not called from the main thread (signal module restriction)."""
    import signal

    if not hasattr(signal, "SIGUSR2"):
        return False

    def _handler(signum, frame):
        try:
            n = int(os.environ.get("PDT_PROFILE_STEPS", default_steps))
        except ValueError:
            n = default_steps
        trace.request(n)

    try:
        signal.signal(signal.SIGUSR2, _handler)
        return True
    except ValueError:  # not the main thread
        return False


class OnDemandProfiler:
    """Progress-windowed on-demand capture for step-less processes
    (serve.py's ``POST /profile?steps=N``).

    The serving schedulers have no global step counter, but they DO have
    monotonic progress counters (continuous engine: ``chunks``; static:
    ``batches``/``requests``). ``capture()`` starts a ``jax.profiler``
    trace, waits until ``progress_fn`` has advanced by ``steps`` (or
    ``timeout_s`` passes — an idle server must not pin a request thread
    forever), stops, and reports what it saw. One capture at a time:
    concurrent callers get ``busy``.
    """

    def __init__(self, out_dir):
        import threading

        self.dir = str(Path(out_dir) / "profile")
        self._lock = threading.Lock()
        self.captures = 0

    def capture(self, steps: int = 0, progress_fn=None,
                timeout_s: float = 30.0, poll_s: float = 0.05) -> dict:
        if not self._lock.acquire(blocking=False):
            return {"busy": True,
                    "error": "a profile capture is already running"}
        try:
            Path(self.dir).mkdir(parents=True, exist_ok=True)
            jax.profiler.start_trace(self.dir)
            t0 = time.monotonic()
            base = progress_fn() if (progress_fn and steps > 0) else 0
            seen, timed_out = 0, False
            while progress_fn is not None and steps > 0:
                seen = progress_fn() - base
                if seen >= steps:
                    break
                if time.monotonic() - t0 > timeout_s:
                    timed_out = True
                    break
                time.sleep(poll_s)
            jax.profiler.stop_trace()
            self.captures += 1
            try:
                from .health import bump_counter

                bump_counter("profile_captures_total")
            except Exception:  # noqa: BLE001
                pass
            return {
                "profile_dir": self.dir,
                "steps_requested": int(steps),
                "steps_observed": int(seen),
                "duration_s": round(time.monotonic() - t0, 3),
                "timed_out": timed_out,
                "captures_total": self.captures,
            }
        except Exception as e:  # noqa: BLE001 — surface, don't kill serve
            try:
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                pass
            return {"error": f"{type(e).__name__}: {e}"}
        finally:
            self._lock.release()
