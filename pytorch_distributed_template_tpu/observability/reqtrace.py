"""Request-scoped distributed tracing across the serving fleet.

The serving stack spans up to three processes per request (fleet
router -> serve.py replica -> engine scheduler), but the span tracing
that exists (observability/trace.py) is process-local: each process
dumps its own Chrome trace with no shared request identity, so a slow
p99 request cannot be decomposed into router-queue vs admission-wait
vs admit vs decode time. This module is the Dapper/OpenTelemetry-style
layer on top:

- **Identity**: the first hop (router, or serve.py for direct
  traffic) mints a request id (:func:`mint_request_id`) and
  propagates it via the ``X-Request-Id`` header; every hop echoes it
  back on the response, so a client log line joins server-side spans.
- **Recording**: each process appends request-keyed span records to
  its own ``spans.jsonl`` through a :class:`RequestTracer` — one JSON
  line per span, wall-clock anchored (each file opens with an anchor
  record pairing ``time.time()`` with ``time.monotonic()``), written
  line-buffered so a live fleet can be stitched mid-run and a crash
  loses at most one torn line.
- **Stitching**: :func:`stitch_spans` merges the per-process files
  into per-request timelines, aligning clocks causally (a replica
  span can never start before the router dispatched it — skewed files
  are shifted by the median violation), decomposes each request into
  non-overlapping segments (router queue / WFQ admission wait / proxy
  hop / replica queue / admit-to-first-token / decode / stream — plus
  ``page_ship`` on disaggregated fleets: the prefill-stage execution +
  page transfer + decode-side import of a prefill→decode handoff,
  ISSUE 12), and
  reports the residual instead of hiding it. :func:`to_perfetto`
  emits one merged Chrome/Perfetto trace with flow events linking the
  router's proxy span to the replica's handler span per request.
- **SLO plumbing**: :class:`SloWatcher` checks per-request TTFT/e2e
  against configured thresholds, maintains ``slo_breach_total``
  counters (scraped via ``/metrics`` at both router and replica), and
  writes bounded ``slow_request_<rid>.json`` dumps carrying the
  request's full span timeline — modeled on the health layer's
  anomaly dumps (cooldown + max_dumps, so a bad hour cannot fill a
  disk).

Stdlib-only: the fleet router imports this and must stay jax-free.
``scripts/trace_stitch.py`` is the CLI; ``scripts/telemetry_report.py``
renders the attribution section from the same functions.
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from pathlib import Path
from typing import Dict, List, Optional

# one percentile convention package-wide (linear interpolation):
# loadgen's client summaries, this stitcher, and the engines must
# never disagree on what "p99" means
from ..utils.promtext import percentile as _pctl

SPANS_FILENAME = "spans.jsonl"

# ---------------------------------------------------------------------------
# request ids
# ---------------------------------------------------------------------------

_RID_OK = re.compile(r"^[A-Za-z0-9_.:-]{1,64}$")


def mint_request_id() -> str:
    """A fresh 16-hex request id (collision odds are irrelevant at
    fleet request rates; short enough to grep and to echo in headers)."""
    return uuid.uuid4().hex[:16]


def sanitize_request_id(rid) -> Optional[str]:
    """A client-supplied ``X-Request-Id`` value, validated — or None
    when absent/hostile (caller mints a fresh one). Bounded charset and
    length: the id lands in filenames (slow-request dumps) and JSONL."""
    if not rid or not isinstance(rid, str):
        return None
    rid = rid.strip()
    return rid if _RID_OK.match(rid) else None


# ---------------------------------------------------------------------------
# deadline propagation (ISSUE 9)
# ---------------------------------------------------------------------------

DEADLINE_HEADER = "X-Deadline-Ms"
DEADLINE_EXPIRED_HEADER = "X-Deadline-Expired"

#: clamp bounds for a client-supplied deadline budget (milliseconds):
#: 0/negative is meaningless, and anything past an hour is "no deadline
#: in practice" — clamping keeps hostile headers from minting huge ints
MIN_DEADLINE_MS = 1
MAX_DEADLINE_MS = 3_600_000


class Deadline:
    """A request's remaining time budget, monotonic-clock only.

    The wire form is RELATIVE (``X-Deadline-Ms: 1500`` = "you have
    1.5 s from receipt"), so propagation is clock-skew-free by
    construction: each hop anchors the budget to its OWN
    ``time.monotonic()`` at receipt and forwards the REMAINING budget
    (``header_value()``), never an absolute timestamp two clocks could
    disagree about. Wall-clock steps (NTP) cannot move a deadline
    mid-request."""

    __slots__ = ("t0", "budget_s")

    def __init__(self, budget_s: float, t0: Optional[float] = None):
        self.budget_s = float(budget_s)
        self.t0 = time.monotonic() if t0 is None else float(t0)

    @classmethod
    def from_header(cls, value, t0: Optional[float] = None
                    ) -> Optional["Deadline"]:
        """Parse an ``X-Deadline-Ms`` header -> Deadline, or None when
        absent. Raises ``ValueError`` on a malformed value (the caller
        answers 400 — a silently dropped deadline would serve an
        unbounded request the client thinks is bounded)."""
        if value is None or (isinstance(value, str)
                             and not value.strip()):
            return None
        ms = int(str(value).strip())     # ValueError on garbage
        if ms <= 0:
            raise ValueError(f"{DEADLINE_HEADER} must be a positive "
                             f"integer (got {ms})")
        ms = max(MIN_DEADLINE_MS, min(ms, MAX_DEADLINE_MS))
        return cls(ms / 1e3, t0=t0)

    def remaining_s(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        return self.budget_s - (now - self.t0)

    def expired(self, now: Optional[float] = None) -> bool:
        return self.remaining_s(now) <= 0.0

    def header_value(self, now: Optional[float] = None) -> str:
        """The REMAINING budget for the next hop (floor 1 ms: a
        forwarded deadline of 0 would be a malformed header)."""
        return str(max(int(round(self.remaining_s(now) * 1e3)),
                       MIN_DEADLINE_MS))

    def deadline_at(self) -> float:
        """Absolute monotonic expiry (engine-internal convenience)."""
        return self.t0 + self.budget_s


# ---------------------------------------------------------------------------
# serve-path provenance (ISSUE 18)
# ---------------------------------------------------------------------------

SERVE_PATH_HEADER = "X-Serve-Path"

#: admit modes, mutually exclusive — the first fingerprint token.
#: ``stream`` is a chunked streaming-prefill admission (paged
#: underneath, but its correctness surface — per-chunk scatter + ring
#: slack accounting — is its own path).
PATH_MODES = ("cold", "warm", "paged", "stream")

#: ordered feature flags; a fingerprint includes the ones that are
#: truthy in the path dict, in THIS order, so the same feature set
#: always renders the same string (the string keys a metric family —
#: ``serve_path_<fp>_total`` — and strings that differ only by token
#: order would split one path's counts across two series).
#:
#:   int8    - pool pages hold quantized KV (kv layout)
#:   ring    - sliding-window ring layout pool
#:   wrap    - this request's ring actually wrapped (ring_wrap plan)
#:   adopt   - admit consumed adopted (radix-shared) pool pages
#:   promote - admit consumed pages promoted back from a spill tier
#:   pull    - admit consumed pages pulled from a peer replica's pool
#:   ship    - admit consumed pages imported from a shipped payload
#:             (disaggregated prefill→decode handoff)
#:   spec    - speculative decode produced the tokens
PATH_FLAGS = ("int8", "ring", "wrap", "adopt", "promote", "pull",
              "ship", "spec")

_FP_OK = re.compile(r"^[a-z0-9_]{1,96}$")


def path_fingerprint(path: dict) -> str:
    """A request's path dict -> its compact fingerprint string.

    The dict is accumulated by whichever scheduler served the request
    (``mode`` + the :data:`PATH_FLAGS` booleans + ``tp``/``dp``/
    ``brownout`` ints); the string is lowercase ``[a-z0-9_]`` only, so
    it is simultaneously a legal ``X-Serve-Path`` header value and a
    legal metric-name fragment (``serve_path_<fp>_total`` passes the
    prometheus charset and the repo's promtext lint)."""
    mode = str(path.get("mode") or "cold")
    toks = [mode if mode in PATH_MODES else "cold"]
    for flag in PATH_FLAGS:
        if path.get(flag):
            toks.append(flag)
    tp = int(path.get("tp") or 1)
    if tp > 1:
        toks.append(f"tp{tp}")
    dp = int(path.get("dp") or 1)
    if dp > 1:
        toks.append(f"dp{dp}")
    level = int(path.get("brownout") or 0)
    if level > 0:
        toks.append(f"b{level}")
    return "_".join(toks)


def sanitize_serve_path(value) -> Optional[str]:
    """A propagated ``X-Serve-Path`` value, validated — or None when
    absent/hostile. Bounded lowercase charset: the value lands in
    metric names and loadgen summaries verbatim."""
    if not value or not isinstance(value, str):
        return None
    value = value.strip()
    return value if _FP_OK.match(value) else None


def fingerprint_features(fp: str) -> List[str]:
    """Fingerprint -> its feature tokens (attribution unit: the audit
    report ranks these across divergence bundles). The mode token is
    prefixed ``mode_`` so ``cold`` the mode never collides with a
    future flag named cold."""
    toks = [t for t in str(fp).split("_") if t]
    if not toks:
        return []
    return [f"mode_{toks[0]}"] + toks[1:]


# ---------------------------------------------------------------------------
# the per-process tracer
# ---------------------------------------------------------------------------


class RequestTracer:
    """Append request-keyed span records to one ``spans.jsonl``.

    Each record::

        {"rid": ..., "name": ..., "proc": ..., "pid": ..., "tid": ...,
         "t": <epoch seconds>, "dur_ms": ..., "attrs": {...}?}

    Times are wall-clock (epoch) floats derived from monotonic
    measurements through a per-process anchor captured at construction
    — callers time with ``time.monotonic()`` (never subject to NTP
    steps mid-request) and the stitcher gets absolute timestamps it
    can align across processes. The file opens append + line-buffered:
    concurrent tracers in one process serialize on a lock, a crash
    loses at most the torn tail line (the stitcher skips it), and a
    live fleet can be stitched mid-run.

    A bounded in-memory ring keeps the most recent records so the
    :class:`SloWatcher` can dump a slow request's full timeline
    without re-reading the file.
    """

    def __init__(self, path, process: str = "serve",
                 ring: int = 4096):
        self.path = Path(path)
        self.process = str(process)
        self.pid = os.getpid()
        self._anchor_epoch = time.time()
        self._anchor_mono = time.monotonic()
        self._lock = threading.Lock()
        self._ring: "deque" = deque(maxlen=int(ring))
        self.records_written = 0
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = open(self.path, "a", buffering=1)
        self._write({"anchor": 1, "proc": self.process, "pid": self.pid,
                     "epoch": round(self._anchor_epoch, 6),
                     "mono": round(self._anchor_mono, 6)})

    # -- internals ----------------------------------------------------------

    def _epoch(self, mono: float) -> float:
        return self._anchor_epoch + (mono - self._anchor_mono)

    def _write(self, rec: dict) -> None:
        # default=repr: attrs are caller-arbitrary; one bad value must
        # not void the line (same contract as trace.py's dump)
        line = json.dumps(rec, default=repr)
        with self._lock:
            if self._f is not None:
                try:
                    self._f.write(line + "\n")
                    self.records_written += 1
                except (OSError, ValueError):
                    pass                 # a full disk must not 500 requests
            if "anchor" not in rec:
                self._ring.append(rec)

    # -- recording ----------------------------------------------------------

    def add(self, rid: str, name: str, t0: float,
            t1: Optional[float] = None, **attrs) -> None:
        """Record a span measured by the caller with
        ``time.monotonic()``: ``t0`` start, ``t1`` end (None = instant
        event at ``t0``)."""
        rec = {
            "rid": str(rid), "name": str(name),
            "proc": self.process, "pid": self.pid,
            "tid": threading.get_ident() % 1_000_000,
            "t": round(self._epoch(t0), 6),
            "dur_ms": (round((t1 - t0) * 1e3, 3)
                       if t1 is not None else 0.0),
        }
        if attrs:
            rec["attrs"] = attrs
        self._write(rec)

    def event(self, rid: str, name: str, **attrs) -> None:
        """Instant event at now."""
        self.add(rid, name, time.monotonic(), None, **attrs)

    @contextmanager
    def span(self, rid: str, name: str, **attrs):
        """``with tracer.span(rid, "proxy", replica="r1"): ...`` —
        records even when the body raises (``error: true`` attr)."""
        t0 = time.monotonic()
        try:
            yield attrs
        except BaseException:
            attrs = {**attrs, "error": True}
            raise
        finally:
            self.add(rid, name, t0, time.monotonic(), **attrs)

    # -- introspection / lifecycle ------------------------------------------

    def timeline(self, rid: str) -> List[dict]:
        """Recent records for one request (the SLO dump payload)."""
        rid = str(rid)
        with self._lock:
            return [dict(r) for r in self._ring if r.get("rid") == rid]

    def flush(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.flush()
                except (OSError, ValueError):
                    pass

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None


# ---------------------------------------------------------------------------
# SLO watcher: thresholds -> counters + bounded slow-request dumps
# ---------------------------------------------------------------------------


class SloWatcher:
    """Per-request SLO check with bounded forensic dumps.

    ``observe(rid, ttft_s=..., e2e_s=...)`` compares against the
    configured thresholds (None = not checked). Every breach bumps the
    counters; at most ``max_dumps`` ``slow_request_<rid>.json`` files
    are written, no closer together than ``cooldown_s`` (wall time) —
    the same bounding discipline as the health layer's anomaly dumps,
    because the pathology that breaches SLOs is exactly the pathology
    that breaches them thousands of times an hour. The dump carries
    the request's span timeline from the tracer's ring, so "p99 was
    300 ms" comes with "240 ms of it was WFQ wait"."""

    def __init__(self, ttft_s: Optional[float] = None,
                 e2e_s: Optional[float] = None,
                 dump_dir=None, tracer: Optional[RequestTracer] = None,
                 max_dumps: int = 8, cooldown_s: float = 30.0):
        self.ttft_s = float(ttft_s) if ttft_s else None
        self.e2e_s = float(e2e_s) if e2e_s else None
        self.dump_dir = Path(dump_dir) if dump_dir is not None else None
        self.tracer = tracer
        self.max_dumps = int(max_dumps)
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()
        self._c = {"slo_breach_total": 0, "slo_ttft_breach_total": 0,
                   "slo_e2e_breach_total": 0, "slo_dumps_written": 0}
        self._last_dump_t: Optional[float] = None

    @property
    def enabled(self) -> bool:
        return self.ttft_s is not None or self.e2e_s is not None

    #: terminal classifications that are OUT of the served-latency SLO:
    #: a cancelled request's latency is the client's choice, a
    #: deadline-truncated one's is the deadline's (ISSUE 9) — counting
    #: either as a breach would punish the mechanisms that bound tails
    EXEMPT_OUTCOMES = ("cancelled", "deadline")

    def observe(self, rid: str, ttft_s: Optional[float] = None,
                e2e_s: Optional[float] = None, **extra) -> List[str]:
        """Returns the breach reasons (empty = inside SLO)."""
        if extra.get("stop_reason") in self.EXEMPT_OUTCOMES:
            return []
        reasons = []
        if (self.ttft_s is not None and ttft_s is not None
                and ttft_s > self.ttft_s):
            reasons.append("ttft")
        if (self.e2e_s is not None and e2e_s is not None
                and e2e_s > self.e2e_s):
            reasons.append("e2e")
        if not reasons:
            return reasons
        now = time.monotonic()
        dump = False
        with self._lock:
            self._c["slo_breach_total"] += 1
            if "ttft" in reasons:
                self._c["slo_ttft_breach_total"] += 1
            if "e2e" in reasons:
                self._c["slo_e2e_breach_total"] += 1
            if (self.dump_dir is not None
                    and self._c["slo_dumps_written"] < self.max_dumps
                    and (self._last_dump_t is None
                         or now - self._last_dump_t >= self.cooldown_s)):
                self._c["slo_dumps_written"] += 1
                self._last_dump_t = now
                dump = True
        if dump:
            self._dump(rid, reasons, ttft_s, e2e_s, extra)
        return reasons

    def _dump(self, rid, reasons, ttft_s, e2e_s, extra) -> None:
        payload = {
            "rid": str(rid),
            "reasons": reasons,
            "ttft_s": ttft_s,
            "e2e_s": e2e_s,
            "thresholds": {"ttft_s": self.ttft_s, "e2e_s": self.e2e_s},
            "t": time.time(),
            **({"extra": extra} if extra else {}),
        }
        if self.tracer is not None:
            payload["timeline"] = self.tracer.timeline(rid)
        try:
            self.dump_dir.mkdir(parents=True, exist_ok=True)
            safe = sanitize_request_id(str(rid)) or "unknown"
            path = self.dump_dir / f"slow_request_{safe}.json"
            path.write_text(json.dumps(payload, indent=2, default=repr))
        except OSError:
            pass                          # forensics are best-effort

    def stats(self) -> dict:
        with self._lock:
            return dict(self._c)


# ---------------------------------------------------------------------------
# stitching: per-process spans.jsonl files -> cross-process timelines
# ---------------------------------------------------------------------------


def discover_span_files(run_dir) -> List[Path]:
    """Every ``spans.jsonl`` under a fleet run dir (the router writes
    one at the top, each replica one under its save dir)."""
    return sorted(Path(run_dir).rglob(SPANS_FILENAME))


def resolve_span_files(explicit=None, run_dir=None) -> List[Path]:
    """Explicit span paths + run-dir discovery, deduped on the
    RESOLVED path — the one owner of the invariant that an overlap
    (``--spans run/spans.jsonl --run-dir run``) must not double-load
    every span record. Explicit paths keep their caller-given order,
    discovered ones follow."""
    files: List[Path] = []
    candidates = list(explicit or [])
    if run_dir is not None:
        candidates += discover_span_files(run_dir)
    for f in candidates:
        p = Path(f).resolve()
        if p not in files:
            files.append(p)
    return files


def load_spans(paths) -> List[dict]:
    """Parse span files; torn tail lines (live runs, crashes) skip."""
    spans: List[dict] = []
    for path in paths:
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(rec, dict):
                        spans.append(rec)
        except OSError:
            continue
    return spans


def _proc_key(rec: dict) -> tuple:
    return (rec.get("proc", "?"), rec.get("pid", 0))


def _by_rid(spans: List[dict]) -> Dict[str, List[dict]]:
    out: Dict[str, List[dict]] = {}
    for s in spans:
        rid = s.get("rid")
        if rid:
            out.setdefault(rid, []).append(s)
    return out


def _named(recs: List[dict], name: str,
           proc: Optional[str] = None) -> Optional[dict]:
    for r in recs:
        if r.get("name") == name and (proc is None
                                      or r.get("proc") == proc):
            return r
    return None


def _last_named(recs: List[dict], name: str,
                proc: Optional[str] = None) -> Optional[dict]:
    """The LATEST-starting matching span. A router retry records one
    ``proxy`` span per attempt under the same rid; the attempt that
    actually carried the request is the last one — attribution and
    flow linkage must not anchor on a dead first attempt."""
    best = None
    for r in recs:
        if r.get("name") == name and (proc is None
                                      or r.get("proc") == proc):
            if best is None or float(r.get("t", 0.0)) \
                    >= float(best.get("t", 0.0)):
                best = r
    return best


def estimate_offsets(spans: List[dict]) -> Dict[tuple, float]:
    """Causal clock alignment per (proc, pid).

    Single-host fleets share one wall clock, but multi-host (or
    synthetic/test) span sets can carry skew. The causal invariant:
    a replica's handler span cannot START before the router's proxy
    span for the same request did (the request had not been sent yet).
    For each non-router process, collect ``proxy.t - http.t`` over the
    rids both sides recorded; when the median is positive (the child
    systematically appears to start BEFORE its parent), the child's
    clock is behind — shift that process forward by the median
    violation. Processes already causal (median <= 0) are untouched:
    genuine queueing delay must not be "aligned" away."""
    deltas: Dict[tuple, List[float]] = {}
    for rid, recs in _by_rid(spans).items():
        proxy = _last_named(recs, "proxy", proc="router")
        if proxy is None:
            continue
        http = _named(recs, "http")
        if http is None or http.get("proc") == "router":
            continue
        deltas.setdefault(_proc_key(http), []).append(
            float(proxy["t"]) - float(http["t"]))
    offsets: Dict[tuple, float] = {}
    for key, ds in deltas.items():
        ds = sorted(ds)
        med = ds[len(ds) // 2]
        if med > 0.0:
            offsets[key] = med
    return offsets


def apply_offsets(spans: List[dict],
                  offsets: Dict[tuple, float]) -> List[dict]:
    if not offsets:
        return spans
    out = []
    for s in spans:
        off = offsets.get(_proc_key(s))
        if off and "t" in s:
            s = dict(s, t=float(s["t"]) + off)
        out.append(s)
    return out


def _t1(rec: dict) -> float:
    return float(rec["t"]) + float(rec.get("dur_ms", 0.0)) / 1e3


def _segments(recs: List[dict]) -> Dict[str, float]:
    """One request's non-overlapping latency segments, from whichever
    spans exist (full fleet path, or direct-to-replica with no router
    spans). Every segment is clamped at >= 0; missing spans simply
    produce fewer segments — the residual column owns the gap."""
    req = _named(recs, "request", proc="router")
    aw = _named(recs, "admission_wait", proc="router")
    proxy = _last_named(recs, "proxy", proc="router")
    ship = _named(recs, "page_ship", proc="router")
    pull = _named(recs, "peer_pull", proc="router")
    tier = _named(recs, "tier")
    http = _named(recs, "http")
    if http is not None and http.get("proc") == "router":
        http = None
    qw = _named(recs, "queue_wait")
    ft = _named(recs, "first_token")
    done = _named(recs, "complete")

    seg: Dict[str, float] = {}

    def put(name, value):
        if value is not None and value == value:   # drop NaN
            seg[name] = max(round(float(value), 6), 0.0)

    if req is not None and aw is not None:
        put("router_recv", float(aw["t"]) - float(req["t"]))
    if aw is not None:
        put("admission_wait", float(aw.get("dur_ms", 0.0)) / 1e3)
    if ship is not None:
        # disaggregated handoff (ISSUE 12): the 12th segment. The
        # router's page_ship span runs from the prefill-stage dispatch
        # to the decode-stage dispatch — remote prefill execution +
        # page transfer + decode-side import as one non-overlapping
        # slice; ``route`` then covers only the routing ahead of it,
        # and the decode proxy span (the LAST proxy — _last_named)
        # starts where page_ship ends, so the decomposition stays
        # gap-free and coverage holds.
        put("page_ship", float(ship.get("dur_ms", 0.0)) / 1e3)
        if aw is not None:
            put("route", float(ship["t"]) - _t1(aw))
    elif pull is not None and aw is not None:
        # miss-driven peer page pull (ISSUE 13): the router pulled a
        # peer's pages ahead of the proxy hop — its own slice, with
        # "route" ending where the pull begins (the proxy span starts
        # right after the pull, so the decomposition stays gap-free)
        put("peer_pull", float(pull.get("dur_ms", 0.0)) / 1e3)
        put("route", float(pull["t"]) - _t1(aw))
    elif proxy is not None and aw is not None:
        put("route", float(proxy["t"]) - _t1(aw))
    if proxy is not None and http is not None:
        put("proxy_send", float(http["t"]) - float(proxy["t"]))
    if http is not None and qw is not None:
        put("replica_recv", float(qw["t"]) - float(http["t"]))
    if tier is not None:
        # spill-tier promotion (ISSUE 13): runs at tick start while
        # the request is still queued, INSIDE the queue_wait window —
        # carved out below so the two stay non-overlapping
        put("tier", float(tier.get("dur_ms", 0.0)) / 1e3)
    if qw is not None:
        put("scheduler_queue",
            float(qw.get("dur_ms", 0.0)) / 1e3
            - (float(tier.get("dur_ms", 0.0)) / 1e3
               if tier is not None else 0.0))
    if ft is not None and qw is not None:
        put("admit", float(ft["t"]) - _t1(qw))
    if done is not None and ft is not None:
        put("decode", float(done["t"]) - float(ft["t"]))
    if http is not None and done is not None:
        put("stream", _t1(http) - float(done["t"]))
    if proxy is not None and http is not None:
        put("proxy_return", _t1(proxy) - _t1(http))
    if req is not None and proxy is not None:
        put("router_send", _t1(req) - _t1(proxy))
    return seg


def stitch_spans(spans: List[dict],
                 client_e2e_by_rid: Optional[Dict[str, float]] = None
                 ) -> dict:
    """Merge span records into per-request timelines + attribution.

    Returns::

        {"offsets": {"proc:pid": seconds_shifted, ...},
         "counts": {"requests": N, "stitched": n_cross_process,
                    "partial": n_single_process},
         "requests": [{"rid", "procs", "stitched", "e2e_s",
                       "e2e_source", "ttft_s", "segments": {...},
                       "attributed_s", "coverage", "residual_s",
                       "tokens"?}, ...]}

    A request is **stitched** when spans from >= 2 processes agree on
    its rid (the cross-process contract CI gates on); single-process
    rids are **partial** — orphan spans are reported, never dropped
    silently. ``e2e_s`` prefers the client's measured total (when a
    loadgen summary is joined in), falling back to the router request
    span, then the replica handler span; ``coverage`` is the attributed
    fraction and ``residual_s`` the remainder — reported, not hidden.
    """
    offsets = estimate_offsets(spans)
    spans = apply_offsets(spans, offsets)
    rows = []
    stitched = partial = 0
    for rid, recs in sorted(_by_rid(spans).items()):
        recs = sorted(recs, key=lambda r: float(r.get("t", 0.0)))
        procs = sorted({r.get("proc", "?") for r in recs})
        seg = _segments(recs)
        req = _named(recs, "request", proc="router")
        http = _named(recs, "http")
        done = _named(recs, "complete")
        ft = _named(recs, "first_token")
        e2e = None
        source = None
        if client_e2e_by_rid and rid in client_e2e_by_rid:
            e2e = float(client_e2e_by_rid[rid])
            source = "client"
        elif req is not None:
            e2e = float(req.get("dur_ms", 0.0)) / 1e3
            source = "router"
        elif http is not None:
            e2e = float(http.get("dur_ms", 0.0)) / 1e3
            source = "replica"
        attributed = round(sum(seg.values()), 6)
        is_stitched = len(procs) >= 2
        if is_stitched:
            stitched += 1
        else:
            partial += 1
        row = {
            "rid": rid,
            "procs": procs,
            "stitched": is_stitched,
            "spans": len(recs),
            "segments": seg,
            "attributed_s": attributed,
        }
        if ft is not None:
            ttft = (ft.get("attrs") or {}).get("ttft_s")
            if ttft is not None:
                row["ttft_s"] = float(ttft)
        if done is not None:
            tokens = (done.get("attrs") or {}).get("tokens")
            if tokens is not None:
                row["tokens"] = int(tokens)
        if e2e is not None:
            row["e2e_s"] = round(e2e, 6)
            row["e2e_source"] = source
            row["residual_s"] = round(e2e - attributed, 6)
            row["coverage"] = (round(attributed / e2e, 4)
                               if e2e > 0 else None)
        rows.append(row)
    return {
        "offsets": {f"{p}:{pid}": round(off, 6)
                    for (p, pid), off in offsets.items()},
        "counts": {"requests": len(rows), "stitched": stitched,
                   "partial": partial},
        "requests": rows,
    }


def attribution(stitched: dict) -> dict:
    """Tail-latency attribution over stitched requests: per-segment
    p50/p99 seconds, e2e/TTFT percentiles, median coverage, and the
    p99 request's own breakdown (the "where did THAT request's time
    go" row). Residuals are first-class: ``residual_p99_s`` says how
    much of the tail the spans do NOT explain."""
    rows = [r for r in stitched.get("requests", ())
            if r.get("stitched") and r.get("e2e_s") is not None]
    # NOT "requests": that name belongs to the stitch counts (total
    # ids seen); this is the subset that was cross-process stitched
    # WITH a measured e2e — the rows the percentiles below come from
    out: dict = {"attributed_requests": len(rows)}
    if not rows:
        return out
    names = sorted({n for r in rows for n in r["segments"]})
    for name in names:
        vals = sorted(r["segments"][name] for r in rows
                      if name in r["segments"])
        out[f"seg_{name}_p50_s"] = round(_pctl(vals, 0.50), 6)
        out[f"seg_{name}_p99_s"] = round(_pctl(vals, 0.99), 6)
    e2es = sorted(r["e2e_s"] for r in rows)
    out["e2e_p50_s"] = round(_pctl(e2es, 0.50), 6)
    out["e2e_p99_s"] = round(_pctl(e2es, 0.99), 6)
    ttfts = sorted(r["ttft_s"] for r in rows if r.get("ttft_s")
                   is not None)
    if ttfts:
        out["ttft_p50_s"] = round(_pctl(ttfts, 0.50), 6)
        out["ttft_p99_s"] = round(_pctl(ttfts, 0.99), 6)
    covs = sorted(r["coverage"] for r in rows
                  if r.get("coverage") is not None)
    if covs:
        out["coverage_p50"] = round(_pctl(covs, 0.50), 4)
        out["coverage_min"] = round(covs[0], 4)
    residuals = sorted(abs(r["residual_s"]) for r in rows
                       if r.get("residual_s") is not None)
    if residuals:
        out["residual_p99_s"] = round(_pctl(residuals, 0.99), 6)
    # the p99 request, decomposed: sort by e2e, take the p99 index row
    worst = sorted(rows, key=lambda r: r["e2e_s"])[
        min(len(rows) - 1, int(0.99 * len(rows)))]
    out["p99_request"] = {
        "rid": worst["rid"], "e2e_s": worst["e2e_s"],
        "segments": worst["segments"],
        "residual_s": worst.get("residual_s"),
    }
    return out


# ---------------------------------------------------------------------------
# Perfetto / Chrome trace output
# ---------------------------------------------------------------------------


def _flow_id(rid: str) -> int:
    # stable across runs of the stitcher (hash() is salted per process)
    h = 0
    for ch in rid:
        h = (h * 131 + ord(ch)) & 0x7FFFFFFF
    return h or 1


#: virtual thread id for the modeled kernel-class track — far above any
#: real tid so the anatomy row never interleaves with measured spans
_ANATOMY_TID = 999_983


def to_perfetto(spans: List[dict],
                offsets: Optional[Dict[tuple, float]] = None,
                anatomy: Optional[dict] = None,
                anatomy_rids=None) -> dict:
    """One merged Chrome-trace-event JSON over every process's spans,
    with per-process ``process_name`` metadata and ``s``/``f`` flow
    events linking the router's proxy span to the replica's handler
    span per request — load it in Perfetto and follow a request across
    process rows.

    ``anatomy`` (ISSUE 16) is a rendered ``decode_step_anatomy``
    section (observability/anatomy.render_anatomy — classes with
    ``frac_time``/``bound`` + ``dispatch_gap_frac``): each selected
    request's decode window (first_token -> complete) gains a "step
    anatomy (modeled)" track splitting it into kernel-class slices
    proportional to their modeled time share, with the dispatch gap as
    its own trailing slice. ``anatomy_rids`` restricts the expansion
    (trace_stitch passes the p99 request); None expands every stitched
    decode window."""
    if offsets is None:
        offsets = estimate_offsets(spans)
    spans = apply_offsets(spans, offsets)
    events: List[dict] = []
    pid_map: Dict[tuple, int] = {}

    def pid_for(rec: dict) -> int:
        key = _proc_key(rec)
        if key not in pid_map:
            pid_map[key] = len(pid_map) + 1
            events.append({
                "ph": "M", "name": "process_name", "pid": pid_map[key],
                "args": {"name": f"{key[0]} (pid {key[1]})"},
            })
        return pid_map[key]

    t_origin = min((float(s["t"]) for s in spans if "t" in s
                    and s.get("rid")), default=0.0)
    for s in spans:
        if "t" not in s or not s.get("rid"):
            continue
        ev = {
            "name": s.get("name", "?"), "ph": "X",
            "ts": round((float(s["t"]) - t_origin) * 1e6, 1),
            "dur": max(round(float(s.get("dur_ms", 0.0)) * 1e3, 1), 1),
            "pid": pid_for(s), "tid": s.get("tid", 0),
            "args": {"rid": s["rid"], **(s.get("attrs") or {})},
        }
        events.append(ev)
    # flow events per cross-process rid: proxy (router) -> http
    # (replica); the LAST proxy attempt is the one the replica served
    for rid, recs in _by_rid(spans).items():
        proxy = _last_named(recs, "proxy", proc="router")
        http = _named(recs, "http")
        if proxy is None or http is None \
                or http.get("proc") == "router":
            continue
        fid = _flow_id(rid)
        events.append({
            "ph": "s", "cat": "request", "name": "req", "id": fid,
            "pid": pid_for(proxy), "tid": proxy.get("tid", 0),
            "ts": round((float(proxy["t"]) - t_origin) * 1e6, 1),
            "args": {"rid": rid},
        })
        events.append({
            "ph": "f", "cat": "request", "name": "req", "id": fid,
            "bp": "e",
            "pid": pid_for(http), "tid": http.get("tid", 0),
            "ts": round((float(http["t"]) - t_origin) * 1e6, 1),
            "args": {"rid": rid},
        })
    if anatomy and (anatomy.get("classes") or {}):
        classes = [(cls, c) for cls, c in sorted(
            anatomy["classes"].items(),
            key=lambda kv: -(kv[1].get("frac_time") or 0.0))
            if (c.get("frac_time") or 0.0) > 0.0]
        gap = float(anatomy.get("dispatch_gap_frac") or 0.0)
        named_pids: set = set()
        want = set(anatomy_rids) if anatomy_rids is not None else None
        for rid, recs in _by_rid(spans).items():
            if want is not None and rid not in want:
                continue
            ft = _named(recs, "first_token")
            done = _named(recs, "complete")
            if ft is None or done is None:
                continue
            t0, t1 = float(ft["t"]), float(done["t"])
            if t1 <= t0:
                continue
            pid = pid_for(ft)
            if pid not in named_pids:
                named_pids.add(pid)
                events.append({
                    "ph": "M", "name": "thread_name", "pid": pid,
                    "tid": _ANATOMY_TID,
                    "args": {"name": "step anatomy (modeled)"},
                })
            dur_us = (t1 - t0) * 1e6
            dev_us = dur_us * (1.0 - gap)
            cursor = (t0 - t_origin) * 1e6
            for cls, c in classes:
                d = dev_us * float(c["frac_time"])
                events.append({
                    "name": f"kernel/{cls}", "ph": "X",
                    "cat": "anatomy", "ts": round(cursor, 1),
                    "dur": max(round(d, 1), 1),
                    "pid": pid, "tid": _ANATOMY_TID,
                    "args": {"rid": rid,
                             "frac_time": c.get("frac_time"),
                             "bound": c.get("bound")},
                })
                cursor += d
            if gap > 0:
                events.append({
                    "name": "dispatch_gap", "ph": "X",
                    "cat": "anatomy", "ts": round(cursor, 1),
                    "dur": max(round(dur_us - dev_us, 1), 1),
                    "pid": pid, "tid": _ANATOMY_TID,
                    "args": {"rid": rid,
                             "dispatch_gap_frac": round(gap, 4)},
                })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def stitch_run(run_dir,
               client_e2e_by_rid: Optional[Dict[str, float]] = None
               ) -> dict:
    """Run-dir convenience: discover + load + stitch + attribute."""
    spans = load_spans(discover_span_files(run_dir))
    report = stitch_spans(spans, client_e2e_by_rid=client_e2e_by_rid)
    report["attribution"] = attribution(report)
    return report
