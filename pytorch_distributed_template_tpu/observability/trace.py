"""Lightweight span tracing: ``with span("data/next_batch"): ...``.

Host-side structured timing for the paths ``jax.profiler`` cannot see
(it traces device programs; the question "was the step slow because of
data wait, checkpoint flush, or the dispatch itself?" is a HOST
timeline question). Spans nest, survive exceptions, cost two
``perf_counter`` calls plus a deque append, and record into a bounded
ring as Chrome trace-event ``"X"`` (complete) events — ``dump()``
writes a file that chrome://tracing and Perfetto load directly.

Two consumers beyond the viewer:

- the watchdog (utils/watchdog.py) snapshots ``active_spans()`` when a
  step stalls, so the dump says WHICH call never returned ("stuck 214 s
  inside checkpoint/save") next to the faulthandler stacks;
- tests assert nesting and exception safety on the recorded events.

The module-level ``span()`` uses one process-wide recorder
(``get_recorder()``); subsystems that want isolation construct their
own ``SpanRecorder`` and use its ``.span()`` method.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from contextlib import contextmanager
from pathlib import Path
from typing import Optional


class SpanRecorder:
    """Bounded ring of finished spans + registry of open ones.

    Chrome trace-event fields per finished span: ``name``, ``ph: "X"``,
    ``ts``/``dur`` (microseconds, one process-wide monotonic origin),
    ``pid``/``tid``, and ``args`` (user attrs; ``error: true`` when the
    body raised).
    """

    def __init__(self, capacity: int = 8192):
        self.capacity = int(capacity)
        self.events: "collections.deque" = collections.deque(
            maxlen=self.capacity
        )
        self._lock = threading.Lock()
        # open spans per thread: {tid: [ {name, t0, args}, ... ]}
        self._open: dict = {}
        self._t0 = time.perf_counter()  # trace time origin

    # -- the core API --------------------------------------------------------

    @contextmanager
    def span(self, name: str, **attrs):
        tid = threading.get_ident()
        t0 = time.perf_counter()
        frame = {"name": name, "t0": t0, "args": attrs}
        with self._lock:
            self._open.setdefault(tid, []).append(frame)
        try:
            yield frame
        except BaseException:
            frame["args"] = {**frame["args"], "error": True}
            raise
        finally:
            t1 = time.perf_counter()
            with self._lock:
                stack = self._open.get(tid)
                if stack and stack[-1] is frame:
                    stack.pop()
                    if not stack:
                        del self._open[tid]
                event = {
                    "name": name,
                    "ph": "X",
                    "ts": round((t0 - self._t0) * 1e6, 1),
                    "dur": round((t1 - t0) * 1e6, 1),
                    "pid": os.getpid(),
                    "tid": tid,
                }
                if frame["args"]:
                    event["args"] = dict(frame["args"])
                self.events.append(event)

    # -- introspection -------------------------------------------------------

    def active_spans(self) -> list:
        """Currently-open spans across all threads, outermost first —
        the watchdog's 'what is the process stuck inside' snapshot."""
        now = time.perf_counter()
        out = []
        with self._lock:
            for tid, stack in self._open.items():
                for depth, frame in enumerate(stack):
                    out.append({
                        "tid": tid,
                        "depth": depth,
                        "name": frame["name"],
                        "elapsed_ms": round((now - frame["t0"]) * 1e3, 3),
                        **({"args": dict(frame["args"])}
                           if frame["args"] else {}),
                    })
        return out

    def drain(self) -> list:
        """Finished events so far; clears the ring."""
        with self._lock:
            out = list(self.events)
            self.events.clear()
        return out

    def snapshot(self) -> list:
        with self._lock:
            return list(self.events)

    # -- output --------------------------------------------------------------

    def to_chrome(self) -> dict:
        """The Chrome trace-event JSON object (load in chrome://tracing
        or Perfetto)."""
        return {"traceEvents": self.snapshot(),
                "displayTimeUnit": "ms"}

    def dump(self, path) -> Optional[Path]:
        """Write the Chrome trace file; returns the path (None when
        nothing was recorded)."""
        events = self.to_chrome()
        if not events["traceEvents"]:
            return None
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        # default=repr: span attrs are caller-arbitrary, and a single
        # non-JSON attr must not void the whole trace file
        path.write_text(json.dumps(events, default=repr))
        return path

    def clear(self) -> None:
        with self._lock:
            self.events.clear()


_default = SpanRecorder()


def get_recorder() -> SpanRecorder:
    """The process-wide recorder behind the module-level ``span()``."""
    return _default


def span(name: str, **attrs):
    """``with span("checkpoint/save"): ...`` on the default recorder."""
    return _default.span(name, **attrs)
