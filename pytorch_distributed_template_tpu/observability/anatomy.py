"""Step anatomy: kernel-class cost attribution married to measured time.

``costmodel`` answers "what is IN this executable" (per-kernel-class
FLOPs/bytes + roofline placement); the telemetry layer answers "how
long did the step take". This module joins them into the **step
anatomy** surfaced on ``/metrics?format=json``
(``decode_step_anatomy`` / ``train_step_anatomy``), in flight-recorder
records, in ``telemetry_report``'s "Step anatomy" section, and in the
stitched Perfetto trace:

- per class: attributed time (the class's share of the roofline-modeled
  device time, scaled onto the measured wall EWMA), FLOPs, bytes, and
  whether the class sits under the compute, HBM, or ICI ceiling;
- ``dispatch_gap_frac``: the fraction of measured wall time the device
  model can NOT account for — host dispatch, data waits, queue gaps
  (the continuous engine's analog of the trainer's ``data_wait_ms``).

The analysis itself is an AOT lower+compile of the executable's
abstract signature, which is NOT free — so :class:`AnatomyStore` runs
it once per (kind, signature) on a single daemon worker thread, and
the hot path (``observe`` per chunk/step) is a dict update. The
``quick_anatomy`` bench rung gates the end-to-end overhead < 2% with
the paired-window discipline. ``PDT_ANATOMY=0`` disables the whole
subsystem (every surface degrades to an absent section).
"""
from __future__ import annotations

import os
import queue
import threading
from typing import Dict, Optional

from . import costmodel


def anatomy_enabled(default: bool = True) -> bool:
    """The one switch: ``PDT_ANATOMY=0`` turns every anatomy surface
    off (registration, background compiles, /metrics sections)."""
    raw = os.environ.get("PDT_ANATOMY")
    if raw is None:
        return default
    return raw.strip().lower() not in ("0", "false", "off", "no", "")


def analyze_step(jitted_fn, *args, **kwargs) -> Optional[dict]:
    """One-shot synchronous anatomy of a jitted fn (AOT compile —
    startup/bench use, not the hot loop): class costs + roofline.
    None when lowering or the backend's cost analysis fails."""
    try:
        costs = costmodel.analyze_jitted(jitted_fn, *args, **kwargs)
        return costmodel.roofline(costs)
    except Exception:  # noqa: BLE001 — anatomy must never break a run
        return None


def analyze_compiled(compiled) -> Optional[dict]:
    """Anatomy of an already-compiled executable (no extra compile)."""
    try:
        return costmodel.roofline(
            costmodel.executable_class_costs(compiled))
    except Exception:  # noqa: BLE001
        return None


def render_anatomy(analysis: dict, wall_ms: Optional[float] = None,
                   observed: int = 0, top_n: int = 0) -> dict:
    """Roofline analysis + measured wall time -> the JSON anatomy
    section. Class time = ``frac_time`` of the modeled device time
    scaled onto the measured wall minus the dispatch gap; without a
    measured wall the modeled times stand on their own."""
    classes = analysis.get("classes") or {}
    est_s = float(analysis.get("est_step_time_s") or 0.0)
    device_ms = None
    gap_frac = None
    if wall_ms and wall_ms > 0:
        device_ms = min(est_s * 1e3, wall_ms)
        gap_frac = max(0.0, 1.0 - est_s * 1e3 / wall_ms)
    items = sorted(classes.items(),
                   key=lambda kv: -kv[1].get("est_time_s", 0.0))
    if top_n:
        items = items[:top_n]
    out_classes = {}
    for cls, c in items:
        if not c.get("count"):
            continue
        frac = float(c.get("frac_time") or 0.0)
        row = {
            "frac_time": round(frac, 4),
            "flops": round(float(c.get("flops") or 0.0), 1),
            "bytes": round(float(c.get("bytes") or 0.0), 1),
            "bound": c.get("bound"),
        }
        if device_ms is not None:
            row["time_ms"] = round(frac * device_ms, 4)
        out_classes[cls] = row
    out = {
        "classes": out_classes,
        "est_step_time_ms": round(est_s * 1e3, 4),
        "total_flops": round(sum(float(c.get("flops", 0.0))
                                 for c in classes.values()), 1),
        "peak_flops": analysis.get("peak_flops"),
        "hbm_bytes_s": analysis.get("hbm_bytes_s"),
    }
    if wall_ms is not None:
        out["wall_ms"] = round(float(wall_ms), 4)
    if gap_frac is not None:
        out["dispatch_gap_frac"] = round(gap_frac, 4)
    if observed:
        out["observed_steps"] = int(observed)
    return out


class AnatomyStore:
    """Per-kind anatomy with background analysis and a dict-update
    hot path.

    ``register(kind, jitted_fn, args)`` abstracts the args (shape/
    dtype/sharding only — no live buffer refs cross the thread
    boundary, the executables donate) and queues ONE analysis per
    (kind, signature) on the shared worker thread; re-registrations of
    a seen signature are a set lookup. ``observe(kind, wall_ms)`` is
    the per-chunk/step hot call: a counter bump + EWMA. ``snapshot()``
    renders the /metrics sections; ``version`` bumps when an analysis
    lands, so callers attach anatomy to a flight record exactly when
    it changes instead of every step."""

    _EWMA_ALPHA = 0.1

    def __init__(self, enabled: Optional[bool] = None):
        self.enabled = (anatomy_enabled() if enabled is None
                        else bool(enabled))
        self.version = 0
        self._lock = threading.Lock()
        self._seen: set = set()
        self._analyses: Dict[str, dict] = {}
        self._sig_counts: Dict[str, int] = {}
        self._walls: Dict[str, dict] = {}
        self._queue: "queue.Queue" = queue.Queue()
        self._worker: Optional[threading.Thread] = None

    # -- registration / analysis (cold path) ---------------------------

    def _ensure_worker(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._run, name="anatomy-worker", daemon=True)
            self._worker.start()

    def _run(self) -> None:
        while True:
            kind, fn, args, kwargs = self._queue.get()
            analysis = analyze_step(fn, *args, **kwargs)
            with self._lock:
                if analysis is not None:
                    self._analyses[kind] = analysis
                    self.version += 1
            self._queue.task_done()

    def register(self, kind: str, jitted_fn, args, kwargs=None) -> bool:
        """Queue one background analysis of ``jitted_fn`` at this
        abstract signature, deduped — steady state is one frozenset
        lookup. Returns True when a new analysis was queued."""
        if not self.enabled:
            return False
        try:
            abstract = costmodel.abstractify(tuple(args))
        except Exception:  # noqa: BLE001
            return False
        sig = (kind, str([
            (getattr(x, "shape", None), str(getattr(x, "dtype", None)))
            for x in _flat_leaves(abstract)]))
        sig = (sig[0], hash(sig[1]))
        with self._lock:
            if sig in self._seen:
                return False
            self._seen.add(sig)
            self._sig_counts[kind] = self._sig_counts.get(kind, 0) + 1
        self._ensure_worker()
        self._queue.put((kind, jitted_fn, abstract, kwargs or {}))
        return True

    def put_analysis(self, kind: str, analysis: dict) -> None:
        """Install an already-computed analysis (tests; one-shot
        callers that compiled synchronously anyway)."""
        with self._lock:
            self._analyses[kind] = analysis
            self.version += 1

    def wait_idle(self, timeout_s: float = 30.0) -> bool:
        """Block until queued analyses finish (tests/bench — never the
        serving path)."""
        import time

        t0 = time.monotonic()
        while not self._queue.empty():
            if time.monotonic() - t0 > timeout_s:
                return False
            time.sleep(0.01)
        # queue empty != task done; poll the join flag briefly
        while self._queue.unfinished_tasks:
            if time.monotonic() - t0 > timeout_s:
                return False
            time.sleep(0.01)
        return True

    # -- hot path ------------------------------------------------------

    def observe(self, kind: str, wall_ms: float) -> None:
        """Per-step/chunk measured wall time for ``kind`` — a dict
        update, safe at serving chunk rate."""
        if not self.enabled:
            return
        w = self._walls.get(kind)
        if w is None:
            self._walls[kind] = {"ewma_ms": float(wall_ms), "n": 1}
        else:
            w["ewma_ms"] += self._EWMA_ALPHA * (wall_ms - w["ewma_ms"])
            w["n"] += 1

    # -- surfaces ------------------------------------------------------

    def snapshot(self, kind: Optional[str] = None, top_n: int = 0):
        """The /metrics section: one rendered anatomy per kind (or the
        single requested kind; None while analysis hasn't landed)."""
        if not self.enabled:
            return None if kind is not None else {}
        with self._lock:
            analyses = (dict(self._analyses) if kind is None
                        else {kind: self._analyses.get(kind)})
        out = {}
        for k, analysis in analyses.items():
            if analysis is None:
                continue
            w = self._walls.get(k) or {}
            rendered = render_anatomy(
                analysis, wall_ms=w.get("ewma_ms"),
                observed=w.get("n", 0), top_n=top_n)
            if self._sig_counts.get(k, 0) > 1:
                rendered["signatures"] = self._sig_counts[k]
            out[k] = rendered
        return out.get(kind) if kind is not None else out


def _flat_leaves(tree):
    import jax

    return jax.tree.leaves(tree)
