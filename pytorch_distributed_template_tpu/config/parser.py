"""Config-driven experiment system.

TPU-native re-design of the reference's ``parse_config.py``
(/root/reference/parse_config.py). Behavior kept at parity:

- A JSON config fully describes an experiment; components are built from
  ``{"type": ..., "args": {...}}`` blocks (parse_config.py:79-107) — here
  resolved through registries (see ``config/registry.py``).
- CLI flags address nested keys with ``;``-separated keychains
  (parse_config.py:134-156); unset CLI flags are skipped via a sentinel,
  so an explicit ``--set key null`` override really nulls the key.
- ``-r`` resume rediscovers the config next to the checkpoint
  (parse_config.py:59-66); passing ``-c`` too overlays the new config's
  top-level keys for fine-tuning (parse_config.py:69-71); ``-s`` overrides
  ``trainer.save_dir`` (parse_config.py:72-73).
- Run directory layout ``save_dir/name/{train,test}/<MMDD_HHMMSS>`` with the
  merged config persisted into it (parse_config.py:28-39).
- ``get_logger(name, verbosity)`` with verbosity {0: WARNING, 1: INFO,
  2: DEBUG} (parse_config.py:109-118).

Deliberate differences from the reference (documented, not bugs):
- Only the main process (``process_index() == 0``) creates the run dir and
  writes the config snapshot — the reference lets every rank write and races
  on shared filesystems (parse_config.py:37-39 executed per-rank).
- ``init_obj`` resolves via Registry-or-module (the reference requires a
  module), and the keychain override for batch size targets ``train_loader``
  (the reference's ``data_loader;args;batch_size`` target names a key absent
  from its own config — a latent bug we do not replicate).
"""
from __future__ import annotations

import json
import logging
from datetime import datetime
from functools import partial, reduce
from operator import getitem
from pathlib import Path

from ..observability.logging import setup_logging
from ..utils.util import read_json, write_json
from .registry import resolve


class ConfigParser:
    def __init__(self, config, resume=None, modification=None, run_id=None,
                 training=True):
        """
        :param config: dict of config (contents of a config JSON file).
        :param resume: path to a checkpoint to resume from, or None.
        :param modification: dict {keychain: value} of CLI overrides, where a
            keychain is ``;``-separated (e.g. ``optimizer;args;lr``).
        :param run_id: unique run identifier; timestamp when None.
        :param training: selects the ``train`` vs ``test`` run subdirectory.
        """
        self._config = _update_config(config, modification)
        # resolve(): orbax requires absolute paths end-to-end.
        self.resume = Path(resume).resolve() if resume is not None else None

        save_dir = Path(self.config["trainer"]["save_dir"])
        exper_name = self.config["name"]
        if run_id is None:
            run_id = datetime.now().strftime(r"%m%d_%H%M%S")
        self._run_id = run_id
        traindir = "train" if training else "test"
        # Absolute: orbax (tensorstore) requires absolute checkpoint paths.
        self._save_dir = (save_dir / exper_name / traindir / run_id).resolve()

        # Only the main process touches the filesystem (reference races here).
        from ..parallel.dist import is_main_process

        if is_main_process():
            self.save_dir.mkdir(parents=True, exist_ok=True)
            write_json(self.config, self.save_dir / "config.json")
            setup_logging(self.save_dir)

        self.log_levels = {0: logging.WARNING, 1: logging.INFO, 2: logging.DEBUG}

    @classmethod
    def from_args(cls, args, options=(), training=True):
        """Build from argparse. Returns ``(parsed_args, config_parser)``.

        Mirrors /root/reference/parse_config.py:49-77 including the resume
        config rediscovery and fine-tune overlay. ``--auto-resume`` (when
        the entry point defines it) locates the experiment's newest
        checkpoint and resumes it — the relaunch half of the
        crash/preempt -> relaunch -> resume recovery contract
        (SURVEY.md §5 failure detection); a fresh run starts when no
        checkpoint exists yet.
        """
        for opt in options:
            args.add_argument(*opt.flags, default=None, type=opt.type)
        if hasattr(args, "add_argument"):
            # Generic keychain override: repeatable, value parsed as JSON
            # when possible (numbers, bools, dicts) else kept as a string.
            # Superset of the reference's declared CustomArgs
            # (parse_config.py:133-156 + train.py:94-98): any nested key is
            # addressable without pre-declaring a flag, e.g.
            #   --set "arch;args;seq_layout" zigzag
            #   --set "mesh;axes" '{"data": 2, "seq": 4}'
            args.add_argument(
                "--set", action="append", nargs=2, default=None,
                metavar=("KEYCHAIN", "VALUE"),
                help="Override a ;-separated config keychain "
                     "(repeatable; VALUE parsed as JSON when possible).",
            )
        if not isinstance(args, tuple):
            args = args.parse_args()

        if (getattr(args, "auto_resume", False) and args.resume is None
                and args.config is not None):
            scan_cfg = read_json(Path(args.config))
            if getattr(args, "save_dir", None) is not None:
                # honor -s here too, else the scan looks in the wrong tree
                scan_cfg["trainer"]["save_dir"] = args.save_dir
            found = find_latest_checkpoint(scan_cfg)
            if found is not None:
                args.resume = str(found)
                logging.getLogger(__name__).warning(
                    "--auto-resume: resuming from %s", found
                )

        if args.resume is not None:
            resume = Path(args.resume)
            cfg_fname = _resume_config_path(resume)
        else:
            msg_no_cfg = (
                "Configuration file needs to be specified. "
                "Add '-c config.json', for example."
            )
            assert args.config is not None, msg_no_cfg
            resume = None
            cfg_fname = Path(args.config)

        config = read_json(cfg_fname)
        if args.config and resume:
            # fine-tuning: overlay the new config's top-level keys
            config.update(read_json(args.config))
        if getattr(args, "save_dir", None) is not None:
            config["trainer"]["save_dir"] = args.save_dir

        # Unset argparse flags arrive as None and must be skipped; explicit
        # ``--set key null`` must APPLY None. Distinguish via _UNSET.
        modification = {}
        for opt in options:
            val = getattr(args, _get_opt_name(opt.flags))
            modification[opt.target] = _UNSET if val is None else val
        for chain, raw in (getattr(args, "set", None) or ()):
            modification[chain] = _parse_cli_value(raw)
        return args, cls(config, resume, modification, training=training)

    def init_obj(self, name, namespace, *args, **kwargs):
        """Instantiate the component described by config block ``name``.

        ``config.init_obj('arch', MODELS)`` is equivalent to
        ``MODELS.get(config['arch']['type'])(**config['arch']['args'])``.
        ``namespace`` may be a Registry or a plain module (reference parity,
        parse_config.py:79-92).
        """
        module_name = self[name]["type"]
        module_args = dict(self[name].get("args", {}))
        if any(k in module_args for k in kwargs):
            raise ValueError("Overwriting kwargs given in config file is not allowed")
        module_args.update(kwargs)
        return resolve(namespace, module_name)(*args, **module_args)

    def init_ftn(self, name, namespace, *args, **kwargs):
        """Return the component callable with config args partially applied.

        Parity with /root/reference/parse_config.py:94-107.
        """
        module_name = self[name]["type"]
        module_args = dict(self[name].get("args", {}))
        if any(k in module_args for k in kwargs):
            raise ValueError("Overwriting kwargs given in config file is not allowed")
        module_args.update(kwargs)
        return partial(resolve(namespace, module_name), *args, **module_args)

    def __getitem__(self, name):
        return self.config[name]

    def __contains__(self, name):
        return name in self.config

    def get(self, name, default=None):
        return self.config.get(name, default)

    def get_logger(self, name, verbosity=2):
        assert verbosity in self.log_levels, (
            f"verbosity option {verbosity} is invalid. "
            f"Valid options are {list(self.log_levels)}."
        )
        logger = logging.getLogger(name)
        logger.setLevel(self.log_levels[verbosity])
        return logger

    @property
    def config(self):
        return self._config

    @property
    def save_dir(self) -> Path:
        return self._save_dir

    @property
    def log_dir(self) -> Path:
        return self._save_dir

    @property
    def run_id(self) -> str:
        return self._run_id


def find_latest_checkpoint(config: dict):
    """Newest ``checkpoint-epochN`` across the experiment's train runs.

    Two-level ranking. The RUN is chosen by recency (newest checkpoint
    mtime in it) — NOT by the run-id name, since MMDD_HHMMSS ids carry no
    year and lie across a New Year boundary, and NOT by epoch, since a
    fresh retrain legitimately restarts epoch numbering. WITHIN the
    chosen run, ``(epoch, completeness, mtime)`` ranks: an epoch-edge
    checkpoint beats an interval slot of the same epoch (the slot holds
    mid-epoch state, and async flush order can leave it with the newer
    mtime), while an interval slot from a later, crashed epoch wins on
    its epoch. Returns None when the experiment has never checkpointed.
    """
    import re

    base = (
        Path(config["trainer"]["save_dir"]) / config["name"] / "train"
    )
    by_run: dict = {}  # run path -> [(epoch, completeness, mtime, path)]
    if base.is_dir():
        for run in base.iterdir():
            cands = by_run.setdefault(run, [])
            for ck in run.glob("checkpoint-epoch*"):
                m = re.match(r"checkpoint-epoch(\d+)$", ck.name)
                if m and ck.is_dir():
                    cands.append(
                        (int(m.group(1)), 1, ck.stat().st_mtime, ck)
                    )
            # mid-epoch A/B interval slots + the emergency save from an
            # unhandled-exception exit: epoch from the sidecar. Both
            # rank as "incomplete" (an epoch-edge checkpoint of the
            # same epoch wins); among incompletes of one epoch, mtime
            # decides — the emergency save at the crash moment is
            # newest by construction
            for ck in list(run.glob("checkpoint-interval-[ab]")) + list(
                    run.glob("checkpoint-emergency")):
                if not ck.is_dir():
                    continue
                epoch = 0
                try:
                    epoch = int(json.loads(
                        (run / f"{ck.name}.meta.json").read_text()
                    ).get("epoch", 0))
                except (OSError, ValueError):
                    pass  # sidecar lost: rank below any epoch checkpoint
                cands.append((epoch, 0, ck.stat().st_mtime, ck))
    runs = [c for c in by_run.values() if c]
    if not runs:
        return None
    newest_run = max(runs, key=lambda cands: max(c[2] for c in cands))
    return max(newest_run, key=lambda c: c[:3])[3]


def _resume_config_path(resume: Path) -> Path:
    """Find the run-dir config snapshot next to a checkpoint path.

    The reference stores flat ``checkpoint-epochN.pth`` files so the config
    is at ``resume.parent/config.json`` (parse_config.py:59-61). Our orbax
    checkpoints are *directories* (``checkpoint-epochN/``), so accept either
    a checkpoint dir (config one level up) or a run dir itself.
    """
    for candidate in (resume.parent / "config.json", resume / "config.json",
                      resume.parent.parent / "config.json"):
        if candidate.exists():
            return candidate
    return resume.parent / "config.json"  # let read_json raise the clear error


_UNSET = object()  # unset CLI flag; distinct from an explicit null override


def _update_config(config, modification):
    if modification is None:
        return config
    for k, v in modification.items():
        # Skip only flags never given on the CLI; an explicit None (e.g.
        # ``--set key null``) is a real override and applies.
        if v is not _UNSET:
            _set_by_path(config, k, v)
    return config


def _get_opt_name(flags):
    for flg in flags:
        if flg.startswith("--"):
            return flg.lstrip("-").replace("-", "_")
    return flags[0].lstrip("-").replace("-", "_")


def _parse_cli_value(raw: str):
    """JSON-decode a ``--set`` value when possible, else keep the string.

    ``0.002`` -> float, ``true`` -> bool, ``{"data": 2}`` -> dict,
    ``zigzag`` -> str (not valid JSON, stays literal).
    """
    try:
        return json.loads(raw)
    except (ValueError, TypeError):
        return raw


def _set_by_path(tree, keys, value):
    """Set a ``;``-keychain, creating missing intermediate dicts (so
    ``--set`` can introduce keys a config omits, e.g. a model option that
    has a default)."""
    keys = keys.split(";")
    node = tree
    for k in keys[:-1]:
        node = node.setdefault(k, {})
        if not isinstance(node, dict):
            raise TypeError(
                f"keychain {';'.join(keys)} crosses non-dict value at {k!r}"
            )
    node[keys[-1]] = value


def _get_by_path(tree, keys):
    return reduce(getitem, keys, tree)
