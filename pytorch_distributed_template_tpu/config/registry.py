"""Decorator-based component registries.

The reference wires components by reflection: a config block names a class
(``"type"``) plus kwargs (``"args"``) and ``ConfigParser.init_obj`` does
``getattr(module, type)(**args)`` against an arbitrary module
(/root/reference/parse_config.py:79-92). We keep the exact config schema and
expressive power but resolve names through explicit registries instead of
module ``getattr`` — safer (no arbitrary attribute lookup), discoverable
(``REGISTRY.names()``), and it decouples config names from Python module
layout. A plain module still works anywhere a registry is accepted (the
parser falls back to ``getattr``), preserving the reference's semantics for
user extension.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional


class Registry:
    """A name -> callable mapping with a decorator-style ``register``."""

    def __init__(self, name: str):
        self._name = name
        self._entries: Dict[str, Callable] = {}

    @property
    def name(self) -> str:
        return self._name

    def register(self, name: Optional[str] = None, *, aliases: tuple = ()):
        """Register a callable. Usable as ``@R.register()`` or ``@R.register("Name")``."""

        def _do_register(obj: Callable) -> Callable:
            key = name if name is not None else obj.__name__
            keys = (key, *aliases)
            # Validate every key before inserting any, so a collision never
            # leaves a partial registration behind.
            for k in keys:
                if k in self._entries:
                    raise KeyError(
                        f"'{k}' already registered in registry '{self._name}'"
                    )
            for k in keys:
                self._entries[k] = obj
            return obj

        # Allow bare usage: @R.register (without parens)
        if callable(name):
            obj, name = name, None
            return _do_register(obj)
        return _do_register

    def get(self, key: str) -> Callable:
        if key not in self._entries:
            raise KeyError(
                f"'{key}' is not registered in registry '{self._name}'. "
                f"Available: {sorted(self._entries)}"
            )
        return self._entries[key]

    def __contains__(self, key: str) -> bool:
        return key in self._entries

    def names(self):
        return sorted(self._entries)

    def __repr__(self) -> str:
        return f"Registry({self._name!r}, {self.names()})"


def resolve(namespace: Any, key: str) -> Callable:
    """Look up ``key`` in a Registry or fall back to ``getattr`` on a module.

    This is the single seam that preserves the reference's reflection
    semantics (/root/reference/parse_config.py:92) while defaulting to
    explicit registries.
    """
    if isinstance(namespace, Registry):
        return namespace.get(key)
    return getattr(namespace, key)


# The framework-wide registries. Components self-register at import time from
# their defining modules (models/, engine/optim.py, data/, ...).
MODELS = Registry("models")
LOSSES = Registry("losses")
METRICS = Registry("metrics")
OPTIMIZERS = Registry("optimizers")
SCHEDULERS = Registry("lr_schedulers")
LOADERS = Registry("data_loaders")
DATASETS = Registry("datasets")
