from .registry import (
    Registry,
    MODELS,
    LOSSES,
    METRICS,
    OPTIMIZERS,
    SCHEDULERS,
    LOADERS,
    DATASETS,
)
from .parser import ConfigParser
