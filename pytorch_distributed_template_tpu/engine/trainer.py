"""Training loops: epoch policy up top, one compiled SPMD step underneath.

Mirrors the reference's BaseTrainer/Trainer split
(/root/reference/base/base_trainer.py + trainer/trainer.py): the base class
owns the epoch loop, metric monitoring, best-model tracking, early stopping,
and checkpoint policy; the concrete Trainer owns the per-epoch batch loop.

Key structural translation (SURVEY.md §3.1 hot loop -> jit):
- reference per-batch Python (H2D, forward, loss, dist.reduce, backward,
  DDP allreduce, step) -> ONE jitted ``train_step`` consuming pre-sharded
  prefetched batches, with the state donated (no copy per step);
- validation gathers nothing: metric sufficient statistics are psum'd
  in-graph and every host ends the epoch with identical global values.
  Because of that, monitor/early-stop decisions are *deterministically
  identical* on every host — the reference's pickle ``all_gather`` consensus
  (base_trainer.py:101-107) degenerates to plain local control flow here;
  rank gating remains only for I/O (logging, TB, checkpoint metadata);
- the reference's per-epoch ``lr_scheduler.step()`` is a pure function of
  the step counter compiled into the optimizer (engine/optim.py).
"""
from __future__ import annotations

import hashlib
import math
import os
import time
from abc import abstractmethod
from collections import deque
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..checkpoint import CheckpointManager
from ..data.loader import host_prefetch, prefetch_to_device
from ..models.base import describe, inject_mesh
from ..observability import FlightRecorder, MetricTracker, TensorboardWriter
from ..observability.crosshost import CrossHostAggregator
from ..observability.health import (
    HealthMonitor, health_counters, health_layout, health_metric_keys,
)
from ..observability.telemetry import drain_compile_events
from ..observability.trace import get_recorder as get_span_recorder
from ..observability.trace import span
from ..ops.augment import build_augment
from ..observability.anatomy import analyze_compiled, anatomy_enabled
from ..observability.anatomy import render_anatomy as _render_anatomy
from ..observability.profiler import (
    ThroughputMeter, TraceCapture, executable_flops, mfu,
)
from ..parallel import batch_sharding, dist, mesh_from_config
from ..resilience import faults
from ..utils import preemption
from ..utils.debug import configure_debug
from ..utils.util import maybe_tqdm
from ..utils.watchdog import StepWatchdog
from .optim import build_optimizer
from .state import create_sharded_train_state
from .steps import (
    finalize_metrics, instrument_step, make_eval_step, make_train_step,
)


def _endless_reshuffling(loader):
    """Endless loader for iteration-based training that reshuffles on every
    full pass (the reference's ``inf_loop`` relies on torch DataLoader
    reshuffling per re-iteration, utils/util.py:24-27; ours must advance
    the epoch counter explicitly or every pass replays one permutation)."""
    pass_idx = 0
    while True:
        if hasattr(loader, "set_epoch"):
            loader.set_epoch(pass_idx)
        yield from loader
        pass_idx += 1


class BaseTrainer:
    """Epoch-policy skeleton (reference base/base_trainer.py:10-107)."""

    def __init__(self, config):
        self.config = config
        cfg_trainer = config["trainer"]
        self.logger = config.get_logger(
            "trainer", cfg_trainer.get("verbosity", 2)
        )
        self.epochs = cfg_trainer["epochs"]
        self.save_period = cfg_trainer.get("save_period", 1)
        # mid-epoch safety net for long epochs (0 = off): every N batches
        # an async save lands in the alternating checkpoint-interval-a/b
        # slots (manager.save_interval), so a crash loses at most N steps.
        # Deterministic host-side condition -> every host saves together
        # (orbax saves are collective). Same partial-epoch resume semantics
        # as preemption: resume continues at the next epoch.
        self.save_interval_steps = int(
            cfg_trainer.get("save_interval_steps", 0)
        )
        self.monitor = cfg_trainer.get("monitor", "off")

        if self.monitor == "off":
            self.mnt_mode = "off"
            self.mnt_best = 0
        else:
            self.mnt_mode, self.mnt_metric = self.monitor.split()
            assert self.mnt_mode in ("min", "max")
            self.mnt_best = math.inf if self.mnt_mode == "min" else -math.inf
            self.early_stop = cfg_trainer.get("early_stop", math.inf)
            # None (e.g. ``--set "trainer;early_stop" null``) or <=0 both
            # mean "never stop early".
            if self.early_stop is None or self.early_stop <= 0:
                self.early_stop = math.inf

        self.start_epoch = 1
        # (epoch, next_batch) cursor maintained by the batch loop —
        # what the data_state sidecar and the emergency save record
        self._cursor = None
        self._resume_next_batch = 0
        self.checkpoint_dir = config.save_dir
        self.ckpt_manager = CheckpointManager(self.checkpoint_dir)
        self.writer = TensorboardWriter(
            config.log_dir, self.logger, cfg_trainer.get("tensorboard", False)
        )

    @abstractmethod
    def _train_epoch(self, epoch: int) -> dict:
        raise NotImplementedError

    def train(self) -> dict:
        """Full training loop (reference base_trainer.py:60-107).

        Monitoring runs identically on every host (epoch metrics are global
        device reductions, so all hosts agree bit-for-bit); only I/O is
        gated on the main process. Early stop therefore needs no cross-host
        consensus exchange.
        """
        preemption.install()
        not_improved_count = 0
        log: dict = {}
        try:
            for epoch in range(self.start_epoch, self.epochs + 1):
                result = self._train_epoch(epoch)

                log = {"epoch": epoch}
                log.update(result)
                if dist.is_main_process():
                    for key, value in log.items():
                        self.logger.info("    %-15s: %s", str(key), value)

                best = False
                if self.mnt_mode != "off":
                    try:
                        improved = (
                            self.mnt_mode == "min"
                            and log[self.mnt_metric] <= self.mnt_best
                        ) or (
                            self.mnt_mode == "max"
                            and log[self.mnt_metric] >= self.mnt_best
                        )
                    except KeyError:
                        if dist.is_main_process():
                            self.logger.warning(
                                "Warning: Metric '%s' is not found. Model "
                                "performance monitoring is disabled.",
                                self.mnt_metric,
                            )
                        self.mnt_mode = "off"
                        improved = False

                    if improved:
                        self.mnt_best = log[self.mnt_metric]
                        not_improved_count = 0
                        best = True
                    else:
                        not_improved_count += 1

                if preemption.sync_requested():
                    # any host got SIGTERM: checkpoint NOW (regardless of
                    # save_period) and stop everywhere together — resume
                    # loses at most the in-flight epoch (utils/preemption.py)
                    if dist.is_main_process():
                        self.logger.warning(
                            "Preemption signal received; saving checkpoint "
                            "at epoch %d and stopping.", epoch,
                        )
                    self._save_checkpoint(epoch, save_best=best)
                    break

                if epoch % self.save_period == 0:
                    self._save_checkpoint(epoch, save_best=best)

                if (self.mnt_mode != "off"
                        and not_improved_count > self.early_stop):
                    if dist.is_main_process():
                        self.logger.info(
                            "Validation performance didn't improve for %s "
                            "epochs. Training stops.", self.early_stop,
                        )
                    break
        except Exception as exc:
            # unhandled-exception emergency checkpoint (resilience
            # subsystem): land the live state + data_state before the
            # process dies, so the supervisor's relaunch resumes at the
            # exact next batch instead of the last periodic save. The
            # original exception always propagates.
            self._emergency_save(exc)
            raise
        finally:
            # stop the watchdog FIRST: no steps run past this point, and
            # the async checkpoint flush below can legitimately take
            # longer than the stall threshold
            watchdog = getattr(self, "watchdog", None)
            if watchdog is not None:
                watchdog.stop()
            if watchdog is not None:
                # the final flush can legitimately outlast the
                # supervisor's hang timeout; keep the external
                # heartbeat alive so a healthy finishing run is not
                # SIGKILLed mid-checkpoint-write
                with watchdog.heartbeat_keepalive():
                    self.ckpt_manager.wait()
            else:
                self.ckpt_manager.wait()
            trace = getattr(self, "trace", None)
            if trace is not None:
                trace.close()  # flush a still-open profiler window
            recorder = getattr(self, "recorder", None)
            if recorder is not None:
                recorder.close()
            if dist.is_main_process():
                # host-span timeline as a Chrome trace-event file
                # (chrome://tracing / Perfetto); complements the XLA
                # profiler's device capture in log_dir/profile
                try:
                    get_span_recorder().dump(
                        self.config.log_dir / "trace.json"
                    )
                except Exception:  # teardown diagnostics must not
                    self.logger.warning("could not write trace.json",
                                        exc_info=True)  # crash the run
            self._write_summary(log)
        return log

    def _write_summary(self, log: dict) -> None:
        """Machine-readable run outcome: ``summary.json`` in the run dir
        (final epoch's metrics, the monitored best, where it stopped).
        The reference's outcome lives only in info.log text; tooling around
        experiments (sweeps, dashboards, the relaunch loop) wants JSON."""
        if not dist.is_main_process() or not log:
            return
        try:
            import json

            summary = {
                **{k: (v if isinstance(v, int) else
                       float(v) if isinstance(v, float) else v)
                   for k, v in log.items()},
                "monitor": f"{self.mnt_mode} {self.mnt_metric}"
                           if self.mnt_mode != "off" else "off",
                # +/-inf means "no epoch ever improved" (e.g. NaN metrics);
                # json.dumps would emit non-standard Infinity, so map to None.
                "monitor_best": (
                    float(self.mnt_best)
                    if self.mnt_mode != "off" and math.isfinite(self.mnt_best)
                    else None
                ),
                "run_dir": str(self.config.save_dir),
            }
            (self.config.save_dir / "summary.json").write_text(
                json.dumps(summary, indent=2)
            )
        except Exception:  # never let bookkeeping kill a finished run
            self.logger.warning("could not write summary.json",
                                exc_info=True)

    def _save_checkpoint(self, epoch: int, save_best: bool = False) -> None:
        raise NotImplementedError

    # -- resilience: emergency save + data_state sidecar --------------------

    def _data_state_snapshot(self) -> Optional[dict]:
        """The step-accurate-resume sidecar for the state being saved:
        where the NEXT batch after this checkpoint lives (epoch +
        batch ordinal, normalized past epoch edges), plus the sampler
        cursor and an RNG fingerprint for forensics. None when the
        trainer has no cursor yet (nothing ran)."""
        if self._cursor is None:
            return None
        epoch, next_batch = self._cursor
        len_epoch = int(getattr(self, "len_epoch", 0) or 0)
        if len_epoch and next_batch >= len_epoch:
            epoch, next_batch = epoch + 1, 0
        ds = {
            "epoch": int(epoch),
            "next_batch": int(next_batch),
            "len_epoch": len_epoch,
        }
        state = getattr(self, "state", None)
        if state is not None:
            try:
                import jax as _jax

                ds["global_step"] = int(_jax.device_get(state.step))
                key_bytes = np.asarray(
                    _jax.device_get(_jax.random.key_data(state.rng))
                ).tobytes()
                ds["rng_fingerprint"] = hashlib.sha256(
                    key_bytes).hexdigest()[:12]
            except Exception:  # sidecar forensics must not block a save
                pass
        loader = getattr(self, "train_loader", None)
        if loader is not None:
            ds["batch_size"] = int(getattr(loader, "batch_size", 0))
            sampler = getattr(loader, "sampler", None)
            if sampler is not None and hasattr(sampler, "state"):
                ds["sampler"] = sampler.state()
            else:
                ds["shuffle"] = bool(getattr(loader, "shuffle", False))
                ds["data_seed"] = int(getattr(loader, "seed", 0))
        return ds

    def _emergency_save(self, exc: Exception) -> None:
        """Best-effort checkpoint on the unhandled-exception path.

        Skipped when (a) disabled (``trainer.emergency_checkpoint:
        false``), (b) the exception IS a checkpoint-write fault
        (re-entering the failing checkpointer would double-fault), or
        (c) there is no state yet. Never raises — the original
        exception is the story, this is just the save of what survives
        it."""
        if not bool(self.config["trainer"].get("emergency_checkpoint",
                                               True)):
            return
        if getattr(exc, "is_checkpoint_fault", False):
            self.logger.warning(
                "Emergency checkpoint SKIPPED: the failure is the "
                "checkpoint path itself (%s).", exc,
            )
            return
        state = getattr(self, "state", None)
        model = getattr(self, "model", None)
        if state is None or self._cursor is None:
            return
        try:
            self.ckpt_manager.save_emergency(
                epoch=self._cursor[0],
                state=state,
                arch=type(model).__name__ if model is not None else "?",
                config=dict(self.config.config),
                monitor_best=(
                    self.mnt_best
                    if isinstance(self.mnt_best, (int, float)) else 0.0
                ),
                data_state=self._data_state_snapshot(),
            )
            self.logger.warning(
                "Emergency checkpoint saved after %s: %s",
                type(exc).__name__, exc,
            )
        except Exception:  # noqa: BLE001 — never mask the original error
            self.logger.warning(
                "Emergency checkpoint failed (original error propagates)",
                exc_info=True,
            )


class Trainer(BaseTrainer):
    """Concrete trainer (reference trainer/trainer.py:11-123), jit-compiled.

    :param model: a flax module from the MODELS registry.
    :param criterion: per-example loss ``(output, target) -> [B]``.
    :param metric_ftns: list of per-example metric fns.
    :param config: ConfigParser.
    :param train_loader / valid_loader: ArrayDataLoader-compatible.
    :param len_epoch: if given, iteration-based training over an endless
        loader (reference trainer.py:21-27).
    :param mesh: device mesh; built from config when None.
    """

    def __init__(self, model, criterion, metric_ftns, config,
                 train_loader, valid_loader=None, len_epoch: Optional[int] = None,
                 mesh=None, seed: int = 0):
        super().__init__(config)
        configure_debug(config["trainer"].get("debug"))
        # deterministic fault plan (resilience/faults): PDT_FAULTS env
        # wins over the ``trainer.faults`` config string; installed per
        # trainer build so one-shot faults re-arm for each fresh run
        faults.install_from_env_or_config(
            config["trainer"].get("faults")
        )
        # loader_raise targets the TRAIN input pipeline specifically —
        # the validation loader reaching the same batch ordinal first
        # must not consume the one-shot spec
        faults.watch_loader(train_loader)
        self._seed = int(seed)
        self.mesh = mesh if mesh is not None else mesh_from_config(config)
        model = inject_mesh(model, self.mesh)
        self.model = model
        self.criterion = criterion
        self.metric_ftns = list(metric_ftns)

        self.train_loader = train_loader
        tok_path = getattr(train_loader, "tokenizer_path", None)
        if tok_path is not None and dist.is_main_process():
            # pin the run's tokenizer IN the run dir: the corpus-side
            # cache is keyed by (file, vocab, train fraction) and a
            # later run can rewrite it, but generate.py must round-trip
            # prompts through the merges THIS run's embeddings saw
            # (data/tokenizer.tokenizer_from_config prefers this copy)
            import shutil

            try:
                shutil.copyfile(tok_path,
                                self.checkpoint_dir / "tokenizer.json")
            except OSError as e:  # non-fatal: corpus cache still works
                self.logger.warning("could not pin tokenizer: %s", e)
        if len_epoch is None:
            # config-level opt-in to iteration-based training (the
            # reference enables it by passing len_epoch to its Trainer;
            # here `trainer.len_epoch` in the JSON reaches the CLI path)
            len_epoch = config["trainer"].get("len_epoch")
        if len_epoch is None:
            self.len_epoch = len(train_loader)
            self._train_iter = None
        else:
            self.len_epoch = int(len_epoch)
            self._train_iter = iter(_endless_reshuffling(train_loader))
        self.valid_loader = valid_loader
        self.do_validation = valid_loader is not None
        self.log_step = max(int(np.sqrt(train_loader.batch_size)), 1)

        dk = config.get("data_keys", {}) or {}
        self.input_key = dk.get("input", "image")
        self.target_key = dk.get("target", "label")

        # --- optimizer + schedule (per-step, epoch-indexed; optim.py) ------
        self.tx, self.lr_fn, self.plateau = build_optimizer(
            config, self.len_epoch
        )

        # --- state init + placement (multi-host-legal jit creation; see
        # engine/state.create_sharded_train_state) --------------------------
        ema_decay = float(config["trainer"].get("ema_decay", 0.0))
        template = train_loader.arrays[self.input_key][:1]
        self._device_transform = getattr(
            train_loader, "device_transform", None
        )
        if self._device_transform is not None:
            # init must trace the model with the dtype it will actually
            # see (e.g. float32 after on-device uint8 normalization)
            template = np.asarray(
                self._device_transform({self.input_key: template})[
                    self.input_key
                ]
            )
        self.state, self.state_sharding = create_sharded_train_state(
            model, self.tx, template,
            self.mesh, seed=seed, with_ema=ema_decay > 0,
        )
        self.batch_sharding = batch_sharding(self.mesh)
        if dist.is_main_process():
            self.logger.info(describe(model, self.state.params))

        # --- resume (reference base_trainer.py:48-49,134-163) -------------
        if config.resume is not None:
            self.state, self.start_epoch, restored_best = (
                self.ckpt_manager.restore(
                    config.resume, self.state, config.config,
                    type(model).__name__,
                )
            )
            if restored_best is not None:
                self.mnt_best = restored_best
            # step-accurate resume (resilience subsystem): the
            # data_state sidecar overrides the epoch-granular
            # ``meta.epoch + 1`` with the exact (epoch, next_batch)
            # the checkpointed state stopped at
            if bool(config["trainer"].get("step_accurate_resume", True)):
                self._apply_data_state(
                    CheckpointManager.load_data_state(config.resume)
                )
        elif config["trainer"].get("init_from"):
            # params-only warm start (``trainer.init_from`` in the JSON or
            # --set): graft matching param leaves from a checkpoint into
            # the fresh state — the transfer/LoRA-fine-tune primitive.
            # Unlike resume, optimizer state and epoch restart from zero.
            from ..checkpoint import warm_start_params

            params, restored, skipped = warm_start_params(
                config["trainer"]["init_from"], self.state.params
            )
            self.state = self.state.replace(
                params=params,
                # EMA shadows start at the warm-started weights, not at
                # the discarded fresh init (leaves are immutable jax
                # Arrays — sharing them is safe)
                **({"ema_params": params}
                   if self.state.ema_params is not None else {}),
            )
            self.logger.info(
                "Warm start from %s: %d param tensors restored, %d kept "
                "their init%s", config["trainer"]["init_from"],
                len(restored), len(skipped),
                (" (e.g. " + ", ".join(skipped[:3]) + ")") if skipped
                else "",
            )

        # host-side mirror of state.lr_scale (plateau LR control; survives
        # resume via the checkpointed state)
        replicated = jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec()
        )
        self._replicate = jax.jit(lambda x: x, out_shardings=replicated)
        self._lr_scale_host = (
            float(jax.device_get(self.state.lr_scale))
            if self.state.lr_scale is not None else 1.0
        )
        if self.plateau is not None:
            self.plateau.scale = self._lr_scale_host
        self._plateau_warned = False

        # --- compile the hot loop -----------------------------------------
        grad_clip = config["trainer"].get("grad_clip_norm", 0.0)
        grad_accum = int(config["trainer"].get("grad_accum_steps", 1))
        self.skip_nonfinite = bool(
            config["trainer"].get("skip_nonfinite", False)
        )
        self.log_grad_norm = bool(
            config["trainer"].get("log_grad_norm", False)
        )
        # --- health summary (observability/health): a few scalar
        # reductions compiled INTO the step; fetched one step deferred,
        # so detection never syncs the dispatch pipeline ---------------
        health_cfg = config["trainer"].get("health", {}) or {}
        self._health_enabled = bool(health_cfg.get("enabled", True))
        self._health_keys = (
            health_metric_keys(self.state.params)
            if self._health_enabled else []
        )
        train_step = make_train_step(
            model, self.tx, criterion, self.metric_ftns,
            input_key=self.input_key, target_key=self.target_key,
            grad_clip_norm=grad_clip, grad_accum_steps=grad_accum,
            ema_decay=ema_decay, skip_nonfinite=self.skip_nonfinite,
            augment=build_augment(config["trainer"].get("augment")),
            mixup_alpha=float(config["trainer"].get("mixup_alpha", 0.0)),
            log_grad_norm=self.log_grad_norm,
            trainable_patterns=config["optimizer"].get("args", {}).get(
                "trainable"
            ),
            health=self._health_enabled,
            # in-graph deterministic fault (nan_grad@step:N), or None
            inject_nan_grad_step=faults.nan_grad_step(),
        )
        metric_sharding = jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec()
        )
        train_keys = self._metric_keys() + (
            ["skipped_sum"] if self.skip_nonfinite else []
        ) + (["grad_norm_sum"] if self.log_grad_norm else []
             ) + self._health_keys
        train_step_jit = jax.jit(
            train_step,
            donate_argnums=0,
            out_shardings=(self.state_sharding,
                           {k: metric_sharding for k in train_keys}),
        )
        eval_step = make_eval_step(
            model, criterion, self.metric_ftns,
            input_key=self.input_key, target_key=self.target_key,
            use_ema=ema_decay > 0
            and bool(config["trainer"].get("eval_with_ema", True)),
        )
        eval_step_jit = jax.jit(
            eval_step,
            out_shardings={
                k: metric_sharding for k in self._metric_keys()
            },
        )

        # --- background AOT warmup (engine/warmup.py): compile the steps
        # from abstract batches on a thread NOW, overlapping the rest of
        # init + first-epoch data startup, so step 1 dispatches a ready
        # executable instead of paying trace+compile inline. Any failure
        # degrades to the lazy jit path (warmup.result -> None). --------
        self._warmup = None
        if bool(config["trainer"].get("aot_warmup", True)):
            from .warmup import StepWarmup, abstract_batch

            try:
                warmup = StepWarmup()
                warmup.add(
                    "train_step", train_step_jit, self.state,
                    abstract_batch(train_loader, self.batch_sharding,
                                   transform=self._device_transform),
                )
                if valid_loader is not None:
                    warmup.add(
                        "eval_step", eval_step_jit, self.state,
                        abstract_batch(
                            valid_loader, self.batch_sharding,
                            transform=getattr(valid_loader,
                                              "device_transform", None),
                        ),
                    )
                self._warmup = warmup.start()
            except Exception:  # noqa: BLE001 — warmup is best-effort
                self.logger.warning(
                    "could not start AOT warmup; steps compile lazily",
                    exc_info=True,
                )
        self._train_step = instrument_step(
            train_step_jit, "train_step", warmup=self._warmup
        )
        self._eval_step = instrument_step(
            eval_step_jit, "eval_step", warmup=self._warmup
        )

        self.train_metrics = MetricTracker("loss", writer=self.writer)
        self.valid_metrics = MetricTracker(
            "loss", *[m.__name__ for m in self.metric_ftns], writer=self.writer
        )

        # --- profiling (SURVEY.md §5 tracing tier; reference had only the
        # steps_per_sec scalar) ---------------------------------------------
        prof_cfg = config["trainer"].get("profiler", {}) or {}
        self.profile_enabled = bool(prof_cfg.get("enabled", False))
        self.throughput = ThroughputMeter()          # log_step windows (TB)
        self.epoch_meter = ThroughputMeter()         # whole-epoch averages
        self.trace = TraceCapture(
            config.log_dir,
            start_step=prof_cfg.get("trace_start_step", 10),
            num_steps=prof_cfg.get("trace_steps", 0),
        )
        self._peak_flops = prof_cfg.get("peak_flops_per_device")
        self._flops_per_step = None  # measured lazily on the first batch
        # step anatomy (ISSUE 16): kernel-class roofline analysis of the
        # compiled train step, sharing the first-step AOT compile with
        # the FLOPs probe; rendered against the live steps/s each log
        # window (train_step_anatomy flight-record field)
        self._train_anatomy = None
        # latch: the first-step meter reset (+ the profiler's one-time
        # AOT cost analysis) runs at most once per process
        self._first_step_timed = False
        # host->device transfer pipeline depth (data/loader.
        # prefetch_to_device): 2 double-buffers; deeper hides burstier
        # host gathers at the cost of depth x batch bytes of HBM
        self.prefetch_depth = max(
            int(config["trainer"].get("prefetch_depth", 2)), 1
        )

        # --- flight recorder (observability/telemetry): one structured
        # JSONL record per step in <run_dir>/telemetry.jsonl on process 0,
        # ring-buffered in memory everywhere (the watchdog's stall dump
        # reads the ring) -----------------------------------------------
        tel_cfg = config["trainer"].get("telemetry", {}) or {}
        self.recorder = FlightRecorder(
            run_dir=(self.checkpoint_dir
                     if dist.is_main_process()
                     and bool(tel_cfg.get("enabled", True)) else None),
            capacity=int(tel_cfg.get("capacity", 512)),
            memory_every=int(tel_cfg.get("memory_every", 16)),
        )
        # anomaly detection over the deferred health summaries; dumps
        # (anomaly_<step>.json) on process 0 only, detection everywhere
        self.health = HealthMonitor(
            health_cfg, recorder=self.recorder,
            spans=get_span_recorder(),
            log_dir=(config.log_dir if dist.is_main_process() else None),
            layout=health_layout(self.state.params),
        )
        # per-log-window host stats exchange + straggler flag (no-op
        # collective single-host; auto-enabled on multi-host jobs)
        self.crosshost = CrossHostAggregator(
            tel_cfg.get("crosshost"), is_main=dist.is_main_process()
        )
        # runtime-triggered profiling (SIGUSR2 in train.py) notes its
        # captures on the flight-recorder timeline
        self.trace.attach_recorder(self.recorder)
        # tokens/step for LM data (integer [B, T] inputs): feeds the
        # per-record tokens field and the tokens/s aggregate. Exactly
        # rank 2 — integer image arrays (uint8 [B, H, W, C]) are not
        # token streams and must not emit a fake tokens_per_sec
        arr = train_loader.arrays.get(self.input_key)
        dtype = getattr(arr, "dtype", None)
        shape = getattr(arr, "shape", ())
        self._tokens_per_example = (
            int(shape[1])
            if dtype is not None and np.issubdtype(dtype, np.integer)
            and len(shape) == 2 else None
        )

        # hung-step detection (utils/watchdog.py); 0 disables. Wired to
        # the telemetry tier: a stall dumps active spans + the trailing
        # step records next to the faulthandler stacks.
        self.watchdog = StepWatchdog(
            timeout_s=float(config["trainer"].get("watchdog_secs", 0)),
            recorder=self.recorder,
            spans=get_span_recorder(),
            # file dump on process 0 only (same gating as the recorder's
            # JSONL above): hosts sharing a log dir must not race on one
            # stall_dump.json; every host still dumps stacks to stderr
            dump_path=(config.log_dir / "stall_dump.json"
                       if dist.is_main_process() else None),
            # supervisor liveness: the same beat the stall monitor uses
            # also touches the heartbeat file the resilience supervisor
            # watches from outside (PDT_HEARTBEAT_FILE exported by
            # scripts/supervise.py; trainer.heartbeat_file otherwise)
            heartbeat_path=(os.environ.get("PDT_HEARTBEAT_FILE")
                            or config["trainer"].get("heartbeat_file")),
        )

    def _metric_keys(self):
        return ["loss_sum", "count"] + [
            f"{m.__name__}_sum" for m in self.metric_ftns
        ]

    # -- resilience: step-accurate resume -----------------------------------

    def _apply_data_state(self, ds: Optional[dict]) -> None:
        """Turn a checkpoint's ``data_state`` sidecar into a mid-epoch
        resume point: ``start_epoch`` becomes the in-flight epoch and
        ``_batches`` fast-forwards its first epoch to ``next_batch``.
        Falls back (with a warning) to the epoch-granular semantics
        when the sidecar is absent, the run is iteration-based
        (endless loader: batch ordinals are not stable coordinates),
        or the data geometry changed under the checkpoint."""
        if not ds:
            return
        if self._train_iter is not None:
            self.logger.warning(
                "data_state present but len_epoch (iteration-based) "
                "training resumes at epoch granularity."
            )
            return
        if (int(ds.get("len_epoch", self.len_epoch)) != self.len_epoch
                or int(ds.get("batch_size",
                              self.train_loader.batch_size))
                != self.train_loader.batch_size):
            self.logger.warning(
                "data_state geometry mismatch (checkpoint len_epoch=%s/"
                "batch_size=%s vs current %s/%s); resuming at epoch "
                "granularity.", ds.get("len_epoch"), ds.get("batch_size"),
                self.len_epoch, self.train_loader.batch_size,
            )
            return
        epoch = int(ds.get("epoch", self.start_epoch))
        next_batch = int(ds.get("next_batch", 0))
        if next_batch >= self.len_epoch:  # normalized at save, but be safe
            epoch, next_batch = epoch + 1, 0
        self.start_epoch = epoch
        self._resume_next_batch = next_batch
        if next_batch and dist.is_main_process():
            self.logger.info(
                "Step-accurate resume: continuing epoch %d at batch %d "
                "(global step %s).", epoch, next_batch,
                ds.get("global_step", "?"),
            )

    # -- epoch loops --------------------------------------------------------

    def _batches(self, epoch: int):
        # mid-epoch fast-forward applies exactly once: to the resumed
        # epoch itself (the ordinal skip is exact because the epoch
        # permutation is a pure function of (seed, epoch))
        skip = (self._resume_next_batch
                if epoch == self.start_epoch else 0)
        if self._train_iter is not None:
            for i in range(self.len_epoch):
                yield i, next(self._train_iter)
        else:
            self.train_loader.set_epoch(epoch)
            if skip and hasattr(self.train_loader, "iter_batches"):
                it = self.train_loader.iter_batches(start_batch=skip)
            else:
                it = iter(self.train_loader)
                for _ in range(skip):  # generic-iterable fallback
                    next(it, None)
            yield from enumerate(it, start=skip)

    def _train_epoch(self, epoch: int) -> dict:
        self.train_metrics.reset()
        self.health.epoch_start()  # promotion pause is epoch-scoped
        self.throughput.reset()  # exclude validation/checkpoint wall time
        self.epoch_meter.reset()  # (epoch 1 includes compile unless the
        # profiler's post-compile reset fires; later epochs are clean)
        accum = None
        batches = (b for _, b in self._batches(epoch))
        depth = int(self.config["trainer"].get("host_prefetch", 2))
        if depth > 0:
            batches = host_prefetch(batches, depth)
        prefetched = prefetch_to_device(batches, self.batch_sharding,
                                        size=self.prefetch_depth,
                                        transform=self._device_transform)
        main = dist.is_main_process()
        if main:
            # reference trainer/trainer.py:45 wraps the hot loop in tqdm;
            # auto-gated on a TTY (or trainer.progress true/false)
            prefetched = maybe_tqdm(
                prefetched, total=self.len_epoch,
                desc=f"train {epoch}",
                enable=self.config["trainer"].get("progress"),
            )
        # Mid-epoch preemption polling: the SIGTERM notice window (~30s on
        # cloud TPUs) is far shorter than an ImageNet epoch, so waiting for
        # the epoch edge would forfeit the save. Single-host polls the free
        # local flag every batch; multi-host polls the consensus collective
        # every preempt_check_steps batches so every host breaks at the
        # SAME batch (a lone early exit would hang peers' collectives).
        check_every = max(
            int(self.config["trainer"].get("preempt_check_steps", 100)), 1
        )
        single_host = dist.process_count() == 1
        preempted = False  # consensus result: identical on every host
        # idempotent; trainer.watchdog_secs must exceed the first-step
        # compile time or epoch 1 will false-alarm
        self.watchdog.start()
        batches_it = iter(prefetched)
        # resumed mid-epoch: batch ordinals continue from the resume
        # point (the generator under `batches` already fast-forwarded)
        start_batch = (self._resume_next_batch
                       if epoch == self.start_epoch else 0)
        self._cursor = (epoch, start_batch)
        batch_idx = start_batch - 1
        # Sync-free stepping: log-step metric fetches are DEFERRED by one
        # log window. The entry enqueued at step N is completed at step
        # N + log_step, when its device buffers have long resolved — so
        # the host never float()-blocks on the step it just dispatched
        # (the old per-log-step pipeline bubble). Holds at most one
        # entry (a handful of scalar metric buffers).
        pending_log = deque()
        t_iter = time.perf_counter()
        while True:
            # data-wait = time blocked on the prefetch pipeline; near
            # zero when prefetch hides the gather, the whole step time
            # when the loader is the bottleneck — the telemetry field
            # that answers "is this run input-bound?"
            t_wait = time.perf_counter()
            with span("data/next_batch"):
                try:
                    batch = next(batches_it)
                except StopIteration:
                    break
            data_wait_ms = (time.perf_counter() - t_wait) * 1e3
            batch_idx += 1
            step = (epoch - 1) * self.len_epoch + batch_idx
            # deterministic fault hook (resilience/faults): slow_host /
            # crash / kill fire HERE, before the step dispatches, so
            # kill@step:N means exactly N completed steps
            faults.on_step(step)
            self.trace.before_step(step)
            with span("train/step", step=step):
                self.state, m = self._train_step(self.state, batch)
            # the dispatched step completes on-device even if the host
            # dies after this point: the cursor counts it done
            self._cursor = (epoch, batch_idx + 1)
            self.trace.after_step(step, sync=m)
            self.watchdog.beat()
            if self._health_keys:
                # strip the health scalars out of the epoch accumulator
                # (they are per-step signals, not sufficient statistics)
                # and hand them to the monitor, which fetches them one
                # step deferred — no sync on the step just dispatched
                hm = {k: m.pop(k) for k in self._health_keys if k in m}
                self.health.enqueue(
                    step, hm,
                    meta={"epoch": epoch, "batch_idx": batch_idx},
                )
            self.throughput.update(self.train_loader.batch_size)
            self.epoch_meter.update(self.train_loader.batch_size)
            # per-step flight record; wall_ms is the full loop iteration
            # (dispatch + donation backpressure + data wait), so summed
            # wall time over a window is the honest steps/s denominator
            rec = {
                "wall_ms": round((time.perf_counter() - t_iter) * 1e3, 3),
                "data_wait_ms": round(data_wait_ms, 3),
                "examples": self.train_loader.batch_size,
            }
            t_iter = time.perf_counter()
            if self._tokens_per_example:
                rec["tokens"] = (self._tokens_per_example
                                 * self.train_loader.batch_size)

            if not self._first_step_timed:
                # The run's first step carries the compile (or the AOT
                # warm-install) cost: exclude it from steady-state
                # meters UNCONDITIONALLY — this used to happen only
                # under the profiler, so unprofiled runs reported a
                # steps_per_sec that silently averaged in the compile
                # step. (Keyed on the latch alone, not batch_idx == 0:
                # a step-accurate resume enters mid-epoch, where the
                # first — compiling — step has a nonzero ordinal.)
                self._first_step_timed = True
                if self.profile_enabled:
                    # ONE AOT lower+compile of the step feeds both the
                    # FLOPs probe and the kernel-class anatomy; the
                    # latch stays set even when the backend reports no
                    # FLOPs
                    compiled = None
                    try:
                        compiled = self._train_step.lower(
                            self.state, batch).compile()
                    except Exception:  # noqa: BLE001 — profiling must
                        pass           # never break the step loop
                    if compiled is not None:
                        self._flops_per_step = executable_flops(
                            compiled)
                        if anatomy_enabled():
                            self._train_anatomy = analyze_compiled(
                                compiled)
                jax.block_until_ready(m)
                self.throughput.reset()  # exclude compilation from rates
                self.epoch_meter.reset()

            accum = m if accum is None else jax.tree.map(jnp.add, accum, m)

            if self.crosshost.should_exchange(batch_idx, self.log_step):
                # EVERY host reaches this collective at the same batch
                # (deterministic condition); only process 0 attaches the
                # aggregate to its record
                agg = self.crosshost.exchange(
                    self.recorder.last(self.log_step)
                )
                if agg is not None and main:
                    rec["hosts"] = agg["hosts"]
                    if "wall_spread" in agg:
                        rec["wall_spread"] = agg["wall_spread"]
                    if agg.get("straggler"):
                        rec["straggler"] = True
                        rec["straggler_hosts"] = agg["straggler_hosts"]

            if main and batch_idx % self.log_step == 0:
                # deferred fetch: complete the PREVIOUS log window's
                # entry (its step finished while this window's steps
                # dispatched), enqueue this one; only the TB image grid
                # needs the live batch, so it logs at enqueue time.
                # Compile events drain NOW so this step's own compile
                # (the lazy first-step case) rides under its own step
                # id, not whichever record happens to flush next
                if pending_log:
                    self._flush_log_entry(pending_log.popleft())
                events = drain_compile_events()
                if events:
                    rec["compile_events"] = events
                self.writer.set_step(step)
                self._log_input_images(batch)
                pending_log.append((step, epoch, batch_idx, m, rec))
            else:
                self.recorder.record(step, **rec)

            if ((single_host or (batch_idx + 1) % check_every == 0)
                    and preemption.sync_requested()):
                preempted = True
                if main:
                    self.logger.warning(
                        "Preemption signal: breaking epoch %d at batch %d "
                        "(partial epoch will be checkpointed).",
                        epoch, batch_idx + 1,
                    )
                break

            if (self.save_interval_steps
                    and (batch_idx + 1) % self.save_interval_steps == 0):
                # A/B-slot async save: the step loop continues while the
                # write flushes in the background (no wait() here)
                self.ckpt_manager.save_interval(
                    epoch=epoch, step=batch_idx + 1, state=self.state,
                    arch=type(self.model).__name__,
                    config=dict(self.config.config),
                    monitor_best=(
                        self.mnt_best
                        if isinstance(self.mnt_best, (int, float)) else 0.0
                    ),
                    data_state=self._data_state_snapshot(),
                )
                if main:
                    self.logger.info(
                        "Interval checkpoint at epoch %d batch %d.",
                        epoch, batch_idx + 1,
                    )

        while pending_log:
            # drain the deferred log entry (epoch end syncs anyway via
            # finalize_metrics below, so this fetch costs nothing extra)
            self._flush_log_entry(pending_log.popleft())
        self.health.drain()  # observe the last step's deferred summary

        log = (
            finalize_metrics(jax.tree.map(float, accum)) if accum else {}
        )
        # whole-epoch throughput (the finalize_metrics float() above synced
        # the device, so the window is honest); + MFU when the profiler
        # measured the compiled step's FLOPs
        if log:
            rate = self.epoch_meter.rate()
            log["examples_per_sec"] = round(rate["examples_per_sec"], 1)
            util = mfu(self._flops_per_step, rate["steps_per_sec"],
                       peak_per_device=self._peak_flops)
            if util is not None:
                log["mfu"] = round(util, 4)
        # Keep the tracker's smoothed loss for TB parity, but report the
        # exact global epoch averages. A preempted epoch skips validation —
        # the SIGTERM notice window is for checkpointing, not eval.
        if self.do_validation and not preempted:
            with span("train/validate", epoch=epoch):
                val_log = self._valid_epoch(epoch)
            log.update(**{f"val_{k}": v for k, v in val_log.items()})
        # a preempted epoch skipped validation, so the monitored key is
        # legitimately absent — not a plateau decision and not a misconfig
        if self.plateau is not None and not preempted:
            self._plateau_step(log)
        return log

    def _flush_log_entry(self, entry) -> None:
        """Complete one deferred log-step record (sync-free stepping).

        Called one log window after the entry's step was dispatched —
        by then ``log_step`` further steps have been queued behind it,
        so ``jax.device_get`` reads already-resolved buffers instead of
        blocking the dispatch pipeline on the newest step (the old
        ``float()``-per-log-step host sync). The entry's flight record
        lands in the JSONL one window late but under its own step id;
        window throughput is dispatch-rate (bounded-queue steady state
        tracks completion rate; epoch numbers still come from the
        synced ``finalize_metrics`` path).
        """
        step, epoch, batch_idx, m, rec = entry
        with span("train/log", step=step):
            m = jax.device_get(m)
            self.writer.set_step(step)
            loss_val = (float(m["loss_sum"])
                        / max(float(m["count"]), 1.0))
            self.train_metrics.update("loss", loss_val)
            lr_val = float(self.lr_fn(step)) * self._lr_scale_host
            self.writer.add_scalar("lr", lr_val)
            rec["loss"] = round(loss_val, 6)
            rec["lr"] = lr_val
            if self.log_grad_norm:
                rec["grad_norm"] = round(
                    float(m["grad_norm_sum"])
                    / max(float(m["count"]), 1.0), 6,
                )
            if self.profile_enabled and step > 0:
                rate = self.throughput.rate()
                self.writer.add_scalar(
                    "examples_per_sec", rate["examples_per_sec"]
                )
                rec["steps_per_sec"] = round(
                    rate["steps_per_sec"], 4)
                rec["examples_per_sec"] = round(
                    rate["examples_per_sec"], 1)
                if self._tokens_per_example:
                    rec["tokens_per_sec"] = round(
                        rate["examples_per_sec"]
                        * self._tokens_per_example, 1)
                util = mfu(self._flops_per_step,
                           rate["steps_per_sec"],
                           peak_per_device=self._peak_flops)
                if util is not None:
                    self.writer.add_scalar("mfu", util)
                    rec["mfu"] = round(util, 4)
                if (self._train_anatomy is not None
                        and rate["steps_per_sec"] > 0):
                    # kernel-class anatomy against this window's
                    # measured step wall; the offline analyzer reads
                    # the LAST record carrying the field
                    rec["train_step_anatomy"] = _render_anatomy(
                        self._train_anatomy,
                        wall_ms=1e3 / rate["steps_per_sec"])
            self.logger.debug(
                "Train Epoch: %d %s Loss: %.6f",
                epoch, self._progress(batch_idx + 1), loss_val,
            )
        hc = health_counters()
        if hc["anomaly_total"]:
            rec["anomaly_total"] = hc["anomaly_total"]
        if hc["straggler_windows_total"]:
            rec["straggler_windows_total"] = hc["straggler_windows_total"]
        self.recorder.record(step, **rec)

    def _plateau_step(self, log: dict) -> None:
        """Per-epoch ReduceLROnPlateau update of ``state.lr_scale``.

        Runs identically on every host (epoch metrics are global
        reductions), so the replicated scalar stays consistent without a
        collective. The jit identity makes the new value a born-global
        array (legal multi-host, like create_sharded_train_state).
        """
        value = log.get(self.plateau.monitor)
        if value is None:
            # typo'd monitor key or validation disabled: say so once instead
            # of silently training at full LR forever (mirrors the trainer's
            # monitor-metric-not-found warning)
            if not self._plateau_warned and dist.is_main_process():
                self.logger.warning(
                    "Warning: ReduceLROnPlateau monitor '%s' not found in "
                    "epoch metrics %s; plateau LR scheduling is inactive.",
                    self.plateau.monitor, sorted(log),
                )
            self._plateau_warned = True
            return
        # NaN/inf flows into the controller: comparisons with NaN are False,
        # so it counts as a bad epoch — exactly torch's behavior (and the
        # LR drop it triggers is often what rescues a diverging run)
        new_scale = self.plateau.step(float(value))
        if new_scale != self._lr_scale_host:
            if dist.is_main_process():
                self.logger.info(
                    "ReduceLROnPlateau: %s did not improve for %d epochs; "
                    "lr scale %.3g -> %.3g",
                    self.plateau.monitor, self.plateau.patience + 1,
                    self._lr_scale_host, new_scale,
                )
            self._lr_scale_host = new_scale
            self.state = self.state.replace(
                lr_scale=self._replicate(np.float32(new_scale))
            )

    def _valid_epoch(self, epoch: int) -> dict:
        """Validation with in-graph global reduction (vs reference's pickle
        gather of the full prediction set, trainer.py:75-88)."""
        self.valid_metrics.reset()
        if hasattr(self.valid_loader, "set_epoch"):
            self.valid_loader.set_epoch(epoch)
        accum = None
        val_batches = prefetch_to_device(
            self.valid_loader, self.batch_sharding,
            size=self.prefetch_depth,
            transform=getattr(self.valid_loader, "device_transform", None),
        )
        if dist.is_main_process():
            val_batches = maybe_tqdm(
                val_batches, total=len(self.valid_loader),
                desc=f"valid {epoch}",
                enable=self.config["trainer"].get("progress"),
            )
        for batch in val_batches:
            m = self._eval_step(self.state, batch)
            accum = m if accum is None else jax.tree.map(jnp.add, accum, m)
            self.watchdog.beat()
        result = finalize_metrics(jax.tree.map(float, accum)) if accum else {}
        if dist.is_main_process():
            self.writer.set_step(epoch * self.len_epoch, mode="valid")
            for k, v in result.items():
                self.valid_metrics.update(k, v)
        return result

    # -- checkpointing ------------------------------------------------------

    def _save_checkpoint(self, epoch: int, save_best: bool = False) -> None:
        if save_best and not self.health.promotion_allowed():
            # trainer.health.pause_best_promotion: an epoch that fired a
            # numerics anomaly does not crown model_best — its monitored
            # metric may be the artifact of the very step that fired
            save_best = False
            if dist.is_main_process():
                self.logger.warning(
                    "Health: anomaly at step %s this epoch; best-model "
                    "promotion skipped for epoch %d "
                    "(health.pause_best_promotion).",
                    self.health.last_anomaly_step, epoch,
                )
        self.ckpt_manager.save(
            epoch=epoch,
            state=self.state,
            arch=type(self.model).__name__,
            config=dict(self.config.config),
            monitor_best=(
                self.mnt_best if isinstance(self.mnt_best, (int, float)) else 0.0
            ),
            save_best=save_best,
            # completed epoch ⇒ (epoch+1, batch 0); preemption-cut
            # epoch ⇒ the exact mid-epoch next batch (the cursor knows)
            data_state=self._data_state_snapshot(),
        )
        keep = int(self.config["trainer"].get("keep_last", 0))
        if keep > 0:
            self.ckpt_manager.prune(keep)

    # -- misc ---------------------------------------------------------------

    def _log_input_images(self, batch) -> None:
        """TB input grid (reference trainer.py:69 make_grid) for image data."""
        x = batch.get(self.input_key)
        if x is None or x.ndim != 4 or self.writer.writer is None:
            return
        imgs = np.asarray(jax.device_get(x[:8])).astype(np.float32)
        lo, hi = imgs.min(), imgs.max()
        imgs = (imgs - lo) / max(hi - lo, 1e-6)
        grid = np.concatenate(list(imgs), axis=1)  # [H, 8*W, C]
        self.writer.add_image("input", grid, dataformats="HWC")

    def _progress(self, batch_idx: int) -> str:
        current = batch_idx * self.train_loader.batch_size
        total = getattr(self.train_loader, "n_samples", self.len_epoch)
        if self._train_iter is not None:
            current, total = batch_idx, self.len_epoch
        return f"[{current}/{total} ({100.0 * current / total:.0f}%)]"
