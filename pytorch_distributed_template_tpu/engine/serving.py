"""Shared serving-side loading: checkpoint/artifact -> (model, params).

The one place that knows how to turn ``config.resume`` into something
``generate()`` can run, used by both front-ends (the one-shot
``generate.py`` CLI and the ``serve.py`` HTTP server):

- a TRAINING checkpoint restores through the full TrainState template
  (optimizer slots and all — engine/evaluator.restore_template_state),
  honoring ``use_ema``;
- a params-only SERVING artifact (scripts/quantize_checkpoint.py,
  scripts/merge_lora.py) restores just the param tree, sharded over the
  mesh per the model's partition rules (multi-host-legal);
- the run's BPE tokenizer, when the experiment trained through
  ``BpeLMLoader``, rides along for text round-tripping.
"""
from __future__ import annotations

import logging

import jax

from ..checkpoint import load_serving_meta, restore_serving_params
from ..config.registry import MODELS
from ..data.tokenizer import tokenizer_from_config
from ..models.base import inject_mesh
from ..parallel import apply_rules, dist, mesh_from_config
from .evaluator import restore_template_state

logger = logging.getLogger(__name__)


class GenerationService:
    """The request-level generation entry shared by BOTH front-ends
    (generate.py one-shot CLI, serve.py HTTP server): prompt encoding +
    validation, speculative-vs-sampled dispatch, and text/ids decoding
    live HERE once — a fix in one front-end cannot miss the other.

    ``generate`` is serialized with a lock: one chip, one compiled
    decode path (harmless for the one-shot CLI, load-bearing for the
    threaded HTTP server).
    """

    def __init__(self, config, use_ema: bool = False):
        import threading

        self.model, self.params, self.tokenizer = load_generation_stack(
            config, use_ema=use_ema
        )
        self.vocab = int(getattr(self.model, "vocab_size", 0))
        self.arch = type(self.model).__name__
        self._lock = threading.Lock()

    def encode_prompt(self, prompt=None, prompt_ids=None) -> list:
        """Text or explicit ids -> validated id list (raises ValueError
        with a caller-presentable message on every bad input)."""
        if prompt_ids is not None:
            try:
                # TypeError (non-iterable payload, nested lists) is as
                # much a client input error as a bad value — normalize
                # to ValueError so serve.py maps it to HTTP 400, not 500.
                # Strings ("123" iterates to [1,2,3]) and non-integral
                # floats (1.9 truncates) would silently generate from
                # ids the client never sent — reject, don't coerce.
                if isinstance(prompt_ids, (str, bytes)):
                    raise ValueError("got a string, not a list")
                ids = []
                for i in prompt_ids:
                    # bool is an int subclass: true/false would coerce
                    # to ids 1/0 — same reject-don't-coerce class
                    if isinstance(i, bool) or int(i) != i:
                        raise ValueError(f"non-integer id {i!r}")
                    ids.append(int(i))
            except (TypeError, ValueError, OverflowError) as e:
                # OverflowError: json.loads accepts Infinity, and
                # int(inf) overflows — still a client input error
                raise ValueError(
                    f"prompt_ids must be a flat list of ints: {e}"
                ) from e
            if self.vocab and any(i >= self.vocab or i < 0 for i in ids):
                raise ValueError(
                    f"prompt id outside [0, {self.vocab}) — nn.Embed "
                    "would silently clamp/wrap it"
                )
        elif prompt is None:
            raise ValueError("pass a prompt or prompt ids")
        elif self.vocab <= 256:
            ids = list(str(prompt).encode("utf-8"))
            if any(i >= self.vocab for i in ids):
                raise ValueError(f"prompt byte >= vocab_size {self.vocab}")
        else:
            if self.tokenizer is None:
                raise ValueError(
                    f"vocab_size {self.vocab} > 256 and no BpeLMLoader "
                    "tokenizer found in the run config: pass prompt ids, "
                    "or train through BpeLMLoader for text round-tripping"
                )
            ids = [int(i) for i in self.tokenizer.encode(str(prompt))]
            if any(i >= self.vocab for i in ids):
                raise ValueError(
                    f"tokenizer id >= model vocab_size {self.vocab} — "
                    "the checkpoint and tokenizer disagree"
                )
        if not ids:
            raise ValueError("empty prompt (need at least one token)")
        return ids

    def decode_text(self, ids):
        """Generated ids -> text, when the model has a text form
        (byte vocab or a recovered tokenizer); else None."""
        import numpy as np

        ids = np.asarray(ids).reshape(-1)
        if self.vocab and self.vocab <= 256:
            return bytes(int(t) for t in ids).decode(
                "utf-8", errors="replace"
            )
        if self.tokenizer is not None:
            # replace (not raise) on ids past the learned vocab: BPE
            # training can stop short of the configured head size, and
            # an undertrained model may emit those ids
            return self.tokenizer.decode(ids, errors="replace")
        return None

    def generate(self, prompt=None, prompt_ids=None,
                 max_new_tokens: int = 64, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 0.0, seed: int = 0,
                 speculative: int = 0) -> dict:
        """One validated generation request ->
        ``{"ids", "text"?, "speculative"?}``."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from .generate import generate, generate_speculative

        if speculative > 0 and temperature > 0:
            raise ValueError(
                "speculative generation is greedy-exact; drop "
                "temperature (sampled speculative decoding is not "
                "implemented)"
            )
        ids = self.encode_prompt(prompt, prompt_ids)
        arr = jnp.asarray(np.asarray(ids, np.int32)[None, :])
        with self._lock:
            stats = None
            if speculative > 0:
                out, stats = generate_speculative(
                    self.model, self.params, arr,
                    max_new_tokens=int(max_new_tokens),
                    draft_len=int(speculative), return_stats=True,
                )
            else:
                out = generate(
                    self.model, self.params, arr,
                    max_new_tokens=int(max_new_tokens),
                    temperature=float(temperature), top_k=int(top_k),
                    top_p=float(top_p), rng=jax.random.key(int(seed)),
                )
        new = np.asarray(out[0, arr.shape[1]:])
        resp: dict = {"ids": [int(t) for t in new]}
        text = self.decode_text(new)
        if text is not None:
            resp["text"] = text
        if stats is not None:
            resp["speculative"] = stats
        return resp


def load_generation_stack(config, use_ema: bool = False):
    """``(model, params, tokenizer | None)`` for ``config.resume``."""
    assert config.resume is not None, "generation requires a checkpoint (-r)"
    dist.initialize()  # multi-host rendezvous parity with train.py/test.py
    mesh = mesh_from_config(config)
    model = inject_mesh(config.init_obj("arch", MODELS), mesh)
    if not hasattr(model, "max_len"):
        raise SystemExit(
            f"arch {type(model).__name__} has no decode support"
        )

    serving_meta = load_serving_meta(config.resume)
    if serving_meta is not None:
        # Params-only serving artifact: the artifact's config.json
        # already carries the serving arch args, so the model above IS
        # the serving model — restore its param tree directly; there is
        # no TrainState (and --ema is moot: the weight choice was baked
        # in at artifact-production time).
        if use_ema:
            logger.warning(
                "--ema ignored: %s is a params-only serving artifact "
                "(quantized/merged from %s)", config.resume,
                serving_meta.get("source_params", "params"),
            )
        template = jax.eval_shape(
            lambda: model.init(jax.random.key(0), model.batch_template(1))
        )["params"]
        # Restore sharded over the mesh per the model's partition rules
        # (the quant tree's kernel_q leaves match the same `/kernel`
        # rule patterns; scale vectors replicate). A host-local restore
        # + device_put would break on multi-host meshes.
        rules = (model.partition_rules()
                 if hasattr(model, "partition_rules") else [])
        params = restore_serving_params(
            config.resume, template, apply_rules(template, mesh, rules)
        )
    else:
        state, _ = restore_template_state(config, model, mesh)
        params = (
            state.ema_params
            if use_ema and state.ema_params is not None else state.params
        )
    return model, params, tokenizer_from_config(config)
