"""Shared serving-side loading: checkpoint/artifact -> (model, params).

The one place that knows how to turn ``config.resume`` into something
``generate()`` can run, used by both front-ends (the one-shot
``generate.py`` CLI and the ``serve.py`` HTTP server):

- a TRAINING checkpoint restores through the full TrainState template
  (optimizer slots and all — engine/evaluator.restore_template_state),
  honoring ``use_ema``;
- a params-only SERVING artifact (scripts/quantize_checkpoint.py,
  scripts/merge_lora.py) restores just the param tree, sharded over the
  mesh per the model's partition rules (multi-host-legal);
- the run's BPE tokenizer, when the experiment trained through
  ``BpeLMLoader``, rides along for text round-tripping.
"""
from __future__ import annotations

import logging

import jax

from ..checkpoint import load_serving_meta, restore_serving_params
from ..config.registry import MODELS
from ..data.tokenizer import tokenizer_from_config
from ..models.base import inject_mesh
from ..parallel import apply_rules, dist, mesh_from_config
from .evaluator import restore_template_state

logger = logging.getLogger(__name__)


class DeadlineExceeded(RuntimeError):
    """A request's ``X-Deadline-Ms`` budget expired before (or while)
    it could be served (ISSUE 9). serve.py maps this to HTTP 504 with
    the ``X-Deadline-Expired`` marker header; the continuous engine
    never raises it mid-flight (an expired decoding row finalizes
    with its partial tokens and ``stop_reason: "deadline"`` instead —
    truncation beats throwing work away)."""


class GenerationService:
    """The request-level generation entry shared by BOTH front-ends
    (generate.py one-shot CLI, serve.py HTTP server): prompt encoding +
    validation, speculative-vs-sampled dispatch, and text/ids decoding
    live HERE once — a fix in one front-end cannot miss the other.

    ``generate`` is serialized with a lock: one chip, one compiled
    decode path (harmless for the one-shot CLI, load-bearing for the
    threaded HTTP server).
    """

    def __init__(self, config, use_ema: bool = False,
                 tensor_parallel: int = 0, **kw):
        model, params, tokenizer = load_generation_stack(
            config, use_ema=use_ema, tensor_parallel=tensor_parallel
        )
        self._setup(model, params, tokenizer, **kw)

    @classmethod
    def from_model(cls, model, params, tokenizer=None, **kw):
        """Build a service around an already-loaded (model, params) —
        the bench rungs and scheduler tests construct services this
        way instead of going through checkpoint restore."""
        obj = cls.__new__(cls)
        obj._setup(model, params, tokenizer, **kw)
        return obj

    #: serving roles (disaggregated prefill/decode, ISSUE 12): a
    #: "prefill" replica computes prompt KV into its pool and SHIPS the
    #: pages (``prefill_export``) — it refuses decode-scale budgets; a
    #: "decode" replica ingests shipped pages (``import_remote_pages``)
    #: and serves decode; "both" (default) is the classic colocated
    #: replica, byte-identical to the pre-disaggregation stack.
    ROLES = ("both", "prefill", "decode")
    #: the largest budget a prefill-role replica serves on /generate:
    #: 1 token = prefill + first sample (health pokes, manual tests);
    #: anything longer is decode work the router mis-routed
    PREFILL_MAX_NEW = 1

    def _setup(self, model, params, tokenizer=None, prefix_cache=None,
               spec_draft_layers: int = 0, tracer=None, slo=None,
               role: str = "both"):
        import inspect
        import threading

        from ..utils.promtext import LatencyHistogram

        from ..parallel.tp import tp_degree

        self.model, self.params, self.tokenizer = model, params, tokenizer
        self.vocab = int(getattr(self.model, "vocab_size", 0))
        self.arch = type(self.model).__name__
        if role not in self.ROLES:
            raise ValueError(f"unknown serving role {role!r} "
                             f"(one of {self.ROLES})")
        self.role = role
        # TP serving (ISSUE 10): the mesh rides on the model
        # (load_generation_stack injects it); tp=1 keeps every path
        # byte-identical to the single-chip stack
        self._mesh = getattr(model, "mesh", None)
        self.tp = tp_degree(self._mesh)
        self._tp_stats = None
        self._tp_stats_lock = threading.Lock()
        # pad-capable = the model supports per-row left-pad masking
        # (RoPE families, non-rolling cache): enables mixed-length
        # micro-batching and length-bucketed speculative executables
        self._pad_ok = (
            "pad_lens" in inspect.signature(
                type(self.model).__call__).parameters
            and int(getattr(self.model, "window", 0) or 0) == 0
        )
        self._lock = threading.Lock()
        # batched prefill export (ISSUE 13 satellite — PR 12 documented
        # its batch-1-under-the-lock contract as the honest follow-on):
        # concurrent /prefill callers enqueue their chains and ONE
        # leader thread drains the queue under a single service-lock
        # acquisition, so a handoff burst queues behind one lock wait
        # instead of N of them
        self._export_mu = threading.Lock()       # guards the queue
        self._export_leader = threading.Lock()   # one processor
        self._export_q: list = []
        # paged KV prefix cache (engine/kvcache.py): either a prebuilt
        # PrefixCache or a ``serving.prefix_cache`` config dict. A
        # layout that cannot pool (rolling window, int8 KV, no
        # kv_cache_spec) disables LOUDLY instead of failing the load —
        # the operator asked for a server, not a cache
        self._prefix = None
        # pool-fallback observability (ISSUE 15 satellite): when the
        # pool REFUSES to construct, the machine-readable reason
        # (window / kv_quant / undersized / gpt2_layout) survives here
        # so /metrics can count the degradation instead of burying the
        # refusal string in logs
        self.pool_refusal_reason = ""
        if prefix_cache is not None:
            from .kvcache import PrefixCache

            if isinstance(prefix_cache, PrefixCache):
                self._prefix = prefix_cache
            elif dict(prefix_cache).get("enabled"):
                cfg = dict(prefix_cache)
                try:
                    self._prefix = PrefixCache(
                        model, params,
                        block_tokens=int(cfg.get("block_tokens", 32)),
                        pool_blocks=int(cfg.get("pool_blocks", 256)),
                        eviction=cfg.get("eviction", "lru"),
                        paged=bool(cfg.get("paged", True)),
                        # tiered spill hierarchy (ISSUE 13): 0 / None
                        # keeps the classic destroy-on-evict pool
                        host_spill_blocks=int(
                            cfg.get("host_spill_blocks", 0)),
                        disk_spill_dir=cfg.get("disk_spill_dir"),
                        disk_spill_blocks=int(
                            cfg.get("disk_spill_blocks", 0)),
                        # sliding-window ring geometry (ISSUE 15): the
                        # largest single prefill feed the ring must
                        # tolerate; chunked prefill keeps feeds inside
                        ring_slack_tokens=int(
                            cfg.get("prefill_chunk_tokens", 0)
                            or cfg.get("ring_slack_tokens", 512)),
                    )
                except ValueError as e:
                    logger.warning("prefix cache disabled: %s", e)
                    self.pool_refusal_reason = getattr(
                        e, "reason", "unsupported")
        if self.role != "both" and self._prefix is None:
            # role-split serving IS page shipping: a prefill replica
            # with no pool has nothing to export, and a decode replica
            # with no pool has nowhere to land an import — refuse at
            # startup, not at the first handoff
            raise ValueError(
                f"role={self.role!r} needs a prefix cache "
                "(serving.prefix_cache.enabled / --prefix-cache on): "
                "page shipping moves pool pages")
        # early-exit draft depth for speculative requests (ISSUE 7):
        # 0 keeps the n-gram prompt-lookup drafter; > 0 drafts with the
        # model's own first k blocks + head (engine/generate
        # ``draft_layers``), sharing the target's cache and the prefix
        # pool's warm blocks
        self._spec_draft_layers = int(spec_draft_layers)
        if self._spec_draft_layers and (
                "exit_layer" not in inspect.signature(
                    type(model).__call__).parameters
                or not (0 < self._spec_draft_layers
                        < int(getattr(model, "n_layer", 0)))):
            logger.warning(
                "speculative_draft_layers=%d unusable for %s (needs "
                "exit_layer support and 0 < k < n_layer): falling back "
                "to n-gram drafting", self._spec_draft_layers,
                type(model).__name__)
            self._spec_draft_layers = 0
        # request-scoped tracing + SLO plumbing (ISSUE 8,
        # observability/reqtrace.py): the tracer appends request-keyed
        # spans to this process's spans.jsonl, the SLO watcher turns
        # per-request TTFT/e2e into breach counters + bounded
        # slow-request dumps. Both optional (None = zero overhead);
        # serve.py passes them in, library/test use stays untouched.
        self._tracer = tracer
        self._slo = slo
        # fixed-bucket Prometheus histograms (utils/promtext): TTFT and
        # TPOT fill only on schedulers that observe first-token time
        # (the continuous engine); e2e fills everywhere — the fleet
        # poller SUMS these bucket counters into aggregable
        # fleet-level latency (averaging percentile gauges is not
        # aggregation)
        self.hist = {"ttft_seconds": LatencyHistogram(),
                     "tpot_seconds": LatencyHistogram(),
                     "e2e_seconds": LatencyHistogram()}
        # per-request serve-path provenance (ISSUE 18): fingerprint ->
        # served-request count, rendered by serve.py's /metrics as the
        # serve_path_<fingerprint>_total counter family
        self._path_counts: dict = {}
        self._path_lock = threading.Lock()
        # scheduler subclasses overwrite this with richer dicts in
        # their own _setup (after this super() call); the plain
        # serialized service still exposes a token counter for /metrics
        self.stats = {"tokens_generated": 0}

    def _observe_request(self, request_id, t0: float, resp: dict,
                         ttft_s=None) -> None:
        """One completed request's latency bookkeeping for schedulers
        WITHOUT their own engine-side observation point (plain and
        static paths; the continuous engine observes in ``_complete``
        where TTFT and token counts are known): e2e histogram, SLO
        check, and the tracer's ``complete`` event."""
        import time

        e2e = time.monotonic() - t0
        self.hist["e2e_seconds"].observe(e2e)
        tokens = len(resp.get("ids") or ())
        if self._tracer is not None and request_id:
            self._tracer.event(request_id, "complete",
                               e2e_s=round(e2e, 6), tokens=tokens,
                               stop_reason=resp.get("stop_reason"))
        if self._slo is not None and request_id:
            self._slo.observe(request_id, ttft_s=ttft_s, e2e_s=e2e,
                              tokens=tokens)

    def _base_path(self, speculative: int = 0) -> dict:
        """The request-independent half of a serve-path fingerprint
        (ISSUE 18): kv layout + TP geometry + spec intent. Engines add
        the admit mode and the pool events the request consumed before
        :meth:`_finalize_path` renders it."""
        pf = getattr(self, "_prefix", None)
        kvq = str(getattr(pf, "kv_quant", "")
                  or getattr(self.model, "kv_quant", "") or "")
        window = int(getattr(pf, "window", 0)
                     or getattr(self.model, "window", 0) or 0)
        return {"mode": "cold", "tp": self.tp,
                "int8": kvq == "int8", "ring": window > 0,
                "spec": int(speculative) > 0}

    def _finalize_path(self, resp: dict, path: dict,
                       request_id=None) -> str:
        """Render ``path`` to its fingerprint and attach it everywhere
        a completed request is observable: the wire response
        (``serve_path`` — serve.py echoes it as ``X-Serve-Path``), the
        per-fingerprint request counters, and the request's trace."""
        from ..observability.reqtrace import path_fingerprint

        fp = path_fingerprint(path)
        resp["serve_path"] = fp
        with self._path_lock:
            self._path_counts[fp] = self._path_counts.get(fp, 0) + 1
        if self._tracer is not None and request_id:
            self._tracer.event(request_id, "serve_path",
                               fingerprint=fp)
        return fp

    def path_counts_snapshot(self) -> dict:
        """fingerprint -> served-request count (for /metrics)."""
        with self._path_lock:
            return dict(self._path_counts)

    def slo_stats(self):
        """SLO breach counters for /metrics (zeros when no watcher)."""
        if self._slo is None:
            return {"slo_breach_total": 0, "slo_ttft_breach_total": 0,
                    "slo_e2e_breach_total": 0, "slo_dumps_written": 0}
        return self._slo.stats()

    def prefix_cache_stats(self):
        """Prefix-cache counters + pool occupancy for /metrics, or
        None when no pool is attached."""
        return (self._prefix.stats_snapshot()
                if self._prefix is not None else None)

    def tp_stats(self) -> dict:
        """Tensor-parallel serving telemetry for /metrics (ISSUE 10):
        the ``tp_degree`` gauge plus the per-decode-step collective
        byte/count accounting from the compiled HLO (the MULTICHIP
        dryrun technique, parallel/tp.decode_step_collectives).
        Computed ONCE on first success — the accounting compiles a
        1-token decode step AOT, which must never ride the scrape path
        twice, so concurrent scrapes serialize on a lock (the
        continuous engine precomputes at setup; the plain/static
        schedulers pay it on the first scrape). A transient failure is
        NOT cached: the scrape reports zeros and the next one retries.
        tp=1 short-circuits to zeros with no compile."""
        with self._tp_stats_lock:
            if self._tp_stats is not None:
                return self._tp_stats
            from ..parallel.tp import decode_step_collectives

            try:
                self._tp_stats = decode_step_collectives(
                    self.model, self.params)
                return self._tp_stats
            except Exception as e:  # noqa: BLE001 — telemetry must
                # never take the server down; the gauge still reports
                logger.warning("tp collective accounting failed "
                               "(will retry next scrape): %s", e)
                return {"tp_degree": self.tp,
                        "collective_count_per_step": 0,
                        "collective_bytes_per_step": 0,
                        "analytic_floor_bytes": 0,
                        "counts": {}, "bytes": {}}

    def encode_prompt(self, prompt=None, prompt_ids=None) -> list:
        """Text or explicit ids -> validated id list (raises ValueError
        with a caller-presentable message on every bad input)."""
        if prompt_ids is not None:
            try:
                # TypeError (non-iterable payload, nested lists) is as
                # much a client input error as a bad value — normalize
                # to ValueError so serve.py maps it to HTTP 400, not 500.
                # Strings ("123" iterates to [1,2,3]) and non-integral
                # floats (1.9 truncates) would silently generate from
                # ids the client never sent — reject, don't coerce.
                if isinstance(prompt_ids, (str, bytes)):
                    raise ValueError("got a string, not a list")
                ids = []
                for i in prompt_ids:
                    # bool is an int subclass: true/false would coerce
                    # to ids 1/0 — same reject-don't-coerce class
                    if isinstance(i, bool) or int(i) != i:
                        raise ValueError(f"non-integer id {i!r}")
                    ids.append(int(i))
            except (TypeError, ValueError, OverflowError) as e:
                # OverflowError: json.loads accepts Infinity, and
                # int(inf) overflows — still a client input error
                raise ValueError(
                    f"prompt_ids must be a flat list of ints: {e}"
                ) from e
            if self.vocab and any(i >= self.vocab or i < 0 for i in ids):
                raise ValueError(
                    f"prompt id outside [0, {self.vocab}) — nn.Embed "
                    "would silently clamp/wrap it"
                )
        elif prompt is None:
            raise ValueError("pass a prompt or prompt ids")
        elif self.vocab <= 256:
            ids = list(str(prompt).encode("utf-8"))
            if any(i >= self.vocab for i in ids):
                raise ValueError(f"prompt byte >= vocab_size {self.vocab}")
        else:
            if self.tokenizer is None:
                raise ValueError(
                    f"vocab_size {self.vocab} > 256 and no BpeLMLoader "
                    "tokenizer found in the run config: pass prompt ids, "
                    "or train through BpeLMLoader for text round-tripping"
                )
            ids = [int(i) for i in self.tokenizer.encode(str(prompt))]
            if any(i >= self.vocab for i in ids):
                raise ValueError(
                    f"tokenizer id >= model vocab_size {self.vocab} — "
                    "the checkpoint and tokenizer disagree"
                )
        if not ids:
            raise ValueError("empty prompt (need at least one token)")
        return ids

    def encode_stop(self, stop) -> list:
        """Wire-level ``stop`` -> validated stop-token id list.

        Accepts a single id / string or a list of them. Strings encode
        through the same text path as prompts (bytes for byte-vocab
        models, the run's BPE tokenizer otherwise) and must encode to
        EXACTLY one token — the in-graph stop check is per emitted
        token, and silently matching only a suffix of a multi-token
        sequence would stop on the wrong text. Returns [] for None.
        """
        if stop is None:
            return []
        items = stop if isinstance(stop, (list, tuple)) else [stop]
        ids = []
        for s in items:
            if isinstance(s, bool) or isinstance(s, float):
                raise ValueError(f"stop entries are ids or strings, "
                                 f"got {s!r}")
            if isinstance(s, int):
                ids.append(int(s))
            elif isinstance(s, str):
                toks = self.encode_prompt(prompt=s)
                if len(toks) != 1:
                    raise ValueError(
                        f"stop string {s!r} encodes to {len(toks)} "
                        "tokens; only single-token stops are supported "
                        "(pass stop ids for multi-token sequences)"
                    )
                ids.append(int(toks[0]))
            else:
                raise ValueError(f"bad stop entry {s!r}")
        if self.vocab and any(i >= self.vocab or i < 0 for i in ids):
            raise ValueError(f"stop id outside [0, {self.vocab})")
        return ids

    def _check_role(self, max_new: int) -> None:
        """The role gate (disaggregated serving, ISSUE 12): a
        prefill-role replica refuses decode-scale budgets LOUDLY (the
        router mis-routed — serving it would silently re-colocate the
        workload the split exists to separate). Decode and colocated
        roles serve everything: a decode replica must still be able to
        cold-prefill a miss (shipping is an optimization, never a
        correctness dependency)."""
        if self.role == "prefill" and int(max_new) > self.PREFILL_MAX_NEW:
            raise ValueError(
                f"prefill-role replica serves max_new_tokens <= "
                f"{self.PREFILL_MAX_NEW} (got {int(max_new)}): decode "
                "work belongs on a decode-role replica (POST /prefill "
                "ships this prompt's KV pages instead)")

    def prefill_export(self, prompt=None, prompt_ids=None,
                       request_id=None, deadline=None) -> dict:
        """The prefill-role entry (ISSUE 12 tentpole): compute the
        prompt's KV into this replica's pool — paged path when
        supported, scatter-insert fallback otherwise — and export the
        full-block chain as a ship payload for a decode replica.

        NOTHING but pages + token ids ships: the decode replica's warm
        admit recomputes the fed suffix window (which always includes
        the final prompt token) exactly as a cold admit would, so its
        first-token logits — and therefore greedy AND sampled output
        under the request's own seed — are token-identical to a
        colocated run with no sampling state crossing the wire. The
        canonical-rotation contract (PR 5) is what makes the shipped
        bytes position/era-independent: a page is just content + a
        block-table splice on arrival.

        Returns the payload dict (``engine/kvcache.serialize_pages``
        turns it into wire bytes); a prompt too short to fill one block
        returns a payload with ``n_blocks == 0`` — the caller sends
        the decode replica straight to a cold prefill.

        Concurrency (ISSUE 13 satellite): exports COALESCE. Each
        caller enqueues its chain; the first thread to take the
        export-leader lock drains the whole queue under ONE service-
        lock acquisition (computing + exporting every queued chain),
        so a burst of concurrent handoffs pays one lock wait instead
        of N serialized ones — the ``handoff_seconds`` queueing
        component this was measured to dominate. ``prefill_export_
        batches`` / ``prefill_export_max_batch`` make the coalescing
        observable."""
        import time

        from .kvcache import serialize_pages  # noqa: F401 (re-export)

        t0 = time.monotonic()
        if deadline is not None and deadline.expired(t0):
            raise DeadlineExceeded("deadline expired before prefill")
        if self._prefix is None:
            raise ValueError("prefill_export needs a prefix cache "
                             "(serving.prefix_cache.enabled)")
        ids = self.encode_prompt(prompt, prompt_ids)
        pf = self._prefix
        empty = {"version": 1, "block_tokens": pf.block, "n_blocks": 0,
                 "token_ids": [], "tp_geometry": {"tp": pf._tp},
                 "leaves": {}}
        if len(ids) // pf.block == 0:
            return empty          # nothing exportable: sub-block prompt
        import threading

        item = {"ids": ids, "evt": threading.Event(), "result": None,
                "error": None}
        with self._export_mu:
            self._export_q.append(item)
        while not item["evt"].is_set():
            if self._export_leader.acquire(blocking=False):
                try:
                    self._drain_export_queue()
                finally:
                    self._export_leader.release()
            else:
                # a leader is processing; it drains until the queue is
                # empty, so either it takes this item or the loop wins
                # the leader lock on the next spin
                item["evt"].wait(0.002)
        if item["error"] is not None:
            raise item["error"]
        payload = item["result"] or empty
        self.stats["prefill_exports"] = (
            self.stats.get("prefill_exports", 0) + 1)
        if self._tracer is not None and request_id:
            self._tracer.add(request_id, "prefill_export", t0,
                             time.monotonic(),
                             blocks=payload["n_blocks"])
        return payload

    def _drain_export_queue(self) -> None:
        """The export leader's loop: repeatedly drain EVERY queued
        chain and process the batch under one service-lock
        acquisition, until the queue stays empty (a caller enqueueing
        after the final drain becomes the next leader itself). One
        chain's failure is its own — it must not poison batchmates."""
        while True:
            with self._export_mu:
                batch, self._export_q = self._export_q, []
            if not batch:
                return
            with self._lock:
                for it in batch:
                    try:
                        it["result"] = self._export_chain_locked(
                            it["ids"])
                    except Exception as e:  # noqa: BLE001 — per-chain
                        it["error"] = e
            self.stats["prefill_export_batches"] = (
                self.stats.get("prefill_export_batches", 0) + 1)
            self.stats["prefill_export_max_batch"] = max(
                self.stats.get("prefill_export_max_batch", 0),
                len(batch))
            for it in batch:
                it["evt"].set()

    def _export_chain_locked(self, ids):
        """Compute-if-needed + export ONE chain (the leader holds the
        service lock). Paged arm: a 1-token-budget reservation whose
        suffix prefill writes straight into private pages, finished
        immediately so the prompt's blocks adopt zero-copy; scatter
        arm: warm_prefill's plan_insert + capture. Spilled blocks
        promote first — a demoted chain is as exportable as a
        resident one."""
        pf = self._prefix
        if pf.spill is not None:
            pf.promote_spilled(ids)
        if pf.cached_block_count(ids) < len(ids) // pf.block:
            done = False
            if pf.paged:
                res = pf.paged_prefill(self.params, ids, 1)
                if res is not None:
                    _, cache, _, plan = res
                    pf.paged_finish(plan, [], 0)
                    done = True
            if not done and not getattr(pf, "window", 0):
                # no scatter arm for ring layouts: a dry ring pool
                # exports whatever chain is already resident
                pf.warm_prefill(self.params, ids, len(ids) + 1)
        return pf.export_pages(ids)

    def export_cached_pages(self, prompt=None, prompt_ids=None,
                            request_id=None) -> dict:
        """Peer page migration's EXPORT-ONLY entry (ISSUE 13): ship
        whatever full-block chain this replica already holds for the
        prompt — resident pages, plus spilled pages promoted (and
        checksum-verified) on the way out — WITHOUT computing anything
        missing. The fleet manager's miss-driven peer pulls and
        restart re-warm both call this on the holder; a replica that
        holds nothing answers ``n_blocks == 0`` and the puller falls
        back cold. Any role with a pool serves it."""
        if self._prefix is None:
            raise ValueError("export_cached_pages needs a prefix cache "
                             "(serving.prefix_cache.enabled)")
        ids = self.encode_prompt(prompt, prompt_ids)
        pf = self._prefix
        with self._lock:
            if pf.spill is not None:
                pf.promote_spilled(ids)
            payload = pf.export_pages(ids)
        if payload is None:
            payload = {"version": 1, "block_tokens": pf.block,
                       "n_blocks": 0, "token_ids": [],
                       "tp_geometry": {"tp": pf._tp}, "leaves": {}}
        self.stats["peer_exports"] = (
            self.stats.get("peer_exports", 0) + 1)
        return payload

    def import_remote_pages(self, payload, origin: str = "ship") -> dict:
        """The decode-role entry: land a shipped page chain in this
        replica's pool (``bytes`` payloads deserialize here), making
        the prompt's prefix a radix HIT — the very next ``generate``
        for it admits as a zero-recompute block-table pointer update.
        ``origin`` tags the adopted nodes for path provenance (ISSUE
        18): "ship" for the disagg handoff, "pull" when the fleet
        manager dragged the chain here as a peer pull. Runs under the
        service lock (the scheduler's tick-start
        ``refresh_cache_from_pool`` absorbs the import's pool
        donation, same contract as batch-1 speculative requests)."""
        from .kvcache import deserialize_pages

        if self._prefix is None:
            raise ValueError("import_remote_pages needs a prefix cache "
                             "(serving.prefix_cache.enabled)")
        if isinstance(payload, (bytes, bytearray, memoryview)):
            payload = deserialize_pages(bytes(payload))
        with self._lock:
            receipt = self._prefix.import_pages(payload, origin=origin)
        self.stats["remote_admits"] = (
            self.stats.get("remote_admits", 0) + 1)
        return receipt

    def validate_request(self, req: dict) -> None:
        """Cheap host-side validation of a wire-format request body
        (the dict serve.py reads off the socket): raises the same
        ``ValueError`` the matching ``generate()`` call would, WITHOUT
        touching the device. serve.py runs it before committing a 200
        ``text/event-stream`` response, so a bad streaming request
        gets the 400 its non-streaming twin gets instead of a 200 +
        SSE error event (ADVICE r5). Numeric coercions mirror
        serve._run_request — a non-numeric ``max_new_tokens`` is as
        much a 400 as an over-budget one."""
        ids = self.encode_prompt(req.get("prompt"),
                                 req.get("prompt_ids"))
        stops = self.encode_stop(req.get("stop"))
        max_new = int(req.get("max_new_tokens", 64))
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self._check_role(max_new)
        float(req.get("temperature", 0.0))
        int(req.get("top_k", 0))
        float(req.get("top_p", 0.0))
        int(req.get("seed", 0))
        speculative = int(req.get("speculative", 0))
        self._validate_budget(ids, max_new, stops,
                              speculative=speculative)

    def _validate_budget(self, ids, max_new: int, stops,
                         speculative: int = 0) -> None:
        """Scheduler-specific budget/shape checks (subclasses refine):
        the plain and static paths reject prompt + budget past
        ``max_len`` at enqueue."""
        max_len = int(getattr(self.model, "max_len", 0) or 0)
        if max_len and len(ids) + max_new > max_len:
            raise ValueError(
                f"prompt ({len(ids)} tokens) + max_new_tokens "
                f"({max_new}) exceeds model.max_len {max_len}")

    def decode_text(self, ids):
        """Generated ids -> text, when the model has a text form
        (byte vocab or a recovered tokenizer); else None."""
        import numpy as np

        ids = np.asarray(ids).reshape(-1)
        if self.vocab and self.vocab <= 256:
            return bytes(int(t) for t in ids).decode(
                "utf-8", errors="replace"
            )
        if self.tokenizer is not None:
            # replace (not raise) on ids past the learned vocab: BPE
            # training can stop short of the configured head size, and
            # an undertrained model may emit those ids
            return self.tokenizer.decode(ids, errors="replace")
        return None

    def generate(self, prompt=None, prompt_ids=None,
                 max_new_tokens: int = 64, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 0.0, seed: int = 0,
                 speculative: int = 0, stop=None,
                 request_id=None, deadline=None) -> dict:
        """One validated generation request ->
        ``{"ids", "text"?, "stop_reason", "speculative"?}``.

        ``stop``: stop-token ids and/or single-token strings; the
        in-graph loop exits as soon as every row is done, so a stopped
        request costs chip time proportional to what it EMITS, not its
        budget. The stop token is excluded from the response (its
        presence is reported as ``stop_reason: "stop"``).

        ``request_id``: the request-scoped trace id (ISSUE 8) — keys
        this request's spans/SLO observation when a tracer is attached;
        otherwise inert.

        ``deadline``: optional :class:`reqtrace.Deadline` (ISSUE 9).
        The plain path honors it at dispatch boundaries only (checked
        at entry and after the lock wait — a generation already on the
        chip runs out); the continuous scheduler overrides this with
        true mid-flight cancellation at chunk absorbs.
        """
        import time

        import jax
        import jax.numpy as jnp
        import numpy as np

        from .generate import generate

        t_req = time.monotonic()
        if deadline is not None and deadline.expired(t_req):
            raise DeadlineExceeded(
                "deadline expired before dispatch")
        self._check_role(max_new_tokens)
        ids = self.encode_prompt(prompt, prompt_ids)
        stops = self.encode_stop(stop)
        path = self._base_path(speculative)
        arr = jnp.asarray(np.asarray(ids, np.int32)[None, :])
        with self._lock:
            if deadline is not None and deadline.expired():
                # the lock wait ate the budget: shed before spending
                # chip time on tokens nobody is waiting for
                raise DeadlineExceeded(
                    "deadline expired waiting for the chip")
            emitted = None
            if speculative > 0:
                new_ids, stats = self._adaptive_speculative(
                    arr, int(max_new_tokens), int(speculative),
                    float(temperature), int(top_k), float(top_p),
                    int(seed), stops,
                )
                resp = self._response(new_ids, stops=stops,
                                      emitted=len(new_ids))
                resp["speculative"] = stats
                if self._tracer is not None and request_id:
                    self._tracer.event(
                        request_id, "spec",
                        tokens_per_call=stats.get("tokens_per_call"),
                        model_calls=stats.get("model_calls"),
                        disabled=stats.get("speculation_disabled"))
                if (self._prefix is not None
                        and stats.get("prefix_hit_tokens")):
                    # the pool-shared spec arm warm-prefilled through
                    # the prefix cache — a warm admit, with the pool
                    # events warm_prefill stashed
                    path["mode"] = "warm"
                    path.update(getattr(self._prefix,
                                        "last_warm_flags", {}))
                self._finalize_path(resp, path, request_id)
                self._observe_request(request_id, t_req, resp)
                return resp
            # row_rngs (not rng): the row stream is key(seed)
            # EXACTLY, matching what the micro-batched service
            # passes per row — same request + seed samples the
            # same tokens whether or not it shared a batch
            row_rngs = jnp.stack([jax.random.key(int(seed))])
            if (self._prefix is not None and not stops
                    and int(max_new_tokens) >= 1
                    and len(ids) + int(max_new_tokens)
                    <= int(self.model.max_len)):
                # paged prefix cache (engine/kvcache.py): prefill only
                # the uncached suffix, then the normal step loop. Same
                # per-(step, row) key layout as generate(), so sampled
                # output matches the cold path; the stop-token path
                # stays cold (its fused single-dispatch loop builds its
                # own cache in-graph). Out-of-budget requests also fall
                # through, so generate() raises the usual ValueError.
                # None = the pool cannot serve this request at all
                # (e.g. a ring layout's dry pool — no scatter arm
                # exists for window models): the cold path below
                # serves it, counted as a pool fallback.
                new_ids = self._generate_prefix_cached(
                    ids, int(max_new_tokens), float(temperature),
                    int(top_k), float(top_p), row_rngs)
                if new_ids is not None:
                    resp = self._response(new_ids, stops=stops)
                    path.update(getattr(self, "_last_path_info", {}))
                    self._finalize_path(resp, path, request_id)
                    self._observe_request(request_id, t_req, resp)
                    return resp
            if stops:
                out, lengths = generate(
                    self.model, self.params, arr,
                    max_new_tokens=int(max_new_tokens),
                    temperature=float(temperature),
                    top_k=int(top_k), top_p=float(top_p),
                    row_rngs=row_rngs, stop_tokens=stops,
                    return_lengths=True,
                )
                emitted = int(lengths[0])
            else:
                out = generate(
                    self.model, self.params, arr,
                    max_new_tokens=int(max_new_tokens),
                    temperature=float(temperature),
                    top_k=int(top_k), top_p=float(top_p),
                    row_rngs=row_rngs,
                )
        resp = self._response(np.asarray(out[0, arr.shape[1]:]),
                              stops=stops, emitted=emitted)
        self._finalize_path(resp, path, request_id)
        self._observe_request(request_id, t_req, resp)
        return resp

    def _generate_prefix_cached(self, ids, max_new: int,
                                temperature: float, top_k: int,
                                top_p: float, row_rngs):
        """Batch-1 decode through the paged prefix pool. TWO arms:

        - **paged** (kv_cache_spec paged=True, pool healthy): the
          cached prefix is a block-table pointer entry — ZERO admit
          copy — the suffix prefills straight into private pool pages,
          decode reads the pool in place (ops/flash paged kernel on
          TPU), and the finished request's pages adopt into the radix
          index with no capture kernel.
        - **scatter fallback** (unsupported layouts / dry pool):
          kvcache.warm_prefill — cached blocks scatter into a
          contiguous cache, suffix-only prefill, capture-copy insert.

        Both use the SAME step-loop + per-(step, row) key folding as
        engine/generate's eager path, so output matches the cold path
        token for token (float-tolerance exact, like every other
        batched-vs-solo contract in this stack). Caller holds the lock
        and has validated budget/stops."""
        import jax.numpy as jnp
        import numpy as np

        from .generate import _decode_fns, _fold_all_rows, _sample_rows
        from .kvcache import _paged_decode_fns, page_origin_flags

        # path provenance stash (ISSUE 18): which arm served this
        # request + the pool events it consumed; the caller merges it
        # into the request's serve-path fingerprint. Safe as an
        # instance attr — the caller holds the service lock.
        self._last_path_info = {"mode": "cold"}
        if temperature <= 0:
            keys_at = lambda i: row_rngs                   # noqa: E731
        else:
            all_keys = _fold_all_rows(row_rngs, max_new)
            keys_at = lambda i: all_keys[i]                # noqa: E731
        if self._prefix.paged:
            res = self._prefix.paged_prefill(self.params, ids, max_new)
            if res is not None:
                last_logits, cache, tables, plan = res
                step = _paged_decode_fns(
                    self.model, self._prefix.nb_max, temperature,
                    top_k, top_p)
                token = _sample_rows(keys_at(0), last_logits,
                                     temperature, top_k, top_p)
                out = [token[:, None]]
                L = len(ids)
                try:
                    for i in range(1, max_new):
                        token, cache = step(
                            self.params, cache, token, keys_at(i),
                            tables,
                            jnp.asarray([L + i - 1], jnp.int32))
                        out.append(token[:, None])
                    row = np.asarray(jnp.concatenate(out, axis=1))[0]
                except Exception:
                    # a failed step must not strand refs or leak
                    # pages. `cache` may be the pytree just DONATED
                    # into the failing dispatch — syncing dead leaves
                    # would wedge the shared pool for every later
                    # request, so reset instead (the plan's refs and
                    # pages die with the index; finishing against a
                    # fresh index would double-free).
                    if self._prefix.pool_alive(cache):
                        self._prefix.sync_pool_from_cache(cache)
                        self._prefix.paged_finish(plan, [], 0)
                    else:
                        self._prefix.reset_pool()
                    raise
                self._prefix.sync_pool_from_cache(cache)
                # zero-copy insert: prompt AND decoded tokens become
                # sharable in place
                self._prefix.paged_finish(
                    plan, [int(t) for t in row], max_new)
                self._prefix.count_batch1(paged=True)
                self._last_path_info = {
                    "mode": "paged",
                    "wrap": bool(plan.get("ring_wrap")),
                    **page_origin_flags(plan.get("nodes"))}
                return row
        self._prefix.count_batch1(paged=False)
        # pool-fallback accounting (ISSUE 15): a healthy-but-dry paged
        # pool degrades as "dry_pool"; a structurally unpaged pool
        # counts its own reason (gpt2_layout / undersized)
        self._prefix.count_fallback(
            "dry_pool" if self._prefix.paged else "")
        if getattr(self._prefix, "window", 0):
            # ring layouts have NO scatter arm (a rolling contiguous
            # cache is position-dependent): the caller's cold path
            # serves this request instead
            return None
        # a dry-pool fall-through from the paged arm already recorded
        # this request's lookup inside paged_plan — recording again
        # here would double-count prefix_hit_tokens for the SAME
        # request (the counter feeds /metrics and the bench gates)
        last_logits, cache, hit = self._prefix.warm_prefill(
            self.params, ids, len(ids) + max_new,
            record=not self._prefix.paged)
        if hit:
            self._last_path_info = {
                "mode": "warm",
                **getattr(self._prefix, "last_warm_flags", {})}
        _, step = _decode_fns(self.model, temperature, top_k, top_p)
        token = _sample_rows(keys_at(0), last_logits, temperature,
                             top_k, top_p)
        out = [token[:, None]]
        for i in range(1, max_new):
            token, cache = step(self.params, cache, token, keys_at(i))
            out.append(token[:, None])
        return np.asarray(jnp.concatenate(out, axis=1))[0]

    # Speculative fail-safe (VERDICT r4 weak #3 / next #5): prompt-
    # lookup acceptance is workload-dependent — repetitive text accepts
    # ~3 tokens/call, adversarial (sampled natural) text ~1.0 — so the
    # server probes the first chunk and finishes the request with
    # plain decode when projected speedup = acceptance / cost_ratio
    # falls under 1. The cost ratio (verify call / vanilla step) is
    # platform-dependent: isolated-dispatch measurements said ~1.5
    # (BASELINE.md r4), but the r5 end-to-end adversarial bench arm
    # measures ~1.0-1.1 on this chip — batch-1 decode is HBM-bound,
    # and a (D+1)-token verify streams the same weight bytes as a
    # 1-token step — so speculation only mildly loses even at zero
    # acceptance there. 1.25 is the conservative middle; deployments
    # can override the attribute with their own measured ratio.
    SPEC_PROBE = 32
    SPEC_MIN_TOKENS_PER_CALL = 1.25

    def _spec_pad_to(self, t0: int, budget: int, draft: int):
        """Length-bucket a speculative prompt on pad-capable models:
        arbitrary prompt lengths would otherwise pay a fresh XLA
        compile each (~10 s on tunneled devices)."""
        if not self._pad_ok:
            return None
        bucket = 16
        while bucket < t0:
            bucket *= 2
        limit = (int(self.model.max_len) - budget - 2 * (draft + 1))
        pad_to = min(bucket, limit)
        return pad_to if pad_to > t0 else None

    def _spec_generate(self, arr, budget: int, draft: int,
                       temperature: float, top_k: int, top_p: float,
                       rng, stops):
        """One speculative phase, POOL-SHARED when possible (ISSUE 7):
        with a prefix pool attached, the prompt warm-prefills through
        it (cached blocks + suffix-only prefill) and the spec loop
        continues from that cache — the early-exit draft
        (``speculative_draft_layers``) shares the same cache, so BOTH
        target and draft skip the shared prefix's prefill. Without a
        pool (or when the budget + overshoot slack does not fit
        ``max_len``), the plain length-bucketed
        ``generate_speculative`` runs as before."""
        import numpy as np

        from .generate import generate_speculative

        t0 = arr.shape[1]
        # getattr: tests drive _adaptive_speculative on a bare
        # __new__-built service with no _setup (no pool, no draft cfg)
        dl = getattr(self, "_spec_draft_layers", 0)
        prefix = getattr(self, "_prefix", None)
        L = t0 + int(budget) + 2 * (int(draft) + 1)
        if (prefix is not None and L <= int(self.model.max_len)
                and not getattr(prefix, "window", 0)):
            ids = [int(t) for t in np.asarray(arr)[0]]
            # route through the pool only on an actual prefix HIT:
            # the warm path's executables key on the EXACT (t0, L) —
            # worth one compile when the prefill skip pays for it,
            # but cold spec traffic of arbitrary lengths stays on the
            # length-BUCKETED generate_speculative below (the probe
            # must not count: it is not a served lookup)
            probe, _, c = prefix.lookup(ids, record=False)
            prefix.release(probe)
            if c:
                return self._spec_from_pool(
                    prefix, ids, L, budget, draft, temperature,
                    top_k, top_p, rng, stops, dl)
        return generate_speculative(
            self.model, self.params, arr, max_new_tokens=budget,
            draft_len=draft, return_stats=True,
            temperature=temperature, top_k=top_k, top_p=top_p,
            rng=rng, pad_to=self._spec_pad_to(t0, budget, draft),
            stop_tokens=stops or None, draft_layers=dl)

    def _spec_from_pool(self, prefix, ids, L, budget, draft,
                        temperature, top_k, top_p, rng, stops, dl):
        """The pool-shared speculative arm (ISSUE 7): warm prefill
        (cached blocks + suffix-only feed) continuing into the fused
        spec loop; target AND early-exit draft skip the shared
        prefix's prefill."""
        from .generate import speculative_from_cache

        last_logits, cache, hit = prefix.warm_prefill(
            self.params, ids, L)
        out, stats = speculative_from_cache(
            self.model, self.params, ids, cache, last_logits, L,
            budget, draft_len=draft, temperature=temperature,
            top_k=top_k, top_p=top_p, rng=rng,
            stop_tokens=stops or None, draft_layers=dl)
        stats["prefix_hit_tokens"] = hit
        return out, stats

    def _adaptive_speculative(self, arr, max_new: int, draft: int,
                              temperature: float, top_k: int,
                              top_p: float, seed: int, stops):
        """Speculative decode with the acceptance probe: run the first
        ``SPEC_PROBE`` tokens speculatively, then either keep
        speculating (acceptance >= the bar) or finish with plain
        decode (``speculation_disabled: true`` in the stats). Greedy
        output is bit-identical either way (greedy speculation ==
        greedy decode, phase-split or not); sampled output stays
        distribution-exact (each phase's rejection sampler is exact
        given its prefix — the rng PATH differs from the single-shot
        call, the law does not).

        Returns ``(ids, stats)`` — ids are the emitted tokens (stop
        token included when one fired; the response layer strips it).
        """
        import jax
        import jax.numpy as jnp
        import numpy as np

        from .generate import generate

        t0 = arr.shape[1]
        probe = min(self.SPEC_PROBE, max_new)
        key = jax.random.key(seed)
        out, stats = self._spec_generate(
            arr, probe, draft, temperature, top_k, top_p, key, stops)
        emitted = stats["tokens_emitted"]
        ids = [int(t) for t in np.asarray(out)[0, t0:t0 + emitted]]
        stats = dict(stats,
                     probe_tokens_per_call=stats["tokens_per_call"],
                     speculation_disabled=False)
        rest = max_new - probe
        if stops and ids and ids[-1] in stops:
            # a stop landing exactly on the probe's last slot reports
            # stopped=False from generate_speculative (emitted ==
            # budget) — continuing past it would hand the client
            # post-stop tokens
            stats["stopped"] = True
        if stats["stopped"] or rest <= 0:
            return ids, stats
        arr2 = jnp.concatenate(
            [arr, jnp.asarray(np.asarray(ids, np.int32))[None, :]],
            axis=1,
        )
        t1 = arr2.shape[1]
        key2 = jax.random.fold_in(key, 1)
        if stats["probe_tokens_per_call"] >= self.SPEC_MIN_TOKENS_PER_CALL:
            out2, s2 = self._spec_generate(
                arr2, rest, draft, temperature, top_k, top_p, key2,
                stops)
            em2 = s2["tokens_emitted"]
            calls = stats["model_calls"] + s2["model_calls"]
            stopped = s2["stopped"]
        else:
            # acceptance under the bar: plain decode for the rest —
            # each remaining token is one model call, which is exactly
            # what a losing speculative loop must fall back to
            row_rngs = jnp.stack([key2])
            if stops:
                out2, lengths = generate(
                    self.model, self.params, arr2, rest,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    row_rngs=row_rngs, stop_tokens=stops,
                    return_lengths=True,
                )
                em2 = int(lengths[0])
            else:
                out2 = generate(
                    self.model, self.params, arr2, rest,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    row_rngs=row_rngs,
                )
                em2 = rest
            calls = stats["model_calls"] + em2
            stopped = bool(stops) and em2 < rest
            stats["speculation_disabled"] = True
        ids += [int(t) for t in np.asarray(out2)[0, t1:t1 + em2]]
        if stops and ids and ids[-1] in stops:
            stopped = True
        stats.update(
            model_calls=calls,
            tokens_emitted=emitted + em2,
            stopped=stopped,
            tokens_per_call=round((emitted + em2) / max(calls, 1), 3),
        )
        return ids, stats

    def _response(self, new_ids, stops=(), emitted=None) -> dict:
        """Generated row -> wire response (ONE place: the batched and
        serialized paths must never drift apart).

        ``emitted`` = tokens the model actually produced for this row
        (stop token included, frozen pad tail excluded); the stop
        token itself is stripped from the wire ids/text and reported
        as ``stop_reason: "stop"``.
        """
        ids = [int(t) for t in new_ids]
        reason = "length"
        if emitted is not None:
            ids = ids[:emitted]
        if stops and ids and ids[-1] in stops:
            ids = ids[:-1]
            reason = "stop"
        resp: dict = {"ids": ids, "stop_reason": reason}
        text = self.decode_text(ids)
        if text is not None:
            resp["text"] = text
        # every scheduler's responses funnel through here — the ONE
        # place a tokens-served counter stays scheduler-agnostic
        # (surfaced by serve.py's /metrics)
        stats = getattr(self, "stats", None)
        if stats is not None:
            stats["tokens_generated"] = (
                stats.get("tokens_generated", 0) + len(ids))
            if (getattr(self, "pool_refusal_reason", "")
                    and getattr(self, "_prefix", None) is None):
                # pool-fallback observability (ISSUE 15): a REFUSED
                # pool means every served request ran without it —
                # counted here so even the plain scheduler's /metrics
                # carries the degradation
                stats["pool_refused_requests"] = (
                    stats.get("pool_refused_requests", 0) + 1)
        return resp


class BatchedGenerationService(GenerationService):
    """``GenerationService`` with a micro-batch scheduler.

    The plain service serializes requests with a lock: one request
    occupies the chip while others queue, even though ``generate()``
    is batch-capable and decode throughput scales with batch (the
    ``decode`` bench rung runs batch 8 at ~10x batch-1 aggregate
    tok/s). Here concurrent requests queue into a single worker that
    groups COMPATIBLE requests — same (prompt length, max_new_tokens,
    temperature, top_k, top_p) — within a short batching window into
    one batched prefill + shared decode loop. Each request keeps its
    own sampling stream (``generate(row_rngs=...)``), so a request's
    output never depends on which requests shared its batch.

    For RoPE families (the Llama/Mistral family: shift-invariant
    positions + per-row pad masking, ``models/llama.py pad_lens``),
    requests of DIFFERENT prompt lengths batch together within a
    128-token length bucket: shorter rows are LEFT-padded and their
    pad slots masked, which is token-exact vs solo execution
    (tests/test_generate.py). Absolute-position families (GPT-2) and
    rolling-window models group by exact prompt length instead (one
    batch-wide position counter; ring eviction differs per row).
    Speculative requests stay batch-1 by construction and bypass the
    scheduler. ``stats`` (surfaced via /healthz) records how much
    sharing actually happened.
    """

    PAD_BUCKET = 128

    def _setup(self, model, params, tokenizer=None,
               max_batch: int = 8, window_ms: float = 25.0,
               spec_draft_layers: int = 0, tracer=None, slo=None):
        import queue
        import threading

        super()._setup(model, params, tokenizer,   # sets _pad_ok
                       spec_draft_layers=spec_draft_layers,
                       tracer=tracer, slo=slo)
        self._max_batch = int(max_batch)
        self._window_s = float(window_ms) / 1e3
        self._queue: "queue.Queue" = queue.Queue()
        self.stats = {"requests": 0, "batches": 0,
                      "batched_requests": 0, "max_batch_size": 0}
        self._worker_thread = threading.Thread(
            target=self._worker, daemon=True, name="gen-batcher"
        )
        self._worker_thread.start()

    def generate(self, prompt=None, prompt_ids=None,
                 max_new_tokens: int = 64, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 0.0, seed: int = 0,
                 speculative: int = 0, stop=None,
                 request_id=None, deadline=None) -> dict:
        import threading
        import time

        if speculative > 0:
            # batch-1 by construction (single cache position counter);
            # runs under the parent's lock like any other chip user
            return super().generate(
                prompt=prompt, prompt_ids=prompt_ids,
                max_new_tokens=max_new_tokens, temperature=temperature,
                top_k=top_k, top_p=top_p, seed=seed,
                speculative=speculative, stop=stop,
                request_id=request_id, deadline=deadline,
            )
        t_req = time.monotonic()
        if deadline is not None and deadline.expired(t_req):
            raise DeadlineExceeded("deadline expired before dispatch")
        # validate in the CALLER's thread: bad input must raise here
        # (HTTP 400), not poison the worker. The budget rule lives in
        # _validate_budget (ONE owner, shared with serve.py's pre-SSE
        # validate_request): group keys pin max_new_tokens, so if
        # every member individually fits, padding to the longest
        # member's length fits too — one oversized request can never
        # fail its batchmates
        ids = self.encode_prompt(prompt, prompt_ids)
        stops = self.encode_stop(stop)
        self._validate_budget(ids, int(max_new_tokens), stops)
        req = {
            "ids": ids,
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "top_k": int(top_k), "top_p": float(top_p),
            "seed": int(seed),
            # per-ROW stop sets in the loop executable, so requests
            # with different stops still share a batch (not in the key)
            "stop": stops,
            "deadline": deadline,
            "event": threading.Event(),
        }
        # group key computed HERE, in the caller's thread: a raising
        # key function inside the worker can strand a request that is
        # in neither batch nor stash — its event would never be set and
        # this wait() would block forever (advisor r4)
        req["key"] = self._group_key(req)
        self._queue.put(req)
        req["event"].wait()
        if "error" in req:
            raise req["error"]
        self._observe_request(request_id, t_req, req["result"])
        return req["result"]

    def _group_key(self, req):
        n = len(req["ids"])
        length_key = (
            -(-n // self.PAD_BUCKET) if self._pad_ok else n
        )
        return (length_key, req["max_new_tokens"],
                req["temperature"], req["top_k"], req["top_p"])

    def _worker(self):
        import logging
        import queue
        import time

        stash: list = []
        while True:
            # the OUTER try guards everything, including the grouping
            # logic: an exception that escaped it would kill this
            # thread silently and hang every future request behind a
            # queue nobody drains
            batch = []
            try:
                if stash:
                    first = stash.pop(0)
                else:
                    first = self._queue.get()
                # requests carry their precomputed "key" (set in the
                # caller's thread at enqueue): the worker never runs
                # key logic, so no exception here can strand a request
                # outside both batch and stash with its event unset
                batch.append(first)
                key = first["key"]
                # drain compatible stashed requests first
                rest = []
                for r in stash:
                    (batch if r["key"] == key
                     and len(batch) < self._max_batch else rest).append(r)
                stash = rest
                deadline = time.monotonic() + self._window_s
                while len(batch) < self._max_batch:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    try:
                        nxt = self._queue.get(timeout=left)
                    except queue.Empty:
                        break
                    if nxt["key"] == key:
                        batch.append(nxt)
                    else:
                        stash.append(nxt)
                self._run_batch(batch)
            except Exception as e:  # noqa: BLE001 — surfaced per request
                logging.getLogger(__name__).exception(
                    "batch worker error (batch of %d)", len(batch)
                )
                for r in batch:
                    r["error"] = e
                    r["event"].set()

    def _run_batch(self, batch):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from .generate import generate

        # shed members whose deadline expired in the batching window
        # BEFORE forming the batch (ISSUE 9): a static group decodes to
        # the longest member, so one already-dead request would cost
        # everyone its budget
        live = []
        for r in batch:
            dl = r.get("deadline")
            if dl is not None and dl.expired():
                r["error"] = DeadlineExceeded(
                    "deadline expired in the batch queue")
                r["event"].set()
            else:
                live.append(r)
        if not live:
            return
        batch = live
        t0 = max(len(r["ids"]) for r in batch)
        if self._pad_ok:
            # round the padded length up to a small shape menu (powers
            # of two within the bucket): one XLA compile per (shape,
            # budget, sampling) instead of one per distinct batch-max
            # length, at <=2x extra pad slots. Never past what the
            # model's max_len leaves room for (every member fits by
            # the enqueue check, so t0 itself always does).
            shape = 16
            while shape < t0:
                shape *= 2
            max_len = int(getattr(self.model, "max_len", 0) or 0)
            if max_len:
                shape = min(shape,
                            max_len - batch[0]["max_new_tokens"])
            t0 = max(t0, shape)
        # left-pad; pad slots are masked per row
        # (generate(pad_lens=...)) for pad-capable models, and batches
        # are exact-length by group key otherwise (pad_lens all zero)
        arr = jnp.asarray(np.stack([
            [0] * (t0 - len(r["ids"])) + list(r["ids"]) for r in batch
        ]).astype(np.int32))
        pad_lens = np.asarray(
            [t0 - len(r["ids"]) for r in batch], np.int32
        )
        row_rngs = jnp.stack(
            [jax.random.key(r["seed"]) for r in batch]
        )
        any_stop = any(r["stop"] for r in batch)
        lengths = None
        with self._lock:
            if any_stop:
                # the stop-capable while_loop path: per-row stop sets,
                # so rows with different (or no) stops share the batch;
                # the loop exits once every row is done
                out, lengths = generate(
                    self.model, self.params, arr,
                    max_new_tokens=batch[0]["max_new_tokens"],
                    temperature=batch[0]["temperature"],
                    top_k=batch[0]["top_k"], top_p=batch[0]["top_p"],
                    row_rngs=row_rngs,
                    pad_lens=(jnp.asarray(pad_lens)
                              if pad_lens.any() else None),
                    stop_tokens=[r["stop"] for r in batch],
                    return_lengths=True,
                )
                lengths = np.asarray(lengths)
            else:
                out = generate(
                    self.model, self.params, arr,
                    max_new_tokens=batch[0]["max_new_tokens"],
                    temperature=batch[0]["temperature"],
                    top_k=batch[0]["top_k"], top_p=batch[0]["top_p"],
                    row_rngs=row_rngs,
                    pad_lens=(jnp.asarray(pad_lens)
                              if pad_lens.any() else None),
                )
        new = np.asarray(out[:, t0:])
        self.stats["requests"] += len(batch)
        self.stats["batches"] += 1
        if len(batch) > 1:
            self.stats["batched_requests"] += len(batch)
        self.stats["max_batch_size"] = max(
            self.stats["max_batch_size"], len(batch)
        )
        for i, r in enumerate(batch):
            r["result"] = self._response(
                new[i], stops=r["stop"],
                emitted=None if lengths is None else int(lengths[i]),
            )
            # micro-batched requests always run the cold full-prefill
            # path (no pool on this scheduler) — fingerprint is the
            # base layout/geometry
            self._finalize_path(r["result"], self._base_path())
            r["event"].set()


def load_generation_stack(config, use_ema: bool = False,
                          tensor_parallel: int = 0):
    """``(model, params, tokenizer | None)`` for ``config.resume``.

    ``tensor_parallel`` (ISSUE 10; CLI ``--tp`` wins over the config's
    ``serving.tensor_parallel``, both default 1 = single-chip): shard
    the serving model over a ``{"tensor": tp}`` mesh — weights per the
    model's own megatron ``partition_rules()``, KV caches and the
    paged pool on the head axis — so prefill/admit/decode run as ONE
    SPMD program with all-reduce collectives instead of a single-chip
    dispatch. Geometry that cannot shard (kv heads, d_ff, vocab not
    divisible by tp) refuses loudly HERE, before any executable
    builds."""
    from ..parallel.tp import (
        serving_mesh, shard_serving_params, validate_tp_geometry,
    )

    assert config.resume is not None, "generation requires a checkpoint (-r)"
    dist.initialize()  # multi-host rendezvous parity with train.py/test.py
    tp = int(tensor_parallel or 0) or int(
        (config.get("serving") or {}).get("tensor_parallel") or 1)
    kvq = str((config.get("serving") or {}).get("kv_quant") or "")
    if kvq:
        # int8-KV decode cache (ISSUE 15): a SERVING mode — the scale
        # leaves are cache variables, not params — so the serving
        # section can switch it on over a full-precision training
        # arch without touching the checkpoint
        config["arch"].setdefault("args", {})["kv_quant"] = kvq
    mesh = serving_mesh(tp) if tp > 1 else mesh_from_config(config)
    model = inject_mesh(config.init_obj("arch", MODELS), mesh)
    if not hasattr(model, "max_len"):
        raise SystemExit(
            f"arch {type(model).__name__} has no decode support"
        )
    if tp > 1:
        validate_tp_geometry(model, tp)
        logger.info("tensor-parallel serving: tp=%d over %s", tp,
                    [str(d) for d in mesh.devices.flat])

    serving_meta = load_serving_meta(config.resume)
    if serving_meta is not None:
        # Params-only serving artifact: the artifact's config.json
        # already carries the serving arch args, so the model above IS
        # the serving model — restore its param tree directly; there is
        # no TrainState (and --ema is moot: the weight choice was baked
        # in at artifact-production time).
        if use_ema:
            logger.warning(
                "--ema ignored: %s is a params-only serving artifact "
                "(quantized/merged from %s)", config.resume,
                serving_meta.get("source_params", "params"),
            )
        template = jax.eval_shape(
            lambda: model.init(jax.random.key(0), model.batch_template(1))
        )["params"]
        # Restore sharded over the mesh per the model's partition rules
        # (the quant tree's kernel_q leaves match the same `/kernel`
        # rule patterns; scale vectors replicate). A host-local restore
        # + device_put would break on multi-host meshes.
        rules = (model.partition_rules()
                 if hasattr(model, "partition_rules") else [])
        # mesh passed through: the artifact's recorded tp_geometry is
        # validated against it BEFORE orbax touches a byte — a layout
        # the artifact cannot shard refuses loudly instead of failing
        # deep inside a jit (ISSUE 10 satellite)
        params = restore_serving_params(
            config.resume, template, apply_rules(template, mesh, rules),
            mesh=mesh,
        )
    else:
        state, _ = restore_template_state(config, model, mesh)
        params = (
            state.ema_params
            if use_ema and state.ema_params is not None else state.params
        )
    if tp > 1:
        # idempotent when the restore already materialized sharded
        # leaves; covers template paths that fell through replicated
        params = shard_serving_params(model, params, mesh)
    return model, params, tokenizer_from_config(config)
