"""Distributed evaluation: the reference's ``test.py`` as a library.

Parity with /root/reference/test.py:14-101: build components from config,
restore a checkpoint, run a no-grad loop over the test loader, and compute
metrics over the *global* dataset. The reference all_gathers every rank's
outputs/targets as pickles and computes metrics on rank 0 (test.py:87-95);
here metric sufficient statistics reduce in-graph, so every host holds the
identical global result and nothing crosses the interconnect as pickle.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..config.registry import LOADERS, METRICS, MODELS
from ..data.loader import prefetch_to_device
from ..models.base import inject_mesh
from ..parallel import batch_sharding, dist, mesh_from_config
from .losses import resolve_loss
from .optim import build_optimizer
from .state import create_sharded_train_state
from .steps import finalize_metrics, make_eval_step


def _build_test_loader(config):
    """Resolve the eval loader like the reference does: an explicit
    ``test_loader`` block wins; otherwise reuse the experiment's loader
    config with ``training=False`` (reference test.py:43-52 rebuilds the
    training config's loader in eval mode), preferring ``valid_loader``."""
    if config.get("test_loader", None):
        return config.init_obj("test_loader", LOADERS)
    for block in ("valid_loader", "train_loader"):
        spec = config.get(block, None)
        if spec:
            args = dict(spec.get("args", {}))
            args["training"] = False
            args["shuffle"] = False
            return LOADERS.get(spec["type"])(**args)
    raise KeyError(
        "config defines none of test_loader/valid_loader/train_loader"
    )


def restore_template_state(config, model, mesh, template=None):
    """Restore ``config.resume`` into a freshly-built template state.

    The template's tree matches what training saved: optimizer slot shapes
    depend only on optimizer type + param shapes, and ``ema_params`` is
    present iff the training config enabled EMA. Shared by the evaluation
    and sampling CLIs (test.py, generate.py). Returns
    ``(state, ema_decay)``.
    """
    from ..checkpoint import CheckpointManager

    tx, _, _ = build_optimizer(config, steps_per_epoch=1)
    ema_decay = float(config["trainer"].get("ema_decay", 0.0))
    if template is None:
        template = model.batch_template(1)
    state, _ = create_sharded_train_state(
        model, tx, template, mesh, with_ema=ema_decay > 0,
    )
    manager = CheckpointManager(config.resume.parent)
    state, _, _ = manager.restore(
        config.resume, state, config.config, type(model).__name__
    )
    return state, ema_decay


def evaluate(config, mesh=None) -> dict:
    """Evaluate ``config.resume`` on the config's ``test_loader``."""
    logger = config.get_logger("test")
    assert config.resume is not None, "evaluation requires a checkpoint (-r)"

    model = config.init_obj("arch", MODELS)
    criterion = resolve_loss(config["loss"])
    metric_fns = [METRICS.get(m) for m in config["metrics"]]
    test_loader = _build_test_loader(config)
    mesh = mesh if mesh is not None else mesh_from_config(config)
    model = inject_mesh(model, mesh)

    dk = config.get("data_keys", {}) or {}
    input_key = dk.get("input", "image")
    target_key = dk.get("target", "label")

    state, ema_decay = restore_template_state(
        config, model, mesh, template=test_loader.arrays[input_key][:1]
    )

    eval_step = jax.jit(
        make_eval_step(
            model, criterion, metric_fns,
            input_key=input_key, target_key=target_key,
            use_ema=ema_decay > 0
            and bool(config["trainer"].get("eval_with_ema", True)),
        )
    )

    accum = None
    for batch in prefetch_to_device(test_loader, batch_sharding(mesh)):
        m = eval_step(state, batch)
        accum = m if accum is None else jax.tree.map(jnp.add, accum, m)

    n_samples = int(accum["count"]) if accum else 0
    result = finalize_metrics(jax.tree.map(float, accum)) if accum else {}
    if dist.is_main_process():
        logger.info({"n_samples": n_samples, **result})
    return result
