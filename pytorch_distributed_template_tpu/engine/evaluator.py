"""Distributed evaluation: the reference's ``test.py`` as a library.

Parity with /root/reference/test.py:14-101: build components from config,
restore a checkpoint, run a no-grad loop over the test loader, and compute
metrics over the *global* dataset. The reference all_gathers every rank's
outputs/targets as pickles and computes metrics on rank 0 (test.py:87-95);
here metric sufficient statistics reduce in-graph, so every host holds the
identical global result and nothing crosses the interconnect as pickle.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..config.registry import LOADERS, METRICS, MODELS
from ..data.loader import prefetch_to_device
from ..models.base import inject_mesh
from ..observability.trace import span
from ..parallel import batch_sharding, dist, mesh_from_config
from .losses import resolve_loss
from .optim import build_optimizer
from .state import create_sharded_train_state
from .steps import _accepts_example_mask, finalize_metrics, make_eval_step


def _build_test_loader(config):
    """Resolve the eval loader like the reference does: an explicit
    ``test_loader`` block wins; otherwise reuse the experiment's loader
    config with ``training=False`` (reference test.py:43-52 rebuilds the
    training config's loader in eval mode), preferring ``valid_loader``."""
    if config.get("test_loader", None):
        return config.init_obj("test_loader", LOADERS)
    for block in ("valid_loader", "train_loader"):
        spec = config.get(block, None)
        if spec:
            args = dict(spec.get("args", {}))
            args["training"] = False
            args["shuffle"] = False
            return LOADERS.get(spec["type"])(**args)
    raise KeyError(
        "config defines none of test_loader/valid_loader/train_loader"
    )


def restore_template_state(config, model, mesh, template=None):
    """Restore ``config.resume`` into a freshly-built template state.

    The template's tree matches what training saved: optimizer slot shapes
    depend only on optimizer type + param shapes, and ``ema_params`` is
    present iff the training config enabled EMA. Shared by the evaluation
    and sampling CLIs (test.py, generate.py). Returns
    ``(state, ema_decay)``.
    """
    from ..checkpoint import CheckpointManager

    tx, _, _ = build_optimizer(config, steps_per_epoch=1)
    ema_decay = float(config["trainer"].get("ema_decay", 0.0))
    if template is None:
        template = model.batch_template(1)
    state, _ = create_sharded_train_state(
        model, tx, template, mesh, with_ema=ema_decay > 0,
    )
    manager = CheckpointManager(config.resume.parent)
    state, _, _ = manager.restore(
        config.resume, state, config.config, type(model).__name__
    )
    return state, ema_decay


def _make_output_step(model, input_key: str, use_ema: bool, mesh,
                      eval_rng: bool = False):
    """Jitted raw-output forward for ``--save-outputs``: returns the
    model's per-example outputs (logits), materializing them even for
    ``fused_head`` models. This is a second forward pass on top of
    ``eval_step`` — accepted: the dump is opt-in, and keeping the metric
    path's in-graph global reductions untouched beats threading a
    [B, T, V] residual through it.

    The result is sharding-constrained to batch-only (non-batch dims
    replicated): under TP the head kernel is vocab-sharded, and without
    the constraint each host's shards would cover only a V/tp column
    slice of its rows."""
    pass_example_mask = _accepts_example_mask(model)
    out_sharding = batch_sharding(mesh)

    def output_step(state, batch, rng=None):
        params = (
            state.ema_params
            if use_ema and state.ema_params is not None
            else state.params
        )
        variables = {"params": params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        extra = (
            {"example_mask": batch["mask"]} if pass_example_mask else {}
        )
        if eval_rng:
            # SAME per-batch key as eval_step: the dumped logits/mask
            # must describe the batch the metrics actually scored
            extra["rngs"] = {"eval": rng}
        out = model.apply(variables, batch[input_key], train=False, **extra)
        if getattr(model, "mlm_output", False):
            # (logits, per-position eval mask) — the BERT MLM pair
            # (models/bert.py, dispatched by the class attribute, NOT
            # by shape sniffing): keep both; the dump writes the mask
            # next to the logits so saved outputs never depend on the
            # model's private mask rule
            logits, sel = out
            return (
                jax.lax.with_sharding_constraint(
                    logits.astype(jnp.float32), out_sharding
                ),
                jax.lax.with_sharding_constraint(
                    sel.astype(jnp.float32), out_sharding
                ),
            )
        if isinstance(out, tuple):
            # fused_head: (hidden [B,T,D], w [D,V]) — materialize logits
            hidden, w = out
            out = hidden @ w
        return jax.lax.with_sharding_constraint(
            out.astype(jnp.float32), out_sharding
        )

    return output_step


def _host_local_rows(arr) -> np.ndarray:
    """Rows of a batch-sharded global array that live on THIS host, in
    batch order, deduplicating replicated shards (e.g. over a tensor
    axis). The per-host analogue of the reference's gather-to-rank-0
    (test.py:87-95) — over DCN each host dumps its own rows instead of
    pickling activations across the network."""
    by_start = {}
    for s in arr.addressable_shards:
        # batch-only sharding contract: every non-batch dim must be a full
        # slice, else dedup-by-row-start would silently drop columns.
        # A real error (not an assert) so the contract survives `python -O`.
        if not all(
            sl.start in (None, 0) and sl.stop in (None, n)
            for sl, n in zip(s.index[1:], arr.shape[1:])
        ):
            raise ValueError(
                f"shard {s.index} is split along a non-batch axis; "
                "save_outputs requires batch-only sharding"
            )
        start = s.index[0].start or 0
        if start not in by_start:
            by_start[start] = np.asarray(s.data)
    return np.concatenate(
        [by_start[k] for k in sorted(by_start)], axis=0
    )


def evaluate(config, mesh=None, save_outputs=None, seed=None) -> dict:
    """Evaluate ``config.resume`` on the config's ``test_loader``.

    ``save_outputs``: optional directory; when set, every host writes its
    per-example model outputs/targets (pad-filtered, eval order) as
    ``outputs_p{K}.npy`` / ``targets_p{K}.npy`` for post-hoc analysis —
    the capability the reference exposes by gathering raw predictions
    (reference test.py:87-95, base_trainer.py:176-181).

    ``seed``: optional int; seeds eval-time model randomness (the
    ``"eval"`` rng stream, folded per batch — e.g. BertMLM's seeded
    random eval mask). ``None`` keeps the fully deterministic eval path.
    The reference's ``--seed`` crashes outright (reference test.py:125,
    numpy unimported); here it is wired end to end.
    """
    logger = config.get_logger("test")
    assert config.resume is not None, "evaluation requires a checkpoint (-r)"

    model = config.init_obj("arch", MODELS)
    criterion = resolve_loss(config["loss"])
    metric_fns = [METRICS.get(m) for m in config["metrics"]]
    test_loader = _build_test_loader(config)
    mesh = mesh if mesh is not None else mesh_from_config(config)
    model = inject_mesh(model, mesh)

    dk = config.get("data_keys", {}) or {}
    input_key = dk.get("input", "image")
    target_key = dk.get("target", "label")

    template = test_loader.arrays[input_key][:1]
    device_transform = getattr(test_loader, "device_transform", None)
    if device_transform is not None:
        template = np.asarray(
            device_transform({input_key: template})[input_key]
        )
    state, ema_decay = restore_template_state(
        config, model, mesh, template=template
    )

    use_ema = ema_decay > 0 and bool(
        config["trainer"].get("eval_with_ema", True)
    )
    eval_step = jax.jit(
        make_eval_step(
            model, criterion, metric_fns,
            input_key=input_key, target_key=target_key,
            use_ema=use_ema, eval_rng=seed is not None,
        )
    )
    base_key = (
        jax.random.key(int(seed)) if seed is not None else None
    )

    output_step = None
    if save_outputs is not None:
        output_step = jax.jit(
            _make_output_step(
                model, input_key, use_ema=use_ema, mesh=mesh,
                eval_rng=seed is not None,
            )
        )
        dumped_out, dumped_tgt, dumped_msk = [], [], []

    from ..utils.util import maybe_tqdm

    batches = prefetch_to_device(
        test_loader, batch_sharding(mesh),
        size=max(int(config["trainer"].get("prefetch_depth", 2)), 1),
        transform=device_transform,
    )
    if dist.is_main_process():
        # reference test.py:71 wraps the eval loop in tqdm (TTY-gated)
        batches = maybe_tqdm(batches, total=len(test_loader), desc="eval",
                             enable=config["trainer"].get("progress"))
    accum = None
    for i, batch in enumerate(batches):
        # per-batch key: every host folds the same global batch index,
        # so the mask agrees across hosts of a sharded batch
        rng_args = (
            (jax.random.fold_in(base_key, i),)
            if base_key is not None else ()
        )
        with span("eval/step", batch=i):
            m = eval_step(state, batch, *rng_args)
        accum = m if accum is None else jax.tree.map(jnp.add, accum, m)
        if output_step is not None:
            with span("eval/save_outputs", batch=i):
                res = output_step(state, batch, *rng_args)
            keep = _host_local_rows(batch["mask"]).astype(bool)
            if isinstance(res, tuple):          # MLM: (logits, eval mask)
                res, msk = res
                # bool on host: the dump exists for large eval sets, and
                # a f32 position mask would 4x the file + transfer
                dumped_msk.append(
                    _host_local_rows(msk)[keep].astype(bool)
                )
            out = _host_local_rows(res)
            tgt = _host_local_rows(batch[target_key])
            dumped_out.append(out[keep])
            dumped_tgt.append(tgt[keep])

    if output_step is not None:
        from pathlib import Path

        out_dir = Path(save_outputs)
        out_dir.mkdir(parents=True, exist_ok=True)
        p = dist.process_index()
        if dumped_out:
            np.save(out_dir / f"outputs_p{p}.npy", np.concatenate(dumped_out))
            np.save(out_dir / f"targets_p{p}.npy", np.concatenate(dumped_tgt))
            if dumped_msk:
                # the MLM eval mask rides along so post-hoc scoring never
                # re-derives the model's private masking rule
                np.save(out_dir / f"masks_p{p}.npy",
                        np.concatenate(dumped_msk))
            logger.info("saved per-example outputs to %s", out_dir)
        else:
            # No local batches at all: writing a shape/dtype-less
            # placeholder would poison post-hoc cross-host concatenation
            # of outputs_p*.npy, so skip the files and say so.
            logger.info(
                "no local eval rows on process %d; skipping output dump", p
            )

    n_samples = int(accum["count"]) if accum else 0
    result = finalize_metrics(jax.tree.map(float, accum)) if accum else {}
    if dist.is_main_process():
        logger.info({"n_samples": n_samples, **result})
    return result
