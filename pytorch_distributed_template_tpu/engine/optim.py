"""Optimizers and LR schedulers as registry entries over optax.

The reference resolves ``config['optimizer']['type']`` against
``torch.optim`` and ``config['lr_scheduler']['type']`` against
``torch.optim.lr_scheduler`` (/root/reference/train.py:42-43), stepping the
scheduler once per epoch (trainer/trainer.py:90-91). TPU-natively the whole
update is inside the jitted step, so:

- optimizer builders accept torch-style arg names (``lr``, ``betas``,
  ``amsgrad``, ``weight_decay``...) and produce an
  ``optax.GradientTransformation``;
- schedulers are *epoch-indexed scale factories* ``f(epoch) -> scale``,
  converted to per-step optax schedules via ``steps_per_epoch`` at trainer
  build time — numerically matching the reference's per-epoch stepping while
  remaining a pure function of the step counter (checkpoint-resume safe:
  the schedule replays from the restored step).

``build_optimizer(config, steps_per_epoch)`` is the one-stop entry used by
the trainer; ``init_obj('optimizer', OPTIMIZERS)`` also works for direct use.
"""
from __future__ import annotations

import math
import re
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax

from ..config.registry import OPTIMIZERS, SCHEDULERS


def _lr(lr, learning_rate):
    if learning_rate is not None:
        return learning_rate
    return lr


def _decay_mask(exclude):
    """Build an optax weight-decay mask from path patterns.

    ``exclude`` is a list of regexes searched against each parameter's
    ``/``-joined path (e.g. ``h_0/attn/qkv/bias``); matching leaves get NO
    decay. Returns None (decay everything — torch semantics, the default)
    when ``exclude`` is falsy, else a callable ``params -> bool pytree``
    (evaluated at init, so the mask follows whatever tree it is given).

    Not a torch.optim arg: torch decays every parameter, and so do we by
    default. The standard LM/ViT recipes exempt biases, LayerNorms, and
    position embeddings — e.g. ``"weight_decay_exclude":
    ["bias$", "ln_", "wpe"]``.
    """
    if not exclude:
        return None
    from ..parallel.sharding import path_str

    pats = [re.compile(p) for p in exclude]

    def mask(params):
        def decide(path, _):
            return not any(p.search(path_str(path)) for p in pats)

        return jax.tree_util.tree_map_with_path(decide, params)

    return mask


def _trainable_only(tx, patterns):
    """Freeze every param whose ``/``-joined path matches NO regex in
    ``patterns`` — the parameter-efficient fine-tuning switch
    (``"optimizer": {"args": {"trainable": ["lora_"]}}``).

    ``optax.multi_transform`` routes trainable leaves through ``tx`` and
    frozen leaves through ``set_to_zero`` (NOT ``optax.masked``, which
    passes masked-out gradients through as raw updates). Frozen leaves
    therefore receive exactly zero updates AND allocate no moment
    buffers (Adam state is 2x params — the real memory cost of "train
    everything"). Complements models/lora.LoRADense's in-graph
    ``stop_gradient`` (which prunes the frozen dW matmuls from the
    backward); this switch alone also freezes non-LoRA leaves like
    embeddings and norms."""
    from ..parallel.sharding import path_str

    pats = [re.compile(p) for p in patterns]

    def labels(params):
        def decide(path, _):
            return "train" if any(p.search(path_str(path)) for p in pats) \
                else "freeze"

        return jax.tree_util.tree_map_with_path(decide, params)

    return optax.multi_transform(
        {"train": tx, "freeze": optax.set_to_zero()}, labels
    )


def _decayed(weight_decay, base, exclude=None):
    """``add_decayed_weights`` (coupled, torch-style) chained before
    ``base``, honoring an optional exclusion mask."""
    if not weight_decay:
        return base
    return optax.chain(
        optax.add_decayed_weights(weight_decay, mask=_decay_mask(exclude)),
        base,
    )


def _mu_dtype(name):
    """Optional reduced-precision first moment (``mu_dtype: "bfloat16"``):
    halves one of Adam's two moment buffers in HBM — an optimizer-memory
    lever at LM scale (the second moment stays f32; its dynamic range is
    the numerically fragile one). Measured neutral-to-slightly-slower on
    a compute-bound step, so it is opt-in, not a default."""
    return jnp.dtype(name) if name else None


@OPTIMIZERS.register("Adam")
def adam(lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
         amsgrad=False, learning_rate=None, weight_decay_exclude=None,
         mu_dtype=None):
    lr = _lr(lr, learning_rate)
    b1, b2 = betas
    if amsgrad:
        base = optax.amsgrad(lr, b1=b1, b2=b2, eps=eps,
                             mu_dtype=_mu_dtype(mu_dtype))
    else:
        base = optax.adam(lr, b1=b1, b2=b2, eps=eps,
                          mu_dtype=_mu_dtype(mu_dtype))
    return _decayed(weight_decay, base, weight_decay_exclude)


@OPTIMIZERS.register("AdamW")
def adamw(lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.01,
          learning_rate=None, weight_decay_exclude=None, mu_dtype=None):
    b1, b2 = betas
    return optax.adamw(_lr(lr, learning_rate), b1=b1, b2=b2, eps=eps,
                       weight_decay=weight_decay,
                       mask=_decay_mask(weight_decay_exclude),
                       mu_dtype=_mu_dtype(mu_dtype))


@OPTIMIZERS.register("SGD")
def sgd(lr=0.1, momentum=0.0, weight_decay=0.0, nesterov=False,
        learning_rate=None, weight_decay_exclude=None):
    base = optax.sgd(_lr(lr, learning_rate), momentum=momentum or None,
                     nesterov=nesterov)
    return _decayed(weight_decay, base, weight_decay_exclude)


@OPTIMIZERS.register("RMSprop")
def rmsprop(lr=1e-2, alpha=0.99, eps=1e-8, momentum=0.0, weight_decay=0.0,
            learning_rate=None, weight_decay_exclude=None):
    base = optax.rmsprop(_lr(lr, learning_rate), decay=alpha, eps=eps,
                         momentum=momentum or None)
    return _decayed(weight_decay, base, weight_decay_exclude)


@OPTIMIZERS.register("Adagrad")
def adagrad(lr=1e-2, eps=1e-10, weight_decay=0.0, learning_rate=None,
            weight_decay_exclude=None):
    base = optax.adagrad(_lr(lr, learning_rate), eps=eps)
    return _decayed(weight_decay, base, weight_decay_exclude)


@OPTIMIZERS.register("Adadelta")
def adadelta(lr=1.0, rho=0.9, eps=1e-6, weight_decay=0.0,
             learning_rate=None, weight_decay_exclude=None):
    return optax.adadelta(_lr(lr, learning_rate), rho=rho, eps=eps,
                          weight_decay=weight_decay,
                          weight_decay_mask=_decay_mask(weight_decay_exclude))


@OPTIMIZERS.register("Adamax")
def adamax(lr=2e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
           learning_rate=None, weight_decay_exclude=None):
    b1, b2 = betas
    base = optax.adamax(_lr(lr, learning_rate), b1=b1, b2=b2, eps=eps)
    return _decayed(weight_decay, base, weight_decay_exclude)


@OPTIMIZERS.register("NAdam")
def nadam(lr=2e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
          learning_rate=None, weight_decay_exclude=None):
    b1, b2 = betas
    base = optax.nadam(_lr(lr, learning_rate), b1=b1, b2=b2, eps=eps)
    return _decayed(weight_decay, base, weight_decay_exclude)


@OPTIMIZERS.register("RAdam")
def radam(lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
          learning_rate=None, weight_decay_exclude=None):
    b1, b2 = betas
    base = optax.radam(_lr(lr, learning_rate), b1=b1, b2=b2, eps=eps)
    return _decayed(weight_decay, base, weight_decay_exclude)


@OPTIMIZERS.register("Adafactor")
def adafactor(lr=None, weight_decay=0.0, learning_rate=None,
              weight_decay_exclude=None):
    """Factored second-moment Adam (Shazeer & Stern 2018) — the T5/TPU
    recipe: O(n+m) optimizer memory per [n, m] matrix instead of Adam's
    O(n*m). Not in torch.optim; first-class here because optimizer HBM is
    a real TPU ceiling at LM scale."""
    return optax.adafactor(_lr(lr, learning_rate),
                           weight_decay_rate=weight_decay or None,
                           weight_decay_mask=_decay_mask(weight_decay_exclude))


# --- large-batch optimizers (beyond the reference: the TPU data-parallel
# scaling path runs at batch sizes where plain SGD/Adam degrade; LARS/LAMB
# are the standard trust-ratio fixes, Lion the memory-lean alternative) ----

@OPTIMIZERS.register("LARS")
def lars(lr=1.0, momentum=0.9, weight_decay=0.0,
         trust_coefficient=0.001, learning_rate=None,
         weight_decay_exclude=None):
    """Layer-wise adaptive rate scaling (You et al. 2017) — large-batch
    ResNet/ImageNet (the MLPerf recipe)."""
    mask = _decay_mask(weight_decay_exclude)
    kwargs = {} if mask is None else {"weight_decay_mask": mask}
    return optax.lars(
        _lr(lr, learning_rate), weight_decay=weight_decay,
        momentum=momentum, trust_coefficient=trust_coefficient, **kwargs,
    )


@OPTIMIZERS.register("LAMB")
def lamb(lr=1e-3, betas=(0.9, 0.999), eps=1e-6, weight_decay=0.0,
         learning_rate=None, weight_decay_exclude=None):
    """Layer-wise Adam (You et al. 2020) — large-batch transformers."""
    b1, b2 = betas
    return optax.lamb(_lr(lr, learning_rate), b1=b1, b2=b2, eps=eps,
                      weight_decay=weight_decay,
                      mask=_decay_mask(weight_decay_exclude))


@OPTIMIZERS.register("Lion")
def lion(lr=1e-4, betas=(0.9, 0.99), weight_decay=0.0, learning_rate=None,
         weight_decay_exclude=None):
    """Sign-momentum optimizer (Chen et al. 2023): one momentum slot —
    half Adam's optimizer HBM, a real win at TPU memory limits."""
    b1, b2 = betas
    return optax.lion(_lr(lr, learning_rate), b1=b1, b2=b2,
                      weight_decay=weight_decay,
                      mask=_decay_mask(weight_decay_exclude))


# ---------------------------------------------------------------------------
# epoch-indexed LR scale schedules (reference lr_scheduler parity)
# ---------------------------------------------------------------------------

@SCHEDULERS.register("StepLR")
def step_lr(step_size: int, gamma: float = 0.1):
    """Reference default: StepLR(50, 0.1) (config/config.json:56-61)."""
    return lambda epoch: gamma ** (epoch // step_size)


@SCHEDULERS.register("MultiStepLR")
def multi_step_lr(milestones, gamma: float = 0.1):
    # jnp arithmetic: the epoch is a traced int32 inside the jitted step
    # (the schedule is evaluated on the optimizer's step counter in-graph).
    ms = jnp.asarray(sorted(milestones))
    return lambda epoch: gamma ** jnp.sum(epoch >= ms)


@SCHEDULERS.register("ExponentialLR")
def exponential_lr(gamma: float):
    return lambda epoch: gamma ** epoch


@SCHEDULERS.register("CosineAnnealingLR")
def cosine_annealing_lr(T_max: int, eta_min_ratio: float = 0.0):
    def f(epoch):
        cos = (1 + jnp.cos(math.pi * jnp.minimum(epoch, T_max) / T_max)) / 2
        return eta_min_ratio + (1 - eta_min_ratio) * cos

    return f


@SCHEDULERS.register("LinearLR")
def linear_lr(start_factor: float = 1.0 / 3, end_factor: float = 1.0,
              total_iters: int = 5):
    """torch LinearLR: ramp start_factor -> end_factor over total_iters
    epochs, then hold."""

    def f(epoch):
        frac = jnp.minimum(epoch, total_iters) / max(total_iters, 1)
        return start_factor + (end_factor - start_factor) * frac

    return f


@SCHEDULERS.register("ConstantLR")
def constant_lr(factor: float = 1.0 / 3, total_iters: int = 5):
    """torch ConstantLR: scale by ``factor`` until total_iters, then 1."""
    return lambda epoch: jnp.where(epoch < total_iters, factor, 1.0)


@SCHEDULERS.register("PolynomialLR")
def polynomial_lr(total_iters: int = 5, power: float = 1.0):
    def f(epoch):
        frac = 1.0 - jnp.minimum(epoch, total_iters) / max(total_iters, 1)
        return frac ** power

    return f


@SCHEDULERS.register("CosineAnnealingWarmRestarts")
def cosine_annealing_warm_restarts(T_0: int, T_mult: int = 1):
    """torch semantics: cosine cycles of length T_0, T_0*T_mult, ... The
    cycle index is closed-form so the schedule stays a pure function of the
    epoch (jit/resume safe)."""
    if T_mult < 1:
        raise ValueError("T_mult must be >= 1")

    def f(epoch):
        e = jnp.asarray(epoch, jnp.float32)
        if T_mult == 1:
            t_cur, t_i = e % T_0, float(T_0)
        else:
            # cycle index; the +1e-4 absorbs float32 log rounding at restart
            # boundaries (where the ratio is exactly integral but the
            # computed value can land a few ulps below — flooring that would
            # place the restart epoch at the END of the previous cycle and
            # emit scale 0 instead of the intended 1)
            n = jnp.floor(
                jnp.log(e / T_0 * (T_mult - 1) + 1) / math.log(T_mult)
                + 1e-4
            )
            geom = (T_mult ** n - 1) / (T_mult - 1)   # epochs before cycle n
            t_cur = e - T_0 * geom
            t_i = T_0 * T_mult ** n
        return (1 + jnp.cos(math.pi * t_cur / t_i)) / 2

    return f


@SCHEDULERS.register("WarmupCosine")
def warmup_cosine(warmup_epochs: int, total_epochs: int,
                  min_ratio: float = 0.0):
    """TPU-idiomatic default for the big-model ladder (not in reference)."""

    def f(epoch):
        warm = (epoch + 1) / max(warmup_epochs, 1)
        frac = (epoch - warmup_epochs) / max(total_epochs - warmup_epochs, 1)
        cos = (1 + jnp.cos(math.pi * jnp.clip(frac, 0.0, 1.0))) / 2
        decayed = min_ratio + (1 - min_ratio) * cos
        return jnp.where(epoch < warmup_epochs, warm, decayed)

    return f


class PlateauController:
    """Host-side ReduceLROnPlateau (torch.optim.lr_scheduler semantics).

    The reference's lr_scheduler slot resolves any torch scheduler by name
    (/root/reference/train.py:43); plateau scheduling is the one family that
    cannot be a pure function of the step counter — it reacts to a monitored
    metric. Here it drives ``TrainState.lr_scale`` (a replicated scalar the
    jitted step multiplies into the optimizer update), so the compiled step
    never retraces when the LR drops. Epoch metrics are identical on every
    host (in-graph global reductions), so each host's controller makes the
    same decision with no extra collective.

    ``step(value) -> scale`` is called once per epoch with the monitored
    metric; ``monitor`` names the epoch-log key (e.g. ``val_loss``). The
    scale survives checkpoints via TrainState; the counters reset on resume
    (the reference checkpoints no scheduler state either,
    base_trainer.py:109-132).
    """

    def __init__(self, mode: str = "min", factor: float = 0.1,
                 patience: int = 10, threshold: float = 1e-4,
                 threshold_mode: str = "rel", cooldown: int = 0,
                 min_scale: float = 0.0, eps_scale: float = 1e-8,
                 monitor: str = "val_loss"):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be min|max, got {mode!r}")
        if threshold_mode not in ("rel", "abs"):
            raise ValueError(
                f"threshold_mode must be rel|abs, got {threshold_mode!r}"
            )
        if not 0.0 < factor < 1.0:
            raise ValueError("factor must be in (0, 1)")
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_scale = min_scale
        self.eps_scale = eps_scale  # torch's eps, in scale (lr/base_lr) units
        self.monitor = monitor
        self.best = math.inf if mode == "min" else -math.inf
        self.num_bad_epochs = 0
        self.cooldown_counter = 0
        self.scale = 1.0

    def _improved(self, value: float) -> bool:
        if self.mode == "min":
            bar = (
                self.best * (1 - self.threshold)
                if self.threshold_mode == "rel" else self.best - self.threshold
            )
            return value < bar
        bar = (
            self.best * (1 + self.threshold)
            if self.threshold_mode == "rel" else self.best + self.threshold
        )
        return value > bar

    def step(self, value: float) -> float:
        # mirrors torch's sequencing exactly: cooldown ticks down on every
        # epoch (improved or not) and zeroes the bad-epoch count afterwards
        if self._improved(value):
            self.best = value
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad_epochs = 0
        if self.num_bad_epochs > self.patience:
            new_scale = max(self.scale * self.factor, self.min_scale)
            if self.scale - new_scale > self.eps_scale:  # torch's eps gate
                self.scale = new_scale
            self.cooldown_counter = self.cooldown
            self.num_bad_epochs = 0
        return self.scale


def build_optimizer(config, steps_per_epoch: int):
    """Compose optimizer + epoch-scale scheduler into one optax transform.

    Returns ``(tx, lr_fn, plateau)`` where ``lr_fn(step) -> lr`` is for
    logging and ``plateau`` is a PlateauController when the config's
    lr_scheduler is ``ReduceLROnPlateau`` (else None). The epoch used is
    ``step // steps_per_epoch`` with the reference's convention: the
    scheduler has been stepped ``epoch`` times after epoch ``epoch``
    completes, i.e. during epoch e (1-based) the scale is f(e - 1).

    ``"unit": "step"`` on the lr_scheduler block indexes the schedule by
    optimizer step instead (its args then denote steps — e.g.
    ``WarmupCosine(warmup_epochs=2000, total_epochs=100000)`` reads as
    warmup steps / total steps). Smooth per-step warmup for long-epoch LM
    runs; the default ("epoch") keeps reference semantics.
    """
    opt_cfg = config["optimizer"]
    opt_args = dict(opt_cfg.get("args", {}))
    # Adafactor's native default is lr=None (relative-step mode); every
    # other registered optimizer defaults like torch (a numeric lr).
    default_lr = None if opt_cfg["type"] == "Adafactor" else 1e-3
    base_lr = opt_args.get("learning_rate", opt_args.get("lr", default_lr))
    if base_lr is None and opt_cfg["type"] != "Adafactor":
        # only Adafactor can derive its own magnitude; anything else would
        # silently fall through to the registry builder's default lr
        raise ValueError(
            f"optimizer {opt_cfg['type']!r} requires a numeric lr "
            "(lr=None is Adafactor's relative-step mode only)"
        )

    scale_fn: Optional[Callable] = None
    plateau: Optional[PlateauController] = None
    sched_cfg = config["lr_scheduler"] if "lr_scheduler" in config else None
    if sched_cfg and base_lr is None:
        raise ValueError(
            "lr_scheduler requires an explicit numeric optimizer lr "
            f"(got lr=None for {opt_cfg['type']}, which means "
            "optimizer-internal relative stepping)"
        )
    if (sched_cfg and sched_cfg.get("unit") == "step"
            and sched_cfg["type"] == "ReduceLROnPlateau"):
        raise ValueError(
            "ReduceLROnPlateau is metric-driven per epoch; unit='step' "
            "does not apply"
        )
    if sched_cfg and sched_cfg["type"] == "ReduceLROnPlateau":
        args = dict(sched_cfg.get("args", {}))
        # torch spells min_lr/eps in lr units (min_lr possibly as a
        # per-param-group list — we have one group); scale is relative
        if "min_lr" in args:
            min_lr = args.pop("min_lr")
            if isinstance(min_lr, (list, tuple)):
                min_lr = min_lr[0]
            args["min_scale"] = min_lr / base_lr
        if "eps" in args:
            args["eps_scale"] = args.pop("eps") / base_lr
        plateau = PlateauController(**args)
    elif sched_cfg:
        factory = SCHEDULERS.get(sched_cfg["type"])
        scale_fn = factory(**sched_cfg.get("args", {}))

    # granularity of the schedule index: "epoch" (reference semantics — the
    # scheduler steps once per epoch, train.py:43 + trainer.py:90-91) or
    # "step" (the schedule's args are in optimizer steps — the LM warmup
    # idiom, where one epoch can be thousands of steps and an epoch-ticked
    # warmup would jump the LR in cliffs)
    unit = (sched_cfg or {}).get("unit", "epoch")
    if unit not in ("epoch", "step"):
        raise ValueError(f"lr_scheduler unit must be epoch|step, got {unit!r}")

    if scale_fn is not None and unit == "step":
        def schedule(step):
            return base_lr * scale_fn(step)
    elif scale_fn is not None:
        def schedule(step):
            epoch0 = step // max(steps_per_epoch, 1)  # 0-based completed epochs
            return base_lr * scale_fn(epoch0)
    elif base_lr is None:
        # relative-step mode: the optimizer derives its own magnitude; the
        # logging lr_fn reports NaN (there is no single lr to report)
        schedule = None
    else:
        def schedule(step):
            return base_lr

    opt_args.pop("lr", None)
    opt_args["learning_rate"] = schedule
    trainable = opt_args.pop("trainable", None)
    tx = OPTIMIZERS.get(opt_cfg["type"])(**opt_args)
    if trainable:
        tx = _trainable_only(tx, trainable)
    lr_fn = schedule if schedule is not None else (
        lambda step: float("nan")
    )
    return tx, lr_fn, plateau
