"""Optimizers and LR schedulers as registry entries over optax.

The reference resolves ``config['optimizer']['type']`` against
``torch.optim`` and ``config['lr_scheduler']['type']`` against
``torch.optim.lr_scheduler`` (/root/reference/train.py:42-43), stepping the
scheduler once per epoch (trainer/trainer.py:90-91). TPU-natively the whole
update is inside the jitted step, so:

- optimizer builders accept torch-style arg names (``lr``, ``betas``,
  ``amsgrad``, ``weight_decay``...) and produce an
  ``optax.GradientTransformation``;
- schedulers are *epoch-indexed scale factories* ``f(epoch) -> scale``,
  converted to per-step optax schedules via ``steps_per_epoch`` at trainer
  build time — numerically matching the reference's per-epoch stepping while
  remaining a pure function of the step counter (checkpoint-resume safe:
  the schedule replays from the restored step).

``build_optimizer(config, steps_per_epoch)`` is the one-stop entry used by
the trainer; ``init_obj('optimizer', OPTIMIZERS)`` also works for direct use.
"""
from __future__ import annotations

import math
from typing import Callable, Optional

import jax.numpy as jnp
import optax

from ..config.registry import OPTIMIZERS, SCHEDULERS


def _lr(lr, learning_rate):
    if learning_rate is not None:
        return learning_rate
    return lr


@OPTIMIZERS.register("Adam")
def adam(lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
         amsgrad=False, learning_rate=None):
    lr = _lr(lr, learning_rate)
    b1, b2 = betas
    if amsgrad:
        base = optax.amsgrad(lr, b1=b1, b2=b2, eps=eps)
    else:
        base = optax.adam(lr, b1=b1, b2=b2, eps=eps)
    if weight_decay:
        return optax.chain(optax.add_decayed_weights(weight_decay), base)
    return base


@OPTIMIZERS.register("AdamW")
def adamw(lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.01,
          learning_rate=None):
    b1, b2 = betas
    return optax.adamw(_lr(lr, learning_rate), b1=b1, b2=b2, eps=eps,
                       weight_decay=weight_decay)


@OPTIMIZERS.register("SGD")
def sgd(lr=0.1, momentum=0.0, weight_decay=0.0, nesterov=False,
        learning_rate=None):
    base = optax.sgd(_lr(lr, learning_rate), momentum=momentum or None,
                     nesterov=nesterov)
    if weight_decay:
        return optax.chain(optax.add_decayed_weights(weight_decay), base)
    return base


@OPTIMIZERS.register("RMSprop")
def rmsprop(lr=1e-2, alpha=0.99, eps=1e-8, momentum=0.0, learning_rate=None):
    return optax.rmsprop(_lr(lr, learning_rate), decay=alpha, eps=eps,
                         momentum=momentum or None)


@OPTIMIZERS.register("Adagrad")
def adagrad(lr=1e-2, eps=1e-10, learning_rate=None):
    return optax.adagrad(_lr(lr, learning_rate), eps=eps)


# --- large-batch optimizers (beyond the reference: the TPU data-parallel
# scaling path runs at batch sizes where plain SGD/Adam degrade; LARS/LAMB
# are the standard trust-ratio fixes, Lion the memory-lean alternative) ----

@OPTIMIZERS.register("LARS")
def lars(lr=1.0, momentum=0.9, weight_decay=0.0,
         trust_coefficient=0.001, learning_rate=None):
    """Layer-wise adaptive rate scaling (You et al. 2017) — large-batch
    ResNet/ImageNet (the MLPerf recipe)."""
    return optax.lars(
        _lr(lr, learning_rate), weight_decay=weight_decay,
        momentum=momentum, trust_coefficient=trust_coefficient,
    )


@OPTIMIZERS.register("LAMB")
def lamb(lr=1e-3, betas=(0.9, 0.999), eps=1e-6, weight_decay=0.0,
         learning_rate=None):
    """Layer-wise Adam (You et al. 2020) — large-batch transformers."""
    b1, b2 = betas
    return optax.lamb(_lr(lr, learning_rate), b1=b1, b2=b2, eps=eps,
                      weight_decay=weight_decay)


@OPTIMIZERS.register("Lion")
def lion(lr=1e-4, betas=(0.9, 0.99), weight_decay=0.0, learning_rate=None):
    """Sign-momentum optimizer (Chen et al. 2023): one momentum slot —
    half Adam's optimizer HBM, a real win at TPU memory limits."""
    b1, b2 = betas
    return optax.lion(_lr(lr, learning_rate), b1=b1, b2=b2,
                      weight_decay=weight_decay)


# ---------------------------------------------------------------------------
# epoch-indexed LR scale schedules (reference lr_scheduler parity)
# ---------------------------------------------------------------------------

@SCHEDULERS.register("StepLR")
def step_lr(step_size: int, gamma: float = 0.1):
    """Reference default: StepLR(50, 0.1) (config/config.json:56-61)."""
    return lambda epoch: gamma ** (epoch // step_size)


@SCHEDULERS.register("MultiStepLR")
def multi_step_lr(milestones, gamma: float = 0.1):
    # jnp arithmetic: the epoch is a traced int32 inside the jitted step
    # (the schedule is evaluated on the optimizer's step counter in-graph).
    ms = jnp.asarray(sorted(milestones))
    return lambda epoch: gamma ** jnp.sum(epoch >= ms)


@SCHEDULERS.register("ExponentialLR")
def exponential_lr(gamma: float):
    return lambda epoch: gamma ** epoch


@SCHEDULERS.register("CosineAnnealingLR")
def cosine_annealing_lr(T_max: int, eta_min_ratio: float = 0.0):
    def f(epoch):
        cos = (1 + jnp.cos(math.pi * jnp.minimum(epoch, T_max) / T_max)) / 2
        return eta_min_ratio + (1 - eta_min_ratio) * cos

    return f


@SCHEDULERS.register("WarmupCosine")
def warmup_cosine(warmup_epochs: int, total_epochs: int,
                  min_ratio: float = 0.0):
    """TPU-idiomatic default for the big-model ladder (not in reference)."""

    def f(epoch):
        warm = (epoch + 1) / max(warmup_epochs, 1)
        frac = (epoch - warmup_epochs) / max(total_epochs - warmup_epochs, 1)
        cos = (1 + jnp.cos(math.pi * jnp.clip(frac, 0.0, 1.0))) / 2
        decayed = min_ratio + (1 - min_ratio) * cos
        return jnp.where(epoch < warmup_epochs, warm, decayed)

    return f


def build_optimizer(config, steps_per_epoch: int):
    """Compose optimizer + epoch-scale scheduler into one optax transform.

    Returns ``(tx, lr_fn)`` where ``lr_fn(step) -> lr`` is for logging. The
    epoch used is ``step // steps_per_epoch`` with the reference's
    convention: the scheduler has been stepped ``epoch`` times after epoch
    ``epoch`` completes, i.e. during epoch e (1-based) the scale is
    f(e - 1).
    """
    opt_cfg = config["optimizer"]
    opt_args = dict(opt_cfg.get("args", {}))
    base_lr = opt_args.get("learning_rate", opt_args.get("lr", 1e-3))

    scale_fn: Optional[Callable] = None
    sched_cfg = config["lr_scheduler"] if "lr_scheduler" in config else None
    if sched_cfg:
        factory = SCHEDULERS.get(sched_cfg["type"])
        scale_fn = factory(**sched_cfg.get("args", {}))

    if scale_fn is not None:
        def schedule(step):
            epoch0 = step // max(steps_per_epoch, 1)  # 0-based completed epochs
            return base_lr * scale_fn(epoch0)
    else:
        def schedule(step):
            return base_lr

    opt_args.pop("lr", None)
    opt_args["learning_rate"] = schedule
    tx = OPTIMIZERS.get(opt_cfg["type"])(**opt_args)
    return tx, schedule
