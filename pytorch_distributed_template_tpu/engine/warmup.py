"""Background AOT warmup of the compiled steps (warm-path leg 2).

The first invocation of a jitted step traces + XLA-compiles before
executing; on big models that is minutes of dead chip time at the start
of every run. Dataset/loader startup (corpus read, tokenizer training,
shard mmap) runs on the host at the same moment and does not need the
compiler — so this module overlaps them: a background thread
``lower().compile()``s the train/eval steps from *abstract* batches
(``jax.ShapeDtypeStruct`` built from the loader's array specs, never a
real batch) while the trainer finishes its init, and the compiled
executables are installed before step 1.

Two contracts make this safe:

- the warmup CALLS the compiled executable thereafter (via
  ``engine.steps.instrument_step``) instead of hoping the AOT compile
  seeded the dispatch-path jit cache — the same reasoning as the
  serving engine's chunk-ladder warmup (engine/continuous.py), which
  found AOT-then-jit "probably warms" is not a guarantee;
- every failure path (lowering error, backend quirk, unexpected
  dtype) degrades to the lazy jit path with one warning — warmup is an
  optimization, never a dependency. A shape that later diverges from
  the abstract spec raises from the compiled executable; the trainer's
  loaders pad to static shapes, so that indicates a real bug upstream,
  not a warmup limitation.

Composes with the persistent compilation cache (utils/compile_cache):
warm runs satisfy the background compile from disk in seconds, so the
thread finishes long before the first batch is assembled.
"""
from __future__ import annotations

import logging
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)


def abstract_batch(loader, sharding, transform=None,
                   batch_size: Optional[int] = None) -> dict:
    """``jax.ShapeDtypeStruct`` pytree matching what
    ``data.loader.prefetch_to_device`` will feed the step: one leaf per
    loader array at the padded static batch size, plus the ``mask``
    row-validity vector, each carrying the batch ``sharding`` so AOT
    lowering sees exactly the layouts the real transfer produces.

    ``transform`` (the loader's ``device_transform``) is traced through
    ``jax.eval_shape`` so dtype changes (uint8 -> normalized float32)
    land in the abstract batch too. On multi-host meshes the global
    batch dim is ``process_count`` host shards of the local batch —
    the ``make_array_from_process_local_data`` assembly contract.
    """
    import jax

    b = int(batch_size if batch_size is not None else loader.batch_size)
    b *= jax.process_count()
    sds = {
        k: jax.ShapeDtypeStruct((b,) + tuple(v.shape[1:]), v.dtype)
        for k, v in loader.arrays.items()
    }
    norm = getattr(loader, "normalize", None)
    if norm and not getattr(loader, "_norm_on_device", False):
        # HOST-side gather-normalization (loader.py gather_normalize):
        # the stored array stays uint8 but every batch leaves the host
        # float32 — the spec must describe the batch, not the storage
        key = norm.get("key", "image")
        if key in sds:
            sds[key] = jax.ShapeDtypeStruct(sds[key].shape, np.float32)
    sds["mask"] = jax.ShapeDtypeStruct((b,), np.dtype(bool))
    if transform is not None:
        sds = jax.eval_shape(transform, sds)
    return jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                       sharding=sharding),
        sds,
    )


class StepWarmup:
    """Compile registered jitted steps on one background thread.

    Usage (the trainer's init sequence)::

        warmup = StepWarmup()
        warmup.add("train_step", jitted_train, state, abstract_batch)
        warmup.add("eval_step", jitted_eval, state, abstract_eval_batch)
        warmup.start()
        ...                      # loader/dataset startup overlaps here
        compiled = warmup.result("train_step")   # None on failure

    ``add`` arguments may mix concrete arrays (the real state — its
    avals and shardings are exactly what the first call passes) with
    ``ShapeDtypeStruct``s; nothing is executed, only
    ``lower(*args).compile()``. Jobs compile in registration order on
    one thread (the compiler parallelizes internally; a second host
    thread would just contend). ``result`` blocks until that job
    settles — by the first step the compile is normally long done, and
    when it is not, waiting on the in-flight compile is strictly no
    worse than starting the same compile lazily.
    """

    def __init__(self):
        self._jobs: list = []        # (name, fn, args)
        self._done: dict = {}        # name -> threading.Event
        self._compiled: dict = {}    # name -> compiled executable
        self._thread: Optional[threading.Thread] = None

    def add(self, name: str, jitted_fn, *args) -> None:
        if self._thread is not None:
            raise RuntimeError("warmup thread already started")
        self._jobs.append((name, jitted_fn, args))
        self._done[name] = threading.Event()

    def start(self) -> "StepWarmup":
        if self._thread is None and self._jobs:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="aot-warmup")
            self._thread.start()
        return self

    def _run(self) -> None:
        for name, fn, args in self._jobs:
            try:
                self._compiled[name] = fn.lower(*args).compile()
            except Exception:  # noqa: BLE001 — degrade to lazy compile
                logger.warning(
                    "AOT warmup of %s failed; falling back to lazy "
                    "compile on first call", name, exc_info=True,
                )
            finally:
                self._done[name].set()
        self._jobs = []  # release the arg references (state, specs)

    def result(self, name: str, timeout: Optional[float] = None):
        """The compiled executable for ``name``, or None (unknown name,
        compile failed, or ``timeout`` expired while still compiling)."""
        ev = self._done.get(name)
        if ev is None:
            return None
        ev.wait(timeout)
        return self._compiled.get(name)
