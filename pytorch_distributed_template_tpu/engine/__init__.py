from . import losses, metrics, optim  # register components
from .state import TrainState, create_train_state
from .steps import make_train_step, make_eval_step
from .trainer import Trainer
