"""Continuous (slot-based) batching for serving (VERDICT r4 next #3).

The static micro-batch scheduler (serving.BatchedGenerationService)
forms a batch once and decodes it to the longest budget: rows that
finish early keep occupying the chip, and new arrivals wait out the
whole loop. This module replaces that with a persistent decode engine:

- a shared KV cache of ``slots`` rows over the model's full
  ``max_len``, advanced by a single global position counter;
- requests ADMIT into free rows mid-flight: a batch-1 prefill against
  a fresh cache positioned at ``p - bucket`` computes the prompt's
  K/V with the correct absolute-slot RoPE rotations, and a row-scatter
  copies it into the shared cache, with the row's ``pad_len = p - L``
  hiding everything before its prompt (the same per-row-constant-shift
  argument that makes mixed-length batching exact — models/llama.py
  ``_cached_attention``);
- decode runs in CHUNKS of ``chunk`` in-graph steps (``lax.scan``)
  with per-row budgets, stop sets, sampling params, and rng streams —
  the round-5 per-row machinery from engine/generate — so rows finish
  independently and their slots free between chunks;
- the worker dispatches one chunk AHEAD when no arrivals are waiting,
  hiding the host round trip (load-bearing on tunneled devices, where
  each fenced dispatch costs ~105 ms — BASELINE.md);
- when the global position would not fit another request the engine
  waits for drain and starts a new ERA (reset the counter; stale K/V
  needs no zeroing — every row's ``pad_len`` masks it);
- with a prefix cache attached (engine/kvcache.py, the
  ``prefix_cache`` constructor arg), admissions whose prompt prefix is
  pooled scatter the cached block chain into their cache slots and
  prefill ONLY the suffix (``_warm_admit_fn``) — pool blocks are
  era-independent (canonical rotation space), so reuse survives era
  resets for free.

Token-exactness: a request's tokens depend only on its own prompt,
seed, and sampling config — never on admission time or batch
composition (tests pin this against solo ``generate()`` runs, float-
tolerance exact like the static scheduler's mixed-length batching).

Restricted to pad-capable models (RoPE positions + non-rolling cache);
``serve.py`` falls back to the static scheduler otherwise. The
reference has no serving path at all (/root/reference/test.py is batch
eval) — this subsystem is beyond-reference capability, measured by the
``serve_mixed`` bench rung.
"""
from __future__ import annotations

import functools
import logging
import queue as queue_mod
import threading
import time

import numpy as np

from ..observability.anatomy import AnatomyStore
from ..observability.trace import span
from ..utils.promtext import percentile
from .serving import GenerationService

logger = logging.getLogger(__name__)


@functools.lru_cache(maxsize=64)
def _admit_fn(model, bucket: int, k: int, n_stop: int):
    """Compiled admission for ``k`` same-bucket prompts: ONE dispatch
    does the batched prefill (a fresh ``[k]``-row cache positioned so
    every prompt ends at ``pos0 + bucket``), samples the first tokens
    (stream index 0 per row — identical to solo ``generate()``'s key
    folding), scatters the prefilled rows into the shared cache,
    advances the shared ``pos_index``, and writes the slot-state
    arrays.

    Everything is fused into one executable with PACKED integer/float
    side inputs because the tunnel serializes small RPCs: the earlier
    shape of this path (per-request prefill + separate scatter +
    per-slot host scalars) measured ~1.4 s per admission wave, and
    even split-but-batched dispatches left the uniform burst 4x
    behind the static scheduler. Donates the shared cache and slot
    arrays.

    ``ints`` columns: [slot, budget, pad_len, stop_0..stop_{W-1},
    pos0] (pos0 replicated down its column; row 0 is read).
    ``floats`` columns: [temperature, top_p]; ``topk_k`` rides
    separately as int.
    """
    import jax
    import jax.numpy as jnp

    from ..parallel.tp import constrain_kv_tree
    from .generate import _sample_rows_traced

    total = int(model.max_len)
    mesh = getattr(model, "mesh", None)

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def admit(params, shared, arrays, prompts, ints, floats,
              keys_data_k, topk_k):
        slots = ints[:, 0]
        budgets_k = ints[:, 1]
        pad_k = ints[:, 2]
        stops_k = ints[:, 3:3 + n_stop]
        pos0 = ints[0, 3 + n_stop]
        temps_k = floats[:, 0]
        ps_k = floats[:, 1]
        keys = jax.random.wrap_key_data(keys_data_k)
        shapes = jax.eval_shape(
            lambda p: model.apply(
                {"params": p}, jnp.zeros((k, total), jnp.int32),
                train=False, decode=True, mutable=["cache"],
            ),
            params,
        )[1]["cache"]
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                             shapes)
        cache = dict(constrain_kv_tree(cache, mesh))  # TP head shard
        cache["pos_index"] = pos0.astype(jnp.int32)
        logits, vs = model.apply(
            {"params": params, "cache": cache}, prompts,
            train=False, decode=True, prefill=True, mutable=["cache"],
            pad_lens=pad_k,
        )
        tok0 = _sample_rows_traced(
            jax.vmap(jax.random.fold_in)(keys,
                                         jnp.zeros((k,), jnp.int32)),
            logits[:, -1], temps_k, topk_k, ps_k,
        )

        # scatter the k prefilled rows into the shared cache (every
        # K/V-shaped leaf; duplicate slots from group padding rewrite
        # identical content, so order doesn't matter)
        new = vs["cache"]

        def put(s, n):
            if (s.ndim >= 1 and n.ndim == s.ndim and n.shape[0] == k
                    and s.shape[1:] == n.shape[1:]):
                # one indexed scatter per leaf (duplicate padded slots
                # write identical rows, so scatter order is moot); the
                # earlier k-way DUS unroll bloated the executable
                return s.at[slots].set(n.astype(s.dtype))
            return s

        shared = dict(jax.tree.map(put, dict(shared), new))
        # the shared position counter advances to the admission point;
        # chunks advance it in-graph from here (no per-dispatch host
        # rewrite)
        shared["pos_index"] = (pos0 + bucket).astype(jnp.int32)

        (tok, emitted, done, budgets, pad_lens, keys_data, stops,
         temps, ks, ps) = arrays
        arrays_out = (
            tok.at[slots].set(tok0),
            emitted.at[slots].set(jnp.ones((k,), jnp.int32)),
            done.at[slots].set(jnp.zeros((k,), bool)),
            budgets.at[slots].set(budgets_k),
            pad_lens.at[slots].set(pad_k),
            keys_data.at[slots].set(keys_data_k),
            stops.at[slots].set(stops_k),
            temps.at[slots].set(temps_k),
            ks.at[slots].set(topk_k),
            ps.at[slots].set(ps_k),
        )
        return shared, arrays_out, tok0

    return admit


@functools.lru_cache(maxsize=64)
def _warm_admit_fn(model, feed: int, k: int, n_stop: int, nb: int,
                   block: int, rotary: bool, rope_base: float,
                   kv_quant: str = ""):
    """Prefix-cache-aware admission: ``_admit_fn`` with the paged KV
    pool spliced in (engine/kvcache.py). The fed token window is only
    ``feed`` wide — the group's largest UNCACHED suffix snapped to the
    same power-of-two ladder as cold admission buckets, so the
    compile-cache/warmup story is untouched — and each row's cached
    prefix blocks are scattered into its cache slots (re-rotated from
    canonical to absolute-slot RoPE space by the row's constant start
    angle) before the prefill runs.

    Correctness shape: row ``j``'s prompt occupies slots
    ``pad_j .. p-1``; its blocks cover ``pad_j .. pad_j + c_j - 1`` and
    the fed window covers ``[p - feed, p)``. Because
    ``feed >= suffix_j`` for every row, the two always tile the prompt;
    where they overlap, the prefill's own DUS write wins over the
    scattered copy at every layer, so overlapped positions are
    RECOMPUTED exactly as the cold path computes them. Unused block
    lanes (-1 ids, group padding) redirect into the fed window, where
    the same DUS overwrite makes their garbage dead by construction.

    ``ints`` layout is ``_admit_fn``'s with ``pos0 = p - feed``; the
    pool rides as a ``{path: [P, block, H, D]}`` dict plus ``[k, nb]``
    block ids. Donates the shared cache and slot arrays; the pool is
    read-only here (capture owns its donation).
    """
    import jax
    import jax.numpy as jnp

    from ..parallel.tp import constrain_kv_tree
    from .generate import _sample_rows_traced
    from .kvcache import scatter_blocks

    total = int(model.max_len)
    mesh = getattr(model, "mesh", None)

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def admit(params, shared, arrays, prompts, ints, floats,
              keys_data_k, topk_k, pool, block_ids):
        slots = ints[:, 0]
        budgets_k = ints[:, 1]
        pad_k = ints[:, 2]
        stops_k = ints[:, 3:3 + n_stop]
        pos0 = ints[0, 3 + n_stop]
        temps_k = floats[:, 0]
        ps_k = floats[:, 1]
        keys = jax.random.wrap_key_data(keys_data_k)
        shapes = jax.eval_shape(
            lambda p: model.apply(
                {"params": p}, jnp.zeros((k, total), jnp.int32),
                train=False, decode=True, mutable=["cache"],
            ),
            params,
        )[1]["cache"]
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                             shapes)
        cache = constrain_kv_tree(cache, mesh)        # TP head shard
        cache = dict(scatter_blocks(
            dict(cache), pool, block_ids, pad_k, pos0, feed, block,
            rotary=rotary, rope_base=rope_base, kv_quant=kv_quant))
        cache["pos_index"] = pos0.astype(jnp.int32)
        logits, vs = model.apply(
            {"params": params, "cache": cache}, prompts,
            train=False, decode=True, prefill=True, mutable=["cache"],
            pad_lens=pad_k,
        )
        tok0 = _sample_rows_traced(
            jax.vmap(jax.random.fold_in)(keys,
                                         jnp.zeros((k,), jnp.int32)),
            logits[:, -1], temps_k, topk_k, ps_k,
        )
        new = vs["cache"]

        def put(s, n):
            if (s.ndim >= 1 and n.ndim == s.ndim and n.shape[0] == k
                    and s.shape[1:] == n.shape[1:]):
                return s.at[slots].set(n.astype(s.dtype))
            return s

        shared = dict(jax.tree.map(put, dict(shared), new))
        shared["pos_index"] = (pos0 + feed).astype(jnp.int32)

        (tok, emitted, done, budgets, pad_lens, keys_data, stops,
         temps, ks, ps) = arrays
        arrays_out = (
            tok.at[slots].set(tok0),
            emitted.at[slots].set(jnp.ones((k,), jnp.int32)),
            done.at[slots].set(jnp.zeros((k,), bool)),
            budgets.at[slots].set(budgets_k),
            pad_lens.at[slots].set(pad_k),
            keys_data.at[slots].set(keys_data_k),
            stops.at[slots].set(stops_k),
            temps.at[slots].set(temps_k),
            ks.at[slots].set(topk_k),
            ps.at[slots].set(ps_k),
        )
        return shared, arrays_out, tok0

    return admit


@functools.lru_cache(maxsize=64)
def _paged_admit_fn(model, feed: int, k: int, n_stop: int, nb: int):
    """TRUE paged admission (ISSUE 7 tentpole): NO cache build, NO
    scatter copy. The shared cache is gone — the engine's cache pytree
    IS the block pool, and this executable (a) writes the group's block
    tables into the shared table array (the entire "warm admit" for the
    cached prefix: a pointer update), (b) prefills ONLY each row's
    uncached suffix through the model's paged path (its K/V lands
    directly in the row's private pool pages), and (c) samples first
    tokens + writes slot state, all in one dispatch.

    Positions are row-local: row ``j``'s suffix occupies window lanes
    ``pad_j..feed-1`` at positions ``c_j..L_j-1`` (``rs_j = L_j - feed``
    is lane 0's position; lanes below ``pad_j`` write the scratch
    page). Shared radix pages cover positions ``< c_j`` and are never
    written — warm admit device-copy bytes are ZERO by construction.

    ``ints`` columns: [slot, budget, pad_0.., stop_0..stop_{W-1}, rs].
    Donates the pool cache, tables, slot arrays, and starts.
    """
    import jax
    import jax.numpy as jnp

    from .generate import _sample_rows_traced

    @functools.partial(jax.jit, donate_argnums=(1, 2, 3, 4))
    def admit(params, cache, tables, arrays, starts, prompts, ints,
              floats, keys_data_k, topk_k, tables_k):
        slots = ints[:, 0]
        budgets_k = ints[:, 1]
        pad_k = ints[:, 2]
        stops_k = ints[:, 3:3 + n_stop]
        rs_k = ints[:, 3 + n_stop]
        temps_k = floats[:, 0]
        ps_k = floats[:, 1]
        keys = jax.random.wrap_key_data(keys_data_k)
        tables = tables.at[slots].set(tables_k)
        logits, vs = model.apply(
            {"params": params, "cache": cache}, prompts,
            train=False, decode=True, prefill=True, mutable=["cache"],
            pad_lens=pad_k, block_tables=tables_k, row_starts=rs_k,
        )
        cache = dict(vs["cache"])
        tok0 = _sample_rows_traced(
            jax.vmap(jax.random.fold_in)(keys,
                                         jnp.zeros((k,), jnp.int32)),
            logits[:, -1], temps_k, topk_k, ps_k,
        )
        starts = starts.at[slots].set(rs_k + feed)
        (tok, emitted, done, budgets, pad_lens, keys_data, stops,
         temps, ks, ps) = arrays
        arrays_out = (
            tok.at[slots].set(tok0),
            emitted.at[slots].set(jnp.ones((k,), jnp.int32)),
            done.at[slots].set(jnp.zeros((k,), bool)),
            budgets.at[slots].set(budgets_k),
            pad_lens.at[slots].set(jnp.zeros((k,), jnp.int32)),
            keys_data.at[slots].set(keys_data_k),
            stops.at[slots].set(stops_k),
            temps.at[slots].set(temps_k),
            ks.at[slots].set(topk_k),
            ps.at[slots].set(ps_k),
        )
        return cache, tables, arrays_out, starts, tok0

    return admit


@functools.lru_cache(maxsize=16)
def _paged_chunk_fn(model, steps: int, n_stop: int):
    """``steps`` in-graph paged decode steps: every slot's single token
    feeds at its OWN row-local position (``starts``) and its K/V
    appends into its private pool page through the block table
    (models/llama._paged_attention); attention reads the pool in place
    (ops/flash paged kernel on TPU). Frozen rows pass ``pad_lens=1`` so
    their (ignored) writes land in the scratch page — a done row can
    never dirty a page the radix index might share. Donates the pool
    cache."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from .generate import _isin, _sample_rows_traced

    @functools.partial(jax.jit, donate_argnums=1)
    def chunk(params, cache, tables, starts, tok, emitted, done, budgets,
              pad_lens, keys_data, stops, temps, ks, ps):
        del pad_lens               # paged rows have no left-pad space
        keys = jax.random.wrap_key_data(keys_data)
        done = done | _isin(tok, stops) | (emitted >= budgets)

        def body(carry, _):
            cache, starts, tok, emitted, done = carry
            logits, vs = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                train=False, decode=True, mutable=["cache"],
                pad_lens=done.astype(jnp.int32),
                block_tables=tables, row_starts=starts,
            )
            lg = logits[:, -1]
            step_keys = jax.vmap(jax.random.fold_in)(keys, emitted)
            nxt = lax.cond(
                jnp.any((temps > 0.0) & ~done),
                lambda: _sample_rows_traced(step_keys, lg, temps, ks,
                                            ps),
                lambda: jnp.argmax(lg, axis=-1).astype(jnp.int32),
            )
            nxt = jnp.where(done, 0, nxt)
            live = (~done).astype(jnp.int32)
            emitted = emitted + live
            starts = starts + live
            done = done | _isin(nxt, stops) | (emitted >= budgets)
            return (dict(vs["cache"]), starts, nxt, emitted, done), nxt

        (cache, starts, tok, emitted, done), toks = lax.scan(
            body, (cache, starts, tok, emitted, done), None,
            length=steps)
        return cache, starts, jnp.swapaxes(toks, 0, 1), tok, emitted, \
            done

    return chunk


@functools.lru_cache(maxsize=16)
def _chunk_fn(model, steps: int, n_stop: int):
    """``steps`` in-graph decode steps over all slots: per-row rng
    streams (folded at each row's own emission index, matching solo
    ``generate()`` exactly), traced per-row sampling, stop sets,
    budgets; finished rows freeze. Donates the cache."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from .generate import _isin, _sample_rows_traced

    @functools.partial(jax.jit, donate_argnums=1)
    def chunk(params, cache, tok, emitted, done, budgets, pad_lens,
              keys_data, stops, temps, ks, ps):
        keys = jax.random.wrap_key_data(keys_data)
        # re-derive done for the FED tokens: a freshly admitted row
        # whose first token already hit a stop (or whose budget is 1)
        # must freeze from step one — the host defers that check to
        # here so admission never forces a device sync
        done = done | _isin(tok, stops) | (emitted >= budgets)

        def body(carry, _):
            cache, tok, emitted, done = carry
            logits, vs = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                train=False, decode=True, mutable=["cache"],
                pad_lens=pad_lens,
            )
            lg = logits[:, -1]
            step_keys = jax.vmap(jax.random.fold_in)(keys, emitted)
            # all-greedy batches skip the sampling branch AT RUNTIME
            # (lax.cond executes one side): the traced sampler's
            # full-vocab sort is pure waste for greedy traffic, and
            # greedy rows inside a mixed batch still take argmax
            # per-row inside the sampled branch — outputs identical.
            # Gated on LIVE rows only: a completed slot keeps its
            # temperature until reused, and one stale sampled slot
            # would otherwise disable the shortcut for all later
            # greedy traffic
            nxt = lax.cond(
                jnp.any((temps > 0.0) & ~done),
                lambda: _sample_rows_traced(step_keys, lg, temps, ks,
                                            ps),
                lambda: jnp.argmax(lg, axis=-1).astype(jnp.int32),
            )
            nxt = jnp.where(done, 0, nxt)
            emitted = emitted + (~done).astype(jnp.int32)
            done = done | _isin(nxt, stops) | (emitted >= budgets)
            return (vs["cache"], nxt, emitted, done), nxt

        (cache, tok, emitted, done), toks = lax.scan(
            body, (cache, tok, emitted, done), None, length=steps)
        return cache, jnp.swapaxes(toks, 0, 1), tok, emitted, done

    return chunk


class ContinuousBatchingService(GenerationService):
    """``GenerationService`` with the slot scheduler above. The wire
    API is identical to the static scheduler's (prompt / budget /
    sampling / seed / stop per request); there are NO group keys —
    per-row budgets, stops, and sampling live in the executable, so
    ANY mix of requests shares the engine. ``stats`` adds slot
    occupancy and end-to-end latency percentiles (surfaced via
    ``/healthz``)."""

    MAX_STOPS = 8          # static stop-set width in the executable
    GROW_MAX = 8           # adaptive chunk growth cap, x base chunk
    # growth cap when live rows carry stop tokens (they can finish
    # mid-chunk); clamped by GROW_MAX so every pickable length stays
    # inside the precompiled ladder whatever GROW_MAX is tuned to
    GROW_MAX_STOPS = 4
    STREAM_DELTAS = True   # generate(on_tokens=...) emits incremental
    # per-chunk token deltas (serve.py "stream": true)

    def _setup(self, model, params, tokenizer=None, slots: int = 8,
               chunk: int = 8, window_ms: float = 5.0,
               warm_buckets=None, prefix_cache=None, recorder=None,
               spec_draft_layers: int = 0, tracer=None, slo=None,
               brownout=None, role: str = "both", tsdb=None,
               prefill_chunk_tokens: int = 0):
        super()._setup(model, params, tokenizer,
                       prefix_cache=prefix_cache,
                       spec_draft_layers=spec_draft_layers,
                       tracer=tracer, slo=slo, role=role)
        self._recorder = recorder
        # fleet timeline store (ISSUE 14): each absorbed chunk feeds
        # one observation — counters become interval rates, queue/slot
        # occupancy sample as gauges (observability/timeseries.py);
        # the quick_timeseries bench rung gates the per-chunk cost
        self._tsdb = tsdb
        # step anatomy (ISSUE 16): kernel-class cost attribution for
        # the decode-chunk executable. Registration queues ONE
        # background AOT analysis per signature; the per-chunk cost is
        # a set lookup + an EWMA update (gated < 2% by the
        # quick_anatomy bench rung). PDT_ANATOMY=0 disables it.
        self._anatomy = AnatomyStore()
        self._anatomy_pending: list = []   # (t_dispatch, steps) FIFO
        self._anatomy_tried = False        # registered once (shapes are
        #                                    era-invariant — one sig)
        self._anatomy_steps = 0            # steps of the analyzed chunk
        self._anatomy_seen_version = 0     # last version put in a record
        # pool_exhaust fault window: until this monotonic instant the
        # prefix pool reports dry (paged admissions defer, scatter
        # lookups miss) — 0 = no window active
        self._pool_dry_until = 0.0
        # sliding-window models (ISSUE 15): the rolling contiguous
        # cache disqualifies the scatter engine (_pad_ok is False),
        # but the paged RING layout serves them — positions are
        # row-local and pad masking is the paged path's own
        ring_ok = (self._prefix is not None and self._prefix.paged
                   and getattr(self._prefix, "window", 0) > 0)
        if not self._pad_ok and not ring_ok:
            raise ValueError(
                f"{type(model).__name__} is not pad-capable (RoPE "
                "positions + non-rolling cache needed): use the static "
                "BatchedGenerationService, or attach a paged prefix "
                "cache for the sliding-window ring layout")
        import jax

        self._slots = int(slots)
        self._chunk = int(chunk)
        self._init_brownout(brownout)   # needs _slots/_chunk above
        # TRUE paged decode (ISSUE 7): with a paged-capable pool the
        # shared contiguous cache is replaced by the block pool + a
        # per-slot block table — warm admits become pointer updates
        # (zero device copy), decode reads pool pages in place, and
        # finished requests' pages adopt into the radix index with no
        # capture kernel. Unsupported layouts keep the round-5 scatter
        # fallback below, unchanged.
        self._paged = self._prefix is not None and self._prefix.paged
        # chunked streaming prefill (ISSUE 15 tentpole): prompts whose
        # uncached suffix exceeds this stream through fixed-size
        # prefill chunks across scheduler ticks instead of minting one
        # giant admit-bucket executable that stalls the decode batch.
        # Power-of-two so bucketed feeds stay inside the warmed
        # ladder; MANDATORY (and capped at the ring slack) for window
        # models, whose single-dispatch feeds are bounded by the ring
        # geometry contract.
        chunk_tok = int(prefill_chunk_tokens or 0)
        if chunk_tok and (chunk_tok & (chunk_tok - 1)):
            raise ValueError(
                f"serving.prefill_chunk_tokens={chunk_tok} must be a "
                "power of two (admission feeds snap to the bucket "
                "ladder)")
        if self._paged and getattr(self._prefix, "window", 0) > 0:
            cap = int(self._prefix.ring_slack_tokens)
            chunk_tok = min(chunk_tok or cap, cap)
        elif chunk_tok and not self._paged:
            logger.warning(
                "prefill_chunk_tokens=%d ignored: chunked streaming "
                "prefill needs the paged pool (scatter/no-pool serves "
                "monolithically)", chunk_tok)
            chunk_tok = 0
        self._prefill_chunk = chunk_tok
        self._tables = None          # [slots, nb_max] device block table
        self._starts = None          # [slots] row-local next-fed position
        # host-side key derivation: the default threefry impl's key
        # data for integer seed s is [s >> 32, s & 0xffffffff]; going
        # through jax.random.key() per request costs a device round
        # trip IN THE CALLER'S THREAD, which serialized burst arrivals
        # through the tunnel and split them into admission waves.
        # Probe once; non-threefry impls fall back to the device path.
        probe = np.asarray(jax.random.key_data(
            jax.random.key(0x123456789A)))
        want = np.asarray([0x123456789A >> 32,
                           0x123456789A & 0xFFFFFFFF], np.uint32)
        self._host_keys = (probe.shape == (2,)
                           and np.array_equal(probe, want))
        self._window_s = float(window_ms) / 1e3
        self._queue: "queue_mod.Queue" = queue_mod.Queue()
        self._latencies: list = []
        # server-side TTFT per request (ISSUE 8 satellite): stamped at
        # the first absorb that hands a row its tokens — the earliest
        # moment the first token is actually servable to the client
        self._ttfts: list = []
        # prompt-length buckets whose (bucket, k) admit executables are
        # primed at startup alongside the chunk ladder: normalized
        # through the scheduler's own bucketing, deduped, and dropped
        # (LOUDLY — an operator asked for them) when even a 1-token
        # budget cannot fit the era
        self._warm_buckets = sorted({
            self._bucket(int(b)) for b in (warm_buckets or ())
            if int(b) > 0
            and self._bucket(int(b)) + 1 <= int(model.max_len)
        })
        dropped = [int(b) for b in (warm_buckets or ())
                   if int(b) <= 0
                   or self._bucket(int(b)) + 1 > int(model.max_len)]
        if dropped:
            logger.warning(
                "warm_buckets %s dropped (not in (0, max_len=%d) after "
                "bucketing): their admit executables will compile at "
                "the first matching arrival instead",
                dropped, int(model.max_len),
            )
        self.stats = {"requests": 0, "completed": 0, "chunks": 0,
                      "admissions": 0, "eras": 0, "max_active": 0,
                      "tokens_generated": 0, "cancelled": 0,
                      "paged_chunks": 0, "paged_admissions": 0,
                      "deferred_admissions": 0, "deadline_expired": 0,
                      "brownout_clamped": 0,
                      # disaggregated serving (ISSUE 12): pages shipped
                      # in from prefill-role replicas / exports served
                      "remote_admits": 0, "prefill_exports": 0,
                      # chunked streaming prefill (ISSUE 15): chunks
                      # dispatched, prompt tokens streamed through
                      # them, and requests that streamed at all
                      "prefill_chunks": 0, "streamed_prefill_tokens": 0,
                      "streamed_requests": 0}
        self._warm_chunk_ladder()
        if self.tp > 1:
            # precompute the per-step collective accounting with the
            # rest of the warmup (one AOT compile) so neither the
            # scheduler thread nor a /metrics scrape pays it later
            self.tp_stats()
        self._worker_thread = threading.Thread(
            target=self._worker, daemon=True, name="gen-continuous")
        self._worker_thread.start()

    # ---- brownout ladder (ISSUE 9) ---------------------------------------

    def _init_brownout(self, cfg) -> None:
        """Attach the hysteresis ladder (utils/brownout.py) from a
        ``serving.brownout`` config dict (``{"enabled": true, ...}``)
        or a prebuilt controller. Off by default: degradation modes
        change observable behavior (clamped budgets), so the operator
        opts in."""
        from ..utils.brownout import BrownoutController

        self._brownout = None
        self._bo_queue_norm = 1.0
        self._bo_max_new = 0
        self._bo_breach_ewma = 0.0
        self._bo_last = (0, 0)          # (breaches, completed) marks
        self._bo_lock = threading.Lock()
        if cfg is None:
            return
        if isinstance(cfg, BrownoutController):
            self._brownout = cfg
            return
        cfg = dict(cfg)
        if not cfg.get("enabled"):
            return
        # queue_norm: queue depth equal to slots*queue_norm reads as
        # pressure 1.0 ("at capacity") — the ladder thresholds are in
        # those units
        self._bo_queue_norm = float(cfg.get("queue_norm", 1.0))
        # level-3 budget cap; 0 derives a default from the chunk size
        self._bo_max_new = int(cfg.get("max_new_cap", 0)) \
            or self._chunk * 4
        kw = {}
        if "enter" in cfg:
            kw["enter"] = tuple(cfg["enter"])
        if "exit" in cfg:
            kw["exit"] = tuple(cfg["exit"])
        self._brownout = BrownoutController(
            dwell_s=float(cfg.get("dwell_s", 2.0)),
            on_change=self._on_brownout_change, **kw)

    def _on_brownout_change(self, old: int, new: int,
                            pressure: float) -> None:
        logger.warning("brownout level %d -> %d (pressure %.2f)",
                       old, new, pressure)
        if self._recorder is not None:
            self._recorder.record(
                self.stats["chunks"], event="brownout",
                brownout_level=new, brownout_prev=old,
                brownout_pressure=round(pressure, 4))

    @property
    def brownout_level(self) -> int:
        return self._brownout.level if self._brownout is not None else 0

    def brownout_stats(self) -> dict:
        if self._brownout is None:
            return {"brownout_level": 0}
        # scrape-driven refresh: ticks only run under traffic, so an
        # idle engine's ladder would otherwise freeze at its last
        # level forever — each /metrics read feeds the controller the
        # CURRENT pressure (hysteresis dwell still applies, so scrapes
        # cannot flap it)
        with self._bo_lock:
            self._brownout.update(self._brownout_pressure())
            return self._brownout.stats()

    def _brownout_pressure(self, waiting: int = 0) -> float:
        """Normalized pressure: the max of (a) waiting requests
        (still-queued plus the tick's drained-but-unadmitted pending
        set — the worker drains the queue into ``pending`` before each
        tick, so the raw qsize alone under-reads) over
        ``slots * queue_norm``, (b) the pool's live-referenced page
        fraction (resident-but-shareable pages are a HEALTHY cache —
        only pages pinned by live requests signal pressure), and
        (c) an EWMA of the recent SLO breach rate (breaches per
        completion), each normalized so 1.0 ≈ at capacity."""
        p = (self._queue.qsize() + waiting) / max(
            self._slots * self._bo_queue_norm, 1e-9)
        if self._prefix is not None:
            snap = self._prefix.stats_snapshot()
            total = max(snap.get("prefix_pool_blocks", 0), 1)
            p = max(p, snap.get("prefix_pool_blocks_referenced", 0)
                    / total)
        if self._slo is not None:
            s = self._slo.stats()
            breaches = s.get("slo_breach_total", 0)
            completed = self.stats.get("completed", 0)
            db = breaches - self._bo_last[0]
            dc = completed - self._bo_last[1]
            if dc > 0:
                self._bo_breach_ewma += 0.3 * (
                    min(db / dc, 1.0) - self._bo_breach_ewma)
                self._bo_last = (breaches, completed)
            p = max(p, self._bo_breach_ewma)
        return p

    def _pool_dry(self) -> bool:
        """The pool_exhaust fault window: while active, the paged
        reservation path reports dry (admissions defer) and the
        scatter lookup path reports a miss."""
        return (self._pool_dry_until > 0.0
                and time.monotonic() < self._pool_dry_until)

    def _warm_chunk_ladder(self):
        """Compile every chunk length the scheduler can pick — base
        chunk and its power-of-two growth ladder up to GROW_MAX — on
        throwaway all-done slot state, BEFORE the worker starts.

        Adaptive growth chooses a length from the ladder based on
        ``min_left``, which depends on which requests share the engine
        at that instant — timing-nondeterministic, so without this a
        length can be first seen mid-traffic and every slot stalls
        behind a fresh XLA compile (~30 s for the 124M serving model
        through the tunnel; the serve_mixed rung's chunk=8 arm
        measured ~10x slower from exactly that). One-time startup
        cost, same contract as the padded admission width in
        ``_admit_group``.

        Deliberately EXECUTES each length instead of AOT
        ``.lower().compile()``: the AOT path builds a separate
        executable that is not guaranteed to seed the dispatch-path
        jit cache the worker actually hits, and a warmup that only
        probably warms is worse than ~120 frozen-row decode steps
        (~1 s; all slots are done, rows freeze, nothing is emitted).

        ``warm_buckets`` (constructor arg) extends the same contract to
        the ADMIT executables: each configured prompt-length bucket's
        ``(bucket, k)`` admission compiles here on throwaway slot state
        — with them covering the deployment's traffic shape, the first
        arrival wave never stalls behind an XLA compile. Off by default
        (each bucket costs one batched-prefill compile at startup)."""
        from .generate import fresh_cache

        total = int(self.model.max_len)
        self._init_arrays()
        arrays = self._arrays
        if self._paged:
            # paged warmup runs against the REAL pool: with an all -1
            # table every write lands in the scratch page and every
            # read is masked, so executing the ladder cannot dirty a
            # sharable page — and the executables warmed are exactly
            # the dispatch-path ones
            import jax.numpy as jnp

            cache = self._prefix.paged_cache()
            tables = jnp.full((self._slots, self._prefix.nb_max), -1,
                              jnp.int32)
            starts = jnp.zeros((self._slots,), jnp.int32)
            if self._warm_buckets:
                self._warm_paged_signatures(cache, tables, starts,
                                            arrays, total)
                self._arrays = None
                return
            steps = self._chunk
            while steps <= min(self._chunk * self.GROW_MAX, total):
                fn = _paged_chunk_fn(self.model, steps, self.MAX_STOPS)
                out = fn(self.params, cache, tables, starts, *arrays)
                cache, starts = out[0], out[1]
                steps *= 2
            self._prefix.sync_pool_from_cache(cache)
            self._arrays = None
            return
        cache = fresh_cache(self.model, self.params, self._slots, total)
        steps = self._chunk
        while steps <= min(self._chunk * self.GROW_MAX, total):
            fn = _chunk_fn(self.model, steps, self.MAX_STOPS)
            out = fn(self.params, cache, *arrays)
            cache = out[0]           # the cache argument is donated
            steps *= 2
        if self._warm_buckets:
            self._warm_admit_ladder(cache, arrays)
        self._arrays = None          # the worker builds its own state

    def _warm_admit_once_paged(self, feed, cache, tables, arrays,
                               starts):
        """Execute ONE paged admission wave at ``feed`` on the given
        state (dummy rows: fully padded, budget 1 — every write lands
        in the scratch page) and return the donated-through state."""
        import jax
        import jax.numpy as jnp

        k, W = self._slots, self.MAX_STOPS
        nb = self._prefix.nb_max
        kd = np.asarray(jax.random.key_data(jax.random.key(0)))
        keys_data = jnp.asarray(np.tile(kd, (k, 1)))
        ints = np.zeros((k, 4 + W), np.int32)
        ints[:, 0] = np.arange(k)
        ints[:, 1] = 1                  # budget 1
        ints[:, 2] = feed               # all lanes padded
        ints[:, 3:3 + W] = -1
        ints[:, 3 + W] = -feed          # rs: last lane at position 0
        return _paged_admit_fn(self.model, feed, k, W, nb)(
            self.params, cache, tables, arrays, starts,
            jnp.zeros((k, feed), jnp.int32), jnp.asarray(ints),
            jnp.zeros((k, 2), jnp.float32), keys_data,
            jnp.zeros((k,), jnp.int32),
            jnp.full((k, nb), -1, jnp.int32))[:4]

    def _warm_paged_signatures(self, cache, tables, starts, arrays,
                               total: int):
        """Warm the paged executables at the SIGNATURES live traffic
        actually dispatches. A jit signature includes each argument's
        commitment/sharding, not just its shape: the pool starts life
        as uncommitted ``jnp.zeros`` but every jit OUTPUT is committed,
        so after the first real admission all engine state is committed
        — a ladder warmed only on construction-time (uncommitted)
        state compiles executables the dispatch path never hits, and
        the first arrival wave stalls behind fresh XLA compiles anyway
        (measured: ~2 s on CPU — long enough to trip the fleet's
        wedged-replica detector). Three signature classes cover the
        engine's lifetime:

        1. **first admission**: committed pool cache + fresh
           (uncommitted) tables/slot arrays — happens exactly once;
        2. **steady-state chunks**: everything committed (all chunk
           inputs come out of an admit/chunk dispatch);
        3. **steady-state admissions**: everything committed.

        Bootstrap: one admission on the all-uncommitted construction
        state (its signature is never dispatched again — the price of
        obtaining committed state without guessing shardings), pool
        synced so ``paged_cache()`` hands back committed leaves, then
        classes 1-3 executed in dispatch order per feed bucket /
        chunk-ladder step."""
        import jax
        import jax.numpy as jnp

        k = self._slots
        nb = self._prefix.nb_max
        b, feeds = 16, []
        while b <= max(self._warm_buckets):
            feeds.append(b)
            b *= 2
        # bootstrap: commit every state leaf the way jit outputs are
        cache, tables, arrays, starts = self._warm_admit_once_paged(
            feeds[0], cache, tables, arrays, starts)
        self._prefix.sync_pool_from_cache(cache)
        # class 1: committed pool, FRESH uncommitted tables/arrays —
        # the first real admission's exact signature, per feed bucket
        self._init_arrays()
        for feed in feeds:
            out = self._warm_admit_once_paged(
                feed, self._prefix.paged_cache(),
                jnp.full((k, nb), -1, jnp.int32), self._arrays,
                jnp.zeros((k,), jnp.int32))
            self._init_arrays()     # fresh (uncommitted) per feed
            self._prefix.sync_pool_from_cache(out[0])
        cache, tables, arrays, starts = out
        # class 2: the chunk ladder on fully-committed state,
        # rebuilding the arrays tuple exactly as _dispatch_chunk does
        steps = self._chunk
        while steps <= min(self._chunk * self.GROW_MAX, total):
            fn = _paged_chunk_fn(self.model, steps, self.MAX_STOPS)
            cache, starts, _, tok, emitted, done = fn(
                self.params, cache, tables, starts, *arrays)
            arrays = (tok, emitted, done) + tuple(arrays[3:])
            steps *= 2
        # class 3: steady-state admissions (everything committed)
        for feed in feeds:
            cache, tables, arrays, starts = \
                self._warm_admit_once_paged(feed, cache, tables,
                                            arrays, starts)
        jax.block_until_ready(arrays[0])
        self._prefix.sync_pool_from_cache(cache)

    def _warm_admit_ladder(self, cache, arrays):
        """Execute the admit executable for every configured bucket on
        the throwaway warmup state (cache/arrays donate through the
        chain and are discarded by the caller). Dummy rows: budget 1,
        fully-padded prompts at era position ``p = bucket`` — the
        values are irrelevant, the (bucket, k) specialization is the
        product."""
        import jax
        import jax.numpy as jnp

        k, W = self._slots, self.MAX_STOPS
        kd = np.asarray(jax.random.key_data(jax.random.key(0)))
        keys_data = jnp.asarray(np.tile(kd, (k, 1)))
        buckets = self._warm_buckets
        if self._prefix is not None and buckets:
            # prefix-cache hits admit with feed = bucket(largest
            # UNCACHED suffix) — any ladder value up to the configured
            # prompt bucket, not just the bucket itself. Prime the
            # whole power-of-two sub-ladder so the first shared-prefix
            # wave after startup never stalls every slot behind a
            # fresh XLA compile (the exact class of stall warm_buckets
            # exists to prevent)
            b, sub = 16, []
            while b <= max(buckets):
                sub.append(b)
                b *= 2
            buckets = sorted(set(buckets) | set(sub))
        for bucket in buckets:
            pos0 = 0                       # admission at p == bucket
            ints = np.zeros((k, 4 + W), np.int32)
            ints[:, 0] = np.arange(k)      # one row per slot
            ints[:, 1] = 1                 # budget 1
            ints[:, 2] = pos0 + bucket - 1  # pad_len: 1-token prompts
            ints[:, 3:3 + W] = -1
            ints[:, 3 + W] = pos0
            if self._prefix is not None:
                # prefix-cache deployments run every admission through
                # the warm executable (a full miss feeds block_ids of
                # all -1) — prime THAT shape, not the legacy one
                nb = self._prefix.nb_max
                cache, arrays, _ = _warm_admit_fn(
                    self.model, bucket, k, W, nb, self._prefix.block,
                    self._prefix.rotary, self._prefix.rope_base,
                    self._prefix.kv_quant)(
                    self.params, cache, arrays,
                    jnp.zeros((k, bucket), jnp.int32),
                    jnp.asarray(ints), jnp.zeros((k, 2), jnp.float32),
                    keys_data, jnp.zeros((k,), jnp.int32),
                    self._prefix.pool,
                    jnp.full((k, nb), -1, jnp.int32))
            else:
                cache, arrays, _ = _admit_fn(self.model, bucket, k, W)(
                    self.params, cache, arrays,
                    jnp.zeros((k, bucket), jnp.int32), jnp.asarray(ints),
                    jnp.zeros((k, 2), jnp.float32), keys_data,
                    jnp.zeros((k,), jnp.int32))
        jax.block_until_ready(arrays[0])

    # ---- request entry ---------------------------------------------------

    def generate(self, prompt=None, prompt_ids=None,
                 max_new_tokens: int = 64, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 0.0, seed: int = 0,
                 speculative: int = 0, stop=None,
                 on_tokens=None, cancel=None, request_id=None,
                 deadline=None) -> dict:
        """Same contract as the parent plus ``on_tokens``: a callback
        receiving each batch of freshly decoded token ids for THIS
        request as its chunks absorb (stop tokens filtered — the
        concatenated deltas equal the final response's ``ids``). Runs
        on the scheduler thread: must not block. Powers serve.py's
        ``"stream": true`` server-sent events.

        ``cancel``: an optional ``threading.Event``. Once set, the
        request is finalized at its NEXT chunk absorb — the row's slot
        frees immediately for waiting traffic instead of decoding out
        the rest of its budget (a disconnected streaming client's main
        cost). The call returns the tokens decoded so far with
        ``stop_reason: "cancelled"``; a request still in the queue is
        dropped without ever taking a slot. Speculative requests
        (``speculative > 0``) bypass the slot engine (batch-1 under
        the parent's lock) and IGNORE ``cancel`` — they run their
        whole budget.

        ``deadline``: an optional :class:`reqtrace.Deadline` (ISSUE 9).
        Treated as a CANCEL the engine raises itself: a queued request
        whose deadline expires is dropped before taking a slot, and a
        decoding row is finalized at its next absorb with
        ``stop_reason: "deadline"`` and whatever tokens it produced —
        the slot frees for live traffic instead of decoding tokens
        nobody is waiting for."""
        if speculative > 0 and self.brownout_level >= 1:
            # brownout level 1 (no_spec): speculative decode's extra
            # verify bandwidth goes back to the batch — the request is
            # served, just without the latency optimization
            speculative = 0
        if speculative > 0:
            # batch-1 by construction; runs under the parent's lock
            # (the scheduler's own dispatches take the same lock)
            result = super().generate(
                prompt=prompt, prompt_ids=prompt_ids,
                max_new_tokens=max_new_tokens, temperature=temperature,
                top_k=top_k, top_p=top_p, seed=seed,
                speculative=speculative, stop=stop,
                request_id=request_id, deadline=deadline)
            if on_tokens is not None and result.get("ids"):
                on_tokens(list(result["ids"]))   # single final delta
            return result
        ids = self.encode_prompt(prompt, prompt_ids)
        stops = self.encode_stop(stop)
        max_new = int(max_new_tokens)
        # role gate (ISSUE 12): a prefill-role replica refuses decode-
        # scale budgets before they ever take a slot
        self._check_role(max_new)
        # ONE owner for the enqueue rules (shared with serve.py's
        # pre-SSE validate_request — a rule changed here cannot drift
        # from the 400 path): stop-set width, max_new >= 1, and the
        # budget on the BUCKETED prompt length (admission rounds
        # prompts up to the executable bucket, so a request that only
        # fits unbucketed could never be admitted and would hang)
        self._validate_budget(ids, max_new, stops)
        seed = int(seed)
        if self._host_keys and seed >= 0:
            key_data = np.asarray(
                [seed >> 32, seed & 0xFFFFFFFF], np.uint32)
        else:
            import jax

            key_data = np.asarray(
                jax.random.key_data(jax.random.key(seed)))
        req = {
            "ids": ids, "budget": max_new,
            "temperature": float(temperature), "top_k": int(top_k),
            "top_p": float(top_p), "seed": seed, "stop": stops,
            "on_tokens": on_tokens, "cancel": cancel, "rid": request_id,
            "deadline": deadline,
            # raw key data, derived WITHOUT device work in the
            # caller's thread (host path above): per-request device
            # ops serialized burst arrivals through the tunnel
            "key_data": key_data,
            "event": threading.Event(), "t0": time.monotonic(),
        }
        self._queue.put(req)
        req["event"].wait()
        if "error" in req:
            raise req["error"]
        return req["result"]

    def _validate_budget(self, ids, max_new: int, stops,
                         speculative: int = 0) -> None:
        """The slot engine's enqueue-time checks, for serve.py's
        pre-SSE validation: speculative requests bypass the engine
        (parent's plain budget rule); slot requests check the BUCKETED
        prompt length (admission rounds prompts up to the executable
        bucket — a request that only fits unbucketed could never admit
        and would hang) and the static stop-set width."""
        if speculative > 0:
            return super()._validate_budget(ids, max_new, stops)
        if len(stops) > self.MAX_STOPS:
            raise ValueError(
                f"at most {self.MAX_STOPS} stop tokens per request "
                f"(got {len(stops)})")
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        max_len = int(self.model.max_len)
        if getattr(self, "_paged", False):
            # paged admissions are position-free (row-local positions,
            # pages reserved up front): the raw prompt length is the
            # budget constraint, NOT its admission bucket — a long
            # prompt admits through chunked streaming prefill without
            # rounding itself out of the model (ISSUE 15)
            if len(ids) + max_new > max_len:
                raise ValueError(
                    f"prompt ({len(ids)} tokens) + max_new_tokens "
                    f"({max_new}) exceeds model.max_len {max_len}")
            return
        if self._bucket(len(ids)) + max_new > max_len:
            raise ValueError(
                f"prompt ({len(ids)} tokens, admission bucket "
                f"{self._bucket(len(ids))}) + max_new_tokens "
                f"({max_new}) exceeds model.max_len {max_len}")

    # ---- scheduler internals --------------------------------------------

    @classmethod
    def _grow_cap(cls, live) -> int:
        """Adaptive chunk-growth cap (x base chunk) for the CURRENT
        live set: full ``GROW_MAX`` only when no live row can exit a
        chunk early. Rows with stop tokens can finish mid-chunk, and
        rows carrying a CANCEL event (streaming clients that may
        disconnect) are honored at the next absorb — both classes cap
        growth at ``GROW_MAX_STOPS`` so a freed slot (or a cancelled
        client's slot) is recycled within a short chunk, not up to
        GROW_MAX x chunk + one pipelined chunk later (ADVICE r5)."""
        return (min(cls.GROW_MAX_STOPS, cls.GROW_MAX)
                if any(m["req"]["stop"]
                       or m["req"].get("cancel") is not None
                       or m["req"].get("deadline") is not None
                       for m in live)
                else cls.GROW_MAX)

    @staticmethod
    def _bucket(n: int) -> int:
        b = 16
        while b < n:
            b *= 2
        return b

    def _admissible(self, req) -> bool:
        """Fits at the CURRENT position? The prompt must land before
        the global counter (bucket <= p) and the budget inside the
        era's remaining room. (Era-start placement for an idle engine
        is the FIFO-prefix loop in ``_tick``.)"""
        bucket = self._bucket(len(req["ids"]))
        return (bucket <= self._p
                and self._p + req["budget"] <= int(self.model.max_len))

    def _admit_group(self, reqs: list, slots: list):
        """Admit same-bucket requests in ONE prefill dispatch + ONE
        scatter dispatch, nothing forced (the first tokens stay device
        futures until the next absorb — admission must never stall the
        pipeline).

        The group is PADDED to a fixed width ``k = self._slots`` by
        repeating the last request (its duplicate rows scatter onto
        the same slot — a same-content rewrite, harmless): admission
        executables specialize on (bucket, k), and arrival-wave sizes
        are timing-nondeterministic, so a variable k means fresh XLA
        compiles landing mid-traffic (measured: the serve_mixed rung
        collapsed 201 -> 43 tok/s from exactly that)."""
        import jax.numpy as jnp

        if self._paged:
            return self._admit_group_paged(reqs, slots)
        t_admit0 = time.monotonic()
        ev0 = (self._prefix.counter("prefix_evictions")
               if self._prefix is not None and self._tracer is not None
               else 0)
        n = len(reqs)
        k = self._slots
        W = self.MAX_STOPS
        pad_reqs = reqs + [reqs[-1]] * (k - n)
        pad_slots = list(slots) + [slots[-1]] * (k - n)
        bucket = self._bucket(max(len(r["ids"]) for r in reqs))
        # ---- prefix-cache lookup: longest fully-blocked cached prefix
        # per request; the fed window shrinks to the largest UNCACHED
        # suffix (snapped to the same ladder — always <= bucket, so the
        # admissibility/era math above stays valid unchanged). Refs are
        # held until the copy kernels are dispatched, so a same-tick
        # insert can never evict a block this group is about to read.
        matches = None
        if self._prefix is not None:
            if self._pool_dry():
                # pool_exhaust fault window (scatter arm): every
                # lookup misses — admissions pay the full prefill
                matches = [([], [], 0) for _ in reqs]
            else:
                # promote=False: spilled chains were promoted at tick
                # start — a donation here would kill the cache the
                # admit dispatch below aliases
                matches = [self._prefix.lookup(r["ids"], promote=False)
                           for r in reqs]
            feed = self._bucket(max(
                len(r["ids"]) - m[2] for r, m in zip(reqs, matches)))
        else:
            feed = bucket
        pos0 = self._p - feed
        prompts = np.zeros((k, feed), np.int32)
        ints = np.full((k, 4 + W), pos0, np.int32)
        floats = np.zeros((k, 2), np.float32)
        topks = np.zeros((k,), np.int32)
        for j, r in enumerate(pad_reqs):
            m = min(len(r["ids"]), feed)   # fed = trailing tokens; any
            # leading truncation is covered by the row's cached blocks
            prompts[j, feed - m:] = r["ids"][len(r["ids"]) - m:]
            ints[j, 0] = pad_slots[j]
            ints[j, 1] = r["budget"]
            ints[j, 2] = self._p - len(r["ids"])
            ints[j, 3:3 + W] = -1
            for jj, sid in enumerate(r["stop"]):
                ints[j, 3 + jj] = sid
            floats[j] = (r["temperature"], r["top_p"])
            topks[j] = r["top_k"]
        keys_data = jnp.asarray(
            np.stack([r["key_data"] for r in pad_reqs]))
        if self._prefix is None:
            self._cache, self._arrays, tok0 = _admit_fn(
                self.model, bucket, k, W)(
                self.params, self._cache, self._arrays,
                jnp.asarray(prompts), jnp.asarray(ints),
                jnp.asarray(floats), keys_data, jnp.asarray(topks))
        else:
            nb = self._prefix.nb_max
            block_ids = np.full((k, nb), -1, np.int32)
            pad_matches = matches + [matches[-1]] * (k - n)
            for j, (_, blocks, _) in enumerate(pad_matches):
                block_ids[j, :len(blocks)] = blocks
            try:
                self._cache, self._arrays, tok0 = _warm_admit_fn(
                    self.model, feed, k, W, nb, self._prefix.block,
                    self._prefix.rotary, self._prefix.rope_base,
                    self._prefix.kv_quant)(
                    self.params, self._cache, self._arrays,
                    jnp.asarray(prompts), jnp.asarray(ints),
                    jnp.asarray(floats), keys_data, jnp.asarray(topks),
                    self._prefix.pool, jnp.asarray(block_ids))
            except Exception:
                # a failed dispatch (e.g. an OOM'd first compile) must
                # not strand the lookup refs: leaked refs pin blocks
                # against eviction FOREVER on a server that recovers
                for nodes, _, _ in matches:
                    self._prefix.release(nodes)
                raise
            # the scatter arm's admit-copy cost, made observable (the
            # paged path above never pays it): every cached block each
            # row reused crossed HBM into the fresh group cache
            self._prefix.record_copy_bytes(
                sum(len(m[1]) for m in matches))
            # inserts + the ref release ride one helper (its finally
            # owns the release from here on)
            self._insert_prefixes(reqs, slots, ints, matches)
        from .kvcache import page_origin_flags

        for j, (r, slot) in enumerate(zip(reqs, slots)):
            # serve-path provenance (ISSUE 18): admit mode + the pool
            # events this request's cached blocks rode in on, finalized
            # into the fingerprint at _complete
            hit = matches[j][2] if matches is not None else 0
            path = {"mode": "warm" if hit else "cold",
                    "brownout": self.brownout_level}
            if matches is not None and hit:
                path.update(page_origin_flags(matches[j][0]))
            self._meta[slot] = {
                "req": r, "emitted": 1, "out": [],
                "tok0_ref": (tok0, j),
                "pad_len": int(ints[j, 2]), "done": False,
                "path": path,
            }
        self.stats["admissions"] += n
        if self._tracer is not None:
            t_admit1 = time.monotonic()
            evictions = (self._prefix.counter("prefix_evictions") - ev0
                         if self._prefix is not None else 0)
            for j, r in enumerate(reqs):
                rid = r.get("rid")
                if not rid:
                    continue
                # queue wait: enqueue -> this admit dispatch
                self._tracer.add(rid, "queue_wait", r["t0"], t_admit0,
                                 bucket=bucket)
                hit = matches[j][2] if matches is not None else 0
                self._tracer.add(
                    rid, "admit", t_admit0, t_admit1,
                    mode=("warm" if hit else "cold"),
                    bucket=bucket, feed=feed, group=n,
                    prefix_hit_tokens=hit,
                    copy_blocks=(len(matches[j][1])
                                 if matches is not None else 0))
            if evictions:
                # pool pressure attributed to the admission that paid
                # it (the group's first traced request carries it)
                rid = next((r.get("rid") for r in reqs
                            if r.get("rid")), None)
                if rid:
                    self._tracer.event(rid, "kv_evictions",
                                       blocks=evictions, group=n)

    def _reserve_pages(self, r):
        """Host-side page reservation for one paged admission —
        ``PrefixCache.paged_plan`` owns the math (lookup + private
        chain covering the uncached suffix AND the full decode budget,
        up front so a mid-decode row can never block on the pool).
        ``None`` = pool exhausted right now — the caller defers the
        admission (completions free pages; progress is guaranteed
        because one full-budget chain always fits an otherwise-idle
        pool, enforced at PrefixCache construction). A deferred
        request re-reserves EVERY tick: only its first attempt may
        count toward the hit/lookup stats, or a second of deferral
        would fabricate hundreds of phantom hit-tokens."""
        if self._pool_dry():
            # pool_exhaust fault window: the pool reports dry — the
            # caller defers exactly as it would for genuine exhaustion
            # (the machinery under test). ``_page_retry`` stays unset:
            # no lookup ran, so the first REAL attempt still records.
            r["_page_attempts"] = r.get("_page_attempts", 0) + 1
            return None
        first = not r.get("_page_retry")
        r["_page_retry"] = True
        r["_page_attempts"] = r.get("_page_attempts", 0) + 1
        # promote=False: tick-start promotion already ran; a pool
        # donation here would invalidate the live paged cache mid-tick
        return self._prefix.paged_plan(r["ids"], r["budget"],
                                       record=first, promote=False)

    def _needs_streaming(self, r) -> bool:
        """True while a reserved request's remaining uncached suffix
        is wider than one prefill chunk — it streams instead of
        admitting (ISSUE 15)."""
        plan = r.get("_pages")
        if plan is None or not self._prefill_chunk:
            return False
        done = plan.get("done", plan["c"])
        return len(r["ids"]) - done > self._prefill_chunk

    def _stream_prefill_step(self, r) -> str:
        """One chunk of streaming prefill for a pending long request
        (ISSUE 15 tentpole). Returns ``"chunked"`` when a chunk
        dispatched — the tick's single streaming slot is consumed, so
        decode rows get the engine back between chunks and TPOT holds
        flat under a long arrival — ``"deferred"`` when the pool
        cannot supply the reservation (the caller STOPS walking
        pending: reserving for a later request instead would starve
        this one, the same FIFO contract as the admission loop; the
        admission loop owns the deferred_admissions count), and
        ``"skip"`` when the request needs no streaming.

        The full page plan (shared prefix + private chain covering
        prompt AND budget) reserves up front on first sight — a dry
        pool defers the whole request, never a mid-stream chunk. Each
        chunk feeds ``prefill_chunk`` prompt tokens through the SAME
        batch-1 paged prefill executable (one shape for the stream's
        lifetime — no giant admit buckets), writes straight into the
        plan's private pages, and zero-copy ADOPTS the completed full
        blocks into the radix — a same-document request arriving
        mid-prefill warm-hits the chunks already landed. Runs before
        the tick's cache refresh (the dispatch donates the pool the
        engine cache aliases)."""
        import jax.numpy as jnp

        from .kvcache import _paged_prefill_fn

        ids = r["ids"]
        chunk = self._prefill_chunk
        plan = r.get("_pages")
        if plan is None:
            if len(ids) <= chunk:
                return "skip"
            plan = self._reserve_pages(r)
            if plan is None:
                return "deferred"       # dry pool: retried next tick
            r["_pages"] = plan
            plan["done"] = plan["c"]
            if len(ids) - plan["c"] > chunk:
                self.stats["streamed_requests"] += 1
        done = plan.get("done", plan["c"])
        if len(ids) - done <= chunk:
            return "skip"               # ready for normal admission
        pf = self._prefix
        t0 = time.monotonic()
        row = np.full((1, pf.nb_max), -1, np.int32)
        for i, b in enumerate(plan["blocks"]):
            row[0, i] = b
        for idx, bid in (plan.get("shared") or {}).items():
            row[0, idx] = bid
        for idx, bid in plan["private"].items():
            row[0, idx] = bid
        suffix = jnp.asarray(
            np.asarray(ids[done:done + chunk], np.int32)[None, :])
        _, cache = _paged_prefill_fn(self.model, chunk, pf.nb_max)(
            self.params, pf.paged_cache(), suffix, jnp.asarray(row),
            jnp.asarray([done], jnp.int32))
        pf.sync_pool_from_cache(cache)
        plan["done"] = done + chunk
        self.stats["prefill_chunks"] += 1
        self.stats["streamed_prefill_tokens"] += chunk
        if not plan.get("ring_wrap"):
            # mid-prefill sharing: completed full blocks adopt now,
            # ref-pinned (this request keeps reading them); pinned
            # nodes release with the plan at paged_finish. Adopted
            # pages move private -> "shared" so the row's block table
            # KEEPS pointing at them (they are the prompt's history —
            # later chunks and the final admit read through them).
            adopted, anodes = pf.adopt(
                ids[:plan["done"]], dict(plan["private"]), acquire=True)
            if adopted:
                taken = set(adopted)
                shared = dict(plan.get("shared") or {})
                for idx in [i for i, b in plan["private"].items()
                            if b in taken]:
                    shared[idx] = plan["private"].pop(idx)
                plan["shared"] = shared
                plan["adopt_nodes"] = (
                    list(plan.get("adopt_nodes") or []) + anodes)
        if self._tracer is not None and r.get("rid"):
            self._tracer.add(
                r["rid"], "prefill_chunk", t0, time.monotonic(),
                tokens=chunk, done=plan["done"], total=len(ids))
        return "chunked"

    def _admit_group_paged(self, reqs: list, slots: list):
        """Paged admission: ONE dispatch writes the group's block
        tables (the whole warm-prefix "copy" — a pointer update),
        prefills ONLY each row's uncached suffix straight into its
        private pool pages, and samples first tokens. Zero admit-path
        device copies; ``scatter_blocks`` never runs here. After the
        dispatch, each prompt's full blocks ADOPT into the radix index
        in place — the group's own pages become sharable with no
        capture kernel. Page reservations were made by
        ``_reserve_pages`` in ``_tick`` (so a dry pool defers the
        request instead of stranding a slot)."""
        import jax.numpy as jnp

        pf = self._prefix
        bt = pf.block
        t_admit0 = time.monotonic()
        ev0 = (pf.counter("prefix_evictions")
               if self._tracer is not None else 0)
        n = len(reqs)
        k = self._slots
        W = self.MAX_STOPS
        nb = pf.nb_max
        pad_reqs = reqs + [reqs[-1]] * (k - n)
        pad_slots = list(slots) + [slots[-1]] * (k - n)
        # "done" covers both the radix-cached prefix AND any chunks a
        # streamed prefill already landed (ISSUE 15): the admit feeds
        # only what remains, so a streamed long prompt admits through
        # the same small-bucket executable as a short one
        feed = self._bucket(max(
            len(r["ids"]) - r["_pages"].get("done", r["_pages"]["c"])
            for r in reqs))
        prompts = np.zeros((k, feed), np.int32)
        ints = np.zeros((k, 4 + W), np.int32)
        floats = np.zeros((k, 2), np.float32)
        topks = np.zeros((k,), np.int32)
        tables_k = np.full((k, nb), -1, np.int32)
        for j, r in enumerate(pad_reqs):
            plan = r["_pages"]
            ids = plan["ids"]
            c = plan.get("done", plan["c"])
            s = len(ids) - c               # unfed suffix (>= 1: the
            # radix lookup never serves the final prompt token, and a
            # streamed prefill always leaves the final chunk to the
            # admit)
            prompts[j, feed - s:] = ids[c:]
            ints[j, 0] = pad_slots[j]
            ints[j, 1] = r["budget"]
            ints[j, 2] = feed - s          # leading invalid lanes
            ints[j, 3:3 + W] = -1
            for jj, sid in enumerate(r["stop"]):
                ints[j, 3 + jj] = sid
            ints[j, 3 + W] = len(ids) - feed   # lane 0's position
            floats[j] = (r["temperature"], r["top_p"])
            topks[j] = r["top_k"]
            for i, b in enumerate(plan["blocks"]):
                tables_k[j, i] = b
            for idx, bid in (plan.get("shared") or {}).items():
                # pages this request streamed and adopted mid-prefill
                # (ISSUE 15): index-owned now, still its history
                tables_k[j, idx] = bid
            for idx, bid in plan["private"].items():
                tables_k[j, idx] = bid
        keys_data = jnp.asarray(
            np.stack([r["key_data"] for r in pad_reqs]))
        try:
            (self._cache, self._tables, self._arrays, self._starts,
             tok0) = _paged_admit_fn(self.model, feed, k, W, nb)(
                self.params, self._cache, self._tables, self._arrays,
                self._starts, jnp.asarray(prompts), jnp.asarray(ints),
                jnp.asarray(floats), keys_data, jnp.asarray(topks),
                jnp.asarray(tables_k))
        except Exception:
            # a failed dispatch must not strand refs or leak pages —
            # including the ref-pins a streamed prefill's per-chunk
            # adoptions accumulated in adopt_nodes (ISSUE 15)
            for r in reqs:
                plan = r.pop("_pages")
                pf.release(plan["nodes"])
                pf.release(plan.get("adopt_nodes") or [])
                pf.free_blocks(list(plan["private"].values()))
            raise
        pf.sync_pool_from_cache(self._cache)
        for j, (r, slot) in enumerate(zip(reqs, slots)):
            plan = r.pop("_pages")
            # zero-copy insert of the prompt's own full blocks: the
            # pages just written in place become sharable immediately
            # (ref-pinned — this slot keeps reading them). NEVER for a
            # ring_wrap plan (ISSUE 15): its decode will RECYCLE these
            # very slots, so adopting them would hand the radix pages
            # whose content a later wrap overwrites under other
            # readers — the same guard paged_finish and the streaming
            # path apply.
            if not plan.get("ring_wrap"):
                adopted, anodes = pf.adopt(
                    plan["ids"], dict(plan["private"]), acquire=True)
                for bid in adopted:
                    for idx in [i for i, b in plan["private"].items()
                                if b == bid]:
                        del plan["private"][idx]
                # EXTEND, never overwrite: a streamed prefill already
                # pinned its per-chunk adoptions here (ISSUE 15) —
                # clobbering them leaks the pins forever
                plan["adopt_nodes"] = (
                    list(plan.get("adopt_nodes") or []) + anodes)
            # serve-path provenance (ISSUE 18): "stream" marks prompts
            # whose prefill arrived via chunked streaming before this
            # admit; node origins name the pool events behind the
            # cached prefix (adopt/promote/pull/ship)
            from .kvcache import page_origin_flags

            streamed = plan.get("done", plan["c"]) > plan["c"]
            path = {"mode": "stream" if streamed else "paged",
                    "wrap": bool(plan.get("ring_wrap")),
                    "brownout": self.brownout_level,
                    **page_origin_flags(plan.get("nodes"))}
            self._meta[slot] = {
                "req": r, "emitted": 1, "out": [],
                "tok0_ref": (tok0, j),
                "pad_len": 0, "done": False, "pages": plan,
                "path": path,
            }
        self.stats["admissions"] += n
        self.stats["paged_admissions"] += n
        if self._tracer is not None:
            t_admit1 = time.monotonic()
            evictions = pf.counter("prefix_evictions") - ev0
            for j, (r, slot) in enumerate(zip(reqs, slots)):
                rid = r.get("rid")
                if not rid:
                    continue
                plan = self._meta[slot]["pages"]
                self._tracer.add(rid, "queue_wait", r["t0"], t_admit0,
                                 bucket=self._bucket(len(r["ids"])))
                self._tracer.add(
                    rid, "admit", t_admit0, t_admit1, mode="paged",
                    bucket=self._bucket(len(r["ids"])),
                    feed=feed, group=n,
                    prefix_hit_tokens=plan["c"],
                    # streamed = prompt tokens landed by chunked
                    # prefill before this admit (ISSUE 15) — honest
                    # split from genuine radix hits
                    streamed_tokens=(
                        plan.get("done", plan["c"]) - plan["c"]),
                    # the paged contract: warm admits are pointer
                    # updates — copy bytes are zero by construction
                    copy_blocks=0,
                    private_pages=len(plan["private"]),
                    deferred=r.get("_page_attempts", 1) > 1)
            if evictions:
                rid = next((r.get("rid") for r in reqs
                            if r.get("rid")), None)
                if rid:
                    self._tracer.event(rid, "kv_evictions",
                                       blocks=evictions, group=n)

    def _init_arrays(self):
        """The persistent device slot state, built ONCE (and after an
        error reset): every slot done with budget 0, so nothing runs
        until an admission writes real rows via ``_slot_update_fn``."""
        import jax
        import jax.numpy as jnp

        S, W = self._slots, self.MAX_STOPS
        kd = np.asarray(jax.random.key_data(jax.random.key(0)))
        self._arrays = (
            jnp.zeros((S,), jnp.int32),                  # tok
            jnp.zeros((S,), jnp.int32),                  # emitted
            jnp.ones((S,), bool),                        # done
            jnp.zeros((S,), jnp.int32),                  # budgets
            jnp.zeros((S,), jnp.int32),                  # pad_lens
            jnp.asarray(np.tile(kd, (S, 1))),            # key data
            jnp.full((S, W), -1, jnp.int32),             # stops
            jnp.zeros((S,), jnp.float32),                # temps
            jnp.zeros((S,), jnp.int32),                  # top_ks
            jnp.zeros((S,), jnp.float32),                # top_ps
        )

    def _dispatch_chunk(self, steps: int):
        """Queue one ``steps``-step chunk on the device (async —
        nothing is forced here) and advance the host position mirror.
        The cache's ``pos_index`` lives on device (set by admissions,
        advanced in-graph by each step) — no per-dispatch transfers.
        ``steps < self._chunk`` only at era end, where the remaining
        room is smaller than a full chunk (tail executables are
        lru-cached like any other)."""
        tok, emitted, done, budgets, pad_lens, keys, stops, temps, \
            ks, ps = self._arrays
        t_dispatch = time.monotonic()
        if self._paged:
            chunk = _paged_chunk_fn(self.model, steps, self.MAX_STOPS)
            self._register_anatomy(
                chunk, steps,
                (self.params, self._cache, self._tables, self._starts,
                 tok, emitted, done, budgets, pad_lens, keys, stops,
                 temps, ks, ps))
            with span("serve/chunk_dispatch", steps=steps, paged=True):
                cache, starts, toks, tok, emitted, done = chunk(
                    self.params, self._cache, self._tables,
                    self._starts, tok, emitted, done, budgets,
                    pad_lens, keys, stops, temps, ks, ps)
            self._cache = cache
            self._starts = starts
            self._prefix.sync_pool_from_cache(cache)
            self.stats["paged_chunks"] += 1
        else:
            chunk = _chunk_fn(self.model, steps, self.MAX_STOPS)
            self._register_anatomy(
                chunk, steps,
                (self.params, self._cache, tok, emitted, done,
                 budgets, pad_lens, keys, stops, temps, ks, ps))
            with span("serve/chunk_dispatch", steps=steps):
                cache, toks, tok, emitted, done = chunk(
                    self.params, self._cache, tok, emitted, done,
                    budgets, pad_lens, keys, stops, temps, ks, ps)
            self._cache = cache
        self._arrays = (tok, emitted, done, budgets, pad_lens, keys,
                        stops, temps, ks, ps)
        self._p += steps
        self.stats["chunks"] += 1
        if self._anatomy.enabled:
            self._anatomy_pending.append((t_dispatch, steps))
        return toks, emitted, done

    def _register_anatomy(self, chunk, steps: int, args) -> None:
        """Queue the ONE background anatomy analysis of the decode
        chunk executable. The arg shapes are era-invariant (slots and
        stop width are fixed), so a single registration covers the
        engine's lifetime — later calls are one boolean check."""
        if self._anatomy_tried or not self._anatomy.enabled:
            return
        self._anatomy_tried = True
        if self._anatomy.register("decode_chunk", chunk, args):
            self._anatomy_steps = steps

    def anatomy_snapshot(self):
        """The ``decode_step_anatomy`` /metrics section (None until
        the background analysis lands or when PDT_ANATOMY=0)."""
        return self._anatomy.snapshot("decode_chunk")

    def _absorb(self, toks, emitted, done):
        """Force a dispatched chunk's outputs and hand tokens to their
        requests; finished rows complete and free their slots."""
        with span("serve/absorb"):
            toks = np.asarray(toks)
            emitted = np.asarray(emitted)
            done = np.asarray(done)
        t_absorb = time.monotonic()
        if self._anatomy_pending:
            # chunk wall = dispatch -> force of this chunk's outputs
            # (absorbs run in dispatch order). Only chunks matching the
            # analyzed executable's step count feed the EWMA — tail
            # chunks at era end run fewer in-graph steps and would
            # skew the modeled-vs-measured gap
            t0, steps = self._anatomy_pending.pop(0)
            if steps == self._anatomy_steps or not self._anatomy_steps:
                self._anatomy.observe(
                    "decode_chunk", (t_absorb - t0) * 1e3)
        tok0_np: dict = {}          # one D2H read per admission group
        for s in range(self._slots):
            m = self._meta[s]
            if m is None or m["done"]:
                continue
            n_before = len(m["out"])
            if not m["out"]:
                # first absorb for this row: its admission-time token
                # future is long since resolved (the chunk that just
                # forced ran after it). Memoized per group — a
                # np.asarray per ROW was 8 separate device reads
                # (~0.1 s of serialized tunnel RPCs per wave).
                arr, j = m["tok0_ref"]
                if id(arr) not in tok0_np:
                    tok0_np[id(arr)] = np.asarray(arr)
                m["out"].append(int(tok0_np[id(arr)][j]))
            fresh = int(emitted[s]) - m["emitted"]
            m["out"].extend(int(t) for t in toks[s, :fresh])
            m["emitted"] = int(emitted[s])
            m["done"] = bool(done[s])
            if "t_first" not in m and m["out"]:
                # server-side TTFT: the first absorb that makes this
                # row's first token servable (host-observed — the
                # device produced it earlier, but nothing could be
                # streamed before this force)
                m["t_first"] = t_absorb
                ttft = t_absorb - m["req"]["t0"]
                self._ttfts.append(ttft)
                if len(self._ttfts) > 1024:
                    del self._ttfts[:512]
                self.hist["ttft_seconds"].observe(ttft)
                rid = m["req"].get("rid")
                if self._tracer is not None and rid:
                    self._tracer.event(rid, "first_token",
                                      ttft_s=round(ttft, 6))
            elif self._tracer is not None and fresh > 0:
                rid = m["req"].get("rid")
                if rid:
                    self._tracer.event(rid, "decode_chunk",
                                       tokens=fresh)
            ev = m["req"].get("cancel")
            if ev is not None and not m["done"] and ev.is_set():
                # cancelled mid-flight: finalize with what's decoded,
                # free the slot for waiting traffic (the device row
                # keeps stepping until the slot is reused — bounded
                # waste; the SLOT availability is the win). In paged
                # mode the still-stepping zombie row keeps WRITING its
                # private pool pages, so their cleanup defers until
                # the slot is re-admitted or the engine idles
                # (_finish_pages zombie arm) — freeing them now could
                # hand a page the zombie still writes to a new request
                m["done"] = True
                m["zombie"] = True
            dl = m["req"].get("deadline")
            if (dl is not None and not m["done"]
                    and dl.expired(t_absorb)):
                # deadline expired mid-decode: the engine raises the
                # cancel itself (ISSUE 9) — same zombie bookkeeping as
                # a client disconnect, but classified "deadline"
                m["done"] = True
                m["zombie"] = True
                m["deadline"] = True
            cb = m["req"].get("on_tokens")
            if cb is not None:
                # delta = this absorb's emissions, minus stop ids (a
                # stop can only be the LAST emitted token — the row
                # freezes after it — so filtering ≡ the final
                # response's trailing-stop strip)
                stops = m["req"]["stop"]
                delta = [t for t in m["out"][n_before:]
                         if t not in stops]
                if delta:
                    try:
                        cb(delta)
                    except Exception:   # noqa: BLE001 — a consumer's
                        pass            # callback must not kill absorb
        for s in range(self._slots):
            m = self._meta[s]
            if m is not None and m["done"]:
                self._complete(s)
        if self._recorder is not None:
            # per-chunk serving telemetry: cumulative counters, so the
            # offline analyzer (scripts/telemetry_report.py) reads the
            # LAST record for totals; prefix-cache fields ride along
            # when the pool is enabled
            rec = {
                "event": "serve_chunk",
                "live_slots": sum(mm is not None for mm in self._meta),
                "queue_depth": self._queue.qsize(),
                "tokens_generated_total":
                    self.stats.get("tokens_generated", 0),
                "admissions_total": self.stats.get("admissions", 0),
            }
            if self._anatomy.version != self._anatomy_seen_version:
                # step anatomy rides a flight record exactly when the
                # analysis (re)lands — the offline analyzer reads the
                # LAST record carrying the field, so one emission per
                # version is enough and keeps the JSONL lean
                snap = self._anatomy.snapshot("decode_chunk")
                if snap:
                    rec["decode_step_anatomy"] = snap
                    self._anatomy_seen_version = self._anatomy.version
            if self.tp > 1:
                # TP serving telemetry (ISSUE 10): constant per-step
                # accounting (precomputed at setup — tp_stats caches),
                # recorded per chunk so the offline analyzer's
                # "Tensor parallel (serving)" section reads it from the
                # same JSONL as everything else
                tps = self.tp_stats()
                rec.update(
                    tp_degree=tps["tp_degree"],
                    tp_collective_count_per_step=tps[
                        "collective_count_per_step"],
                    tp_collective_bytes_per_step=tps[
                        "collective_bytes_per_step"],
                    tp_collective_floor_bytes=tps[
                        "analytic_floor_bytes"])
            if self._prefix is not None:
                snap = self._prefix.stats_snapshot()
                chunks = max(self.stats.get("chunks", 0), 1)
                rec.update(
                    prefix_hit_tokens_total=snap["prefix_hit_tokens"],
                    prefix_hit_requests_total=snap[
                        "prefix_hit_requests"],
                    prefix_lookups_total=snap["prefix_lookups"],
                    prefix_evictions_total=snap["prefix_evictions"],
                    prefix_pool_blocks_used=snap[
                        "prefix_pool_blocks_used"],
                    prefix_pool_blocks=snap["prefix_pool_blocks"],
                    prefix_pool_blocks_resident=snap[
                        "prefix_pool_blocks_resident"],
                    prefix_pool_blocks_referenced=snap[
                        "prefix_pool_blocks_referenced"],
                    prefix_adopted_blocks_total=snap[
                        "prefix_adopted_blocks"],
                    warm_admit_copy_bytes_total=snap[
                        "warm_admit_copy_bytes"],
                    paged_decode_frac=round(
                        self.stats.get("paged_chunks", 0) / chunks, 4),
                    # long-context serving (ISSUE 15): chunked-prefill
                    # progress + the pool-fallback family for the
                    # analyzer's prefix-cache section
                    prefill_chunks_total=self.stats.get(
                        "prefill_chunks", 0),
                    streamed_prefill_tokens_total=self.stats.get(
                        "streamed_prefill_tokens", 0),
                    pool_fallback_total=snap.get(
                        "pool_fallback_total", 0),
                )
                if snap.get("tier_enabled"):
                    # KV tier telemetry (ISSUE 13): cumulative demote/
                    # promote traffic + occupancy per tier, read by the
                    # offline analyzer's "KV tiers (serving)" section
                    rec.update(
                        tier_demoted_blocks_total=snap[
                            "tier_demoted_blocks"],
                        tier_promoted_blocks_total=snap[
                            "tier_promoted_blocks"],
                        tier_demote_bytes_total=snap[
                            "tier_demote_bytes"],
                        tier_promote_bytes_total=snap[
                            "tier_promote_bytes"],
                        tier_checksum_failures_total=snap[
                            "tier_checksum_failures"],
                        tier_exhaust_drops_total=snap[
                            "tier_exhaust_drops"],
                        tier_host_blocks=snap["tier_host_blocks"],
                        tier_host_bytes=snap["tier_host_bytes"],
                        tier_disk_blocks=snap["tier_disk_blocks"],
                        tier_disk_bytes=snap["tier_disk_bytes"],
                    )
            self._recorder.record(self.stats["chunks"], **rec)
        if self._tsdb is not None:
            counters = {
                "tokens_generated_total":
                    self.stats.get("tokens_generated", 0),
                "admissions_total": self.stats.get("admissions", 0),
                "chunks_total": self.stats.get("chunks", 0),
                "completed_total": self.stats.get("completed", 0),
                "cancelled_total": self.stats.get("cancelled", 0),
                "deadline_expired_total":
                    self.stats.get("deadline_expired", 0),
            }
            gauges = {
                "queue_depth": self._queue.qsize(),
                "live_slots": sum(mm is not None
                                  for mm in self._meta),
                "brownout_level": self.brownout_level,
            }
            if self._prefix is not None:
                snap = self._prefix.stats_snapshot()
                counters["prefix_hit_tokens_total"] = snap[
                    "prefix_hit_tokens"]
                gauges["prefix_pool_blocks_used"] = snap[
                    "prefix_pool_blocks_used"]
            self._tsdb.observe(counters=counters, gauges=gauges)

    def _insert_prefixes(self, reqs, slots, ints, matches):
        """Put the admitted prompts' own full blocks back into the pool:
        plan the index inserts on the host (allocating from the free
        list, LRU-evicting unreferenced blocks when full), then ONE
        fixed-shape capture dispatch — padded to ``(slots, nb_max)``
        like the admit itself, so arrival-wave sizes never mint fresh
        XLA executables mid-traffic. Lookup refs release only after
        both copy kernels are enqueued (device program order makes the
        reads safe against any later overwrite)."""
        try:
            nb = self._prefix.nb_max
            rows, cap_slots, cap_pads = [], [], []
            any_new = False
            for j, r in enumerate(reqs):
                blocks, start = self._prefix.plan_insert(r["ids"])
                row = [-1] * nb
                for i, b in enumerate(blocks):
                    row[start + i] = b
                if blocks:
                    any_new = True
                rows.append(row)
                cap_slots.append(slots[j])
                cap_pads.append(int(ints[j, 2]))
            while len(rows) < self._slots:   # fixed executable shape
                rows.append([-1] * nb)
                cap_slots.append(cap_slots[-1])
                cap_pads.append(cap_pads[-1])
            if any_new:
                self._prefix.capture(self._cache, cap_slots, cap_pads,
                                     rows)
        finally:
            for nodes, _, _ in matches:
                self._prefix.release(nodes)

    def _finish_pages(self, slot: int, m: dict) -> None:
        """Paged end-of-request page bookkeeping: ADOPT the request's
        full (prompt + decoded) blocks into the radix index in place —
        the zero-copy insert that makes freshly decoded tokens
        immediately sharable — then free the unadoptable tail and drop
        the slot's refs. Cancelled rows are ZOMBIES (the device lane
        keeps stepping into its private pages until the slot is
        reused): their cleanup is stashed and re-run from the next
        admit to this slot or the next idle tick."""
        pf = self._prefix
        plan = m.get("pages")
        if plan is None:
            return
        if m.get("zombie"):
            self._zombies[slot] = (plan, list(m["out"]),
                                   int(m["emitted"]))
            return
        self._cleanup_pages(plan, list(m["out"]), int(m["emitted"]))

    def _cleanup_pages(self, plan, out, emitted: int) -> None:
        # PrefixCache.paged_finish owns the end-of-request page
        # bookkeeping (adopt written blocks, free the tail, release
        # plan + adopt refs) — shared with the batch-1 path
        self._prefix.paged_finish(plan, out, emitted)

    def _reap_zombies(self, slot=None) -> None:
        """Run deferred page cleanup — for one slot (about to be
        re-admitted: the admit dispatch replaces the zombie's row
        state, so its writes stop targeting the old pages) or for all
        (engine idle: no chunks dispatch, nothing steps)."""
        slots = ([slot] if slot is not None
                 else list(self._zombies.keys()))
        for s in slots:
            stash = self._zombies.pop(s, None)
            if stash is not None:
                self._cleanup_pages(*stash)

    def _complete(self, slot: int):
        m = self._meta[slot]
        req = m["req"]
        if self._paged:
            ad0 = (self._prefix.counter("prefix_adopted_blocks")
                   if self._tracer is not None else 0)
            self._finish_pages(slot, m)
            if self._tracer is not None and req.get("rid"):
                adopted = (self._prefix.counter("prefix_adopted_blocks")
                           - ad0)
                if adopted:
                    # zero-copy radix adoption of this request's pages
                    # (prompt + decoded tokens become sharable)
                    self._tracer.event(req["rid"], "kv_adopt",
                                       blocks=adopted)
        resp = self._response(
            m["out"], stops=req["stop"], emitted=m["emitted"])
        ev = req.get("cancel")
        if (ev is not None and ev.is_set()
                and resp["stop_reason"] == "length"
                and m["emitted"] < req["budget"]):
            # finalized early by cancellation, not by budget — a row
            # that genuinely hit its stop token keeps "stop"
            resp["stop_reason"] = "cancelled"
            self.stats["cancelled"] = self.stats.get("cancelled", 0) + 1
        if (m.get("deadline") and resp["stop_reason"] == "length"
                and m["emitted"] < req["budget"]):
            # finalized by its own expired deadline, not by budget
            resp["stop_reason"] = "deadline"
            self.stats["deadline_expired"] = (
                self.stats.get("deadline_expired", 0) + 1)
        path = self._base_path()
        path.update(m.get("path") or {})
        self._finalize_path(resp, path, req.get("rid"))
        req["result"] = resp
        req["event"].set()
        self._meta[slot] = None
        self.stats["completed"] += 1
        t_done = time.monotonic()
        lat = t_done - req["t0"]
        self._latencies.append(lat)
        if len(self._latencies) > 1024:
            del self._latencies[:512]
        # latency exports + SLO check at the engine's own observation
        # point: e2e covers enqueue -> completion, TPOT the decode
        # cadence after the first token (ISSUE 8). Cancelled and
        # deadline-truncated requests stay OUT of the served-e2e
        # histogram (ISSUE 9): their latency is the client's/deadline's
        # choice, and counting them would reward truncation with
        # "better" tails. TPOT stays in — the decode cadence was real.
        served = resp["stop_reason"] not in ("cancelled", "deadline")
        if served:
            self.hist["e2e_seconds"].observe(lat)
        t_first = m.get("t_first")
        emitted_n = int(m["emitted"])
        ttft = (t_first - req["t0"]) if t_first is not None else None
        if t_first is not None and emitted_n > 1:
            self.hist["tpot_seconds"].observe(
                (t_done - t_first) / (emitted_n - 1))
        rid = req.get("rid")
        if self._tracer is not None and rid:
            self._tracer.event(
                rid, "complete", e2e_s=round(lat, 6),
                tokens=emitted_n, stop_reason=resp["stop_reason"])
        if self._slo is not None and rid:
            self._slo.observe(rid, ttft_s=ttft, e2e_s=lat,
                              tokens=emitted_n,
                              stop_reason=resp["stop_reason"])

    def queue_depth(self) -> int:
        """Requests waiting for a slot (not yet admitted)."""
        return self._queue.qsize()

    def live_slots(self) -> int:
        """Slots currently decoding a request."""
        meta = getattr(self, "_meta", None) or []
        return sum(m is not None for m in meta)

    def latency_percentiles(self) -> dict:
        lats = sorted(self._latencies[-1024:])
        if not lats:
            return {}
        pick = lambda q: round(percentile(lats, q), 4)   # noqa: E731
        out = {"p50_s": pick(0.50), "p95_s": pick(0.95),
               "p99_s": pick(0.99), "n": len(lats)}
        # server-side TTFT (ISSUE 8 satellite): stamped at the first
        # absorb per request, so serving latency decomposes into
        # first-token wait vs decode tail without a client in the loop
        ttfts = sorted(self._ttfts[-1024:])
        if ttfts:
            tp = lambda q: round(percentile(ttfts, q), 4)    # noqa: E731
            out.update(ttft_p50_s=tp(0.50), ttft_p95_s=tp(0.95),
                       ttft_p99_s=tp(0.99))
        return out

    def _worker(self):
        """The scheduler loop. Single thread owns the device state;
        the outer try mirrors the static worker's contract: an
        exception surfaces on every in-flight request rather than
        silently killing the thread."""
        self._meta = [None] * self._slots
        self._cache = None
        self._arrays = None
        self._p = 0
        self._zombies: dict = {}
        pending: list = []
        while True:
            involved = [m["req"] for m in self._meta if m is not None]
            try:
                active = any(m is not None for m in self._meta)
                if not active and not pending:
                    pending.append(self._queue.get())   # block when idle
                    deadline = time.monotonic() + self._window_s
                    while time.monotonic() < deadline:
                        try:
                            pending.append(self._queue.get_nowait())
                        except queue_mod.Empty:
                            time.sleep(self._window_s / 10)
                while True:
                    try:
                        pending.append(self._queue.get_nowait())
                    except queue_mod.Empty:
                        break
                involved = ([m["req"] for m in self._meta
                             if m is not None]
                            + [r for r in pending])
                self.stats["requests"] = (self.stats["completed"]
                                          + len(involved))
                with self._lock:
                    self._tick(pending)
            except Exception as e:  # noqa: BLE001 — surfaced per request
                logger.exception("continuous scheduler error")
                for r in involved:
                    r["error"] = e
                    r["event"].set()
                if self._paged:
                    # drop every page reservation this wreckage holds:
                    # leaked refs would pin pool pages against eviction
                    # forever on a recovering server
                    pf = self._prefix
                    plans = (
                        [m["pages"] for m in self._meta
                         if m is not None and m.get("pages")]
                        + [r["_pages"] for r in pending
                           if r.get("_pages")]
                        + [z[0] for z in self._zombies.values()]
                    )
                    for plan in plans:
                        try:
                            pf.release(plan["nodes"])
                            pf.release(plan["adopt_nodes"])
                            pf.free_blocks(
                                list(plan["private"].values()))
                        except Exception:  # noqa: BLE001 — best effort
                            pass
                    self._zombies = {}
                    self._tables = None
                    self._starts = None
                    # a dispatch that failed AFTER donating the cache
                    # leaves the pool's buffers dead — rebuilding the
                    # next era's cache from them would fail forever.
                    # Reset (content is unrecoverable) AFTER the plan
                    # cleanup above, so its host bookkeeping ran
                    # against the index that issued the refs.
                    if not pf.pool_alive():
                        pf.reset_pool()
                pending.clear()
                self._meta = [None] * self._slots
                self._cache = None
                self._arrays = None
                self._p = 0

    def _tick(self, pending: list):
        """One scheduler round under the lock: era management,
        admissions, one (or two, pipelined) chunk dispatches."""
        from ..resilience import faults

        from .generate import fresh_cache

        # serving fault hook (ISSUE 9): slow_decode sleeps here, hang
        # wedges this thread forever (the designated wedge — /healthz
        # keeps answering from the HTTP threads), pool_exhaust comes
        # back as a spec whose duration opens the dry-pool window
        spec = faults.on_serve_tick(self.stats["chunks"])
        if spec is not None:
            self._pool_dry_until = time.monotonic() + spec.duration_s
            logger.warning("fault pool_exhaust: pool reads dry for "
                           "%.2fs", spec.duration_s)
        if self._brownout is not None:
            with self._bo_lock:
                self._brownout.update(
                    self._brownout_pressure(waiting=len(pending)))
        active = any(m is not None for m in self._meta)
        # drop queued requests whose cancel event fired — or whose
        # deadline expired — before they ever took a slot (zero device
        # work spent on them) — BEFORE era-start positioning, so a
        # dead request's bucket or budget can't inflate/starve the new
        # era's position
        for r in list(pending):
            ev = r.get("cancel")
            dl = r.get("deadline")
            dead = (ev is not None and ev.is_set())
            expired = (not dead and dl is not None and dl.expired())
            if dead or expired:
                pending.remove(r)
                plan = r.pop("_pages", None)
                if plan is not None:
                    # a cancel/expiry BETWEEN streaming-prefill chunks
                    # (ISSUE 15): the plan's remaining private pages
                    # free through the existing paged bookkeeping;
                    # chunks already adopted stay in the radix (valid
                    # content — a same-prefix request still warm-hits
                    # them) with their pins released here
                    self._prefix.paged_finish(
                        plan, [], 0, written=plan.get("done", 0))
                resp = self._response([], stops=r["stop"], emitted=0)
                resp["stop_reason"] = ("cancelled" if dead
                                       else "deadline")
                r["result"] = resp
                r["event"].set()
                key = "cancelled" if dead else "deadline_expired"
                self.stats[key] = self.stats.get(key, 0) + 1
                self.stats["completed"] += 1
        # tiered-spill promotion (ISSUE 13): pending requests whose
        # prefix was demoted to the host/disk tier promote HERE — the
        # one point in the tick where a pool donation is still safe
        # (the refresh below re-adopts the swapped leaves before any
        # dispatch). Mid-tick lookups all pass promote=False for
        # exactly this reason. The pool_exhaust window also reads the
        # tier dry — the fault drains the WHOLE hierarchy.
        if (self._prefix is not None and self._prefix.spill is not None
                and pending and not self._pool_dry()):
            for r in pending[:self._slots]:
                t_tier0 = time.monotonic()
                n = self._prefix.promote_spilled(r["ids"])
                if n and self._tracer is not None and r.get("rid"):
                    # the "tier" attribution segment: time this
                    # admission spent pulling its prefix back up the
                    # hierarchy (reqtrace subtracts it from the
                    # scheduler_queue segment it overlaps)
                    self._tracer.add(r["rid"], "tier", t_tier0,
                                     time.monotonic(), blocks=n)
        # chunked streaming prefill (ISSUE 15 tentpole): ONE chunk of
        # ONE long pending prompt per tick — decode rows interleave
        # between chunks, so a 32k arrival never stalls the decode
        # batch for its whole prefill. Runs BEFORE the cache refresh
        # below: the chunk dispatch donates the pool the engine cache
        # aliases, and the refresh re-adopts the swapped leaves.
        if (self._paged and self._prefill_chunk and pending
                and not self._pool_dry()):
            for r in pending:
                if (len(r["ids"]) > self._prefill_chunk
                        or r.get("_pages") is not None):
                    verdict = self._stream_prefill_step(r)
                    if verdict != "skip":
                        # "chunked": this tick's streaming slot is
                        # spent; "deferred": a dry pool must not
                        # reserve for LATER requests over this one
                        # (FIFO, same as the admission loop)
                        break
        if self._paged and self._cache is not None:
            # a batch-1 speculative request between ticks (same lock)
            # may have reassigned the pool — its scatter insert's
            # capture kernel donates the very leaves this cache
            # aliases. Re-adopt before any dispatch touches them.
            self._cache = self._prefix.refresh_cache_from_pool(
                self._cache)
        if not active:
            # idle: new era (stale K/V is masked by pad_lens; only the
            # position counter resets). Paged mode has NO eras — pages
            # are position-independent — but idle is when zombie
            # (cancelled) rows are provably quiescent, so their
            # deferred page cleanup runs here.
            self._p = 0
            self.stats["eras"] += 1
            if self._paged:
                import jax.numpy as jnp

                self._reap_zombies()
                if self._cache is None:
                    self._cache = self._prefix.paged_cache()
                    self._tables = jnp.full(
                        (self._slots, self._prefix.nb_max), -1,
                        jnp.int32)
                    self._starts = jnp.zeros((self._slots,), jnp.int32)
            elif self._cache is None:
                self._cache = fresh_cache(
                    self.model, self.params, self._slots,
                    int(self.model.max_len))
            if self._arrays is None:
                self._init_arrays()
        # era start positions the counter at the largest bucket a FIFO
        # prefix of pending requests tolerates: the OLDEST request is
        # always admitted (no starvation), and same-wave arrivals of
        # mixed lengths admit together when their budgets all still
        # fit the era at the larger start position. (Paged rows carry
        # their own positions — no era placement needed.)
        if not active and pending and not self._paged:
            max_len = int(self.model.max_len)
            p_cand, chosen = 0, []
            # only the first `slots` pending requests can admit this
            # wave — a longer prefix would inflate the era start (and
            # burn budget room) for requests that must wait anyway
            for r in pending[:self._slots]:
                cand = max(p_cand, self._bucket(len(r["ids"])))
                if all(cand + q["budget"] <= max_len
                       for q in chosen + [r]):
                    p_cand, chosen = cand, chosen + [r]
                else:
                    break
            self._p = p_cand
        # group admissible arrivals by bucket: each group admits in ONE
        # prefill + ONE scatter dispatch (a same-wave burst — the
        # static scheduler's best case — stays one batched prefill)
        free = [s for s in range(self._slots) if self._meta[s] is None]
        groups: dict = {}
        for r in list(pending):
            if not free:
                break
            if (self.brownout_level >= 3
                    and r["budget"] > self._bo_max_new):
                # brownout level 3 (clamp_budget): long generations
                # finish short so slots recycle under saturation; the
                # response's stop_reason stays "length" — honest, the
                # budget WAS exhausted, just a browned-out budget
                r["budget"] = self._bo_max_new
                self.stats["brownout_clamped"] = (
                    self.stats.get("brownout_clamped", 0) + 1)
            if self._paged:
                if self._needs_streaming(r):
                    # still streaming its prompt in chunks (ISSUE 15):
                    # not admissible yet, but LATER pending requests
                    # may admit around it — that interleaving is the
                    # whole point of chunked prefill
                    continue
                # position-free admission: reserve pool pages (shared
                # prefix refs + a private chain for suffix AND budget).
                # A dry pool DEFERS the request — completions free
                # pages; FIFO order holds (we stop at the first
                # un-reservable request instead of skipping it)
                plan = r.get("_pages") or self._reserve_pages(r)
                if plan is None:
                    self.stats["deferred_admissions"] += 1
                    break
                r["_pages"] = plan
                if self._needs_streaming(r):
                    # freshly reserved long prompt: its first chunk
                    # streams next tick (or already streamed this one)
                    continue
                pending.remove(r)
                slot = free.pop(0)
                # this slot's admit dispatch (this tick) neutralizes
                # any zombie lane still writing its old pages
                self._reap_zombies(slot)
                b = self._bucket(len(r["ids"]))
                groups.setdefault(b, []).append((r, slot))
            elif self._admissible(r) and self._p > 0:
                pending.remove(r)
                b = self._bucket(len(r["ids"]))
                groups.setdefault(b, []).append((r, free.pop(0)))
        for pairs in groups.values():
            with span("serve/admit", n=len(pairs)):
                self._admit_group([r for r, _ in pairs],
                                  [s for _, s in pairs])
        self.stats["max_active"] = max(
            self.stats["max_active"],
            sum(m is not None for m in self._meta))
        live = [m for m in self._meta if m is not None]
        if not live:
            return
        min_left = min(m["req"]["budget"] - m["emitted"] for m in live)
        # era-end tail: the admission invariant bounds every live
        # budget by max_len, so min 1 step always remains. Paged rows
        # carry their own positions and preallocated chains — no era,
        # no tail clamp.
        room = (10 ** 9 if self._paged
                else int(self.model.max_len) - self._p)
        steps = min(self._chunk, room)
        # ADAPTIVE chunk growth: when every slot is occupied, no slot
        # can free before min_left steps (a row only exits early via a
        # stop token) — so running one long chunk straight to min_left
        # recycles slots exactly as fast while paying ONE host round
        # trip instead of min_left/chunk of them (each ~105 ms through
        # the tunnel; the uniform-burst case of the serve_mixed rung).
        # With free slots the base chunk stands, keeping admission
        # latency for new arrivals at one short chunk; with stop
        # tokens OR cancel events in play rows can exit mid-chunk
        # (a disconnect is only honored at the next absorb), so
        # growth is capped at 4x to bound the wasted frozen-row
        # steps, the slot-recycle delay, and the cancel latency.
        # brownout level 2 (short_chunks): growth disabled — admission
        # latency for the queue beats saturated-throughput batching
        if (min_left > self._chunk and self.brownout_level < 2
                and not any(m is None for m in self._meta)):
            limit = min(min_left, self._chunk * self._grow_cap(live))
            grown = self._chunk
            while grown * 2 <= limit:
                grown *= 2       # power-of-two LADDER: the executable
                # set is fixed and precompiled at startup
                # (_warm_chunk_ladder) — a length first seen mid-
                # traffic would stall every slot behind a fresh XLA
                # compile, the same timing-nondeterminism the padded
                # admission width kills (measured: the chunk=8 rung
                # collapsed ~10x from exactly that before the warmup)
            steps = min(grown, room)
        out1 = self._dispatch_chunk(steps)
        # dispatch ONE chunk ahead while the first runs, unless queue
        # traffic wants an admission slot between them or everyone
        # will finish inside the first chunk anyway
        min_left -= steps        # remaining after chunk 1
        steps2 = min(self._chunk,
                     (10 ** 9 if self._paged
                      else int(self.model.max_len) - self._p))
        if (self._queue.empty() and min_left > 0
                and not any(m is None for m in self._meta)
                and steps2 >= 1):
            out2 = self._dispatch_chunk(steps2)
            self._absorb(*out1)
            self._absorb(*out2)
        else:
            self._absorb(*out1)
