"""Training state: the single pytree the jitted step transforms.

The reference's mutable training state is spread across ``nn.Module``
parameters, optimizer slots, and the trainer's Python attributes
(/root/reference/base/base_trainer.py:14-49). TPU-natively all
device-resident state lives in one immutable pytree ``(step, params,
batch_stats, opt_state, rng)`` so the train step is a pure function
``(state, batch) -> (state, metrics)`` that XLA can donate and pipeline.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class TrainState:
    step: jnp.ndarray            # scalar int32, global optimizer step count
    params: Any
    batch_stats: Any             # {} for stateless models (e.g. no BatchNorm)
    opt_state: Any
    rng: jax.Array               # base PRNG key; per-step keys fold in `step`
    ema_params: Any = None       # shadow params when EMA is enabled
    lr_scale: Any = None         # scalar multiplier on optimizer updates;
                                 # host-driven (ReduceLROnPlateau) — lives in
                                 # state so it checkpoints and replicates


def create_train_state(model, tx, sample_input, seed: int = 0,
                       init_train: bool = False,
                       with_ema: bool = False) -> TrainState:
    """Initialize params (and batch_stats if the model has them) + optimizer.

    ``sample_input`` is a shape template batch (e.g.
    ``model.batch_template()``). ``with_ema`` seeds an exponential moving
    average of the params (updated in the train step when the trainer's
    ``ema_decay`` > 0; the reference has no EMA, SURVEY.md §2.4 — this is a
    first-class extension).
    """
    root = jax.random.key(seed)
    param_key, dropout_key, state_key = jax.random.split(root, 3)
    variables = model.init(
        {"params": param_key, "dropout": dropout_key},
        sample_input,
        train=init_train,
    )
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    opt_state = tx.init(params)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=opt_state,
        rng=state_key,
        ema_params=jax.tree.map(jnp.copy, params) if with_ema else None,
        lr_scale=jnp.ones((), jnp.float32),
    )


def create_sharded_train_state(model, tx, sample_input, mesh, seed: int = 0,
                               with_ema: bool = False):
    """Mesh-placed TrainState: create INSIDE jit with out_shardings.

    The multi-host-legal placement path shared by the trainer and the
    evaluator: a host-locally built state cannot be ``device_put`` to a
    sharding spanning non-addressable devices, but jit outputs are born
    global; single-host the two are equivalent. ``sample_input`` should be
    numpy so it embeds as a literal rather than a host-local array
    operand. Returns ``(state, state_sharding)``.
    """
    import numpy as np

    from ..parallel.sharding import apply_rules

    sample = np.asarray(sample_input)

    def init_fn():
        return create_train_state(model, tx, sample, seed=seed,
                                  with_ema=with_ema)

    rules = getattr(model, "partition_rules", lambda: [])()
    sharding = apply_rules(jax.eval_shape(init_fn), mesh, rules)
    state = jax.jit(init_fn, out_shardings=sharding)()
    return state, sharding
