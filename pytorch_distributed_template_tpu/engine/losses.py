"""Loss functions.

Reference: ``model/loss.py`` — a single ``nll_loss`` over log-probabilities
(/root/reference/model/loss.py:4-5). Here losses are **per-example** pure
functions ``(output, target) -> [B]``; the engine applies the padding mask
and reduces. That single convention makes every loss exact under the
duplicate-padded final batches the sampler produces (SURVEY.md §7 hard-part
(c)) and lets metrics/losses share reduction machinery inside jit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from ..config.registry import LOSSES


@LOSSES.register("nll_loss")
def nll_loss(output, target):
    """Negative log-likelihood over log-probability outputs (reference
    parity: the model ends in log_softmax)."""
    return -jnp.take_along_axis(output, target[:, None], axis=-1)[:, 0]


@LOSSES.register("cross_entropy")
def cross_entropy(output, target):
    """Softmax cross-entropy over raw logits."""
    return optax.softmax_cross_entropy_with_integer_labels(output, target)


@LOSSES.register("lm_cross_entropy")
def lm_cross_entropy(output, target):
    """Next-token LM loss: output [B, T, V] logits, target [B, T] tokens.

    Shifts internally (predict token t+1 from position t) and returns a
    per-sequence mean so the engine's per-example mask applies unchanged.
    """
    logits = output[:, :-1]
    labels = target[:, 1:]
    tok = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    return tok.mean(axis=-1)


@LOSSES.register("mlm_cross_entropy")
def mlm_cross_entropy(output, target):
    """Masked-LM loss for the BERT family (models/bert.py): ``output``
    is the model's ``(logits [B,T,V], mask [B,T])`` pair — the mask
    marks the positions the model corrupted in-graph — and ``target``
    is the ORIGINAL token stream. Per-example mean cross entropy over
    the masked positions only (unmasked positions would let the model
    score by copying its input)."""
    logits, sel = output
    tok = optax.softmax_cross_entropy_with_integer_labels(logits, target)
    denom = jnp.maximum(sel.sum(axis=-1), 1.0)
    return (tok * sel).sum(axis=-1) / denom


@LOSSES.register("mse_loss")
def mse_loss(output, target):
    return jnp.mean((output - target) ** 2, axis=tuple(range(1, output.ndim)))


@LOSSES.register("smooth_cross_entropy")
def smooth_cross_entropy(smoothing: float = 0.1):
    """FACTORY loss (dict-form config): label-smoothed softmax CE.

    Config: ``"loss": {"type": "smooth_cross_entropy",
    "args": {"smoothing": 0.1}}`` — the dict form is this framework's
    extension over the reference's name-only loss lookup
    (/root/reference/train.py:37); see :func:`resolve_loss`.
    """
    if not 0.0 <= smoothing < 1.0:
        raise ValueError(f"smoothing must be in [0, 1), got {smoothing}")

    def loss(output, target):
        n = output.shape[-1]
        onehot = jax.nn.one_hot(target, n, dtype=output.dtype)
        soft = onehot * (1.0 - smoothing) + smoothing / n
        return optax.softmax_cross_entropy(output, soft)

    return loss


smooth_cross_entropy._loss_factory = True  # dict-form config required


def chunk_shifted_sequence(h, labels, chunk: int, pad_label: int = 0):
    """Split an already-shifted (hidden, labels) pair into scan-ready
    chunk-leading arrays for the fused-head consumers (the chunked loss
    below and engine/metrics.lm_token_accuracy).

    h: [B, T-1, D]; labels: [B, T-1]. Returns ``(h_c [n, B, chunk, D],
    l_c [n, B, chunk], valid [n, chunk])`` where trailing padding rows are
    marked invalid and labels padded with ``pad_label``.
    """
    b, tm1, d = h.shape
    n_chunks = -(-tm1 // chunk)
    t_pad = n_chunks * chunk
    if t_pad != tm1:
        h = jnp.pad(h, ((0, 0), (0, t_pad - tm1), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, t_pad - tm1)),
                         constant_values=pad_label)
    h_c = jnp.moveaxis(h.reshape(b, n_chunks, chunk, d), 1, 0)
    l_c = jnp.moveaxis(labels.reshape(b, n_chunks, chunk), 1, 0)
    valid = (
        (jnp.arange(t_pad) < tm1).astype(jnp.float32)
        .reshape(n_chunks, chunk)
    )
    return h_c, l_c, valid


@LOSSES.register("fused_lm_cross_entropy")
def fused_lm_cross_entropy(chunk: int = 256):
    """FACTORY loss: next-token CE fused with the LM head, sequence-chunked.

    Pairs with a model built with ``fused_head: true`` (models/transformer
    TransformerLM): ``output`` is ``(hidden [B,T,D], head_w [D,V])`` and
    the [B, T, V] logits tensor NEVER materializes — a ``lax.scan`` over
    ``chunk``-token slices computes each slice's logits, its CE, and (via
    ``jax.checkpoint`` on the body) recomputes them in backward, so peak
    HBM holds one [B, chunk, V] slice instead of the full T. At GPT-2
    vocab (50257) and long T this is the dominant activation saved.
    Numerically identical to ``lm_cross_entropy`` on the same params
    (same shift, per-sequence mean) up to float reassociation.
    """
    if chunk <= 0:
        raise ValueError(f"chunk must be positive, got {chunk}")

    def loss(output, target):
        h, w = output                       # [B, T, D], [D, V]
        tm1 = h.shape[1] - 1
        b = h.shape[0]
        h_c, l_c, v_c = chunk_shifted_sequence(
            h[:, :-1], target[:, 1:], chunk
        )

        @jax.checkpoint
        def body(carry, inp):
            hc, lc, vc = inp
            logits = (hc @ w).astype(jnp.float32)       # [B, chunk, V]
            tok = optax.softmax_cross_entropy_with_integer_labels(
                logits, lc
            )
            return carry + jnp.sum(tok * vc[None, :], axis=-1), None

        total, _ = jax.lax.scan(
            body, jnp.zeros((b,), jnp.float32), (h_c, l_c, v_c)
        )
        return total / tm1

    return loss


fused_lm_cross_entropy._loss_factory = True


def resolve_loss(loss_cfg):
    """Resolve the config ``loss`` entry to a per-example callable.

    A plain string keeps the reference's semantics (name lookup,
    train.py:37). A ``{"type", "args"}`` dict treats the registered object
    as a factory called with ``args`` — how parameterized losses (label
    smoothing) stay expressible without breaking the name-only contract.
    Form/kind mismatches raise HERE, at config-resolve time, instead of as
    an opaque arity error inside the first jit trace.
    """
    if isinstance(loss_cfg, str):
        loss = LOSSES.get(loss_cfg)
        if getattr(loss, "_loss_factory", False):
            raise ValueError(
                f"loss '{loss_cfg}' is parameterized; use the dict form "
                f'{{"type": "{loss_cfg}", "args": {{...}}}}'
            )
        return loss
    factory = LOSSES.get(loss_cfg["type"])
    if not getattr(factory, "_loss_factory", False):
        raise ValueError(
            f"loss '{loss_cfg['type']}' takes no args; use the string form "
            f'"loss": "{loss_cfg["type"]}"'
        )
    return factory(**dict(loss_cfg.get("args", {})))
