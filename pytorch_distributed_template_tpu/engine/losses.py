"""Loss functions.

Reference: ``model/loss.py`` — a single ``nll_loss`` over log-probabilities
(/root/reference/model/loss.py:4-5). Here losses are **per-example** pure
functions ``(output, target) -> [B]``; the engine applies the padding mask
and reduces. That single convention makes every loss exact under the
duplicate-padded final batches the sampler produces (SURVEY.md §7 hard-part
(c)) and lets metrics/losses share reduction machinery inside jit.
"""
from __future__ import annotations

import jax.numpy as jnp
import optax

from ..config.registry import LOSSES


@LOSSES.register("nll_loss")
def nll_loss(output, target):
    """Negative log-likelihood over log-probability outputs (reference
    parity: the model ends in log_softmax)."""
    return -jnp.take_along_axis(output, target[:, None], axis=-1)[:, 0]


@LOSSES.register("cross_entropy")
def cross_entropy(output, target):
    """Softmax cross-entropy over raw logits."""
    return optax.softmax_cross_entropy_with_integer_labels(output, target)


@LOSSES.register("lm_cross_entropy")
def lm_cross_entropy(output, target):
    """Next-token LM loss: output [B, T, V] logits, target [B, T] tokens.

    Shifts internally (predict token t+1 from position t) and returns a
    per-sequence mean so the engine's per-example mask applies unchanged.
    """
    logits = output[:, :-1]
    labels = target[:, 1:]
    tok = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    return tok.mean(axis=-1)


@LOSSES.register("mse_loss")
def mse_loss(output, target):
    return jnp.mean((output - target) ** 2, axis=tuple(range(1, output.ndim)))
