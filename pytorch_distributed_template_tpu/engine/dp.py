"""DP×TP serving: N independent tensor-parallel groups in ONE process.

PR 10 made a replica span multiple chips (``--tp N``: one wide SPMD
program). This module is the explicit follow-on (ISSUE 12 / ROADMAP
item 3's geometry half): one *process* now runs ``dp`` independent
engines, each on its own ``tp``-chip group tiling the local device
list — so a decode-role replica can run several small TP groups
(decode is bandwidth-bound; small groups keep the batch per group in
the sweet spot) while a prefill-role replica runs one wide group
(prefill is compute-bound; width buys FLOPs).

Groups are fully independent: each has its own model instance (its own
group-local ``{"tensor": tp}`` mesh from ``parallel/tp.dp_group_mesh``),
its own sharded param copy, its own paged prefix pool, and its own
scheduler thread. NOTHING crosses groups in-graph — the only
cross-group machinery is host-side placement:

- a request routes to the group whose pool holds the deepest cached
  prefix (the in-process twin of the fleet router's cache-aware
  placement), bounded by a load spread so a hot prefix never queues
  behind itself while sibling groups idle; no match = least-loaded,
  ties rotate;
- a page import (``import_remote_pages``) lands on one group's pool,
  and the radix probe above is what steers the follow-up ``generate``
  to that same group — the import IS the affinity record.

Token-exactness is inherited, not re-proven: a request's tokens depend
only on its own prompt, seed, and sampling config (the continuous
engine's contract), and every group runs identical weights — so which
group serves a request cannot change its output, and (dp=2, tp=2) is
token-identical to (dp=1, tp=1) by construction (gated anyway in the
``serve_disagg`` bench rung).

At ``tp == 1`` a group has no mesh: its params are COMMITTED to the
group's device, and jax places every dispatch there (uncommitted
engine state follows committed inputs, then lives on-device as donated
jit outputs) — so dp×1 really is N chips doing independent work, not
N schedulers sharing chip 0.
"""
from __future__ import annotations

import logging
import threading

from ..utils.promtext import percentile

logger = logging.getLogger(__name__)


class _MergedHist:
    """Snapshot-time bucket-sum over the groups' fixed-bucket latency
    histograms — the same aggregation discipline as the fleet poller
    (bucket counters sum exactly; percentile gauges do not)."""

    def __init__(self, hists):
        self._hists = hists

    def snapshot(self) -> dict:
        from ..utils.promtext import add_histograms, zero_histogram

        out = zero_histogram()
        for h in self._hists:
            add_histograms(out, h.snapshot())
        return out


class _StatsView(dict):
    """The facade's ``stats`` dict: a fresh merge of the group
    engines' counters plus the facade's own. Writes (serve.py bumps
    ``deadline_expired`` on pre-dispatch 504s) forward their DELTA to
    the facade's persistent own-counter store, so a counter bumped
    through one snapshot survives into the next."""

    def __init__(self, data, own):
        super().__init__(data)
        self._own = own

    def __setitem__(self, key, value):
        base = self.get(key, 0)
        if isinstance(value, (int, float)) and isinstance(
                base, (int, float)):
            self._own[key] = self._own.get(key, 0) + (value - base)
        else:
            self._own[key] = value
        super().__setitem__(key, value)


class DataParallelService:
    """N independent group engines behind ONE service facade exposing
    the same surface serve.py speaks (generate / validate_request /
    stats / metrics accessors), so the HTTP layer cannot tell dp=4
    from dp=1."""

    def __init__(self, engines, load_spread: float = 4.0):
        if not engines:
            raise ValueError("DataParallelService needs >= 1 engine")
        self._engines = list(engines)
        self._spread = float(load_spread)
        self._rr = 0
        self._lock = threading.Lock()
        self._own_stats: dict = {}
        e0 = self._engines[0]
        self.model = e0.model
        self.arch = e0.arch
        self.vocab = e0.vocab
        self.tokenizer = e0.tokenizer
        self.role = e0.role
        self.tp = e0.tp
        self.dp = len(self._engines)
        self.STREAM_DELTAS = bool(getattr(e0, "STREAM_DELTAS", False))
        self._slots = sum(int(getattr(e, "_slots", 0) or 1)
                          for e in self._engines)

    # -- construction -------------------------------------------------------

    @classmethod
    def from_model_factory(cls, factory, params, dp: int, tp: int,
                           service_cls, tokenizer=None,
                           load_spread: float = 4.0,
                           service_kw=None, service_kw_fn=None):
        """Build ``dp`` group engines: ``factory(mesh)`` returns a
        fresh model instance bound to the group's mesh (None at
        tp=1); ``params`` (host or any-device tree) is re-placed per
        group — sharded over the group mesh at tp>1, committed to the
        group's single device at tp=1. ``service_kw_fn(g)`` overrides
        per-group kwargs (e.g. a recorder only group 0 should own)."""
        import jax

        from ..parallel.tp import (
            dp_group_devices, dp_group_mesh, shard_serving_params,
            validate_dp_geometry, validate_tp_geometry,
        )

        dp, tp = int(dp), int(tp)
        validate_dp_geometry(dp, tp)
        engines = []
        for g in range(dp):
            mesh = dp_group_mesh(g, tp)
            model_g = factory(mesh)
            if mesh is not None:
                validate_tp_geometry(model_g, tp)
                params_g = shard_serving_params(model_g, params, mesh)
            else:
                params_g = jax.device_put(
                    params, dp_group_devices(g, 1)[0])
            kw = dict(service_kw or {})
            if service_kw_fn is not None:
                kw.update(service_kw_fn(g) or {})
            engines.append(service_cls.from_model(
                model_g, params_g, tokenizer, **kw))
            logger.info("dp group %d/%d ready (tp=%d)", g + 1, dp, tp)
        return cls(engines, load_spread=load_spread)

    @classmethod
    def build_from_config(cls, config, service_cls, use_ema: bool = False,
                          dp: int = 2, tp: int = 1,
                          load_spread: float = 4.0,
                          service_kw=None, service_kw_fn=None):
        """The serve.py entry: one checkpoint/artifact restore, then
        ``dp`` group engines around re-placed copies of it."""
        from ..config.registry import MODELS
        from ..models.base import inject_mesh
        from .serving import load_generation_stack

        _, params, tok = load_generation_stack(
            config, use_ema=use_ema,
            tensor_parallel=(tp if int(tp) > 1 else 0))

        def factory(mesh):
            return inject_mesh(config.init_obj("arch", MODELS), mesh)

        return cls.from_model_factory(
            factory, params, dp, tp, service_cls, tokenizer=tok,
            load_spread=load_spread, service_kw=service_kw,
            service_kw_fn=service_kw_fn)

    # -- placement ----------------------------------------------------------

    def _loads(self):
        return [e.queue_depth() + e.live_slots()
                if hasattr(e, "queue_depth") else 0
                for e in self._engines]

    def _pick(self, ids=None) -> int:
        """Cache-aware group choice, the fleet chooser's in-process
        twin: deepest cached prefix wins unless that group's load
        exceeds the least-loaded's by more than the spread (a hot
        prefix must not hotspot one group while siblings idle);
        no match = least-loaded, ties rotate."""
        with self._lock:
            rr = self._rr
            self._rr += 1
        loads = self._loads()
        least = min(loads)
        tied = [i for i, l in enumerate(loads) if l <= least]
        least_i = tied[rr % len(tied)]
        if ids:
            best_i, best_c = None, 0
            for i, e in enumerate(self._engines):
                pf = getattr(e, "_prefix", None)
                if pf is None:
                    continue
                c = pf.cached_block_count(ids)
                if c > best_c:
                    best_c, best_i = c, i
            if best_i is not None and loads[best_i] - least <= self._spread:
                return best_i
        return least_i

    # -- the service surface ------------------------------------------------

    def generate(self, prompt=None, prompt_ids=None, **kw) -> dict:
        try:
            ids = self._engines[0].encode_prompt(prompt, prompt_ids)
        except ValueError:
            ids = None        # the group engine raises the real 400
        g = self._pick(ids)
        return self._engines[g].generate(
            prompt=prompt, prompt_ids=prompt_ids, **kw)

    def prefill_export(self, prompt=None, prompt_ids=None, **kw) -> dict:
        try:
            ids = self._engines[0].encode_prompt(prompt, prompt_ids)
        except ValueError:
            ids = None
        g = self._pick(ids)
        return self._engines[g].prefill_export(
            prompt=prompt, prompt_ids=prompt_ids, **kw)

    def export_cached_pages(self, prompt=None, prompt_ids=None,
                            **kw) -> dict:
        """Export-only peer migration (ISSUE 13): pick PURELY by who
        holds the deepest chain — an export is a read, and generate's
        load-gated pick would divert it to an idle group with an
        empty pool (n_blocks 0 while the pages sit one group over)."""
        try:
            ids = self._engines[0].encode_prompt(prompt, prompt_ids)
        except ValueError:
            ids = None          # group 0 raises the real 400 below
        g = 0
        if ids is not None:
            depths = [(e._prefix.cached_block_count(ids)
                       if e._prefix is not None else 0)
                      for e in self._engines]
            g = max(range(len(depths)), key=lambda i: depths[i])
        return self._engines[g].export_cached_pages(
            prompt=prompt, prompt_ids=prompt_ids, **kw)

    def import_remote_pages(self, payload) -> dict:
        """Land shipped pages on the least-loaded group's pool; the
        follow-up ``generate`` finds them through the same radix probe
        that placed them — the import is its own affinity record."""
        g = self._pick(None)
        receipt = self._engines[g].import_remote_pages(payload)
        receipt["dp_group"] = g
        return receipt

    def validate_request(self, req: dict) -> None:
        self._engines[0].validate_request(req)

    def encode_prompt(self, prompt=None, prompt_ids=None):
        return self._engines[0].encode_prompt(prompt, prompt_ids)

    def encode_stop(self, stop):
        return self._engines[0].encode_stop(stop)

    # -- observability ------------------------------------------------------

    @property
    def stats(self) -> dict:
        merged: dict = {"dp_groups": self.dp}
        for e in self._engines:
            for k, v in (getattr(e, "stats", None) or {}).items():
                if isinstance(v, bool):
                    continue
                if isinstance(v, (int, float)):
                    merged[k] = merged.get(k, 0) + v
        for k, v in self._own_stats.items():
            if isinstance(v, (int, float)):
                merged[k] = merged.get(k, 0) + v
            else:
                merged[k] = v
        return _StatsView(merged, self._own_stats)

    def queue_depth(self) -> int:
        return sum(e.queue_depth() for e in self._engines
                   if hasattr(e, "queue_depth"))

    def live_slots(self) -> int:
        return sum(e.live_slots() for e in self._engines
                   if hasattr(e, "live_slots"))

    def latency_percentiles(self) -> dict:
        lats = sorted(
            x for e in self._engines
            for x in list(getattr(e, "_latencies", ()))[-1024:])
        if not lats:
            return {}
        out = {"p50_s": round(percentile(lats, 0.5), 4),
               "p95_s": round(percentile(lats, 0.95), 4),
               "p99_s": round(percentile(lats, 0.99), 4),
               "n": len(lats)}
        ttfts = sorted(
            x for e in self._engines
            for x in list(getattr(e, "_ttfts", ()))[-1024:])
        if ttfts:
            out.update(
                ttft_p50_s=round(percentile(ttfts, 0.5), 4),
                ttft_p95_s=round(percentile(ttfts, 0.95), 4),
                ttft_p99_s=round(percentile(ttfts, 0.99), 4))
        return out

    @property
    def hist(self) -> dict:
        base = getattr(self._engines[0], "hist", None) or {}
        return {k: _MergedHist([e.hist[k] for e in self._engines])
                for k in base}

    def prefix_cache_stats(self):
        snaps = [s for s in (e.prefix_cache_stats()
                             for e in self._engines) if s]
        if not snaps:
            return None
        out: dict = {}
        for k, v0 in snaps[0].items():
            if isinstance(v0, bool):
                out[k] = all(s.get(k, False) for s in snaps)
            elif isinstance(v0, (int, float)):
                out[k] = sum(s.get(k, 0) for s in snaps)
            else:
                out[k] = v0
        lk = out.get("prefix_lookups", 0)
        out["prefix_hit_rate"] = round(
            out.get("prefix_hit_requests", 0) / lk, 4) if lk else 0.0
        return out

    def tp_stats(self) -> dict:
        # identical geometry per group: group 0 speaks for all — the
        # per-step collective accounting is a property of the program,
        # not of which group runs it
        return self._engines[0].tp_stats()

    def slo_stats(self) -> dict:
        # the SLO watcher is one shared object across groups
        return self._engines[0].slo_stats()

    @property
    def brownout_level(self) -> int:
        return max((getattr(e, "brownout_level", 0)
                    for e in self._engines), default=0)

    def brownout_stats(self) -> dict:
        stats = [e.brownout_stats() for e in self._engines
                 if hasattr(e, "brownout_stats")]
        if not stats:
            return {"brownout_level": 0}
        worst = max(stats,
                    key=lambda s: int(s.get("brownout_level", 0)))
        out = dict(worst)
        out["brownout_level"] = max(
            int(s.get("brownout_level", 0)) for s in stats)
        return out
