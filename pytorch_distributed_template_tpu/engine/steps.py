"""The jitted train/eval step builders — the framework's hot loop.

Replaces the reference's per-batch Python sequence (H2D copy, zero_grad,
forward, loss, ``dist.reduce``, backward, DDP allreduce, optimizer step —
/root/reference/trainer/trainer.py:45-58) with ONE compiled SPMD program:

- the batch arrives already sharded over the mesh's data axes;
- ``jnp`` reductions over the sharded batch dimension compile to ``psum``
  over ICI (the DDP gradient allreduce *and* the reference's per-step
  ``reduce_loss`` collective, fused into the step instead of blocking it —
  the reference syncs before backward, SURVEY.md §2.1 bug list);
- masked per-example losses/metrics make duplicate-padded batches exact;
- the optimizer update runs in-graph (optax), so there is no host round-trip
  between micro-batches.

Metrics are returned as sufficient statistics ``{name_sum, count}`` — the
TPU-idiomatic version of the reference's gather-everything-to-rank-0 eval
(SURVEY.md §3.5).
"""
from __future__ import annotations

import functools
import inspect
from typing import Callable, Dict, Sequence

import jax
import jax.numpy as jnp
import optax


def _masked_sum(per_example, mask):
    return jnp.sum(per_example * mask)


def _accepts_example_mask(model) -> bool:
    """Whether the model's ``__call__`` takes ``example_mask`` — models with
    cross-example coupling (MoE capacity routing) need the batch mask inside
    the forward pass; per-token models are exact from loss masking alone."""
    try:
        return "example_mask" in inspect.signature(
            type(model).__call__
        ).parameters
    except (TypeError, ValueError):  # exotic callables
        return False


def make_train_step(model, tx, criterion: Callable,
                    metric_fns: Sequence[Callable] = (),
                    input_key: str = "image", target_key: str = "label",
                    grad_clip_norm: float = 0.0,
                    grad_accum_steps: int = 1,
                    ema_decay: float = 0.0,
                    skip_nonfinite: bool = False,
                    augment=None,
                    mixup_alpha: float = 0.0,
                    log_grad_norm: bool = False,
                    trainable_patterns=None,
                    health: bool = False,
                    inject_nan_grad_step=None):
    """Build ``train_step(state, batch) -> (state, metrics)``.

    ``metrics`` holds scalar sums + count; callers divide after accumulating
    across batches (exact masked averages).

    ``grad_accum_steps > 1`` splits the batch into that many microbatches and
    runs them through a ``lax.scan`` (one compiled body, k iterations),
    summing *unnormalized* (masked-sum) gradients and dividing once by the
    global valid count — the same mean-gradient math as the unaccumulated
    step on the full batch (equal up to float reassociation; dropout draws
    per-microbatch keys and BatchNorm normalizes per microbatch, so those
    layers see genuinely different — not wrong — randomness/statistics), at
    1/k the activation memory. The reference has no accumulation (SURVEY.md
    §2.4); this is the TPU-idiomatic way to trade HBM for FLOPs alongside
    remat.

    ``ema_decay > 0`` maintains ``state.ema_params`` (shadow weights) with
    ``ema = d*ema + (1-d)*params`` after each update.

    ``skip_nonfinite`` guards the update in-graph: when any gradient leaf
    (or the loss) is non-finite the whole update is suppressed via
    ``jnp.where`` — params/opt_state/EMA keep their old values and
    ``skipped_sum`` counts the event — instead of poisoning the weights.
    A branchless select keeps the step a single static XLA program (no
    host round-trip, unlike torch-style ``if not torch.isfinite(loss)``
    Python checks). The step counter still advances so dropout keys and
    schedules stay aligned with wall progress.

    ``augment`` (ops/augment.build_augment) is applied to the input batch
    in-graph before the forward pass, keyed per step — train-time only.

    ``health`` adds the numerics-forensics summary
    (observability/health) as ONE packed f32 vector under
    ``metrics["health"]``: per-example loss, global grad/update norms,
    and non-finite element counts for the post-update params and the
    raw gradients per top-level param group (field order:
    ``health_layout(params)``). A handful of scalar reductions and a
    single tiny output, so the summary rides the dispatch pipeline
    instead of stalling it. Appended AFTER the ``skip_nonfinite``
    zeroing so a suppressed step still reports the non-finite counts
    that got it suppressed (that report is the whole point). Callers
    strip the ``health`` key out of the epoch accumulator.

    ``inject_nan_grad_step`` (resilience/faults ``nan_grad@step:N``):
    when set, every gradient leaf is NaN-poisoned at exactly that
    global step via a branchless in-graph select on ``state.step`` —
    the deterministic trigger for the numerics-forensics /
    ``skip_nonfinite`` recovery paths. Injected BEFORE normalization,
    clipping, and the health capture, so the poisoned step looks
    exactly like a real gradient blow-up to every detector downstream.

    ``mixup_alpha > 0`` enables mixup (Zhang et al. 2018) in-graph: one
    Beta(alpha, alpha) draw per step mixes the batch with a random
    permutation of itself, and the loss becomes the matching convex
    combination ``lam * L(out, y) + (1-lam) * L(out, y_perm)``. Metrics
    are still computed against the original labels. Composes with
    ``augment`` (mixup runs after) and grad accumulation (the mixed
    targets ride the batch pytree through the microbatch split).
    """
    pass_example_mask = _accepts_example_mask(model)

    def sumloss_and_output(params, batch_stats, batch, dropout_rng):
        """Masked SUM of per-example losses (normalized by the caller after
        accumulation, so microbatched grads sum exactly).

        The ``losses`` collection collects auxiliary objectives modules sow
        (e.g. the MoE load-balancing loss, models/moe.py); they are scalars
        scaled by the microbatch's valid count so the final
        divide-by-global-count yields their count-weighted mean.
        """
        variables = {"params": params}
        mutable = ["losses"]
        if batch_stats:
            variables["batch_stats"] = batch_stats
            mutable = ["batch_stats", "losses"]
        extra = (
            {"example_mask": batch["mask"]} if pass_example_mask else {}
        )
        output, mutated = model.apply(
            variables, batch[input_key], train=True,
            mutable=mutable, rngs={"dropout": dropout_rng}, **extra,
        )
        new_stats = mutated.get("batch_stats", batch_stats)
        per_ex = criterion(output, batch[target_key])
        if mixup_alpha > 0:
            lam = batch["_mix_lam"].astype(per_ex.dtype)
            per_ex = (
                lam * per_ex
                + (1.0 - lam) * criterion(output, batch["_mix_target"])
            )
        mask = batch["mask"].astype(per_ex.dtype)
        loss_sum = _masked_sum(per_ex, mask)
        aux = jax.tree.leaves(mutated.get("losses", {}))
        if aux:
            loss_sum = loss_sum + sum(jnp.sum(a) for a in aux) * mask.sum()
        return loss_sum, (output, new_stats, mask)

    grad_fn = jax.value_and_grad(sumloss_and_output, has_aux=True)

    def micro_metrics(output, target, mask):
        out = {}
        for fn in metric_fns:
            out[f"{fn.__name__}_sum"] = _masked_sum(fn(output, target), mask)
        return out

    def train_step(state, batch):
        dropout_rng = jax.random.fold_in(state.rng, state.step)
        if augment is not None:
            # 7919/7920 are outside the 0..k-1 microbatch fold-in range
            batch = dict(batch)
            batch[input_key] = augment(
                jax.random.fold_in(dropout_rng, 7919), batch[input_key]
            )
        if mixup_alpha > 0:
            mk = jax.random.fold_in(dropout_rng, 7920)
            lam = jax.random.beta(mk, mixup_alpha, mixup_alpha)
            x = batch[input_key]
            # partner = batch rolled by a random shift: pairs examples
            # uniformly across steps like a permutation, but on a
            # data-sharded batch it compiles to a cheap cyclic shard
            # exchange instead of the full cross-device gather a random
            # x[perm] would cost every step
            shift = jax.random.randint(
                jax.random.fold_in(mk, 1), (), 1, x.shape[0]
            )
            batch = dict(batch)
            batch["_mix_target"] = jnp.roll(  # before x overwrite
                batch[target_key], shift, axis=0
            )
            batch[input_key] = (
                lam.astype(x.dtype) * x
                + (1.0 - lam).astype(x.dtype) * jnp.roll(x, shift, axis=0)
            )
            # broadcast to [B] so the grad-accum microbatch split applies
            batch["_mix_lam"] = jnp.full((x.shape[0],), lam, jnp.float32)
        k = grad_accum_steps

        if k <= 1:
            (loss_sum, (output, new_stats, mask)), grads = grad_fn(
                state.params, state.batch_stats, batch, dropout_rng
            )
            count = mask.sum()
            metrics = {"loss_sum": loss_sum, "count": count}
            metrics.update(micro_metrics(output, batch[target_key], mask))
        else:
            # [B, ...] -> [k, B/k, ...]; B is static so this is shape-checked
            # at trace time.
            def split(x):
                b = x.shape[0]
                if b % k != 0:
                    raise ValueError(
                        f"batch size {b} not divisible by "
                        f"grad_accum_steps {k}"
                    )
                return x.reshape((k, b // k) + x.shape[1:])

            micro = jax.tree.map(split, batch)

            def body(carry, mb):
                stats, gsum, msum = carry
                rng = jax.random.fold_in(dropout_rng, mb["_idx"])
                mb = {kk: v for kk, v in mb.items() if kk != "_idx"}
                (loss_sum, (output, new_stats, mask)), grads = grad_fn(
                    state.params, stats, mb, rng
                )
                m = {"loss_sum": loss_sum, "count": mask.sum()}
                m.update(micro_metrics(output, mb[target_key], mask))
                gsum = jax.tree.map(jnp.add, gsum, grads)
                msum = jax.tree.map(jnp.add, msum, m)
                return (new_stats, gsum, msum), None

            micro["_idx"] = jnp.arange(k)
            zeros_g = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.promote_types(p.dtype,
                                                               jnp.float32)),
                state.params,
            )
            zeros_m = {"loss_sum": jnp.zeros((), jnp.float32),
                       "count": jnp.zeros((), jnp.float32)}
            for fn in metric_fns:
                zeros_m[f"{fn.__name__}_sum"] = jnp.zeros((), jnp.float32)
            (new_stats, grads, metrics), _ = jax.lax.scan(
                body, (state.batch_stats, zeros_g, zeros_m), micro
            )
            loss_sum, count = metrics["loss_sum"], metrics["count"]

        if inject_nan_grad_step is not None:
            poison = jnp.where(
                state.step == jnp.int32(inject_nan_grad_step),
                jnp.float32(jnp.nan), jnp.float32(0.0),
            )
            grads = jax.tree.map(
                lambda g: g + poison.astype(g.dtype), grads
            )

        # Normalize the summed gradients by the global valid count (matches
        # grad-of-mean on the full batch exactly).
        denom = jnp.maximum(count.astype(jnp.float32), 1.0)
        grads = jax.tree.map(
            lambda g: (g / denom).astype(g.dtype), grads
        )

        if trainable_patterns:
            # Mirror the optimizer's ``trainable`` freeze (optim.py
            # _trainable_only) on the gradients themselves: frozen leaves
            # still produce real grads (only LoRADense's base kernels are
            # stop_gradient-pruned in-graph — embeddings, norms, biases
            # are not), and counting those soon-to-be-discarded grads in
            # the global norm below would over-clip the surviving updates
            # and misreport grad_norm. The mask is static (Python bools at
            # trace time), so the zeroed branches fold away.
            import re as _re

            from ..parallel.sharding import path_str

            pats = [_re.compile(p) for p in trainable_patterns]

            def _freeze(path, g):
                if any(p.search(path_str(path)) for p in pats):
                    return g
                return jnp.zeros_like(g)

            grads = jax.tree_util.tree_map_with_path(_freeze, grads)

        # hold the PRE-CLIP gradients for the health summary, AFTER the
        # normalize/freeze transforms: clipping can smear one NaN over
        # every group (NaN global norm -> NaN scale), destroying the
        # per-module attribution the dump exists for, while capturing
        # after the freeze keeps the counted tree identical to the one
        # gnorm below is computed on — the lax.cond fast path in
        # pack_health_summary is only sound when they match (a NaN in a
        # frozen — training-inert — leaf is deliberately out of scope
        # for both)
        health_grads = grads if health else None

        if log_grad_norm or grad_clip_norm > 0 or health:
            # pre-clip global norm of the mean gradient
            gnorm = optax.global_norm(grads)
        if log_grad_norm:
            # count-weighted so finalize_metrics' divide-by-count yields
            # the epoch's mean per-step grad norm
            metrics["grad_norm_sum"] = gnorm * jnp.maximum(count, 1.0)
        if grad_clip_norm > 0:
            scale = jnp.minimum(1.0, grad_clip_norm / (gnorm + 1e-6))
            grads = jax.tree.map(lambda g: g * scale, grads)

        ok = jnp.array(True)
        if skip_nonfinite:
            ok = jnp.isfinite(loss_sum)
            for g in jax.tree.leaves(grads):
                ok = ok & jnp.all(jnp.isfinite(g))
            # zero the grads on a bad step so the (discarded) optimizer
            # update below is NaN-free even under jax_debug_nans
            grads = jax.tree.map(
                lambda g: jnp.where(ok, g, jnp.zeros_like(g)), grads
            )

        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        if state.lr_scale is not None:
            # host-driven LR multiplier (ReduceLROnPlateau): every registered
            # optimizer ends in scale_by_learning_rate, so scaling the final
            # update equals scaling the learning rate
            s = state.lr_scale.astype(jnp.float32)
            updates = jax.tree.map(lambda u: (u * s).astype(u.dtype), updates)
        if health:
            # post-LR-scale update magnitude: an optimizer blow-up shows
            # here even when the gradients themselves were finite
            health_update_norm = optax.global_norm(updates)
        new_params = optax.apply_updates(state.params, updates)
        if skip_nonfinite:
            # branchless select: a suppressed step leaves params/opt_state/
            # batch_stats bit-identical (no host round-trip, stays one XLA
            # program), and its contaminated sufficient statistics are
            # zeroed so epoch aggregates exclude the bad batch entirely
            sel = lambda n, o: jnp.where(ok, n, o)  # noqa: E731
            new_params = jax.tree.map(sel, new_params, state.params)
            new_opt_state = jax.tree.map(sel, new_opt_state, state.opt_state)
            new_stats = jax.tree.map(sel, new_stats, state.batch_stats)
            metrics = {
                kk: jnp.where(ok, v, jnp.zeros_like(v))
                for kk, v in metrics.items()
            }
            metrics["skipped_sum"] = (
                (1.0 - ok.astype(jnp.float32)) * jnp.maximum(count, 1.0)
            )
        new_ema = state.ema_params
        if ema_decay > 0 and new_ema is not None:
            d = jnp.float32(ema_decay)
            new_ema = jax.tree.map(
                lambda e, p: (e * d + p.astype(e.dtype) * (1 - d)),
                new_ema, new_params,
            )
            if skip_nonfinite:
                new_ema = jax.tree.map(
                    lambda n, o: jnp.where(ok, n, o),
                    new_ema, state.ema_params,
                )
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt_state,
            ema_params=new_ema,
        )
        if health:
            # nonfinite_params counts the post-select weights: what the
            # next step will actually train from (0 when the guard
            # suppressed the poisoned update, as designed). Packed as
            # ONE f32 vector, merged after the metrics zeroing above —
            # a suppressed step's health fields must survive to reach
            # the detector
            from ..observability.health import pack_health_summary

            metrics = {**metrics, "health": pack_health_summary(
                loss=loss_sum.astype(jnp.float32) / denom,
                grad_norm=gnorm,
                update_norm=health_update_norm,
                grads=health_grads,
                new_params=new_params,
            )}
        return new_state, metrics

    return train_step


def make_eval_step(model, criterion: Callable,
                   metric_fns: Sequence[Callable] = (),
                   input_key: str = "image", target_key: str = "label",
                   use_ema: bool = False, eval_rng: bool = False):
    """Build ``eval_step(state, batch) -> metrics`` (sufficient statistics).

    Equivalent to the reference's no-grad validation forward
    (trainer/trainer.py:94-113) + the rank-0 global metric computation
    (trainer/trainer.py:75-88), but reduced in-graph: no pickle gathers, no
    full prediction set on one host. ``use_ema`` evaluates the shadow EMA
    weights instead of the live params.

    ``eval_rng=True`` changes the signature to ``eval_step(state, batch,
    rng)`` and exposes the key as the ``"eval"`` rng stream — the
    ``test.py --seed`` path; models that consume eval-time randomness
    (BertMLM's seeded eval mask) pick it up via ``self.has_rng("eval")``
    and everything else ignores it.
    """

    pass_example_mask = _accepts_example_mask(model)

    def eval_step(state, batch, rng=None):
        params = (
            state.ema_params
            if use_ema and state.ema_params is not None
            else state.params
        )
        variables = {"params": params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        extra = (
            {"example_mask": batch["mask"]} if pass_example_mask else {}
        )
        if eval_rng:
            extra["rngs"] = {"eval": rng}
        output = model.apply(variables, batch[input_key], train=False,
                             **extra)
        per_ex = criterion(output, batch[target_key])
        mask = batch["mask"].astype(per_ex.dtype)
        metrics = {
            "loss_sum": _masked_sum(per_ex, mask),
            "count": mask.sum(),
        }
        for fn in metric_fns:
            metrics[f"{fn.__name__}_sum"] = _masked_sum(
                fn(output, batch[target_key]), mask
            )
        return metrics

    return eval_step


def finalize_metrics(sums: Dict[str, float]) -> Dict[str, float]:
    """Convert accumulated sufficient statistics to averages.

    ``count == 0`` (every batch skipped by the non-finite guard) yields
    NaN averages, not 0.0 — a 0.0 loss would be recorded as an unbeatable
    false best by a ``min``-mode monitor. ``skipped_sum`` is a raw example
    count, not an average (its examples are excluded from ``count``).
    """
    raw_count = float(sums.get("count", 1.0))
    count = raw_count or 1.0
    out = {}
    for k, v in sums.items():
        if k == "count":
            continue
        if k == "skipped_sum":
            out["skipped"] = float(v)
        elif k.endswith("_sum"):
            out[k[: -len("_sum")]] = (
                float(v) / count if raw_count > 0 else float("nan")
            )
        else:
            out[k] = float(v)
    return out


def instrument_step(jitted_fn, name: str, warmup=None):
    """Wrap a jitted step callable in telemetry spans that split the
    one-time compile from steady-state dispatch.

    The first invocation of a jitted function traces + XLA-compiles
    before executing — on big models that is minutes, and on the host
    timeline it is indistinguishable from a hang unless labeled. The
    wrapper records the first call as ``<name>/compile+execute`` and
    every later one as ``<name>/dispatch`` (dispatch spans measure jit
    dispatch + donation backpressure, not device runtime — device time
    belongs to ``jax.profiler``). A shape change mid-run recompiles
    inside a ``dispatch`` span; the recompilation still surfaces, as a
    ``compile_events`` entry on the next flight-recorder record
    (observability/telemetry).

    ``warmup``: an optional ``engine.warmup.StepWarmup``. At the first
    call the wrapper collects the background-compiled executable for
    ``name`` and dispatches THROUGH it from then on — so a warmed
    step's first invocation records ``<name>/dispatch`` (with
    ``warm=True``), never ``<name>/compile+execute``. A warmup that
    failed (or was never registered under ``name``) yields None and
    the wrapper falls back to the lazy jit path unchanged.

    AOT attributes (``lower``/``eval_shape``) pass through so cost
    analysis (``profiler.compiled_flops``) keeps working on the wrapped
    callable.
    """
    from ..observability.trace import span

    state = {"first": True, "fn": jitted_fn}

    @functools.wraps(jitted_fn)
    def wrapped(*args, **kwargs):
        if state["first"]:
            state["first"] = False
            compiled = (warmup.result(name)
                        if warmup is not None else None)
            if compiled is not None:
                try:
                    with span(f"{name}/dispatch", warm=True):
                        out = compiled(*args, **kwargs)
                    state["fn"] = compiled
                    return out
                except TypeError:
                    # aval/sharding mismatch between the warmup's
                    # abstract spec and the real inputs (raised BEFORE
                    # execution, so nothing was donated): the degrade-
                    # to-lazy contract must hold here too, not only for
                    # compile-time failures
                    import logging

                    logging.getLogger(__name__).warning(
                        "AOT-warmed %s rejected the real inputs; "
                        "falling back to lazy compile", name,
                        exc_info=True,
                    )
            with span(f"{name}/compile+execute"):
                return state["fn"](*args, **kwargs)
        with span(f"{name}/dispatch"):
            return state["fn"](*args, **kwargs)

    for attr in ("lower", "eval_shape", "trace"):
        if hasattr(jitted_fn, attr):
            setattr(wrapped, attr, getattr(jitted_fn, attr))
    return wrapped
