"""The jitted train/eval step builders — the framework's hot loop.

Replaces the reference's per-batch Python sequence (H2D copy, zero_grad,
forward, loss, ``dist.reduce``, backward, DDP allreduce, optimizer step —
/root/reference/trainer/trainer.py:45-58) with ONE compiled SPMD program:

- the batch arrives already sharded over the mesh's data axes;
- ``jnp`` reductions over the sharded batch dimension compile to ``psum``
  over ICI (the DDP gradient allreduce *and* the reference's per-step
  ``reduce_loss`` collective, fused into the step instead of blocking it —
  the reference syncs before backward, SURVEY.md §2.1 bug list);
- masked per-example losses/metrics make duplicate-padded batches exact;
- the optimizer update runs in-graph (optax), so there is no host round-trip
  between micro-batches.

Metrics are returned as sufficient statistics ``{name_sum, count}`` — the
TPU-idiomatic version of the reference's gather-everything-to-rank-0 eval
(SURVEY.md §3.5).
"""
from __future__ import annotations

from typing import Callable, Dict, Sequence

import jax
import jax.numpy as jnp
import optax


def _masked_sum(per_example, mask):
    return jnp.sum(per_example * mask)


def make_train_step(model, tx, criterion: Callable,
                    metric_fns: Sequence[Callable] = (),
                    input_key: str = "image", target_key: str = "label",
                    grad_clip_norm: float = 0.0):
    """Build ``train_step(state, batch) -> (state, metrics)``.

    ``metrics`` holds scalar sums + count; callers divide after accumulating
    across batches (exact masked averages).
    """

    def loss_and_output(params, batch_stats, batch, dropout_rng):
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
            output, mutated = model.apply(
                variables, batch[input_key], train=True,
                mutable=["batch_stats"], rngs={"dropout": dropout_rng},
            )
            new_stats = mutated["batch_stats"]
        else:
            output = model.apply(
                variables, batch[input_key], train=True,
                rngs={"dropout": dropout_rng},
            )
            new_stats = batch_stats
        per_ex = criterion(output, batch[target_key])
        mask = batch["mask"].astype(per_ex.dtype)
        count = jnp.maximum(mask.sum(), 1.0)
        loss = _masked_sum(per_ex, mask) / count
        return loss, (output, new_stats, mask, count)

    def train_step(state, batch):
        dropout_rng = jax.random.fold_in(state.rng, state.step)
        (loss, (output, new_stats, mask, count)), grads = jax.value_and_grad(
            loss_and_output, has_aux=True
        )(state.params, state.batch_stats, batch, dropout_rng)

        if grad_clip_norm > 0:
            gnorm = optax.global_norm(grads)
            scale = jnp.minimum(1.0, grad_clip_norm / (gnorm + 1e-6))
            grads = jax.tree.map(lambda g: g * scale, grads)

        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt_state,
        )
        metrics = {"loss_sum": loss * count, "count": count}
        for fn in metric_fns:
            metrics[f"{fn.__name__}_sum"] = _masked_sum(
                fn(output, batch[target_key]), mask
            )
        return new_state, metrics

    return train_step


def make_eval_step(model, criterion: Callable,
                   metric_fns: Sequence[Callable] = (),
                   input_key: str = "image", target_key: str = "label"):
    """Build ``eval_step(state, batch) -> metrics`` (sufficient statistics).

    Equivalent to the reference's no-grad validation forward
    (trainer/trainer.py:94-113) + the rank-0 global metric computation
    (trainer/trainer.py:75-88), but reduced in-graph: no pickle gathers, no
    full prediction set on one host.
    """

    def eval_step(state, batch):
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        output = model.apply(variables, batch[input_key], train=False)
        per_ex = criterion(output, batch[target_key])
        mask = batch["mask"].astype(per_ex.dtype)
        metrics = {
            "loss_sum": _masked_sum(per_ex, mask),
            "count": mask.sum(),
        }
        for fn in metric_fns:
            metrics[f"{fn.__name__}_sum"] = _masked_sum(
                fn(output, batch[target_key]), mask
            )
        return metrics

    return eval_step


def finalize_metrics(sums: Dict[str, float]) -> Dict[str, float]:
    """Convert accumulated sufficient statistics to averages."""
    count = float(sums.get("count", 1.0)) or 1.0
    out = {}
    for k, v in sums.items():
        if k == "count":
            continue
        if k.endswith("_sum"):
            out[k[: -len("_sum")]] = float(v) / count
        else:
            out[k] = float(v)
    return out
