"""Paged KV block pool + automatic prefix caching for the serving path.

Production LM traffic is dominated by requests sharing long system /
few-shot prefixes, and prefill is the compute-bound slice of serving
(~16 ms device time per 8x1024 prompt — BASELINE.md). vLLM's
PagedAttention (Kwon et al., SOSP 2023) and SGLang's RadixAttention
(Zheng et al., 2024) showed that block-granular KV management plus a
prefix index over token ids turns that shared work into an HBM copy
instead of a recompute. This module is the TPU-native version of that
idea for THIS framework's cache layout:

- **Block pool** (``PrefixCache``): one bounded device array per
  KV-cache leaf, shaped ``[pool_blocks, block_tokens, kv_heads,
  head_dim]`` — fixed-size token blocks allocated from a free list,
  ref-counted while an admission is reading them, LRU-evicted when the
  pool fills. Block id 0 is a reserved scratch block (never allocated)
  so padded/unused lanes of the fixed-shape kernels always have a legal
  destination.
- **Radix index** (``RadixIndex``): a trie over prompt token ids with
  one edge per FULL block (``block_tokens`` ids) mapping prefixes to
  block chains. Matching is block-granular — two prompts that diverge
  mid-block share nothing for that block (the vLLM hash-per-full-block
  contract); there are no partial-edge splits to manage.
- **Canonical rotation space**: the Llama-family cache stores K rotated
  at absolute cache-slot angles (models/llama._cached_attention), and
  the continuous engine admits a prompt wherever the era's global
  position counter happens to be — so the same prefix lands at
  different slots on different admits. Pool blocks therefore store K in
  CANONICAL space (prefix token ``j`` rotated at angle ``j``); RoPE
  rotations compose additively (``R(aθ)·R(bθ) = R((a+b)θ)``), so
  capture de-rotates by the row's start slot and extraction re-rotates
  by the target start slot — one constant-angle rotation per row,
  fused into the copy kernel. V (and non-rotary families) copy as-is.
  The round-trip is exact in real arithmetic and float-tolerance exact
  in practice — the same contract as the engine's mixed-length
  batching ("logits agree to float tolerance, not bitwise").
- **Suffix-only prefill**: an admission with ``c`` cached prefix tokens
  scatters the block chain into the row's cache slots and feeds only
  the suffix through the model. The fed window is snapped to the same
  power-of-two ladder as cold admissions (engine/continuous._bucket),
  so the compile-cache/warmup story is untouched. Inside the fed
  window the model RECOMPUTES any overlapped prefix positions exactly
  as the cold path would (its DUS write wins over the scattered copy),
  which keeps warm output equal to cold output.

- **Tiered spill hierarchy** (``SpillTier``, ISSUE 13): eviction
  DEMOTES instead of destroys — the LRU-evicted block's bytes move to
  a bounded host-RAM tier (and overflow optionally to a disk tier),
  sha256-checksummed at demote time. A radix miss that extends into a
  spilled chain PROMOTES it back: checksum-verified, landed as private
  pages through the same donating scatter as a page import, then
  adopted — a torn or corrupt spilled page fails verification and is
  recomputed cold, never served wrong. A full or faulted tier degrades
  to the classic destroy-on-evict, counted, with zero correctness
  impact; the whole hierarchy is chaos-tested via the ``slow_spill`` /
  ``corrupt_spill`` / ``tier_exhaust`` fault kinds (resilience/faults).

- **int8-KV pool layout** (ISSUE 15, ``kv_quant == "int8"``): pool
  K/V leaves store int8 pages with f32 scale leaves alongside
  (``[P, bt, KVH]``, one scale per token x kv-head — models/quant
  ``quantize_kv``). The paged path quantizes at the model's page
  write and dequantizes in the paged kernel's tile fetch
  (ops/flash.py dequant epilogue) — half the KV bytes cross HBM on
  decode, the binding constraint per BASELINE.md — and ship/spill/
  export move the quantized bytes (halving wire and tier traffic for
  free; the sha256 spill checksums cover the int8 bytes unchanged).
  Capture de-rotates in f32 then re-quantizes; the scatter fallback
  dequantizes on gather. Parity contract: quantized-vs-f32 agrees to
  the documented int8 tolerance, while warm-vs-cold stays
  token-identical ON THE PAGED PATH (hits replay the exact bytes the
  writer attended to).
- **Sliding-window ring layout** (ISSUE 15, ``window > 0``): per-row
  block tables become RINGS — logical block ``j`` lives in table slot
  ``j % nb_ring`` with ``nb_ring ≈ window/block_tokens + 1 + slack``
  — so decode reads O(window) pages regardless of sequence length.
  The +1 covers band/tile misalignment; the slack pages guarantee a
  multi-token prefill feed (bounded by ``ring_slack_tokens``) never
  clobbers in-band history before its own queries read it. Radix
  caching applies only to requests that never wrap
  (``prompt + budget <= nb_ring * block_tokens`` — the loud
  documented cap); a wrapping request runs fully private and adopts
  nothing. The scatter fallback still refuses ``window > 0`` (a
  rolling contiguous cache's eviction order is position-dependent).

Models declare their layout via ``kv_cache_spec()`` (models/llama.py,
models/transformer.py).
"""
from __future__ import annotations

import functools
import hashlib
import json
import logging
import os
import struct
import threading
import time

import numpy as np

logger = logging.getLogger(__name__)

#: reserved pool block: padded/unused kernel lanes read and write here
SCRATCH_BLOCK = 0

#: wire magic for serialized page payloads (disaggregated serving,
#: ISSUE 12): version bumps change the suffix, never the prefix, so a
#: receiver can refuse a foreign format with one 10-byte read
PAGE_MAGIC = b"PDTPAGES1\n"


def _path_str(path) -> str:
    """Flax cache pytree path -> stable string key ("layers_0/self_attn/
    cached_key") shared by the host pool dict and the traced kernels."""
    parts = []
    for p in path:
        parts.append(str(getattr(p, "key", p)))
    return "/".join(parts)


def _leaf_kind(path_s: str, leaf) -> str | None:
    """'key' / 'value' for poolable K/V cache leaves, 'scale' for the
    int8-KV layout's per-(token, head) scale leaves (ISSUE 15 — they
    pool alongside the pages they rescale), None for everything else
    (pos_index, slot_pos)."""
    name = path_s.rsplit("/", 1)[-1]
    if getattr(leaf, "ndim", 0) == 3 and name in (
            "cached_key_scale", "cached_value_scale"):
        return "scale"
    if getattr(leaf, "ndim", 0) != 4:
        return None
    if name == "cached_key":
        return "key"
    if name == "cached_value":
        return "value"
    return None


def rotate_rows(x, deltas, rope_base: float):
    """Rotate ``[B, T, H, D]`` K rows by a per-row CONSTANT RoPE angle
    ``deltas[b]`` (rotate-half convention, f32 math — the op-for-op
    broadcast form of models/llama.apply_rope). Because RoPE rotations
    compose additively, rotating canonical-space K by the row's start
    slot reproduces the cache's absolute-slot rotation; negative deltas
    invert (capture path)."""
    import jax.numpy as jnp

    from ..models.llama import rope_tables

    d = x.shape[-1]
    cos, sin = rope_tables(jnp.asarray(deltas, jnp.int32), d, rope_base)
    xf = x.astype(jnp.float32)
    rot = jnp.concatenate([-xf[..., d // 2:], xf[..., : d // 2]], axis=-1)
    out = xf * cos[:, None, None, :] + rot * sin[:, None, None, :]
    return out.astype(x.dtype)


def scatter_blocks(cache, pool, block_ids, pads, pos0, feed: int,
                   block: int, rotary: bool, rope_base: float,
                   kv_quant: str = ""):
    """Scatter pool block chains into a (fresh) per-row cache pytree.

    ``cache``: the group cache (leaves ``[k, total, H, D]``).
    ``pool``: ``{path_str: [P, block, H, D]}``.
    ``block_ids``: ``[k, nb]`` int32, ``-1`` = unused lane.
    ``pads``: ``[k]`` row start slots (= rotation delta for K).
    ``pos0``: scalar — the fed window start; unused lanes are
    redirected into ``[pos0, pos0 + feed)``, which the suffix prefill's
    own DUS writes overwrite at every layer before any read, so their
    garbage is dead by construction. Traced; shapes are static.

    ``kv_quant == "int8"`` (ISSUE 15): the pool holds int8 pages +
    ``*_scale`` leaves. V (and non-rotated K at delta 0) copies the
    int8 bytes and scales STRAIGHT across — exact; rotated K
    dequantizes on the gather, re-rotates in f32, and re-quantizes
    (the per-reuse rounding this layout's documented tolerance
    covers). The generic path below already lands 3-dim scale leaves
    (``dest`` indexes the token axis of any trailing shape).
    """
    import jax
    import jax.numpy as jnp

    k, nb = block_ids.shape
    tok = jnp.arange(nb * block)
    used = jnp.repeat(block_ids >= 0, block, axis=1)        # [k, nb*block]
    dest = jnp.where(used, pads[:, None] + tok[None, :],
                     pos0 + (tok % feed)[None, :])
    safe_ids = jnp.clip(block_ids, 0, None)                  # -1 -> scratch

    updates = {}
    if kv_quant and rotary:
        from ..models.quant import quantize_kv

        # K pages must re-rotate to the rows' absolute-slot angles:
        # dequant -> rotate -> requant, jointly producing the int8 page
        # AND its fresh scale leaf (the tree walk below consumes both)
        for ps in pool:
            if not ps.endswith("cached_key") or ps + "_scale" not in pool:
                continue
            sq = pool[ps][safe_ids]              # [k, nb, block, H, D]
            ss = pool[ps + "_scale"][safe_ids]   # [k, nb, block, H]
            deq = sq.astype(jnp.float32) * ss[..., None]
            deq = deq.reshape(k, nb * block, *sq.shape[3:])
            q2, s2 = quantize_kv(rotate_rows(deq, pads, rope_base))
            updates[ps] = q2
            updates[ps + "_scale"] = s2

    def put(path, leaf):
        ps = _path_str(path)
        if ps in updates:
            src = updates[ps]
        elif ps in pool:
            src = pool[ps][safe_ids]             # [k, nb, block, ...]
            src = src.reshape(k, nb * block, *src.shape[3:])
            if rotary and ps.endswith("cached_key"):
                src = rotate_rows(src, pads, rope_base)
        else:
            return leaf
        src = src.astype(leaf.dtype)
        return jax.vmap(lambda row, d, s: row.at[d].set(s))(leaf, dest,
                                                            src)

    return jax.tree_util.tree_map_with_path(put, cache)


@functools.lru_cache(maxsize=32)
def _capture_fn(model, k: int, nb: int, block: int, rotary: bool,
                rope_base: float, kv_quant: str = ""):
    """Compiled pool capture: gather ``nb`` blocks of each of ``k``
    cache rows (row ``slots[j]``, prompt starting at slot ``pads[j]``),
    de-rotate K to canonical space, and write them into the (donated)
    pool at ``block_ids``. Unused lanes (``-1``) read row 0 and write
    the scratch block. One async dispatch; never forces a sync.

    ``kv_quant == "int8"`` (ISSUE 15): cache rows are int8 + scale
    leaves — dequantize, de-rotate (K) in f32, re-quantize, and write
    page + scale leaf together. At delta 0 (batch-1 captures) the
    round-trip is exact (quantize_kv maps each row's max back to ±127,
    so requantizing a just-dequantized row reproduces its bytes)."""
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, donate_argnums=0)
    def capture(pool, cache, slots, pads, block_ids):
        from ..models.quant import quantize_kv

        tok = jnp.arange(nb * block)
        used = jnp.repeat(block_ids >= 0, block, axis=1)
        src_idx = jnp.where(used, pads[:, None] + tok[None, :], 0)
        ids = jnp.where(block_ids >= 0, block_ids, SCRATCH_BLOCK)
        flat = jax.tree_util.tree_flatten_with_path(dict(cache))[0]
        by_path = {_path_str(p): leaf for p, leaf in flat}
        out = {}

        def land(ps, content):
            pool_leaf = pool[ps]
            content = content.astype(pool_leaf.dtype).reshape(
                k, nb, block, *content.shape[2:])
            out[ps] = pool_leaf.at[ids.reshape(-1)].set(
                content.reshape(k * nb, block, *content.shape[3:]))

        for ps in sorted(pool):
            if kv_quant and ps.endswith("_scale"):
                continue                 # landed with its base leaf
            rows = by_path[ps][slots]                       # [k, T, ...]
            content = jax.vmap(lambda r, i: r[i])(rows, src_idx)
            if kv_quant and ps + "_scale" in pool:
                srows = by_path[ps + "_scale"][slots]       # [k, T, H]
                scont = jax.vmap(lambda r, i: r[i])(srows, src_idx)
                deq = content.astype(jnp.float32) * scont[..., None]
                if rotary and ps.endswith("cached_key"):
                    deq = rotate_rows(deq, -pads, rope_base)
                q2, s2 = quantize_kv(deq)
                land(ps, q2)
                land(ps + "_scale", s2)
                continue
            if rotary and ps.endswith("cached_key"):
                content = rotate_rows(content, -pads, rope_base)
            land(ps, content)
        return out

    return capture


@functools.lru_cache(maxsize=32)
def _warm_prefill_fn(model, total: int, feed: int, nb: int, block: int,
                     padded: bool):
    """Compiled batch-1 warm prefill: build a zero ``[1, total]`` cache
    in-graph, scatter the cached block chain at canonical slots 0..c-1
    (delta 0 — at batch 1 the prompt starts at slot 0, so pool space IS
    cache space and K needs no re-rotation), position the counter at
    ``pos0 = L - feed``, and run the trailing ``feed`` prompt tokens
    through the masked continuation path. Pad-capable models
    (``padded``) pass ``prefill=True`` with an all-zero ``pad_lens`` —
    that combination keeps the masked einsum path (the fresh-cache
    flash fast path requires ``pad_lens is None`` and would ignore the
    scattered history) while still taking the model-level
    last-position logits trim, so the ``[1, feed, V]`` head never
    materializes. Returns ``(last_logits, cache)`` — the same contract
    as engine/generate._prefill_fresh, so the normal decode step loop
    takes over unchanged. Full misses never come here (the caller
    routes c == 0 through the genuine flash prefill)."""
    import jax
    import jax.numpy as jnp

    from ..parallel.tp import constrain_kv_tree

    mesh = getattr(model, "mesh", None)

    @jax.jit
    def run(params, suffix, pool, block_ids, pos0):
        shapes = jax.eval_shape(
            lambda p: model.apply(
                {"params": p}, jnp.zeros((1, total), jnp.int32),
                train=False, decode=True, mutable=["cache"],
            ),
            params,
        )[1]["cache"]
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                             shapes)
        cache = constrain_kv_tree(cache, mesh)   # TP head sharding
        cache = scatter_blocks(
            dict(cache), pool, block_ids, jnp.zeros((1,), jnp.int32),
            pos0, feed, block, rotary=False, rope_base=0.0)
        cache["pos_index"] = pos0.astype(jnp.int32)
        extra = ({"prefill": True,
                  "pad_lens": jnp.zeros((1,), jnp.int32)}
                 if padded else {})
        logits, vs = model.apply(
            {"params": params, "cache": cache}, suffix,
            train=False, decode=True, mutable=["cache"], **extra,
        )
        return logits[:, -1], vs["cache"]

    return run


@functools.lru_cache(maxsize=32)
def _paged_prefill_fn(model, feed: int, nb: int):
    """Compiled batch-1 PAGED prefill (ISSUE 7): no cache build, no
    block scatter — the cache pytree IS the pool, the row's block
    table maps its positions to pages (shared radix pages for the
    cached prefix, freshly allocated private pages for the suffix),
    and only the ``feed``-token uncached suffix runs through the
    model, writing K/V straight into the private pages. Returns
    ``(last_logits, cache)`` like ``_warm_prefill_fn`` — the paged
    step loop takes over from there. The cache (= the pool) is
    DONATED, like every other paged executable: XLA aliases the page
    writes in place instead of copying every pool leaf per dispatch —
    the caller must ``sync_pool_from_cache`` the returned cache (the
    old leaves are dead)."""
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, donate_argnums=1)
    def run(params, cache, suffix, tables, rs):
        logits, vs = model.apply(
            {"params": params, "cache": cache}, suffix,
            train=False, decode=True, prefill=True, mutable=["cache"],
            pad_lens=jnp.zeros((1,), jnp.int32),
            block_tables=tables, row_starts=rs,
        )
        return logits[:, -1], vs["cache"]

    return run


@functools.lru_cache(maxsize=32)
def _paged_decode_fns(model, nb: int, temperature: float, top_k: int,
                      top_p: float):
    """Compiled batch-1 paged decode step per (model, sampling): one
    token feeds at its row-local position, its K/V appends into the
    row's private pool page through the block table, and attention
    reads the pool in place (the Pallas paged kernel on TPU). The
    cache is DONATED — without it XLA cannot alias the one-page
    append back to the input and every emitted token would copy the
    ENTIRE pool (orders of magnitude more HBM than the scatter arm
    this path replaces). The caller's step loop reassigns ``cache``
    each iteration and syncs the pool afterwards."""
    import jax

    from .generate import _sample_rows

    @functools.partial(jax.jit, donate_argnums=1)
    def step(params, cache, token, keys, tables, pos):
        logits, vs = model.apply(
            {"params": params, "cache": cache}, token[:, None],
            train=False, decode=True, mutable=["cache"],
            block_tables=tables, row_starts=pos,
        )
        nxt = _sample_rows(keys, logits[:, -1], temperature, top_k,
                           top_p)
        return nxt, vs["cache"]

    return step


@functools.lru_cache(maxsize=4)
def _import_scatter_fn():
    """Compiled page-import scatter: write ``n`` shipped blocks of
    content into the (donated) pool at ``ids``. One dispatch for every
    leaf; donation lets XLA alias the update in place instead of
    copying the whole pool per import. Under TP the donated input's
    head sharding carries through to the output — block ids stay
    replicated host metadata, exactly like every other pool write."""
    import jax

    @functools.partial(jax.jit, donate_argnums=0)
    def imp(pool, ids, content):
        return {ps: pool[ps].at[ids].set(
            content[ps].astype(pool[ps].dtype)) for ps in pool}

    return imp


def serialize_pages(payload: dict) -> bytes:
    """Page payload (``PrefixCache.export_pages``) -> self-contained
    bytes: magic + 4-byte header length + header JSON + concatenated
    raw leaf bytes (header order). The host-staged arm of page
    shipping — what crosses the wire between a prefill-role and a
    decode-role replica when they share no mesh (the CPU/CI arm)."""
    leaves = payload["leaves"]
    header = {
        "version": int(payload.get("version", 1)),
        "block_tokens": int(payload["block_tokens"]),
        "n_blocks": int(payload["n_blocks"]),
        "token_ids": [int(t) for t in payload["token_ids"]],
        "tp_geometry": dict(payload.get("tp_geometry") or {}),
        "leaves": [],
    }
    blobs = []
    nb = int(payload["n_blocks"])
    for ps in sorted(leaves):
        # trim export padding host-side (export gathers power-of-two
        # chains so device shapes never depend on the block count):
        # only real pages cross the wire
        arr = np.ascontiguousarray(np.asarray(leaves[ps])[:nb])
        header["leaves"].append({"path": ps,
                                 "shape": list(arr.shape),
                                 "dtype": str(arr.dtype)})
        blobs.append(arr.tobytes())
    hj = json.dumps(header).encode("utf-8")
    return PAGE_MAGIC + struct.pack(">I", len(hj)) + hj + b"".join(blobs)


def deserialize_pages(data: bytes) -> dict:
    """Inverse of :func:`serialize_pages`; raises ``ValueError`` on a
    foreign/torn payload (the receiving server maps it to HTTP 400)."""
    if not data.startswith(PAGE_MAGIC):
        raise ValueError("not a serialized page payload (bad magic)")
    off = len(PAGE_MAGIC)
    if len(data) < off + 4:
        raise ValueError("truncated page payload (no header length)")
    (hlen,) = struct.unpack(">I", data[off:off + 4])
    off += 4
    try:
        header = json.loads(data[off:off + hlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f"bad page payload header: {e}")
    off += hlen
    leaves = {}
    for spec in header.get("leaves", ()):
        shape = tuple(int(d) for d in spec["shape"])
        dtype = np.dtype(spec["dtype"])
        n = int(np.prod(shape)) * dtype.itemsize
        if off + n > len(data):
            raise ValueError("truncated page payload (leaf bytes)")
        leaves[spec["path"]] = np.frombuffer(
            data[off:off + n], dtype=dtype).reshape(shape)
        off += n
    return {
        "version": int(header.get("version", 1)),
        "block_tokens": int(header["block_tokens"]),
        "n_blocks": int(header["n_blocks"]),
        "token_ids": [int(t) for t in header["token_ids"]],
        "tp_geometry": dict(header.get("tp_geometry") or {}),
        "leaves": leaves,
    }


def ship_pages(src: "PrefixCache", dst: "PrefixCache", ids) -> dict:
    """Move the cached block chain for ``ids`` from one pool to
    another in-process — the device-to-device arm of page shipping.
    When both pools live on the SAME mesh (or both are single-chip on
    one process) the gathered pages stay device arrays end to end and
    the copy rides the interconnect (ICI on real hardware); pools on
    different meshes host-stage, byte-identical to the serialized
    cross-process arm. Returns the import receipt (see
    :meth:`PrefixCache.import_pages`)."""
    device = src.mesh is dst.mesh
    payload = src.export_pages(ids, device=device)
    if payload is None:
        return {"imported_blocks": 0, "cached_tokens": 0, "bytes": 0}
    return dst.import_pages(payload)


def page_origin_flags(nodes) -> dict:
    """Collapse the ``origin`` tags of the radix nodes a request
    consumed into path-fingerprint flags (ISSUE 18). Locally captured
    nodes ("capture") are the baseline warm case and add no flag; the
    pool EVENTS that put content here some other way — a zero-copy
    adoption, a tier promote, a peer pull, a shipped import — each
    set their flag so the serve-path fingerprint names them."""
    flags: dict = {}
    for n in nodes or ():
        o = n.get("origin")
        if o in ("adopt", "promote", "pull", "ship"):
            flags[o] = True
    return flags


class SpillTier:
    """Bounded demote-on-evict store under the device pool (ISSUE 13).

    One entry per evicted pool block, keyed by the FULL token prefix
    up to and including that block (the same key the radix would
    match), holding the block's raw leaf bytes + a sha256 recorded at
    demote time. Two levels: a host-RAM dict bounded at
    ``host_blocks`` entries, whose own LRU overflow demotes further to
    a disk directory (bounded at ``disk_blocks`` files) when one is
    configured, else drops (the classic destroy). EVERY read verifies
    the checksum before the bytes go anywhere near the device pool —
    a failed verification removes the entry and reads as a miss, so a
    corrupt or torn spilled page costs a cold recompute, never a
    wrong token.

    The tier is an optimization with a fault plan: ``tier_exhaust``
    makes :meth:`put` refuse for a window (destroy-on-evict fallback),
    ``corrupt_spill`` flips a byte of the most recent demote AFTER
    checksumming, ``slow_spill`` stalls tier operations — all owned by
    the caller (PrefixCache) via ``faults.on_tier_event``.

    Thread-safety: one internal lock; entries are immutable after put.
    """

    def __init__(self, host_blocks: int = 0, disk_dir=None,
                 disk_blocks: int = 0):
        import threading as _threading

        self.host_blocks = max(int(host_blocks), 0)
        self.disk_dir = str(disk_dir) if disk_dir else None
        self.disk_blocks = max(int(disk_blocks), 0) if self.disk_dir \
            else 0
        if self.disk_dir:
            os.makedirs(self.disk_dir, exist_ok=True)
        self._host: "dict" = {}       # key -> entry (insertion = LRU)
        self._disk: "dict" = {}       # key -> {"path", "sha", "nbytes"}
        self._seq = 0
        self._lock = _threading.Lock()
        #: tier_exhaust fault window: until this instant put() refuses
        self.full_until = 0.0

    @property
    def enabled(self) -> bool:
        return self.host_blocks > 0 or self.disk_blocks > 0

    @staticmethod
    def digest(leaves: dict) -> str:
        """sha256 over the concatenated leaf bytes in sorted-path
        order — the ONE checksum formula (demote and verify share it)."""
        h = hashlib.sha256()
        for ps in sorted(leaves):
            h.update(leaves[ps])
        return h.hexdigest()

    def occupancy(self) -> dict:
        with self._lock:
            host_bytes = sum(e["nbytes"] for e in self._host.values())
            disk_bytes = sum(e["nbytes"] for e in self._disk.values())
            return {"tier_host_blocks": len(self._host),
                    "tier_host_bytes": int(host_bytes),
                    "tier_disk_blocks": len(self._disk),
                    "tier_disk_bytes": int(disk_bytes)}

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._host or key in self._disk

    def put(self, key, leaves: dict, sha: str) -> str | None:
        """Store one demoted block's bytes. Returns the tier it landed
        in (``"host"``) or None (tier full/faulted — the caller counts
        a destroy-on-evict). Host overflow demotes the LRU host entry
        to disk (when configured) or drops it."""
        import time as _time

        if not self.enabled or _time.monotonic() < self.full_until:
            return None
        nbytes = sum(len(b) for b in leaves.values())
        with self._lock:
            self._host.pop(key, None)       # re-demote refreshes LRU
            self._disk.pop(key, None)
            self._host[key] = {"leaves": dict(leaves), "sha": sha,
                               "nbytes": int(nbytes)}
            while len(self._host) > self.host_blocks:
                old_key = next(iter(self._host))
                entry = self._host.pop(old_key)
                self._spill_to_disk_locked(old_key, entry)
        return "host"

    def _spill_to_disk_locked(self, key, entry) -> None:
        """Move one host entry to the disk tier (caller holds the
        lock); no disk tier (or a write failure) drops it — degrade,
        never raise into the eviction path."""
        if not self.disk_blocks:
            return
        self._seq += 1
        path = os.path.join(self.disk_dir,
                            f"{entry['sha'][:12]}-{self._seq}.kvblk")
        try:
            with open(path, "wb") as f:
                for ps in sorted(entry["leaves"]):
                    blob = entry["leaves"][ps]
                    f.write(struct.pack(">I", len(ps)))
                    f.write(ps.encode("utf-8"))
                    f.write(struct.pack(">Q", len(blob)))
                    f.write(blob)
        except OSError:
            return
        self._disk[key] = {"path": path, "sha": entry["sha"],
                           "nbytes": entry["nbytes"]}
        while len(self._disk) > self.disk_blocks:
            old = self._disk.pop(next(iter(self._disk)))
            try:
                os.unlink(old["path"])
            except OSError:
                pass

    @staticmethod
    def _read_disk(path) -> dict:
        leaves = {}
        with open(path, "rb") as f:
            while True:
                head = f.read(4)
                if not head:
                    break
                (n,) = struct.unpack(">I", head)
                ps = f.read(n).decode("utf-8")
                (m,) = struct.unpack(">Q", f.read(8))
                leaves[ps] = f.read(m)
        return leaves

    def get(self, key):
        """Checksum-verified read -> ``(leaves_bytes, "verified")`` or
        ``(None, "miss"|"corrupt")``. A corrupt entry is REMOVED (the
        caller recomputes cold and the tier never serves it again)."""
        with self._lock:
            entry = self._host.get(key)
            disk = None if entry is not None else self._disk.get(key)
        if entry is not None:
            leaves = entry["leaves"]
            sha = entry["sha"]
        elif disk is not None:
            try:
                leaves = self._read_disk(disk["path"])
            except Exception:  # noqa: BLE001 — a torn/bit-rotted file
                # can raise ANYTHING out of the length-prefixed parse
                # (UnicodeDecodeError from the path string, struct
                # errors, OSError...); every parse failure IS the
                # corruption the checksum contract covers — degrade to
                # "corrupt" (cold recompute), never raise into serving
                leaves = {}
            sha = disk["sha"]
        else:
            return None, "miss"
        if not leaves or self.digest(leaves) != sha:
            self.drop(key)
            return None, "corrupt"
        # touch for LRU (host entries only; move-to-end via re-insert)
        with self._lock:
            if key in self._host:
                self._host[key] = self._host.pop(key)
        return leaves, "verified"

    def drop(self, key) -> None:
        with self._lock:
            self._host.pop(key, None)
            disk = self._disk.pop(key, None)
        if disk is not None:
            try:
                os.unlink(disk["path"])
            except OSError:
                pass

    def corrupt_latest(self) -> bool:
        """The ``corrupt_spill`` fault's effect: flip one byte of the
        most recently demoted HOST entry (after its checksum was
        recorded, so the next read fails verification). Returns
        whether an entry was corrupted."""
        with self._lock:
            if not self._host:
                return False
            key = next(reversed(self._host))
            entry = self._host[key]
            ps = sorted(entry["leaves"])[0]
            blob = bytearray(entry["leaves"][ps])
            if not blob:
                return False
            blob[0] ^= 0xFF
            entry["leaves"][ps] = bytes(blob)
            return True


class PoolUnsupported(ValueError):
    """A KV layout the pool cannot serve (ISSUE 15 satellite): carries
    the machine-readable ``reason`` (``window`` / ``kv_quant`` /
    ``undersized`` / ``gpt2_layout``) that feeds the
    ``pool_fallback_total{reason=...}`` counters on /metrics — today
    the refusal string went to logs only and fleet-level fallback was
    invisible."""

    def __init__(self, reason: str, message: str):
        super().__init__(message)
        self.reason = reason


class RadixIndex:
    """Block-granular radix/trie over prompt token ids.

    One edge per full ``block_tokens``-id chunk; each node owns exactly
    one pool block. Matching walks whole blocks (divergence mid-block
    shares nothing for that block). Nodes carry a refcount — held while
    an admission's copy kernel may still read the block — and an LRU
    clock; eviction only ever takes an UNREFERENCED LEAF (children pin
    their ancestors by construction of the walk)."""

    def __init__(self, block_tokens: int):
        self.block = int(block_tokens)
        self.root = {"children": {}, "block": None, "parent": None,
                     "refs": 0, "last_use": 0}
        self._clock = 0
        self.nodes = 0

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _chunks(self, ids):
        ids = list(ids)
        n = len(ids) // self.block
        return [tuple(ids[i * self.block:(i + 1) * self.block])
                for i in range(n)]

    def match(self, ids):
        """Longest fully-blocked cached prefix of ``ids`` ->
        ``(nodes, block_ids)`` (refs NOT acquired — see ``acquire``)."""
        now = self._tick()
        node, nodes, blocks = self.root, [], []
        for chunk in self._chunks(ids):
            nxt = node["children"].get(chunk)
            if nxt is None:
                break
            nxt["last_use"] = now
            nodes.append(nxt)
            blocks.append(nxt["block"])
            node = nxt
        return nodes, blocks

    def acquire(self, nodes):
        for n in nodes:
            n["refs"] += 1

    def release(self, nodes):
        for n in nodes:
            n["refs"] -= 1
            assert n["refs"] >= 0, "radix refcount underflow"

    def insert(self, ids, alloc):
        """Create nodes for every full block of ``ids`` not yet present.
        ``alloc()`` returns a free block id or None (pool exhausted —
        insertion stops there; the present prefix stays useful).
        Returns ``(new_nodes, new_block_ids, start_block_index)``.

        The walked path (existing AND just-created nodes) is PINNED
        for the duration: ``alloc`` may LRU-evict, and evicting the
        very chain being extended would detach the node the next new
        child links under — an unreachable subtree whose blocks leak
        forever."""
        now = self._tick()
        node = self.root
        pinned = []
        new_nodes, new_blocks, start = [], [], None
        try:
            for i, chunk in enumerate(self._chunks(ids)):
                nxt = node["children"].get(chunk)
                if nxt is None:
                    bid = alloc()
                    if bid is None:
                        break
                    # origin feeds per-request path provenance (ISSUE
                    # 18): capture = the scatter arm's capture kernel
                    # wrote this page from a live cache row
                    nxt = {"children": {}, "block": bid, "parent": node,
                           "chunk": chunk, "refs": 0, "last_use": now,
                           "origin": "capture"}
                    node["children"][chunk] = nxt
                    self.nodes += 1
                    new_nodes.append(nxt)
                    new_blocks.append(bid)
                    if start is None:
                        start = i
                nxt["refs"] += 1
                pinned.append(nxt)
                nxt["last_use"] = now
                node = nxt
        finally:
            for n in pinned:
                n["refs"] -= 1
        return new_nodes, new_blocks, (0 if start is None else start)

    def evict_lru(self):
        """Detach the least-recently-used unreferenced LEAF node and
        return its block id (None when everything is pinned)."""
        evicted = self.evict_lru_path()
        return None if evicted is None else evicted[0]

    def evict_lru_path(self):
        """Like :meth:`evict_lru`, but returns ``(block_id,
        token_path)`` where ``token_path`` is the full id prefix up to
        and including the evicted block — the demote tier's key (the
        chunks up the parent chain reconstruct it; the walk is
        O(depth), paid only on eviction)."""
        best, best_key = None, None
        stack = [self.root]
        while stack:
            node = stack.pop()
            for child in node["children"].values():
                if not child["children"]:
                    if child["refs"] == 0 and (
                            best is None
                            or child["last_use"] < best_key):
                        best, best_key = child, child["last_use"]
                else:
                    stack.append(child)
        if best is None:
            return None
        chunks = []
        node = best
        while node is not None and node is not self.root:
            chunks.append(node["chunk"])
            node = node["parent"]
        path = tuple(i for chunk in reversed(chunks) for i in chunk)
        del best["parent"]["children"][best["chunk"]]
        best["parent"] = None
        self.nodes -= 1
        return best["block"], path


class PrefixCache:
    """The serving-path prefix cache: radix index + bounded device
    block pool + the compiled capture/extract kernels.

    Thread-safety: host bookkeeping (index/free list/stats) is guarded
    by a lock; device kernels are dispatched by the caller's scheduler
    thread, whose program order gives the read-before-overwrite
    guarantee the immediate ref release relies on.
    """

    def __init__(self, model, params, block_tokens: int = 32,
                 pool_blocks: int = 256, eviction: str = "lru",
                 paged: bool = True, host_spill_blocks: int = 0,
                 disk_spill_dir=None, disk_spill_blocks: int = 0,
                 ring_slack_tokens: int = 512):
        import jax
        import jax.numpy as jnp

        spec = getattr(model, "kv_cache_spec", None)
        if spec is None:
            raise PoolUnsupported(
                "gpt2_layout",
                f"{type(model).__name__} declares no kv_cache_spec(): "
                "prefix caching needs the decode-cache layout contract")
        spec = spec()
        if spec.get("kv_quant") not in ("", None, "int8"):
            raise PoolUnsupported(
                "kv_quant",
                f"unknown kv_quant {spec['kv_quant']!r} (the int8-KV "
                "pool layout is the only quantized layout)")
        if eviction != "lru":
            raise ValueError(f"unknown eviction policy {eviction!r} "
                             "(only 'lru')")
        if int(block_tokens) < 1 or int(pool_blocks) < 2:
            raise ValueError("need block_tokens >= 1 and pool_blocks "
                             ">= 2 (block 0 is reserved scratch)")
        self.model = model
        self.block = int(block_tokens)
        self.pool_blocks = int(pool_blocks)
        self.rotary = bool(spec.get("rotary"))
        self.rope_base = float(spec.get("rope_base") or 0.0)
        self.kv_quant = str(spec.get("kv_quant") or "")
        # sliding-window ring layout (ISSUE 15): the pool can serve
        # window models ONLY through the paged path (the scatter
        # fallback's contiguous rolling cache has position-dependent
        # eviction order — the original refusal, now scoped to that
        # arm alone). Ring geometry lives here; the model's paged
        # attention consumes it as `j % nb` table semantics.
        self.window = int(spec.get("window", 0) or 0)
        self.ring_slack_tokens = 0
        if self.window:
            if not (bool(paged) and spec.get("paged", False)):
                raise PoolUnsupported(
                    "window",
                    f"window={self.window} needs the paged pool layout "
                    "(the scatter fallback's rolling cache is "
                    "position-dependent)")
            if self.window % self.block or self.window < self.block:
                raise PoolUnsupported(
                    "window",
                    f"window={self.window} must be a positive multiple "
                    f"of block_tokens={self.block} for the ring layout")
            # slack: the largest single prefill FEED the ring tolerates
            # without a dispatch's writes clobbering its own queries'
            # band (power-of-two so bucketed feeds stay inside it)
            slack = 16
            while slack < min(int(ring_slack_tokens), self.window):
                slack *= 2
            self.ring_slack_tokens = slack
        # TP serving (ISSUE 10): pool pages shard on the KV-HEAD axis
        # over the model's serving mesh — each tensor shard owns its
        # KVH/tp slice of every page, while block ids / the radix index
        # stay replicated host metadata (a page id means the same thing
        # on every shard). kv_cache_spec's kv_heads must divide tp —
        # validated up front at load (parallel/tp.validate_tp_geometry)
        # and defensively here.
        from ..parallel.tp import tp_degree

        self.mesh = getattr(model, "mesh", None)
        self._tp = tp_degree(self.mesh)
        if self._tp > 1:
            kv_heads = int(spec.get("kv_heads", 0) or 0)
            if kv_heads and kv_heads % self._tp:
                raise ValueError(
                    f"kv_heads={kv_heads} not divisible by the serving "
                    f"mesh's tensor axis ({self._tp}): the pool cannot "
                    "shard on the head axis")
        # device pool: one [P, block, H, D] leaf per poolable cache leaf,
        # discovered from a [1, block] eval_shape trace (no device work)
        shapes = jax.eval_shape(
            lambda p: model.apply(
                {"params": p}, jnp.zeros((1, self.block), jnp.int32),
                train=False, decode=True, mutable=["cache"],
            ),
            params,
        )[1]["cache"]
        flat = jax.tree_util.tree_flatten_with_path(dict(shapes))[0]
        self.pool = {}
        for path, leaf in flat:
            ps = _path_str(path)
            if _leaf_kind(ps, leaf) is not None:
                self.pool[ps] = self._alloc_pool_leaf(
                    (self.pool_blocks,) + tuple(leaf.shape[1:]),
                    leaf.dtype)
        if not self.pool:
            raise ValueError(
                f"{type(model).__name__} exposes no poolable KV leaves")
        import inspect

        self._padded = "pad_lens" in inspect.signature(
            type(model).__call__).parameters
        self.index = RadixIndex(self.block)
        self._free = list(range(1, self.pool_blocks))  # 0 = scratch
        # block ids allocated to live requests but NOT (yet) owned by
        # the radix index — paged-mode private tail pages (prompt
        # suffixes being written + decode appends). Disjoint from the
        # index's blocks by construction; freed or adopted at request
        # completion.
        self._private: set = set()
        self._lock = threading.Lock()
        self.stats = {
            "prefix_lookups": 0, "prefix_hit_requests": 0,
            "prefix_hit_tokens": 0, "prefix_inserted_blocks": 0,
            "prefix_evictions": 0, "prefix_dropped_inserts": 0,
            # device bytes copied by WARM admits: the scatter fallback
            # pays one block-chain HBM copy per admit; the paged path
            # must keep this at 0 (the ISSUE 7 gate is observable, not
            # aspirational)
            "warm_admit_copy_bytes": 0,
            # zero-copy insertions: pages written in place by a request
            # and handed to the radix index without any device work
            "prefix_adopted_blocks": 0,
            # batch-1 arm counts: which path actually served each
            # request (serve.py derives an honest paged_decode_frac
            # from these on the plain scheduler — the pool being
            # paged-CAPABLE says nothing about what traffic got)
            "batch1_paged_requests": 0,
            "batch1_scatter_requests": 0,
            # page shipping (disaggregated serving, ISSUE 12): blocks
            # exported to / imported from another replica's pool, plus
            # the raw page bytes that crossed. Imports ALSO count into
            # warm_admit_copy_bytes — a shipped page is a genuine
            # device copy the decode replica paid (the paged admit that
            # later reads it stays a zero-copy pointer update), so on a
            # decode-role replica warm_admit_copy_bytes_total equals
            # exactly the page-transfer bytes (accounted like PR 10's
            # collectives: observable, gated in the serve_disagg rung).
            "pages_exported": 0,
            "pages_imported": 0,
            "page_ship_out_bytes": 0,
            "page_ship_in_bytes": 0,
            "page_ship_dropped": 0,
            # tiered spill hierarchy (ISSUE 13): demote-on-evict /
            # promote-on-hit traffic, checksum verdicts, and the
            # degradation counters (a full or faulted tier falls back
            # to destroy-on-evict; a demote that cannot read its block
            # — donation loss mid-flight — likewise)
            "tier_demoted_blocks": 0,
            "tier_demote_bytes": 0,
            "tier_promoted_blocks": 0,
            "tier_promote_bytes": 0,
            "tier_checksum_failures": 0,
            "tier_exhaust_drops": 0,
            "tier_demote_errors": 0,
            # pool-fallback observability (ISSUE 15 satellite): WHY a
            # request degraded to the scatter/no-pool arm, counted per
            # request — /metrics renders these as
            # pool_fallback_total{reason=...}
            "pool_fallback_window": 0,
            "pool_fallback_kv_quant": 0,
            "pool_fallback_undersized": 0,
            "pool_fallback_gpt2_layout": 0,
            "pool_fallback_dry_pool": 0,
        }
        # corrupt_page fault (ISSUE 18): block id marked for a
        # deferred constant-pattern overwrite; applied at the next
        # safe pool-donation point
        self._corrupt_block = None
        # path provenance (ISSUE 18): origin flags of the nodes the
        # most recent warm_prefill consumed (scatter arm only)
        self.last_warm_flags: dict = {}
        # demote-on-evict spill tier (ISSUE 13): None keeps the
        # classic destroy-on-evict byte-identical
        self.spill = None
        if int(host_spill_blocks) > 0 or (
                disk_spill_dir and int(disk_spill_blocks) > 0):
            self.spill = SpillTier(
                host_blocks=int(host_spill_blocks),
                disk_dir=disk_spill_dir,
                disk_blocks=int(disk_spill_blocks))
        self.nb_max = -(-int(model.max_len) // self.block)
        if self.window:
            # ring table width: the in-band pages + 1 (band/tile
            # misalignment) + slack pages so a bounded prefill feed
            # never recycles a slot its own queries still read
            nb_ring = (self.window // self.block + 1
                       + self.ring_slack_tokens // self.block)
            self.nb_max = min(self.nb_max, nb_ring)
        # bytes of ONE pool block across every leaf — the unit of the
        # copy-bytes accounting above (int8 layouts: the quantized
        # bytes + their scale leaves — ~0.53x the f32 page, which is
        # exactly the wire/tier/HBM saving the layout exists for)
        self.page_bytes = int(sum(
            int(np.prod(leaf.shape[1:])) * leaf.dtype.itemsize
            for leaf in self.pool.values()))
        # TRUE paged decode (ISSUE 7): the engines read pool pages in
        # place through per-row block tables — needs the model's paged
        # call path AND a pool that can hold at least one full-budget
        # request's chain; otherwise the scatter fallback serves.
        # fallback_reason is the STRUCTURAL reason requests will take
        # the scatter arm ("" = fully paged) — per-request fallbacks
        # count it into pool_fallback_* (ISSUE 15 satellite).
        self.paged = bool(paged) and bool(spec.get("paged", False))
        self.fallback_reason = ""
        if not spec.get("paged", False):
            self.fallback_reason = "gpt2_layout"
            if bool(paged):
                logger.warning(
                    "paged decode unavailable for %s (kv_cache_spec "
                    "paged=False): warm admits use the scatter "
                    "fallback", type(model).__name__)
        if self.paged and self.pool_blocks - 1 < self.nb_max:
            if self.window:
                # no scatter arm exists for a window model — refuse
                # loudly instead of degrading to a layout that cannot
                # serve
                raise PoolUnsupported(
                    "undersized",
                    f"prefix_cache.pool_blocks={self.pool_blocks} "
                    f"cannot hold one ring request ({self.nb_max} "
                    f"blocks for window={self.window} + slack at "
                    f"block_tokens={self.block})")
            logger.warning(
                "prefix_cache.pool_blocks=%d cannot hold one full-"
                "budget request (%d blocks for max_len=%d at "
                "block_tokens=%d): paged decode disabled, scatter "
                "fallback serves", self.pool_blocks, self.nb_max,
                int(model.max_len), self.block)
            self.paged = False
            self.fallback_reason = "undersized"

    def _alloc_pool_leaf(self, shape, dtype):
        """One zeroed pool leaf, COMMITTED to the serving mesh's head
        sharding when TP is on (so warmup and dispatch signatures
        match); plain uncommitted zeros at tp=1 — byte-identical to the
        pre-TP path."""
        import jax
        import jax.numpy as jnp

        if self._tp <= 1:
            return jnp.zeros(shape, dtype)
        from jax.sharding import NamedSharding

        from ..parallel.tp import kv_pool_pspec

        return jax.device_put(
            jnp.zeros(shape, dtype),
            NamedSharding(self.mesh, kv_pool_pspec(len(shape))))

    # ---- host bookkeeping -------------------------------------------------

    def used_blocks(self) -> int:
        return self.pool_blocks - 1 - len(self._free)

    def _alloc(self):
        """One free block id, evicting the LRU unreferenced leaf when
        the free list is empty; None when everything is pinned. With a
        spill tier attached the evicted block DEMOTES (its bytes +
        checksum move to the tier) instead of being destroyed — the
        read happens synchronously here, before the returned id can be
        overwritten by the caller's (later, async) capture/scatter."""
        if self._free:
            return self._free.pop()
        if self.spill is None:
            bid = self.index.evict_lru()
            if bid is None:
                self.stats["prefix_dropped_inserts"] += 1
                return None
            self.stats["prefix_evictions"] += 1
            return bid
        evicted = self.index.evict_lru_path()
        if evicted is None:
            self.stats["prefix_dropped_inserts"] += 1
            return None
        bid, path = evicted
        self.stats["prefix_evictions"] += 1
        self._demote_block(bid, path)
        return bid

    def _demote_block(self, bid: int, path) -> None:
        """Move one evicted block's content into the spill tier
        (caller holds the lock; ``path`` is the full token prefix up
        to and including the block — the tier key a later promotion
        matches). Every failure mode degrades to the classic
        destroy-on-evict, counted, never raised: the eviction path
        must stay infallible."""
        from ..resilience import faults

        fired = faults.on_tier_event()
        if fired is not None and fired.get("exhaust") is not None:
            self.spill.full_until = (
                time.monotonic() + fired["exhaust"].duration_s)
            logger.warning("fault tier_exhaust: spill tier reads full "
                           "for %.2fs", fired["exhaust"].duration_s)
        try:
            # one D2H gather per leaf: the demote cost (a host sync on
            # the eviction path — bounded at one block, and only under
            # pool pressure; the promote direction is async like every
            # other pool write)
            leaves = {ps: np.asarray(leaf[bid]).tobytes()
                      for ps, leaf in self.pool.items()}
        except Exception:  # noqa: BLE001 — donated/dead leaf mid-error
            self.stats["tier_demote_errors"] += 1
            return
        sha = SpillTier.digest(leaves)
        landed = self.spill.put(path, leaves, sha)
        if landed is None:
            self.stats["tier_exhaust_drops"] += 1
            return
        self.stats["tier_demoted_blocks"] += 1
        self.stats["tier_demote_bytes"] += sum(
            len(b) for b in leaves.values())
        if fired is not None and fired.get("corrupt") is not None:
            if self.spill.corrupt_latest():
                logger.warning("fault corrupt_spill: flipped a byte of "
                               "the just-demoted spill entry")

    def promote_spilled(self, ids) -> int:
        """Extend the device radix with spilled blocks for ``ids``
        (the promote half of the tier hierarchy): walk the spill tier
        past the deepest resident block, checksum-verify each entry,
        land the verified chain as private pages through the same
        donating scatter as a page import, then adopt — a request
        admitted mid-promotion either misses (cold, correct) or hits
        fully-written pages. Returns blocks promoted (0 = nothing
        spilled, tier disabled, or pool too dry to land them).

        DONATES the pool on a nonzero promotion — callers follow the
        import_pages contract (the continuous engine promotes at tick
        start, before ``refresh_cache_from_pool``; batch-1 paths
        promote inside ``lookup`` before they read ``self.pool``).
        A checksum failure counts ``tier_checksum_failures``, drops
        the entry, and stops the walk: everything past it recomputes
        cold — the tier never serves an unverified byte."""
        import jax.numpy as jnp

        from ..resilience import faults

        if self.spill is None:
            return 0
        ids = [int(t) for t in ids]
        nfull = len(ids) // self.block
        with self._lock:
            _, have = self.index.match(ids)
        start = len(have)
        if start >= nfull:
            return 0
        # probe the tier BEFORE paying a fault hook / allocation: the
        # common case (nothing spilled for this prompt) must stay a
        # dict lookup
        probe = tuple(ids[:(start + 1) * self.block])
        if probe not in self.spill:
            return 0
        # slow_spill covers promotes too; a corrupt_spill/tier_exhaust
        # landing on a promote ordinal applies all the same (the most
        # recent demote corrupts / the put window closes) — the evt
        # ordinal counts every tier operation, so a fired spec must
        # never be silently swallowed
        fired = faults.on_tier_event()
        if fired is not None:
            if fired.get("exhaust") is not None:
                self.spill.full_until = (
                    time.monotonic() + fired["exhaust"].duration_s)
            if fired.get("corrupt") is not None:
                self.spill.corrupt_latest()
        chain = []                  # [(block_index, {ps: np_array})]
        for i in range(start, nfull):
            key = tuple(ids[:(i + 1) * self.block])
            blob, verdict = self.spill.get(key)
            if blob is None:
                if verdict == "corrupt":
                    with self._lock:
                        self.stats["tier_checksum_failures"] += 1
                    logger.warning(
                        "spill tier checksum failure at block %d: "
                        "entry dropped, recomputing cold", i)
                break
            content = {}
            ok = True
            for ps, leaf in self.pool.items():
                raw = blob.get(ps)
                shape = tuple(leaf.shape[1:])
                n = int(np.prod(shape)) * leaf.dtype.itemsize
                if raw is None or len(raw) != n:
                    ok = False      # geometry changed under the tier
                    break
                content[ps] = np.frombuffer(
                    raw, dtype=leaf.dtype).reshape(shape)
            if not ok:
                self.spill.drop(key)
                break
            chain.append((i, content))
        if not chain:
            return 0
        priv = self.alloc_chain(len(chain))
        if priv is None:
            return 0                # dry pool: promotion waits its turn
        # one donating scatter, padded to the power-of-two ladder like
        # the import path (a varying chain length must not mint fresh
        # executables on the admission path)
        cap = 1
        while cap < len(chain):
            cap *= 2
        ids_pad = np.full((cap,), SCRATCH_BLOCK, np.int32)
        ids_pad[:len(chain)] = priv
        stacked = {}
        for ps, leaf in self.pool.items():
            rows = np.zeros((cap,) + tuple(leaf.shape[1:]), leaf.dtype)
            for j, (_, content) in enumerate(chain):
                rows[j] = content[ps]
            stacked[ps] = jnp.asarray(rows)
        self.pool = _import_scatter_fn()(
            self.pool, jnp.asarray(ids_pad), stacked)
        owned = {i: bid for (i, _), bid in zip(chain, priv)}
        adopted, _ = self.adopt(ids[:nfull * self.block], owned,
                                origin="promote")
        taken = set(adopted)
        self.free_blocks([b for b in priv if b not in taken])
        # entries whose block actually ADOPTED leave the tier (their
        # content is resident again; a re-eviction re-demotes fresh
        # bytes) — entries the adopt walk never reached (a concurrent
        # eviction broke the resident prefix under us) KEEP their
        # spilled bytes, or the chain would be lost from both tiers
        for i, _ in chain:
            if owned[i] in taken:
                self.spill.drop(tuple(ids[:(i + 1) * self.block]))
        n = len(adopted)
        with self._lock:
            self.stats["tier_promoted_blocks"] += n
            self.stats["tier_promote_bytes"] += n * self.page_bytes
        return n

    def lookup(self, ids, record: bool = True, promote: bool = True):
        """Longest cached, fully-blocked, PROPER prefix of ``ids`` ->
        ``(nodes, block_ids, cached_tokens)``; refs acquired (callers
        MUST ``release(nodes)`` once the copy kernel is dispatched).
        Proper: the prompt's final token is never served from cache —
        its logits must be computed to sample the first output token —
        so ``cached_tokens <= len(ids) - 1``.

        ``record=False`` skips the hit/lookup counters: retries of the
        SAME request (a deferred paged admission re-reserves every
        tick) and routing probes must not inflate
        ``prefix_hit_tokens`` — that counter feeds /metrics, the fleet
        router, and the bench gates.

        ``promote=True`` (the batch-1 default) first promotes any
        spilled extension of the match back into the pool — which may
        DONATE the pool, so callers whose device state aliases it pass
        ``promote=False`` and promote at their own safe point (the
        continuous engine's tick start)."""
        if promote and self.spill is not None:
            self.promote_spilled(ids)
        with self._lock:
            if record:
                self.stats["prefix_lookups"] += 1
            nodes, blocks = self.index.match(ids)
            limit = (len(ids) - 1) // self.block     # proper-prefix cap
            nodes, blocks = nodes[:limit], blocks[:limit]
            c = len(nodes) * self.block
            if c:
                if record:
                    self.stats["prefix_hit_requests"] += 1
                    self.stats["prefix_hit_tokens"] += c
                self.index.acquire(nodes)
            return nodes, blocks, c

    def count_fallback(self, reason: str = "") -> None:
        """Count one request that degraded off the paged pool path
        (ISSUE 15 satellite): ``reason`` defaults to the pool's
        structural ``fallback_reason`` (gpt2_layout / undersized);
        transient dry-pool falls pass ``"dry_pool"``. An empty reason
        (operator turned paged off deliberately) is not counted — a
        choice is not a degradation."""
        reason = reason or self.fallback_reason
        if not reason:
            return
        key = f"pool_fallback_{reason}"
        with self._lock:
            if key in self.stats:
                self.stats[key] += 1

    def count_batch1(self, paged: bool) -> None:
        """Tally which arm served one batch-1 request (paged in-place
        vs scatter fallback) — the plain scheduler's honest
        ``paged_decode_frac`` numerator/denominator."""
        key = ("batch1_paged_requests" if paged
               else "batch1_scatter_requests")
        with self._lock:
            self.stats[key] += 1

    def counter(self, name: str) -> int:
        """One stats counter, cheaply. The engines diff these around
        admissions/completions to attribute pool events (evictions,
        zero-copy adoptions) to the request that triggered them in the
        request-scoped trace (ISSUE 8) — a full ``stats_snapshot()``
        per admit would rebuild the whole dict for one integer."""
        with self._lock:
            return int(self.stats.get(name, 0))

    def release(self, nodes):
        with self._lock:
            self.index.release(nodes)

    def plan_insert(self, ids):
        """Allocate blocks + index nodes for the full blocks of ``ids``
        not yet cached. Returns ``(block_ids, start_block)`` for the
        capture kernel (empty when nothing is new)."""
        with self._lock:
            _, blocks, start = self.index.insert(ids, self._alloc)
            self.stats["prefix_inserted_blocks"] += len(blocks)
            return blocks, start

    # ---- paged-mode chains (ISSUE 7) --------------------------------------

    def alloc_chain(self, n: int):
        """Allocate ``n`` PRIVATE blocks for a request's uncached tail
        (prompt suffix + decode budget), LRU-evicting unreferenced
        radix leaves under pressure. All-or-nothing: on a dry pool the
        partial allocation rolls back and ``None`` returns — the caller
        defers the admission until completions free pages."""
        with self._lock:
            got = []
            for _ in range(int(n)):
                bid = self._alloc()
                if bid is None:
                    self._free.extend(got)
                    return None
                got.append(bid)
            self._private.update(got)
            return got

    def free_blocks(self, ids) -> None:
        """Return private blocks to the free list (request completed or
        admission rolled back)."""
        if not ids:
            return
        with self._lock:
            for bid in ids:
                self._private.discard(bid)
            self._free.extend(ids)

    def adopt(self, token_ids, owned: dict, acquire: bool = False,
              origin: str = "adopt"):
        """ZERO-COPY radix insert: hand privately-written pool pages to
        the index so other requests share them — no capture kernel, no
        device work; the K/V is already canonical in place (ISSUE 7:
        "decoded tokens append into pool blocks the radix index can
        immediately share").

        ``owned`` maps full-block INDEX of ``token_ids`` -> private
        block id holding that block's K/V. The walk creates missing
        nodes where we own the page and stops at a missing node we
        cannot supply; where a node already exists (a concurrent
        request adopted the same content first) the private duplicate
        stays private — the caller frees it after completion.

        ``origin`` tags the created nodes for per-request path
        provenance (ISSUE 18): ``adopt`` (a local request's zero-copy
        pages), ``ship`` (a disaggregated prefill→decode import),
        ``pull`` (a peer-pool pull), ``promote`` (a spill-tier
        promotion). A later admission consuming the page surfaces the
        tag in its serve-path fingerprint.

        Returns ``(adopted_ids, nodes)``: the block ids now owned by
        the index (no longer private) and, when ``acquire``, the
        CREATED nodes ref-pinned for the (still-reading) caller to
        release at completion (pre-existing duplicates need no pin —
        the caller keeps reading its own private copy)."""
        from ..resilience import faults

        bt = self.block
        nfull = len(token_ids) // bt
        with self._lock:
            node = self.index.root
            adopted, nodes = [], []
            now = self.index._tick()
            for i in range(nfull):
                chunk = tuple(token_ids[i * bt:(i + 1) * bt])
                nxt = node["children"].get(chunk)
                if nxt is None:
                    bid = owned.get(i)
                    if bid is None:
                        break
                    nxt = {"children": {}, "block": int(bid),
                           "parent": node, "chunk": chunk,
                           "refs": 0, "last_use": now,
                           "origin": str(origin)}
                    node["children"][chunk] = nxt
                    self.index.nodes += 1
                    self._private.discard(int(bid))
                    adopted.append(int(bid))
                    if acquire:
                        nxt["refs"] += 1
                        nodes.append(nxt)
                nxt["last_use"] = now
                node = nxt
            self.stats["prefix_adopted_blocks"] += len(adopted)
            if adopted:
                # corrupt_page fault (ISSUE 18): mark the first block
                # this adoption landed; the overwrite itself is
                # DEFERRED to the pool's next safe device point
                # (_apply_pending_corruption) — corrupting here would
                # donate the pool out from under a live engine cache
                # mid-tick
                spec = faults.on_page_adopt()
                if spec is not None:
                    self._corrupt_block = int(adopted[0])
            return adopted, nodes

    def record_copy_bytes(self, n_blocks: int) -> None:
        """Account one warm admit's device scatter copy (the fallback
        arm): ``n_blocks`` pool blocks crossed HBM into a contiguous
        per-slot cache."""
        if n_blocks:
            with self._lock:
                self.stats["warm_admit_copy_bytes"] += (
                    int(n_blocks) * self.page_bytes)

    # ---- page shipping (disaggregated serving, ISSUE 12) -----------------

    def cached_block_count(self, ids) -> int:
        """Full blocks of ``ids`` the pool currently holds (NO refs, no
        proper-prefix cap — export ships every full block, and the
        receiving side's own admission lookup re-applies the cap)."""
        with self._lock:
            _, blocks = self.index.match(list(ids))
            return len(blocks)

    def export_pages(self, ids, device: bool = False):
        """Gather the cached full-block chain for ``ids`` out of the
        pool -> a ship payload (``None`` when not even one full block
        is pooled). ``device=True`` keeps the gathered pages as device
        arrays (the same-mesh ICI arm — :func:`ship_pages`); the
        default stages them to host numpy (the serialized arm).

        Refs are held across the gather so a concurrent insert cannot
        evict a block mid-export; the payload's ``token_ids`` cover
        exactly the exported blocks, so import adopts them under the
        same radix keys. ``tp_geometry`` records the exporter's shard
        layout for the receipt — page CONTENT is the logical
        ``[block, H, D]`` tensor either way (block ids and the radix
        are replicated host metadata under TP, PR 10), so a tp=2
        export imports into a tp=1 pool and vice versa."""
        import jax.numpy as jnp

        ids = list(ids)
        with self._lock:
            nodes, blocks = self.index.match(ids)
            if not blocks:
                return None
            self.index.acquire(nodes)
        try:
            nb = len(blocks)
            # pad the gather to the power-of-two ladder: chain lengths
            # are traffic-dependent, and an unpadded gather mints a
            # fresh executable per distinct count — a mid-traffic XLA
            # compile on the handoff path (the same stall class every
            # fixed-shape dispatch in this stack exists to kill).
            # Extra lanes read the scratch block and are sliced away.
            cap = 1
            while cap < nb:
                cap *= 2
            padded = np.full((cap,), SCRATCH_BLOCK, np.int32)
            padded[:nb] = blocks
            idx = jnp.asarray(padded)
            leaves = {}
            for ps, leaf in self.pool.items():
                # leaves stay PADDED [cap, block, H, D] — device
                # shapes must never depend on nb. serialize_pages
                # trims host-side; import_pages clamps to n_blocks.
                arr = leaf[idx]
                leaves[ps] = arr if device else np.asarray(arr)
        finally:
            self.release(nodes)
        with self._lock:
            self.stats["pages_exported"] += nb
            self.stats["page_ship_out_bytes"] += nb * self.page_bytes
        return {
            "version": 1,
            "block_tokens": self.block,
            "n_blocks": nb,
            "token_ids": ids[:nb * self.block],
            "tp_geometry": {"tp": self._tp},
            "leaves": leaves,
        }

    def import_pages(self, payload: dict, origin: str = "ship") -> dict:
        """Adopt a shipped page chain into THIS pool — the receiving
        half of the prefill→decode handoff. ``origin`` tags the
        adopted radix nodes for path provenance (ISSUE 18): "ship"
        for the disagg prefill→decode handoff, "pull" when the fleet
        poller dragged the chain here via peer pull. Blocks the pool
        already holds are skipped (a re-ship of a hot prefix costs
        nothing);
        the rest land as PRIVATE pages first (private pages are never
        evictable, so an in-flight import cannot lose a page to
        pressure), get their content written by one donating scatter
        dispatch, and only then adopt into the radix index — a request
        admitted mid-import either misses (cold prefill, correct) or
        hits fully-written pages, never a torn one.

        Returns ``{"imported_blocks", "cached_tokens", "bytes",
        "dropped"?}``; a pool that cannot supply the chain right now
        drops the import (the decode replica simply cold-prefills —
        shipping is an optimization, never a correctness dependency).
        Raises ``ValueError`` on a payload whose geometry cannot land
        here (block size / leaf shape mismatch)."""
        import jax.numpy as jnp

        if int(payload.get("block_tokens", 0)) != self.block:
            raise ValueError(
                f"page import: block_tokens "
                f"{payload.get('block_tokens')} != pool's {self.block}")
        leaves_in = payload.get("leaves") or {}
        for ps, leaf in self.pool.items():
            src = leaves_in.get(ps)
            if src is None:
                raise ValueError(f"page import: payload missing leaf "
                                 f"{ps!r}")
            if tuple(src.shape[1:]) != tuple(leaf.shape[1:]):
                raise ValueError(
                    f"page import: leaf {ps!r} shape "
                    f"{tuple(src.shape[1:])} != pool's "
                    f"{tuple(leaf.shape[1:])}")
        ids = [int(t) for t in payload["token_ids"]]
        nb = min(int(payload["n_blocks"]),
                 *(int(a.shape[0]) for a in leaves_in.values()))
        nb = min(nb, len(ids) // self.block)
        if nb <= 0:
            return {"imported_blocks": 0, "cached_tokens": 0,
                    "bytes": 0}
        with self._lock:
            _, have = self.index.match(ids)
            have_n = min(len(have), nb)
        need = list(range(have_n, nb))
        if not need:
            return {"imported_blocks": 0,
                    "cached_tokens": nb * self.block, "bytes": 0}
        priv = self.alloc_chain(len(need))
        if priv is None:
            with self._lock:
                self.stats["page_ship_dropped"] += 1
            return {"imported_blocks": 0, "cached_tokens": 0,
                    "bytes": 0, "dropped": True}
        # pad the scatter to the power-of-two ladder (mirror of the
        # export gather): extra lanes write the scratch block, so a
        # varying chain length never mints a fresh executable on the
        # handoff path
        cap = 1
        while cap < len(need):
            cap *= 2
        sel = np.zeros((cap,), np.int64)
        sel[:len(need)] = need
        ids_pad = np.full((cap,), SCRATCH_BLOCK, np.int32)
        ids_pad[:len(need)] = priv
        content = {}
        for ps in self.pool:
            arr = leaves_in[ps][sel]
            content[ps] = (arr if hasattr(arr, "devices")
                           else jnp.asarray(arr))
        self.pool = _import_scatter_fn()(
            self.pool, jnp.asarray(ids_pad), content)
        owned = {have_n + i: bid for i, bid in enumerate(priv)}
        adopted, _ = self.adopt(ids[:nb * self.block], owned,
                                origin=origin)
        taken = set(adopted)
        self.free_blocks([b for b in priv if b not in taken])
        n = len(adopted)
        nbytes = n * self.page_bytes
        with self._lock:
            self.stats["pages_imported"] += n
            self.stats["page_ship_in_bytes"] += nbytes
            # the transfer IS the decode replica's only genuine warm-
            # admit copy: the paged admit that reads these pages stays
            # a pointer update, so this counter's value on a decode
            # replica is exactly the bytes shipped in (rung-gated)
            self.stats["warm_admit_copy_bytes"] += nbytes
        return {"imported_blocks": n,
                "cached_tokens": (have_n + n) * self.block,
                "bytes": nbytes}

    def _apply_pending_corruption(self) -> None:
        """Apply a deferred ``corrupt_page`` fault (ISSUE 18):
        overwrite the marked pool block with a constant pattern
        through the donating import scatter. Called from the pool-
        reading entry points (``refresh_cache_from_pool``,
        ``paged_prefill``, ``warm_prefill``) — places where a pool
        donation is already part of the caller's contract, so the
        corruption can never strand a live cache mid-dispatch."""
        import jax.numpy as jnp

        with self._lock:
            bid, self._corrupt_block = self._corrupt_block, None
        if bid is None:
            return
        content = {
            ps: jnp.ones((1,) + tuple(leaf.shape[1:]), leaf.dtype)
            for ps, leaf in self.pool.items()}
        self.pool = _import_scatter_fn()(
            self.pool, jnp.asarray(np.asarray([bid], np.int32)),
            content)
        logger.warning("fault corrupt_page: overwrote pool block %d "
                       "with a constant pattern", bid)

    def sync_pool_from_cache(self, cache) -> None:
        """Point ``self.pool`` at the pool leaves inside a paged cache
        pytree (the engines donate the pool through their executables —
        after each reassignment the old arrays are dead and this keeps
        the canonical pool reference current). Host-only."""
        import jax

        flat = jax.tree_util.tree_flatten_with_path(dict(cache))[0]
        by_path = {_path_str(p): leaf for p, leaf in flat}
        self.pool = {ps: by_path[ps] for ps in self.pool}

    def pool_alive(self, cache=None) -> bool:
        """True when every pool leaf (of ``cache`` if given, else the
        canonical pool) is still a live device buffer. Every paged
        executable DONATES the pool — a dispatch that fails AFTER
        donation leaves dead leaves behind, and syncing or re-wrapping
        those would wedge the pool permanently."""
        import jax

        if cache is None:
            leaves = list(self.pool.values())
        else:
            flat = jax.tree_util.tree_flatten_with_path(dict(cache))[0]
            leaves = [leaf for p, leaf in flat
                      if _path_str(p) in self.pool]
        return not any(getattr(leaf, "is_deleted", lambda: False)()
                       for leaf in leaves)

    def reset_pool(self) -> None:
        """Last-resort recovery after donation loss: reallocate zeroed
        pool leaves and drop the ENTIRE radix index + private set (the
        cached content died with the donated buffers — adopting or
        matching against zeroed pages would serve garbage). Cumulative
        counters survive; ``prefix_pool_resets`` records the event.
        Callers must drop any cache pytree that aliased the old
        pool."""
        with self._lock:
            self.pool = {
                ps: self._alloc_pool_leaf(leaf.shape, leaf.dtype)
                for ps, leaf in self.pool.items()}
            self.index = RadixIndex(self.block)
            self._free = list(range(1, self.pool_blocks))
            self._private = set()
            self._corrupt_block = None
            self.stats["prefix_pool_resets"] = (
                self.stats.get("prefix_pool_resets", 0) + 1)
        logger.warning(
            "prefix pool reset after donation loss: cached content "
            "dropped, pool reallocated")

    def refresh_cache_from_pool(self, cache):
        """Re-adopt the canonical pool leaves into an engine's paged
        cache pytree. A batch-1 request running between scheduler
        ticks under the shared lock (serve.py routes speculative
        requests that way) can reassign ``self.pool`` — its scatter
        insert ends in the capture kernel, which DONATES the pool
        leaves the engine's persistent cache aliases. Without this
        swap the engine's next dispatch throws "buffer has been
        deleted or donated"; with a non-donating capture it would be
        worse — a silently stale pool missing the request's freshly
        inserted radix blocks. Host-only pointer surgery; returns
        ``cache`` unchanged when already current."""
        import jax

        self._apply_pending_corruption()
        flat = jax.tree_util.tree_flatten_with_path(dict(cache))[0]
        by_path = {_path_str(p): leaf for p, leaf in flat}
        if all(by_path.get(ps) is leaf
               for ps, leaf in self.pool.items()):
            return cache
        out = dict(cache)
        for ps, leaf in self.pool.items():
            parts = ps.split("/")
            node = out
            for part in parts[:-1]:
                node[part] = dict(node[part])
                node = node[part]
            node[parts[-1]] = leaf
        return out

    def paged_cache(self, extra=None) -> dict:
        """A decode-cache pytree whose K/V leaves ARE the pool pages —
        what the paged engines hand to ``model.apply`` alongside a
        block table. Non-K/V cache entries (``pos_index``) ride in
        ``extra``."""
        import jax.numpy as jnp

        out = {}
        for ps, leaf in self.pool.items():
            node = out
            parts = ps.split("/")
            for part in parts[:-1]:
                node = node.setdefault(part, {})
            node[parts[-1]] = leaf
        out["pos_index"] = jnp.zeros((), jnp.int32)
        if extra:
            out.update(extra)
        return out

    def stats_snapshot(self) -> dict:
        with self._lock:
            out = dict(self.stats)
            resident = self.index.nodes
            private = len(self._private)
            referenced = private + self._count_referenced()
        out["prefix_pool_blocks"] = self.pool_blocks - 1
        out["prefix_pool_blocks_used"] = self.used_blocks()
        # occupancy WITHOUT double counting (ISSUE 7 satellite):
        # resident = unique shareable pages owned by the radix index;
        # referenced = pages live requests are actually reading/writing
        # (held shared refs + private tails). On the scatter fallback a
        # hot prefix is resident here AND copied into per-slot caches —
        # the split makes that visible instead of folding both into one
        # "used" number.
        out["prefix_pool_blocks_resident"] = resident
        out["prefix_pool_blocks_referenced"] = referenced
        out["prefix_paged"] = bool(self.paged)
        # spill-tier occupancy gauges (ISSUE 13) ride the same split:
        # spilled pages are neither resident nor referenced — they are
        # the tier below, one promotion away from resident
        out["tier_enabled"] = self.spill is not None
        if self.spill is not None:
            out.update(self.spill.occupancy())
        else:
            out.update({"tier_host_blocks": 0, "tier_host_bytes": 0,
                        "tier_disk_blocks": 0, "tier_disk_bytes": 0})
        lk = out["prefix_lookups"]
        out["prefix_hit_rate"] = round(
            out["prefix_hit_requests"] / lk, 4) if lk else 0.0
        # long-context layouts (ISSUE 15): the pool's geometry — page
        # bytes make the int8 HBM saving observable (the serve_longctx
        # high-water gate), window/ring expose the sliding layout
        out["pool_fallback_total"] = sum(
            v for k2, v in out.items()
            if k2.startswith("pool_fallback_"))
        out["prefix_page_bytes"] = int(self.page_bytes)
        out["prefix_pool_window"] = int(self.window)
        out["prefix_pool_kv_quant"] = 1 if self.kv_quant else 0
        return out

    def _count_referenced(self) -> int:
        """Radix blocks currently ref-pinned by live requests (callers
        hold the lock)."""
        n, stack = 0, [self.index.root]
        while stack:
            node = stack.pop()
            for child in node["children"].values():
                if child["refs"] > 0:
                    n += 1
                stack.append(child)
        return n

    # ---- device paths -----------------------------------------------------

    def capture(self, cache, slots, pads, per_row_block_ids):
        """Fill pool blocks from admitted rows of ``cache`` (one async
        dispatch; the pool leaves are donated through). ``slots`` /
        ``pads``: per-row cache row + prompt start slot;
        ``per_row_block_ids``: ``[k][nb]`` lists, ``-1`` padded."""
        import jax.numpy as jnp

        k = len(slots)
        nb = max((len(b) for b in per_row_block_ids), default=0)
        if nb == 0:
            return
        ids = np.full((k, nb), -1, np.int32)
        for j, row in enumerate(per_row_block_ids):
            ids[j, :len(row)] = row
        self.pool = _capture_fn(
            self.model, k, nb, self.block, self.rotary, self.rope_base,
            self.kv_quant,
        )(self.pool, cache, jnp.asarray(np.asarray(slots, np.int32)),
          jnp.asarray(np.asarray(pads, np.int32)), jnp.asarray(ids))

    def paged_plan(self, ids, budget: int, record: bool = True,
                   promote: bool = True):
        """Page reservation for one request: shared-prefix lookup
        (refs held for the request's lifetime — decode reads those
        pages in place) plus a private chain for the suffix and the
        full budget, allocated up front so a mid-decode row can never
        block on the pool. ``None`` when the pool cannot supply the
        chain right now (batch-1 falls back to the scatter arm; the
        continuous engine defers the admission and retries with
        ``record=False``). ONE owner of the reservation math — the
        continuous engine's ``_reserve_pages`` wraps this.

        Ring layout (``window > 0``, ISSUE 15): a request whose
        ``prompt + budget`` exceeds the ring span WRAPS — its table
        slots recycle, so shared radix pages must not sit in it (they
        would be overwritten under other readers) and nothing it
        writes is adoptable. Such requests run fully private on
        exactly ``nb_max`` pages (the documented "radix caches up to
        ~window deep" cap); non-wrapping requests share and adopt
        exactly like the flat layout."""
        ring_wrap = False
        nfull_total = -(-(len(ids) + int(budget)) // self.block)
        if self.window and nfull_total > self.nb_max:
            ring_wrap = True
            if record:
                with self._lock:
                    self.stats["prefix_lookups"] += 1
            nodes, blocks, c = [], [], 0
            n_need = self.nb_max
        else:
            nodes, blocks, c = self.lookup(ids, record=record,
                                           promote=promote)
            n_need = nfull_total - c // self.block
        priv = self.alloc_chain(n_need)
        if priv is None:
            self.release(nodes)
            return None
        return {
            "ids": list(ids), "c": c, "nodes": nodes, "blocks": blocks,
            "private": {c // self.block + i: bid
                        for i, bid in enumerate(priv)},
            "ring_wrap": ring_wrap,
            # extra shared nodes acquired AFTER reservation (the
            # continuous engine's group-admit dedup) — released by
            # ``paged_finish`` with the plan's own refs
            "adopt_nodes": [],
        }

    def paged_prefill(self, params, ids, budget: int):
        """Batch-1 TRUE paged prefill: the cached prefix is a block-
        table POINTER entry (zero device copy — contrast
        ``warm_prefill``'s scatter), the suffix prefills straight into
        private pages. Returns ``(last_logits, cache, tables, plan)``
        or ``None`` when the pool is dry (caller falls back). The
        caller drives the step loop with ``_paged_decode_fns`` and
        MUST call ``paged_finish(plan, out_ids, emitted)`` when done."""
        import jax.numpy as jnp

        self._apply_pending_corruption()
        plan = self.paged_plan(ids, budget)
        if plan is None:
            return None
        c = plan["c"]
        L = len(ids)
        row = np.full((1, self.nb_max), -1, np.int32)
        for i, b in enumerate(plan["blocks"]):
            row[0, i] = b
        for idx, bid in plan["private"].items():
            row[0, idx] = bid
        tables = jnp.asarray(row)
        done = c
        try:
            # ring layout (ISSUE 15): a single dispatch's feed is
            # bounded by the slack contract (a wider feed could recycle
            # a slot its own queries' band still reads), so a long
            # uncached suffix streams in fixed ``ring_slack_tokens``
            # chunks — every chunk reuses ONE executable shape, and
            # each chunk's writes land before the next chunk reads them
            while self.window and L - done > self.ring_slack_tokens:
                f = self.ring_slack_tokens
                suffix = jnp.asarray(
                    np.asarray(ids[done:done + f], np.int32)[None, :])
                _, cache = _paged_prefill_fn(
                    self.model, f, self.nb_max)(
                    params, self.paged_cache(), suffix, tables,
                    jnp.asarray([done], jnp.int32))
                self.sync_pool_from_cache(cache)
                done += f
            feed = L - done
            suffix = jnp.asarray(
                np.asarray(ids[done:], np.int32)[None, :])
            last_logits, cache = _paged_prefill_fn(
                self.model, feed, self.nb_max)(
                params, self.paged_cache(), suffix, tables,
                jnp.asarray([done], jnp.int32))
        except Exception:
            # the prefill DONATES the pool — a dispatch that fails
            # after donation leaves dead leaves behind, and every
            # later request (paged or scatter) would dispatch against
            # them. Mirror the caller's step-loop handler: normal
            # cleanup while the pool is alive, full reset when the
            # donation was lost (the plan's refs and pages die with
            # the index — releasing against the fresh one would
            # double-free).
            if self.pool_alive():
                self.release(plan["nodes"])
                self.free_blocks(list(plan["private"].values()))
            else:
                self.reset_pool()
            raise
        self.sync_pool_from_cache(cache)
        return last_logits, cache, tables, plan

    def paged_finish(self, plan, out_ids, emitted: int,
                     written=None) -> None:
        """End-of-request paged bookkeeping: zero-copy ADOPT the full
        (prompt + decoded) blocks into the radix index, free the
        unadoptable tail, release the shared-prefix refs.

        ``written`` overrides the default written-token count (prompt
        + fed decode tokens) — the chunked-streaming-prefill path
        finishes a cancelled request mid-prompt, where only the
        streamed chunks ever landed. A ``ring_wrap`` plan adopts
        NOTHING: its recycled slots clobbered the early blocks, so no
        prefix key describes the surviving content."""
        ids = plan["ids"]
        seq = list(ids) + [int(t) for t in out_ids]
        if written is None:
            # positions actually written: the prompt plus every fed
            # decode token (the final sampled token is never fed back)
            written = len(ids) + max(int(emitted) - 1, 0)
        if plan.get("ring_wrap"):
            adopted = []
        else:
            adopted, _ = self.adopt(seq[:int(written)],
                                    dict(plan["private"]))
        taken = set(adopted)
        self.free_blocks([b for b in plan["private"].values()
                          if b not in taken])
        self.release(plan["nodes"])
        self.release(plan.get("adopt_nodes") or [])

    def warm_prefill(self, params, ids, total: int,
                     record: bool = True):
        """Batch-1 prefill through the pool (the generate.py path):
        scatter the cached chain, feed only the suffix, then insert the
        prompt's own full blocks back. Returns ``(last_logits, cache,
        cached_tokens)`` — drop-in for engine/generate._prefill_fresh.
        ``record=False`` when the request's lookup was already counted
        (the paged arm's dry-pool fallback re-looks-up the SAME
        request).

        A full MISS routes through the regular flash prefill
        (engine/generate._prefill_fresh — the cache K/V writes land
        before the flash fast-path return, so the result is still
        capturable): miss-heavy traffic pays the cold path's cost, not
        the masked-einsum continuation's. The fed width on a hit is
        the exact suffix length — the plain path compiles per prompt
        length already, so there is no ladder to protect at batch 1."""
        import jax.numpy as jnp

        from .generate import _prefill_fresh

        if self.window:
            # belt-and-braces: the pool refuses to CONSTRUCT a window
            # layout without the paged path, and the batch-1 caller
            # falls back cold instead of here — scattering a ring into
            # a contiguous rolling cache would be silently wrong
            raise PoolUnsupported(
                "window", "the scatter arm cannot serve a rolling-"
                "window layout (paged ring only)")
        self._apply_pending_corruption()
        L = len(ids)
        nodes, blocks, c = self.lookup(ids, record=record)
        # per-request path provenance (ISSUE 18): the scatter arm
        # consumes its nodes internally, so the caller cannot read
        # their origins from a plan — stash the flags for the batch-1
        # service (single-threaded under the service lock) to pick up
        self.last_warm_flags = page_origin_flags(nodes) if c else {}
        try:
            if c == 0:
                prompt = jnp.asarray(np.asarray(ids, np.int32)[None, :])
                last_logits, cache = _prefill_fresh(
                    self.model, int(total))(params, prompt, None)
            else:
                feed = L - c
                nb = len(blocks)
                bid = np.asarray(blocks, np.int32)[None, :]
                suffix = jnp.asarray(
                    np.asarray(ids[c:], np.int32)[None, :])
                last_logits, cache = _warm_prefill_fn(
                    self.model, int(total), feed, nb, self.block,
                    self._padded,
                )(params, suffix, self.pool, jnp.asarray(bid),
                  jnp.int32(c))
                self.record_copy_bytes(nb)   # the scatter arm's HBM cost
        finally:
            self.release(nodes)
        new_blocks, start = self.plan_insert(ids)
        if new_blocks:
            row = [-1] * start + list(new_blocks)
            self.capture(cache, [0], [0], [row])
        return last_logits, cache, c
