"""Paged KV block pool + automatic prefix caching for the serving path.

Production LM traffic is dominated by requests sharing long system /
few-shot prefixes, and prefill is the compute-bound slice of serving
(~16 ms device time per 8x1024 prompt — BASELINE.md). vLLM's
PagedAttention (Kwon et al., SOSP 2023) and SGLang's RadixAttention
(Zheng et al., 2024) showed that block-granular KV management plus a
prefix index over token ids turns that shared work into an HBM copy
instead of a recompute. This module is the TPU-native version of that
idea for THIS framework's cache layout:

- **Block pool** (``PrefixCache``): one bounded device array per
  KV-cache leaf, shaped ``[pool_blocks, block_tokens, kv_heads,
  head_dim]`` — fixed-size token blocks allocated from a free list,
  ref-counted while an admission is reading them, LRU-evicted when the
  pool fills. Block id 0 is a reserved scratch block (never allocated)
  so padded/unused lanes of the fixed-shape kernels always have a legal
  destination.
- **Radix index** (``RadixIndex``): a trie over prompt token ids with
  one edge per FULL block (``block_tokens`` ids) mapping prefixes to
  block chains. Matching is block-granular — two prompts that diverge
  mid-block share nothing for that block (the vLLM hash-per-full-block
  contract); there are no partial-edge splits to manage.
- **Canonical rotation space**: the Llama-family cache stores K rotated
  at absolute cache-slot angles (models/llama._cached_attention), and
  the continuous engine admits a prompt wherever the era's global
  position counter happens to be — so the same prefix lands at
  different slots on different admits. Pool blocks therefore store K in
  CANONICAL space (prefix token ``j`` rotated at angle ``j``); RoPE
  rotations compose additively (``R(aθ)·R(bθ) = R((a+b)θ)``), so
  capture de-rotates by the row's start slot and extraction re-rotates
  by the target start slot — one constant-angle rotation per row,
  fused into the copy kernel. V (and non-rotary families) copy as-is.
  The round-trip is exact in real arithmetic and float-tolerance exact
  in practice — the same contract as the engine's mixed-length
  batching ("logits agree to float tolerance, not bitwise").
- **Suffix-only prefill**: an admission with ``c`` cached prefix tokens
  scatters the block chain into the row's cache slots and feeds only
  the suffix through the model. The fed window is snapped to the same
  power-of-two ladder as cold admissions (engine/continuous._bucket),
  so the compile-cache/warmup story is untouched. Inside the fed
  window the model RECOMPUTES any overlapped prefix positions exactly
  as the cold path would (its DUS write wins over the scattered copy),
  which keeps warm output equal to cold output.

Scope: non-rolling caches only (``window == 0`` — ring eviction order
is position-dependent) and full-precision KV (``kv_quant == ""`` —
rotating through an int8 round-trip would add quantization error on
every reuse). Models declare their layout via ``kv_cache_spec()``
(models/llama.py, models/transformer.py).
"""
from __future__ import annotations

import functools
import logging
import threading

import numpy as np

logger = logging.getLogger(__name__)

#: reserved pool block: padded/unused kernel lanes read and write here
SCRATCH_BLOCK = 0


def _path_str(path) -> str:
    """Flax cache pytree path -> stable string key ("layers_0/self_attn/
    cached_key") shared by the host pool dict and the traced kernels."""
    parts = []
    for p in path:
        parts.append(str(getattr(p, "key", p)))
    return "/".join(parts)


def _leaf_kind(path_s: str, leaf) -> str | None:
    """'key' / 'value' for poolable K/V cache leaves, None for
    everything else (pos_index, slot_pos, int8 scales)."""
    if getattr(leaf, "ndim", 0) != 4:
        return None
    name = path_s.rsplit("/", 1)[-1]
    if name == "cached_key":
        return "key"
    if name == "cached_value":
        return "value"
    return None


def rotate_rows(x, deltas, rope_base: float):
    """Rotate ``[B, T, H, D]`` K rows by a per-row CONSTANT RoPE angle
    ``deltas[b]`` (rotate-half convention, f32 math — the op-for-op
    broadcast form of models/llama.apply_rope). Because RoPE rotations
    compose additively, rotating canonical-space K by the row's start
    slot reproduces the cache's absolute-slot rotation; negative deltas
    invert (capture path)."""
    import jax.numpy as jnp

    from ..models.llama import rope_tables

    d = x.shape[-1]
    cos, sin = rope_tables(jnp.asarray(deltas, jnp.int32), d, rope_base)
    xf = x.astype(jnp.float32)
    rot = jnp.concatenate([-xf[..., d // 2:], xf[..., : d // 2]], axis=-1)
    out = xf * cos[:, None, None, :] + rot * sin[:, None, None, :]
    return out.astype(x.dtype)


def scatter_blocks(cache, pool, block_ids, pads, pos0, feed: int,
                   block: int, rotary: bool, rope_base: float):
    """Scatter pool block chains into a (fresh) per-row cache pytree.

    ``cache``: the group cache (leaves ``[k, total, H, D]``).
    ``pool``: ``{path_str: [P, block, H, D]}``.
    ``block_ids``: ``[k, nb]`` int32, ``-1`` = unused lane.
    ``pads``: ``[k]`` row start slots (= rotation delta for K).
    ``pos0``: scalar — the fed window start; unused lanes are
    redirected into ``[pos0, pos0 + feed)``, which the suffix prefill's
    own DUS writes overwrite at every layer before any read, so their
    garbage is dead by construction. Traced; shapes are static.
    """
    import jax
    import jax.numpy as jnp

    k, nb = block_ids.shape
    tok = jnp.arange(nb * block)
    used = jnp.repeat(block_ids >= 0, block, axis=1)        # [k, nb*block]
    dest = jnp.where(used, pads[:, None] + tok[None, :],
                     pos0 + (tok % feed)[None, :])
    safe_ids = jnp.clip(block_ids, 0, None)                  # -1 -> scratch

    def put(path, leaf):
        ps = _path_str(path)
        if ps not in pool:
            return leaf
        src = pool[ps][safe_ids]                 # [k, nb, block, H, D]
        src = src.reshape(k, nb * block, *src.shape[3:])
        if rotary and ps.endswith("cached_key"):
            src = rotate_rows(src, pads, rope_base)
        src = src.astype(leaf.dtype)
        return jax.vmap(lambda row, d, s: row.at[d].set(s))(leaf, dest,
                                                            src)

    return jax.tree_util.tree_map_with_path(put, cache)


@functools.lru_cache(maxsize=32)
def _capture_fn(model, k: int, nb: int, block: int, rotary: bool,
                rope_base: float):
    """Compiled pool capture: gather ``nb`` blocks of each of ``k``
    cache rows (row ``slots[j]``, prompt starting at slot ``pads[j]``),
    de-rotate K to canonical space, and write them into the (donated)
    pool at ``block_ids``. Unused lanes (``-1``) read row 0 and write
    the scratch block. One async dispatch; never forces a sync."""
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, donate_argnums=0)
    def capture(pool, cache, slots, pads, block_ids):
        tok = jnp.arange(nb * block)
        used = jnp.repeat(block_ids >= 0, block, axis=1)
        src_idx = jnp.where(used, pads[:, None] + tok[None, :], 0)
        ids = jnp.where(block_ids >= 0, block_ids, SCRATCH_BLOCK)
        flat = jax.tree_util.tree_flatten_with_path(dict(cache))[0]
        by_path = {_path_str(p): leaf for p, leaf in flat}
        out = {}
        for ps, pool_leaf in pool.items():
            rows = by_path[ps][slots]                       # [k, T, H, D]
            content = jax.vmap(lambda r, i: r[i])(rows, src_idx)
            if rotary and ps.endswith("cached_key"):
                content = rotate_rows(content, -pads, rope_base)
            content = content.astype(pool_leaf.dtype).reshape(
                k, nb, block, *content.shape[2:])
            out[ps] = pool_leaf.at[ids.reshape(-1)].set(
                content.reshape(k * nb, block, *content.shape[3:]))
        return out

    return capture


@functools.lru_cache(maxsize=32)
def _warm_prefill_fn(model, total: int, feed: int, nb: int, block: int,
                     padded: bool):
    """Compiled batch-1 warm prefill: build a zero ``[1, total]`` cache
    in-graph, scatter the cached block chain at canonical slots 0..c-1
    (delta 0 — at batch 1 the prompt starts at slot 0, so pool space IS
    cache space and K needs no re-rotation), position the counter at
    ``pos0 = L - feed``, and run the trailing ``feed`` prompt tokens
    through the masked continuation path. Pad-capable models
    (``padded``) pass ``prefill=True`` with an all-zero ``pad_lens`` —
    that combination keeps the masked einsum path (the fresh-cache
    flash fast path requires ``pad_lens is None`` and would ignore the
    scattered history) while still taking the model-level
    last-position logits trim, so the ``[1, feed, V]`` head never
    materializes. Returns ``(last_logits, cache)`` — the same contract
    as engine/generate._prefill_fresh, so the normal decode step loop
    takes over unchanged. Full misses never come here (the caller
    routes c == 0 through the genuine flash prefill)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(params, suffix, pool, block_ids, pos0):
        shapes = jax.eval_shape(
            lambda p: model.apply(
                {"params": p}, jnp.zeros((1, total), jnp.int32),
                train=False, decode=True, mutable=["cache"],
            ),
            params,
        )[1]["cache"]
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                             shapes)
        cache = scatter_blocks(
            dict(cache), pool, block_ids, jnp.zeros((1,), jnp.int32),
            pos0, feed, block, rotary=False, rope_base=0.0)
        cache["pos_index"] = pos0.astype(jnp.int32)
        extra = ({"prefill": True,
                  "pad_lens": jnp.zeros((1,), jnp.int32)}
                 if padded else {})
        logits, vs = model.apply(
            {"params": params, "cache": cache}, suffix,
            train=False, decode=True, mutable=["cache"], **extra,
        )
        return logits[:, -1], vs["cache"]

    return run


class RadixIndex:
    """Block-granular radix/trie over prompt token ids.

    One edge per full ``block_tokens``-id chunk; each node owns exactly
    one pool block. Matching walks whole blocks (divergence mid-block
    shares nothing for that block). Nodes carry a refcount — held while
    an admission's copy kernel may still read the block — and an LRU
    clock; eviction only ever takes an UNREFERENCED LEAF (children pin
    their ancestors by construction of the walk)."""

    def __init__(self, block_tokens: int):
        self.block = int(block_tokens)
        self.root = {"children": {}, "block": None, "parent": None,
                     "refs": 0, "last_use": 0}
        self._clock = 0
        self.nodes = 0

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _chunks(self, ids):
        ids = list(ids)
        n = len(ids) // self.block
        return [tuple(ids[i * self.block:(i + 1) * self.block])
                for i in range(n)]

    def match(self, ids):
        """Longest fully-blocked cached prefix of ``ids`` ->
        ``(nodes, block_ids)`` (refs NOT acquired — see ``acquire``)."""
        now = self._tick()
        node, nodes, blocks = self.root, [], []
        for chunk in self._chunks(ids):
            nxt = node["children"].get(chunk)
            if nxt is None:
                break
            nxt["last_use"] = now
            nodes.append(nxt)
            blocks.append(nxt["block"])
            node = nxt
        return nodes, blocks

    def acquire(self, nodes):
        for n in nodes:
            n["refs"] += 1

    def release(self, nodes):
        for n in nodes:
            n["refs"] -= 1
            assert n["refs"] >= 0, "radix refcount underflow"

    def insert(self, ids, alloc):
        """Create nodes for every full block of ``ids`` not yet present.
        ``alloc()`` returns a free block id or None (pool exhausted —
        insertion stops there; the present prefix stays useful).
        Returns ``(new_nodes, new_block_ids, start_block_index)``.

        The walked path (existing AND just-created nodes) is PINNED
        for the duration: ``alloc`` may LRU-evict, and evicting the
        very chain being extended would detach the node the next new
        child links under — an unreachable subtree whose blocks leak
        forever."""
        now = self._tick()
        node = self.root
        pinned = []
        new_nodes, new_blocks, start = [], [], None
        try:
            for i, chunk in enumerate(self._chunks(ids)):
                nxt = node["children"].get(chunk)
                if nxt is None:
                    bid = alloc()
                    if bid is None:
                        break
                    nxt = {"children": {}, "block": bid, "parent": node,
                           "chunk": chunk, "refs": 0, "last_use": now}
                    node["children"][chunk] = nxt
                    self.nodes += 1
                    new_nodes.append(nxt)
                    new_blocks.append(bid)
                    if start is None:
                        start = i
                nxt["refs"] += 1
                pinned.append(nxt)
                nxt["last_use"] = now
                node = nxt
        finally:
            for n in pinned:
                n["refs"] -= 1
        return new_nodes, new_blocks, (0 if start is None else start)

    def evict_lru(self):
        """Detach the least-recently-used unreferenced LEAF node and
        return its block id (None when everything is pinned)."""
        best, best_key = None, None
        stack = [self.root]
        while stack:
            node = stack.pop()
            for child in node["children"].values():
                if not child["children"]:
                    if child["refs"] == 0 and (
                            best is None
                            or child["last_use"] < best_key):
                        best, best_key = child, child["last_use"]
                else:
                    stack.append(child)
        if best is None:
            return None
        del best["parent"]["children"][best["chunk"]]
        best["parent"] = None
        self.nodes -= 1
        return best["block"]


class PrefixCache:
    """The serving-path prefix cache: radix index + bounded device
    block pool + the compiled capture/extract kernels.

    Thread-safety: host bookkeeping (index/free list/stats) is guarded
    by a lock; device kernels are dispatched by the caller's scheduler
    thread, whose program order gives the read-before-overwrite
    guarantee the immediate ref release relies on.
    """

    def __init__(self, model, params, block_tokens: int = 32,
                 pool_blocks: int = 256, eviction: str = "lru"):
        import jax
        import jax.numpy as jnp

        spec = getattr(model, "kv_cache_spec", None)
        if spec is None:
            raise ValueError(
                f"{type(model).__name__} declares no kv_cache_spec(): "
                "prefix caching needs the decode-cache layout contract")
        spec = spec()
        if spec.get("window", 0):
            raise ValueError(
                "prefix caching needs a non-rolling cache (window == 0):"
                " ring eviction order is position-dependent")
        if spec.get("kv_quant"):
            raise ValueError(
                "prefix caching supports full-precision KV only "
                f"(kv_quant={spec['kv_quant']!r} would re-quantize on "
                "every reuse)")
        if eviction != "lru":
            raise ValueError(f"unknown eviction policy {eviction!r} "
                             "(only 'lru')")
        if int(block_tokens) < 1 or int(pool_blocks) < 2:
            raise ValueError("need block_tokens >= 1 and pool_blocks "
                             ">= 2 (block 0 is reserved scratch)")
        self.model = model
        self.block = int(block_tokens)
        self.pool_blocks = int(pool_blocks)
        self.rotary = bool(spec.get("rotary"))
        self.rope_base = float(spec.get("rope_base") or 0.0)
        # device pool: one [P, block, H, D] leaf per poolable cache leaf,
        # discovered from a [1, block] eval_shape trace (no device work)
        shapes = jax.eval_shape(
            lambda p: model.apply(
                {"params": p}, jnp.zeros((1, self.block), jnp.int32),
                train=False, decode=True, mutable=["cache"],
            ),
            params,
        )[1]["cache"]
        flat = jax.tree_util.tree_flatten_with_path(dict(shapes))[0]
        self.pool = {}
        for path, leaf in flat:
            ps = _path_str(path)
            if _leaf_kind(ps, leaf) is not None:
                self.pool[ps] = jnp.zeros(
                    (self.pool_blocks,) + tuple(leaf.shape[1:]),
                    leaf.dtype)
        if not self.pool:
            raise ValueError(
                f"{type(model).__name__} exposes no poolable KV leaves")
        import inspect

        self._padded = "pad_lens" in inspect.signature(
            type(model).__call__).parameters
        self.index = RadixIndex(self.block)
        self._free = list(range(1, self.pool_blocks))  # 0 = scratch
        self._lock = threading.Lock()
        self.stats = {
            "prefix_lookups": 0, "prefix_hit_requests": 0,
            "prefix_hit_tokens": 0, "prefix_inserted_blocks": 0,
            "prefix_evictions": 0, "prefix_dropped_inserts": 0,
        }
        self.nb_max = -(-int(model.max_len) // self.block)

    # ---- host bookkeeping -------------------------------------------------

    def used_blocks(self) -> int:
        return self.pool_blocks - 1 - len(self._free)

    def _alloc(self):
        """One free block id, evicting the LRU unreferenced leaf when
        the free list is empty; None when everything is pinned."""
        if self._free:
            return self._free.pop()
        bid = self.index.evict_lru()
        if bid is None:
            self.stats["prefix_dropped_inserts"] += 1
            return None
        self.stats["prefix_evictions"] += 1
        return bid

    def lookup(self, ids):
        """Longest cached, fully-blocked, PROPER prefix of ``ids`` ->
        ``(nodes, block_ids, cached_tokens)``; refs acquired (callers
        MUST ``release(nodes)`` once the copy kernel is dispatched).
        Proper: the prompt's final token is never served from cache —
        its logits must be computed to sample the first output token —
        so ``cached_tokens <= len(ids) - 1``."""
        with self._lock:
            self.stats["prefix_lookups"] += 1
            nodes, blocks = self.index.match(ids)
            limit = (len(ids) - 1) // self.block     # proper-prefix cap
            nodes, blocks = nodes[:limit], blocks[:limit]
            c = len(nodes) * self.block
            if c:
                self.stats["prefix_hit_requests"] += 1
                self.stats["prefix_hit_tokens"] += c
                self.index.acquire(nodes)
            return nodes, blocks, c

    def release(self, nodes):
        with self._lock:
            self.index.release(nodes)

    def plan_insert(self, ids):
        """Allocate blocks + index nodes for the full blocks of ``ids``
        not yet cached. Returns ``(block_ids, start_block)`` for the
        capture kernel (empty when nothing is new)."""
        with self._lock:
            _, blocks, start = self.index.insert(ids, self._alloc)
            self.stats["prefix_inserted_blocks"] += len(blocks)
            return blocks, start

    def stats_snapshot(self) -> dict:
        with self._lock:
            out = dict(self.stats)
        out["prefix_pool_blocks"] = self.pool_blocks - 1
        out["prefix_pool_blocks_used"] = self.used_blocks()
        lk = out["prefix_lookups"]
        out["prefix_hit_rate"] = round(
            out["prefix_hit_requests"] / lk, 4) if lk else 0.0
        return out

    # ---- device paths -----------------------------------------------------

    def capture(self, cache, slots, pads, per_row_block_ids):
        """Fill pool blocks from admitted rows of ``cache`` (one async
        dispatch; the pool leaves are donated through). ``slots`` /
        ``pads``: per-row cache row + prompt start slot;
        ``per_row_block_ids``: ``[k][nb]`` lists, ``-1`` padded."""
        import jax.numpy as jnp

        k = len(slots)
        nb = max((len(b) for b in per_row_block_ids), default=0)
        if nb == 0:
            return
        ids = np.full((k, nb), -1, np.int32)
        for j, row in enumerate(per_row_block_ids):
            ids[j, :len(row)] = row
        self.pool = _capture_fn(
            self.model, k, nb, self.block, self.rotary, self.rope_base,
        )(self.pool, cache, jnp.asarray(np.asarray(slots, np.int32)),
          jnp.asarray(np.asarray(pads, np.int32)), jnp.asarray(ids))

    def warm_prefill(self, params, ids, total: int):
        """Batch-1 prefill through the pool (the generate.py path):
        scatter the cached chain, feed only the suffix, then insert the
        prompt's own full blocks back. Returns ``(last_logits, cache,
        cached_tokens)`` — drop-in for engine/generate._prefill_fresh.

        A full MISS routes through the regular flash prefill
        (engine/generate._prefill_fresh — the cache K/V writes land
        before the flash fast-path return, so the result is still
        capturable): miss-heavy traffic pays the cold path's cost, not
        the masked-einsum continuation's. The fed width on a hit is
        the exact suffix length — the plain path compiles per prompt
        length already, so there is no ladder to protect at batch 1."""
        import jax.numpy as jnp

        from .generate import _prefill_fresh

        L = len(ids)
        nodes, blocks, c = self.lookup(ids)
        try:
            if c == 0:
                prompt = jnp.asarray(np.asarray(ids, np.int32)[None, :])
                last_logits, cache = _prefill_fresh(
                    self.model, int(total))(params, prompt, None)
            else:
                feed = L - c
                nb = len(blocks)
                bid = np.asarray(blocks, np.int32)[None, :]
                suffix = jnp.asarray(
                    np.asarray(ids[c:], np.int32)[None, :])
                last_logits, cache = _warm_prefill_fn(
                    self.model, int(total), feed, nb, self.block,
                    self._padded,
                )(params, suffix, self.pool, jnp.asarray(bid),
                  jnp.int32(c))
        finally:
            self.release(nodes)
        new_blocks, start = self.plan_insert(ids)
        if new_blocks:
            row = [-1] * start + list(new_blocks)
            self.capture(cache, [0], [0], [row])
        return last_logits, cache, c
