"""Autoregressive generation with KV-cached incremental decoding.

The reference has no inference path beyond batch evaluation (its
``test.py`` computes metrics, /root/reference/test.py:64-101); a framework
with a GPT-2 family needs actual sampling. TPU-shaped design:

- ONE compiled step function reused for every generated token (static
  shapes: the KV cache is pre-allocated at ``prompt + max_new_tokens`` and
  written in place via ``dynamic_update_slice`` — no growing arrays, no
  per-step recompiles);
- prefill processes the whole prompt in a single call (big matmuls for the
  MXU), then the loop feeds one token at a time;
- sampling (temperature / top-k / greedy) runs in-graph on the logits.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def filter_logits(logits, temperature: float, top_k: int,
                  top_p: float = 0.0):
    """Temperature/top-k/top-p filtering of ``[B, V]`` logits — the
    sampling DISTRIBUTION without the sample, shared by
    ``sample_logits`` and the speculative verifier (which needs the
    filtered probabilities for rejection sampling)."""
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if 0.0 < top_p < 1.0:
        # sort descending; keep tokens while the cumulative probability of
        # STRICTLY-higher-ranked tokens is < top_p (so the boundary token
        # that crosses the threshold is kept, like HF's implementation)
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1) - probs
        keep = cum < top_p                       # [B, V] in sorted order
        # threshold logit = smallest kept logit per row
        thresh = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < thresh, -jnp.inf, logits)
    return logits


def sample_logits(key, logits, temperature: float = 1.0, top_k: int = 0,
                  top_p: float = 0.0):
    """Sample token ids from ``[B, V]`` logits (in-graph).

    ``temperature <= 0`` means greedy argmax. ``top_k > 0`` restricts
    sampling to the k highest-probability tokens. ``top_p`` in (0, 1)
    applies nucleus sampling: the smallest set of tokens whose cumulative
    probability reaches ``top_p`` (the top token always survives).
    ``top_k`` and ``top_p`` compose (k-filter first, as in HF).
    """
    if temperature <= 0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = filter_logits(logits, temperature, top_k, top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def _isin(x, stops):
    """Per-element membership of ``x`` in the id set ``stops`` ([S],
    -1-padded — ids are non-negative, so -1 slots never match)."""
    return jnp.any(x[..., None] == stops, axis=-1)


def _sample_rows_traced(keys, logits, temps, top_ks, top_ps):
    """Per-row sampling with TRACED per-row (temperature, top_k, top_p)
    — the mixed-sampling batching path (one executable serves every
    sampling config instead of one per pinned tuple).

    Op-for-op mirror of ``filter_logits`` + ``sample_logits`` so a row
    sampled here is BIT-IDENTICAL to the same row run solo through the
    static path (tests pin this): same scale-then-filter order, same
    descending-sort idiom, same threshold comparisons. ``temp <= 0``
    rows take the greedy argmax.
    """
    v = logits.shape[-1]

    def one(key, lg, temp, k, p):
        greedy_tok = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        l = lg / jnp.maximum(temp, 1e-30)
        # ONE descending sort serves both filters (a full-vocab sort
        # costs milliseconds per row per step — it was 44 ms/step on
        # the serving chunk before this): top-k filtering only ever
        # -infs values BELOW the kth, so the filtered sort is the
        # unfiltered sort with the tail masked.
        sorted_l = jnp.sort(l, axis=-1)[::-1]
        kth = sorted_l[jnp.clip(k - 1, 0, v - 1)]
        l = jnp.where((k > 0) & (l < kth), -jnp.inf, l)
        # survivors of the strict `< kth` filter: every entry >= kth
        # (value ties at the boundary all survive, like the static
        # path — a fixed count of k would wrongly cut them)
        k_eff = jnp.where(k > 0, jnp.sum(sorted_l >= kth), v)
        sl = jnp.where(jnp.arange(v) < k_eff, sorted_l, -jnp.inf)
        probs = jax.nn.softmax(sl, axis=-1)
        cum = jnp.cumsum(probs, axis=-1) - probs
        keep = cum < p
        thresh = jnp.min(jnp.where(keep, sl, jnp.inf), axis=-1)
        l = jnp.where((p > 0.0) & (p < 1.0) & (l < thresh), -jnp.inf, l)
        samp = jax.random.categorical(key, l).astype(jnp.int32)
        return jnp.where(temp <= 0.0, greedy_tok, samp)

    return jax.vmap(one)(keys, logits, temps, top_ks, top_ps)


def fresh_cache(model, params, batch: int, length: int):
    """Zeroed decode cache for a ``[batch, length]`` budget.

    ``eval_shape`` traces the allocation call without running FLOPs; all
    cache variables zero-initialize, so a zeros pytree of the resulting
    shapes/dtypes IS a fresh cache (including int8 rows + scales under
    ``kv_quant`` — empty slots decode to zeros). The one allocation
    idiom shared by ``generate``, ``generate_speculative``, and the
    bench/serving callers.

    Under a TP serving mesh (ISSUE 10, ``model.mesh`` carrying a
    ``tensor`` axis) the K/V leaves come back COMMITTED sharded on the
    head axis — warmup ladders built from this cache then compile the
    exact signatures live dispatch hits (a committed/uncommitted
    mismatch mints fresh XLA compiles mid-traffic).
    """
    from ..parallel.tp import shard_kv_tree

    shapes = jax.eval_shape(
        lambda p: model.apply(
            {"params": p}, jnp.zeros((batch, length), jnp.int32),
            train=False, decode=True, mutable=["cache"],
        ),
        params,
    )
    cache = jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes[1]["cache"]
    )
    return shard_kv_tree(cache, getattr(model, "mesh", None))


def generate(model, params, prompt: jnp.ndarray, max_new_tokens: int,
             temperature: float = 1.0, top_k: int = 0, top_p: float = 0.0,
             rng: Optional[jax.Array] = None,
             row_rngs: Optional[jax.Array] = None,
             pad_lens=None, stop_tokens=None, row_budgets=None,
             row_temperatures=None, row_top_ks=None, row_top_ps=None,
             pad_id: int = 0, return_lengths: bool = False):
    """Generate up to ``max_new_tokens`` continuations per prompt row.

    :param model: a TransformerLM-family module (``decode=True`` support).
    :param params: trained params pytree (e.g. ``state.params`` or
        ``state.ema_params``).
    :param prompt: ``[B, T0]`` int32 token ids (T0 >= 1).
    :param rng: PRNG key for sampling (defaults to key(0); unused when
        greedy). Split into one independent stream PER ROW.
    :param row_rngs: optional ``[B]`` keys, one per row, overriding the
        ``rng`` split — the micro-batched server passes each request's
        own seed here, so a request's sampled tokens do not depend on
        which other requests shared its batch.
    :param pad_lens: optional ``[B]`` int32 — per-row LEFT-pad length
        for mixed-prompt-length batching (RoPE families only; the
        model masks pad slots per row and slot-index RoPE is exact
        under the per-row constant shift — models/llama.py). Rows'
        prompts occupy ``prompt[b, pad_lens[b]:]``.
    :param stop_tokens: optional stop-token ids — a flat list applied
        to every row, or one list PER ROW (ragged ok). A row freezes
        after emitting a stop token (the stop token itself is
        emitted); once EVERY row is done the in-graph ``while_loop``
        exits, so early-stopping traffic stops burning chip time on
        the rest of its budget (VERDICT r4 missing #1 — the reference
        contract analogue is /root/reference/test.py:64-85: process
        exactly the work given, no more).
    :param row_budgets: optional ``[B]`` per-row token budgets
        (<= max_new_tokens); rows past their budget freeze like
        stopped rows. This is what lets the batching scheduler share
        one executable across requests with different
        ``max_new_tokens`` instead of pinning it in the group key.
    :param row_temperatures / row_top_ks / row_top_ps: optional ``[B]``
        per-row sampling params (traced — one executable serves every
        sampling mix). Rows with temperature <= 0 decode greedily.
        When given, the scalar ``temperature``/``top_k``/``top_p``
        fill rows left as None.
    :param pad_id: id written at frozen positions (after a row's stop
        or budget).
    :param return_lengths: also return ``[B]`` emitted-token counts
        (stop token included; excludes the prompt). The loop's step
        count equals ``lengths.max()`` — the chip-time actually spent.
    :returns: ``[B, T0 + max_new_tokens]`` tokens (prompt included,
        left-pad included for padded rows; frozen tail = ``pad_id``),
        plus ``lengths`` when ``return_lengths``.
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    b, t0 = prompt.shape
    max_new_tokens = int(max_new_tokens)
    if max_new_tokens <= 0:
        out = prompt
        return (out, jnp.zeros((b,), jnp.int32)) if return_lengths else out
    total = t0 + max_new_tokens
    if total > model.max_len:
        raise ValueError(
            f"prompt + max_new_tokens = {total} exceeds model.max_len "
            f"= {model.max_len}"
        )
    if row_rngs is None:
        rng = rng if rng is not None else jax.random.key(0)
        row_rngs = jax.random.split(rng, b)
    elif len(row_rngs) != b:
        raise ValueError(f"row_rngs has {len(row_rngs)} keys for {b} rows")
    if pad_lens is not None:
        import inspect

        if "pad_lens" not in inspect.signature(
            type(model).__call__
        ).parameters:
            raise ValueError(
                f"{type(model).__name__} does not support pad_lens "
                "(mixed-length batching needs per-row pad masking + "
                "shift-invariant positions — the RoPE families)"
            )
        pad_lens = jnp.asarray(pad_lens, jnp.int32)

    per_row_sampling = (row_temperatures is not None
                        or row_top_ks is not None
                        or row_top_ps is not None)
    if (stop_tokens is not None or row_budgets is not None
            or per_row_sampling or return_lengths):
        return _generate_with_stops(
            model, params, prompt, max_new_tokens, row_rngs, pad_lens,
            stop_tokens, row_budgets,
            row_temperatures, row_top_ks, row_top_ps,
            float(temperature), int(top_k), float(top_p),
            int(pad_id), return_lengths,
        )

    # zero cache + prefill in ONE dispatch: an eagerly-built cache
    # pytree is ~50 small allocation dispatches (~0.5 s per request
    # through a tunneled device — the cost the speculative path's
    # single-dispatch form eliminated; BASELINE.md)
    _, step = _decode_fns(model, float(temperature), int(top_k),
                          float(top_p))
    last_logits, cache = _prefill_fresh(model, total)(params, prompt,
                                                      pad_lens)
    if temperature <= 0:
        # greedy ignores keys; reuse the (unfolded) row keys as the
        # step's dummy key argument instead of folding per step
        keys_at = lambda i: row_rngs                       # noqa: E731
    else:
        # ONE dispatch precomputes every step's per-row key ([T, B]);
        # the loop then just indexes — same per-step cost as the old
        # single-stream split
        all_keys = _fold_all_rows(row_rngs, max_new_tokens)
        keys_at = lambda i: all_keys[i]                    # noqa: E731
    token = _sample_rows(keys_at(0), last_logits,
                         temperature, top_k, top_p)
    # tokens stay on device through the loop (no per-step host sync);
    # async dispatch pipelines the steps
    out = [prompt, token[:, None]]
    for i in range(1, max_new_tokens):
        token, cache = step(params, cache, token, keys_at(i), pad_lens)
        out.append(token[:, None])
    return jnp.concatenate(out, axis=1)


def _generate_with_stops(model, params, prompt, max_new: int, row_rngs,
                         pad_lens, stop_tokens, row_budgets,
                         row_temperatures, row_top_ks, row_top_ps,
                         temperature: float, top_k: int, top_p: float,
                         pad_id: int, return_lengths: bool):
    """Host-side normalization for the stop-capable loop: ragged stop
    lists -> a -1-padded ``[B, S]`` array, per-row budgets clipped to
    ``[1, max_new]``, per-row sampling arrays filled from the scalars.
    The device work is ONE dispatch (``_stop_loop``)."""
    import numpy as np

    b, t0 = prompt.shape
    if stop_tokens is None:
        stops = np.full((b, 1), -1, np.int64)
    else:
        rows = list(stop_tokens)
        if not rows:
            stops = np.full((b, 1), -1, np.int64)
        else:
            if not isinstance(rows[0], (list, tuple, np.ndarray)):
                rows = [rows] * b          # flat list: same set per row
            elif len(rows) != b:
                raise ValueError(
                    f"per-row stop_tokens has {len(rows)} rows for {b}")
            width = max(1, max(len(r) for r in rows))
            stops = np.full((b, width), -1, np.int64)
            for i, r in enumerate(rows):
                for j, s in enumerate(r):
                    if int(s) < 0:
                        raise ValueError(f"negative stop token {s}")
                    stops[i, j] = int(s)
    if row_budgets is None:
        budgets = np.full((b,), max_new, np.int64)
    else:
        budgets = np.asarray(row_budgets, np.int64)
        if budgets.shape != (b,):
            raise ValueError(f"row_budgets shape {budgets.shape} != ({b},)")
        if (budgets > max_new).any():
            raise ValueError(
                f"row budget {budgets.max()} exceeds max_new_tokens "
                f"{max_new}")
        budgets = np.clip(budgets, 1, max_new)

    per_row = (row_temperatures is not None or row_top_ks is not None
               or row_top_ps is not None)

    def row_arr(v, fill, dtype):
        a = (np.full((b,), fill, dtype) if v is None
             else np.asarray(v, dtype))
        if a.shape != (b,):
            raise ValueError(f"per-row sampling array shape {a.shape}")
        return jnp.asarray(a)

    samp = (row_arr(row_temperatures, temperature, np.float32),
            row_arr(row_top_ks, top_k, np.int32),
            row_arr(row_top_ps, top_p, np.float32))
    sampling = ("per_row" if per_row
                else ("static", temperature, top_k, top_p))
    run = _stop_loop(model, t0, max_new, int(stops.shape[1]), sampling,
                     pad_lens is not None)
    if pad_lens is None:
        pad_lens = jnp.zeros((b,), jnp.int32)
    buf, lengths = run(params, prompt, jnp.asarray(row_rngs),
                       jnp.asarray(stops, jnp.int32),
                       jnp.asarray(budgets, jnp.int32), samp,
                       pad_lens, jnp.int32(pad_id))
    return (buf, lengths) if return_lengths else buf


@functools.lru_cache(maxsize=32)
def _stop_loop(model, t0: int, max_new: int, n_stop: int, sampling,
               padded: bool):
    """Compiled stop-capable generation: ONE dispatch — in-graph zero
    cache build, prompt prefill, and a ``lax.while_loop`` over
    single-token steps that exits as soon as EVERY row is done (stop
    token emitted or per-row budget reached). Finished rows freeze:
    their emissions are ``pad_id`` and their (ignored) cache writes
    continue. Each row's emitted tokens depend only on its own true
    prefix, so a stopped row is token-exact vs the same row run solo
    and truncated (tests pin this).

    ``sampling`` is ``("static", T, k, p)`` — the classic shared
    config, sampled exactly like the plain path — or ``"per_row"``,
    which reads traced ``[B]`` (temperature, top_k, top_p) arrays so
    ONE executable serves every sampling mix in a shared batch
    (``_sample_rows_traced`` is bit-identical to the static math).
    """
    from jax import lax

    from ..parallel.tp import constrain_kv_tree

    total = t0 + max_new
    per_row = sampling == "per_row"
    mesh = getattr(model, "mesh", None)

    @jax.jit
    def run(params, prompt, row_rngs, row_stops, row_budgets, samp,
            pad_lens, pad_id):
        b = prompt.shape[0]
        shapes = jax.eval_shape(
            lambda p: model.apply(
                {"params": p}, jnp.zeros((b, total), jnp.int32),
                train=False, decode=True, mutable=["cache"],
            ),
            params,
        )[1]["cache"]
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                             shapes)
        cache = constrain_kv_tree(cache, mesh)   # TP head sharding
        extra = {"pad_lens": pad_lens} if padded else {}
        logits, vs = model.apply(
            {"params": params, "cache": cache}, prompt,
            train=False, decode=True, prefill=True, mutable=["cache"],
            **extra,
        )
        cache = vs["cache"]
        # same per-(step, row) key layout as the plain path: emission
        # i uses all_keys[i], so outputs match it bit-for-bit
        all_keys = _fold_all_rows(row_rngs, max_new)

        def sample_at(i, lg):
            if per_row:
                from jax import lax as _lax

                temps, ks, ps = samp
                # all-greedy steps skip the traced sampler's
                # full-vocab sort at runtime (greedy rows in a mixed
                # batch still take per-row argmax inside the branch)
                return _lax.cond(
                    jnp.any(temps > 0.0),
                    lambda: _sample_rows_traced(all_keys[i], lg,
                                                temps, ks, ps),
                    lambda: jnp.argmax(lg, axis=-1).astype(jnp.int32),
                )
            _, T, k, p = sampling
            return _sample_rows(all_keys[i], lg, T, k, p)

        tok0 = sample_at(0, logits[:, -1])
        done = _isin(tok0, row_stops) | (row_budgets <= 1)
        buf = jnp.zeros((b, total), jnp.int32)
        buf = lax.dynamic_update_slice(buf, prompt, (0, 0))
        buf = lax.dynamic_update_slice(buf, tok0[:, None], (0, t0))
        lengths = jnp.ones((b,), jnp.int32)

        def cond(st):
            i, tok, done, buf, lengths, cache = st
            return (i < max_new) & ~jnp.all(done)

        def body(st):
            i, tok, done, buf, lengths, cache = st
            logits, vs = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                train=False, decode=True, mutable=["cache"], **extra,
            )
            nxt = sample_at(i, logits[:, -1])
            nxt = jnp.where(done, jnp.full((b,), pad_id, jnp.int32),
                            nxt)
            buf = lax.dynamic_update_slice(buf, nxt[:, None],
                                           (0, t0 + i))
            lengths = lengths + (~done).astype(jnp.int32)
            done = done | _isin(nxt, row_stops) | (i + 1 >= row_budgets)
            return (i + 1, nxt, done, buf, lengths, vs["cache"])

        i, _, done, buf, lengths, _ = lax.while_loop(
            cond, body, (jnp.int32(1), tok0, done, buf, lengths, cache)
        )
        # the loop exits as soon as EVERY row is done, so positions it
        # never reached still hold the buffer's zeros — enforce the
        # "frozen tail = pad_id" contract for the whole tail here, not
        # just the steps the loop happened to run
        col = jnp.arange(total)[None, :]
        buf = jnp.where(col >= t0 + lengths[:, None], pad_id, buf)
        return buf, lengths

    return run


@functools.partial(jax.jit, static_argnums=1)
def _fold_all_rows(row_rngs, n: int):
    """``[n, B]`` per-(step, row) keys — row streams are independent,
    so a row's samples are a function of (its key, the step index)
    only, never of batch composition."""
    return jax.vmap(
        lambda i: jax.vmap(lambda k: jax.random.fold_in(k, i))(row_rngs)
    )(jnp.arange(n))


def _sample_rows(keys, logits, temperature: float, top_k: int,
                 top_p: float):
    """``sample_logits`` with one key per row ([B] keys, [B, V]
    logits)."""
    if temperature <= 0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.vmap(
        lambda k, lg: sample_logits(k, lg[None, :], temperature, top_k,
                                    top_p)[0]
    )(keys, logits)


def generate_speculative(model, params, prompt: jnp.ndarray,
                         max_new_tokens: int, draft_len: int = 4,
                         ngram: int = 2, return_stats: bool = False,
                         temperature: float = 0.0, top_k: int = 0,
                         top_p: float = 0.0,
                         rng: Optional[jax.Array] = None,
                         pad_to: Optional[int] = None,
                         stop_tokens=None, draft_layers: int = 0):
    """Generation via self-speculative (prompt-lookup) decoding.

    GREEDY (``temperature <= 0``, the default) emits BIT-IDENTICAL
    tokens to ``generate(..., temperature=0)`` — speculation changes
    the schedule, never the output. SAMPLED (``temperature > 0``) is
    DISTRIBUTION-exact rejection sampling: the n-gram drafter proposes
    deterministically, so draft token ``d`` at a position with target
    distribution ``p`` (after temperature/top-k/top-p filtering) is
    accepted with probability ``p(d)``; on rejection the position
    resamples from the residual ``p`` with ``d`` zeroed, renormalized
    — which makes the emitted token exactly ``p``-distributed
    (``P(t) = p(d)·1[t=d] + (1-p(d))·p(t)·1[t≠d]/(1-p(d)) = p(t)``).
    The token stream differs from ``generate()``'s (different rng
    path), but its law is the same.

    Each model call verifies ``draft_len`` guessed tokens at once, so
    on repetitive continuations (code, structured text) one forward
    pass commits several tokens. Decode is HBM-bound (a 1-token step
    and a 5-token step stream the same weight bytes), which is exactly
    why accepted drafts are almost-free throughput.

    The drafter is n-gram prompt lookup (no second model): find the
    most recent earlier occurrence of the trailing ``ngram`` tokens in
    the sequence so far and propose the ``draft_len`` tokens that
    followed it. Each loop iteration feeds ``[last_token, d_1..d_D]``,
    takes the target model's greedy predictions ``p_1..p_{D+1}``, and
    commits ``p_1..p_{na+1}`` where ``na`` is the longest matching
    draft prefix — at least one real token per iteration, like vanilla
    decode, plus up to ``draft_len`` free ones.

    Speculation REWINDS the KV cache after rejection by resetting the
    model-level ``pos_index`` counter: rejected rows stay in the cache
    but are invisible (the visibility mask hides positions beyond the
    counter) and are overwritten by the next iteration's DUS write at
    the same positions. This is only sound for the NON-ROLLING cache —
    a rolling window (Mistral-style ring buffer) evicts on write, which
    cannot be undone — so models must satisfy ``window == 0`` or
    ``window > prompt + budget``.

    The whole generation runs as ONE ``lax.while_loop`` dispatch
    (after the prefill): the loop stops exactly when the budget is
    met, so the token buffer needs only final-iteration slack, not
    per-chunk slack, and there are no mid-generation host round trips
    (~105 ms each through this platform's tunnel — BASELINE.md).
    Round 3 shipped a host-chunked ``lax.scan`` form instead, because
    ``lax.while_loop`` measured ~16x slower — that measurement timed
    the first post-compile dispatch (the tunnel's lazy-warmup,
    BASELINE.md "prefill anomaly, resolved"); properly warmed, the
    while_loop form measures ~2.8 ms per verify call vs ~1.9 ms per
    vanilla 1-token step, and speculation wins wall-clock whenever
    acceptance beats ~1.5 tokens/call.

    Restrictions (asserted): batch 1 (the cache keeps ONE position
    counter; divergent per-row acceptance would need per-row
    counters), ``prompt >= ngram``.

    ``draft_layers > 0`` (ISSUE 7): swap the n-gram drafter for a
    DRAFT MODEL — the target's own first ``draft_layers`` blocks with
    the final norm + LM head on top (``model.apply(exit_layer=...)``).
    The draft shares the target's params AND its KV cache: draft steps
    write layers ``0..draft_layers-1`` K/V at the speculative
    positions, and the verify pass recomputes those exact rows from
    the same tokens (identical values — overwrite, not corruption)
    while filling the remaining layers, so draft/verify cache reuse is
    free and rejection rewinds both at once via the one ``pos_index``.
    Each iteration costs ``D`` early-exit steps (~``draft_layers /
    n_layer`` of a full step each, decode being weight-bound) plus the
    one fused ``D+1``-token verify. Greedy output stays BIT-IDENTICAL
    to plain decode (the verifier decides every token); sampled mode
    stays distribution-exact (the drafter is deterministic-greedy, so
    the same rejection-sampling argument applies).

    ``pad_to`` (RoPE families only): left-pad the prompt to this
    length before compiling, so serving traffic with many distinct
    prompt lengths shares one executable per length bucket instead of
    paying a fresh XLA compile per length. Pad slots are masked from
    attention AND from the n-gram drafter; greedy output is unchanged
    (the verifier, not the drafter, decides tokens — tests pin this),
    and the returned array keeps the caller's unpadded layout.
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    b, t0 = prompt.shape
    if b != 1:
        raise ValueError("speculative decoding supports batch size 1 "
                         f"(got {b}) — the KV cache keeps one position "
                         "counter")
    if not draft_layers and t0 < ngram:
        # checked on the REAL length: bucket padding must not let an
        # under-ngram prompt slip through with pad zeros as its gram.
        # An early-exit draft (draft_layers > 0) never consults
        # n-grams — same condition as speculative_from_cache.
        raise ValueError(f"prompt length {t0} < ngram {ngram}")
    pad = 0
    if pad_to is not None and int(pad_to) > t0:
        import inspect

        if "pad_lens" not in inspect.signature(
            type(model).__call__
        ).parameters:
            raise ValueError(
                f"{type(model).__name__} does not support pad_to "
                "(needs the pad_lens masking path)"
            )
        pad = int(pad_to) - t0
        prompt = jnp.concatenate(
            [jnp.zeros((b, pad), jnp.int32), prompt], axis=1
        )
        t0 = int(pad_to)
    max_new_tokens = int(max_new_tokens)
    D, g = int(draft_len), int(ngram)
    if D < 1:
        raise ValueError("draft_len must be >= 1")
    draft_layers = int(draft_layers)
    if draft_layers:
        import inspect

        if not (0 < draft_layers < int(model.n_layer)):
            raise ValueError(
                f"draft_layers must be in (0, n_layer={model.n_layer}) "
                f"(got {draft_layers}) — the early-exit draft needs a "
                "strict prefix of the target's blocks")
        if "exit_layer" not in inspect.signature(
                type(model).__call__).parameters:
            raise ValueError(
                f"{type(model).__name__} has no exit_layer support: "
                "the early-exit draft needs the Llama-family call path")
    if max_new_tokens <= 0:
        return (prompt, {}) if return_stats else prompt
    # the loop stops exactly at the budget, so the buffer needs slack
    # only for the FINAL iteration: <= D committed tokens of overshoot
    # plus its D+1 written predictions
    L = t0 + max_new_tokens + 2 * (D + 1)
    if L > int(model.max_len):
        raise ValueError(
            f"prompt + max_new_tokens + draft slack = {L} exceeds "
            f"model.max_len = {model.max_len}"
        )
    window = int(getattr(model, "window", 0) or 0)
    if 0 < window <= L:
        raise ValueError(
            f"speculative decoding needs a non-rolling cache: window "
            f"{window} <= prompt + budget + slack {L} would evict rows "
            "that rejection must rewind"
        )

    import numpy as np

    if stop_tokens is None:
        stops_arr = np.full((1,), -1, np.int64)
    else:
        flat = [int(s) for s in stop_tokens]
        if any(s < 0 for s in flat):
            raise ValueError(f"negative stop token in {flat}")
        stops_arr = (np.asarray(flat, np.int64) if flat
                     else np.full((1,), -1, np.int64))
    run = _spec_loop(model, L, D, g, t0, max_new_tokens,
                     float(temperature), int(top_k), float(top_p),
                     padded=pad > 0, n_stop=int(stops_arr.shape[0]),
                     draft_layers=draft_layers)
    rng = rng if rng is not None else jax.random.key(0)
    toks, n, iters = run(params, prompt, rng, jnp.int32(pad),
                         jnp.asarray(stops_arr, jnp.int32))

    # strip any bucket padding: callers get their own layout back;
    # positions past the committed count are junk from the final
    # iteration's chunk write — mask them to pad id 0 (they are only
    # reachable when a stop exits the loop before the budget).
    # Committed generated tokens are positions t0..n-1, i.e. n - t0 of
    # them (the budget exit always overshoots to >= max_new + 1, so
    # the clamp reports max_new exactly as before; the stop exit can
    # commit fewer, and THERE the count must include the stop token).
    emitted = min(int(n) - t0, max_new_tokens)
    out = toks[None, pad: t0 + max_new_tokens]
    if stop_tokens is not None and emitted < max_new_tokens:
        keep = np.arange(out.shape[1]) < (t0 - pad) + emitted
        out = jnp.where(jnp.asarray(keep)[None, :], out, 0)
    if return_stats:
        stats = {
            "model_calls": int(iters),
            # actual emissions: < max_new_tokens when a stop exited
            # the loop early (the budget-exhausted case may commit
            # overshoot, clamped as before)
            "tokens_emitted": emitted,
            "stopped": bool(stop_tokens is not None
                            and emitted < max_new_tokens),
            # numerator clamped to tokens actually RETURNED: the final
            # chunk may commit past max_new_tokens, and counting that
            # overshoot would inflate the reported acceptance rate
            "tokens_per_call": round(
                float(emitted) / max(int(iters), 1), 3
            ),
        }
        return out, stats
    return out


def speculative_from_cache(model, params, prompt_ids, cache, last_logits,
                           total: int, max_new_tokens: int,
                           draft_len: int = 4, ngram: int = 2,
                           temperature: float = 0.0, top_k: int = 0,
                           top_p: float = 0.0,
                           rng: Optional[jax.Array] = None,
                           stop_tokens=None, draft_layers: int = 0):
    """Speculative decoding continuing from an externally-prefilled
    cache — the POOL-SHARED serving path (ISSUE 7): the caller builds
    ``cache`` via ``kvcache.PrefixCache.warm_prefill(params, ids,
    total)`` (cached prefix blocks + suffix-only prefill), so both the
    target and its early-exit draft (``draft_layers``) skip the shared
    prefix's prefill entirely — one cache, one pool, zero extra
    memory. Contract: ``cache`` length ``total`` with ``pos_index ==
    len(prompt_ids)``; ``last_logits`` are the prompt's last-position
    logits. Output is token-identical (greedy) / distribution-exact
    (sampled) to ``generate_speculative`` on the same inputs — the
    same loop executable runs, only the prefill differs. Returns
    ``(out [1, t0 + max_new], stats)``."""
    import numpy as np

    t0 = len(prompt_ids)
    D, g = int(draft_len), int(ngram)
    max_new_tokens = int(max_new_tokens)
    L = int(total)
    if L < t0 + max_new_tokens + 2 * (D + 1):
        raise ValueError(
            f"cache length {L} lacks the spec loop's overshoot slack "
            f"(need >= {t0 + max_new_tokens + 2 * (D + 1)})")
    if not draft_layers and t0 < g:
        raise ValueError(f"prompt length {t0} < ngram {g}")
    if stop_tokens is None:
        stops_arr = np.full((1,), -1, np.int64)
    else:
        flat = [int(s) for s in stop_tokens]
        stops_arr = (np.asarray(flat, np.int64) if flat
                     else np.full((1,), -1, np.int64))
    prompt = jnp.asarray(np.asarray(prompt_ids, np.int32)[None, :])
    run = _spec_loop(model, L, D, g, t0, max_new_tokens,
                     float(temperature), int(top_k), float(top_p),
                     padded=False, n_stop=int(stops_arr.shape[0]),
                     draft_layers=int(draft_layers), external=True)
    rng = rng if rng is not None else jax.random.key(0)
    toks, n, iters = run(params, prompt, rng, jnp.int32(0),
                         jnp.asarray(stops_arr, jnp.int32),
                         (dict(cache), last_logits))
    emitted = min(int(n) - t0, max_new_tokens)
    out = toks[None, : t0 + max_new_tokens]
    if stop_tokens is not None and emitted < max_new_tokens:
        keep = np.arange(out.shape[1]) < t0 + emitted
        out = jnp.where(jnp.asarray(keep)[None, :], out, 0)
    stats = {
        "model_calls": int(iters),
        "tokens_emitted": emitted,
        "stopped": bool(stop_tokens is not None
                        and emitted < max_new_tokens),
        "tokens_per_call": round(float(emitted) / max(int(iters), 1), 3),
    }
    return out, stats


@functools.lru_cache(maxsize=32)
def _spec_loop(model, L: int, D: int, g: int, t0: int, max_new: int,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 0.0, padded: bool = False,
               n_stop: int = 1, draft_layers: int = 0,
               external: bool = False):
    """Compiled speculative generation: ONE dispatch per request —
    zero cache build, prompt prefill, token-buffer setup, and a
    ``lax.while_loop`` that drafts by n-gram lookup, verifies with one
    ``D+1``-token model call per iteration, commits the accepted
    prefix, rewinds ``pos_index``, and exits exactly when ``max_new``
    tokens are committed.

    ``temperature > 0`` switches verification from greedy
    prefix-match to rejection sampling against the filtered target
    distribution (see ``generate_speculative`` for the exactness
    argument); the greedy path is bit-identical to before.

    Everything lives in one executable because on tunneled devices the
    per-FENCED-dispatch round trip is ~105 ms and an eagerly-built
    cache pytree costs ~0.5 s of small allocation dispatches (measured,
    BASELINE.md) — per-request costs that swamp the ~0.5-3 ms verify
    calls. Round 3 shipped host-chunked ``lax.scan`` calls instead,
    citing measured ~16x cliffs for ``lax.while_loop`` and the
    token-buffer DUS; those measurements timed the tunnel's
    first-dispatch lazy-warmup (BASELINE.md "prefill anomaly,
    resolved"), not the program.

    The ``iters < max_new`` cap is belt-and-suspenders (each iteration
    commits >= 1 token, so the commit condition terminates first).

    ``draft_layers > 0`` drafts with the early-exit head instead of
    n-gram lookup (see ``generate_speculative``). ``external=True``
    compiles the ``run_from_cache`` twin: the caller supplies a WARM
    cache of length ``L`` with ``pos_index == t0`` plus the prompt's
    last-position logits — the pool-shared serving path
    (engine/serving), where kvcache.warm_prefill builds the cache from
    radix blocks so BOTH the target and the early-exit draft skip the
    shared prefix's prefill."""
    from jax import lax

    from ..parallel.tp import constrain_kv_tree

    greedy = temperature <= 0
    mesh = getattr(model, "mesh", None)

    @jax.jit
    def run(params, prompt, rng, pad_len, stops, ext=None):
        extra = ({"pad_lens": pad_len[None]} if padded else {})
        if external:
            # warm entry: cache + last logits arrive prefilled (the
            # prefix pool's suffix-only prefill); invariant pos_index
            # == t0 holds by the warm_prefill contract
            cache, logits_last = ext
            cache = dict(cache)
        else:
            # zero KV cache, built in-graph (shapes via eval_shape at
            # trace time — no device work on the host path)
            shapes = jax.eval_shape(
                lambda p: model.apply(
                    {"params": p}, jnp.zeros((1, L), jnp.int32),
                    train=False, decode=True, mutable=["cache"],
                ),
                params,
            )[1]["cache"]
            cache = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), shapes
            )
            cache = constrain_kv_tree(cache, mesh)  # TP head sharding
            # bucket padding (pad_to): pad slots masked from attention
            logits, vs = model.apply(
                {"params": params, "cache": cache}, prompt,
                train=False, decode=True, prefill=True,
                mutable=["cache"], **extra,
            )
            cache = vs["cache"]
            logits_last = logits[:, -1]
        # two disjoint streams: the prefill token's and the loop's
        # (folding iters directly off ``rng`` could collide with the
        # prefill key at iteration counts past the constant)
        rng0, rng_loop = jax.random.split(rng)
        if greedy:
            token0 = jnp.argmax(logits_last, axis=-1).astype(jnp.int32)
        else:
            token0 = sample_logits(
                rng0, logits_last.astype(jnp.float32),
                temperature, top_k, top_p,
            )
        toks = jnp.zeros((L,), jnp.int32)
        toks = lax.dynamic_update_slice(toks, prompt[0], (0,))
        toks = lax.dynamic_update_slice(toks, token0, (t0,))
        # n = committed tokens; the token at n-1 is committed but not
        # yet in the KV cache (invariant: cache pos_index == n - 1)
        n = jnp.int32(t0 + 1)
        # the prefill token itself can be a stop (stops is -1-padded,
        # ids are non-negative, so no-stop configs never match)
        done0 = _isin(token0, stops)[0]
        starts = jnp.arange(L - g + 1)

        def cond(state):
            toks, n, iters, cur_cache, done = state
            return (n - t0 - 1 < max_new) & (iters < max_new) & ~done

        def body(state):
            toks, n, iters, cur_cache, done = state
            if draft_layers > 0:
                # --- draft MODEL: D sequential early-exit steps (the
                # target's first ``draft_layers`` blocks + head) over
                # the SAME cache — each step writes the visited layers'
                # K/V at the speculative position, which the verify
                # pass below recomputes identically (accepted tokens)
                # or rewinds past (rejected); greedy proposals keep the
                # sampled-mode rejection math exact
                def draft_one(j, st):
                    dcache, cur, dr = st
                    dlogits, dvs = model.apply(
                        {"params": params, "cache": dcache}, cur,
                        train=False, decode=True, mutable=["cache"],
                        exit_layer=draft_layers, **extra,
                    )
                    nxt = jnp.argmax(dlogits[0, -1],
                                     axis=-1).astype(jnp.int32)
                    return (dict(dvs["cache"]), nxt[None, None],
                            dr.at[j].set(nxt))

                cur0 = lax.dynamic_slice(toks, (n - 1,), (1,))[None, :]
                dcache, _, draft = lax.fori_loop(
                    0, D, draft_one,
                    (dict(cur_cache), cur0, jnp.zeros((D,), jnp.int32)))
                # rewind the shared position counter for the verify
                # pass (the draft advanced it by D)
                ver_cache = dict(dcache)
                ver_cache["pos_index"] = n - 1
            else:
                # --- draft: latest earlier occurrence of the trailing
                # g-gram (g static shift-compares, not a [L, g] gather —
                # the gather form measured ~35% slower on the current
                # toolchain)
                key = lax.dynamic_slice(toks, (n - g,), (g,))
                match = jnp.ones((L - g + 1,), bool)
                for j in range(g):
                    match = match & (toks[j: L - g + 1 + j] == key[j])
                # continuation must lie in committed history, and the
                # match at i = n-g is the key itself — exclude it;
                # bucket-pad slots are excluded too (drafting from pad
                # zeros would only waste verify slots, never corrupt
                # output)
                valid = (starts + g) < n
                if padded:
                    valid = valid & (starts >= pad_len)
                cand = jnp.where(match & valid, starts, -1)
                i = jnp.max(cand)
                cont = jnp.where(i >= 0, i + g, n - 1)
                draft = lax.dynamic_slice(toks, (cont,), (D,))
                ver_cache = cur_cache

            # --- verify: one chunked decode call on [last, d_1..d_D]
            chunk = lax.dynamic_slice(toks, (n - 1,), (1,))
            chunk = jnp.concatenate([chunk, draft])[None, :]  # [1, D+1]
            logits, vs = model.apply(
                {"params": params, "cache": ver_cache}, chunk,
                train=False, decode=True, mutable=["cache"], **extra,
            )
            if greedy:
                preds = jnp.argmax(logits[0], axis=-1).astype(jnp.int32)
                na = jnp.sum(jnp.cumprod(
                    (draft == preds[:D]).astype(jnp.int32)
                ))
                # committed this round: preds[0..na] (the accepted
                # draft prefix equals the predictions, plus one fresh
                # token); stale buffer/cache rows beyond the commit
                # point are invisible (pos_index rewind) and
                # overwritten next round
                write = preds
            else:
                # rejection sampling against the filtered target
                # distribution p_j at each draft position: the n-gram
                # drafter is deterministic, so accept d_j w.p.
                # p_j(d_j); the first rejected position resamples from
                # p with d_j zeroed, renormalized; if ALL D accept,
                # the bonus position D samples from p_D untouched.
                # Each emitted token is exactly p-distributed.
                flogits = filter_logits(
                    logits[0].astype(jnp.float32), temperature,
                    top_k, top_p,
                )                                       # [D+1, V]
                probs = jax.nn.softmax(flogits, axis=-1)
                it_key = jax.random.fold_in(rng_loop, iters)
                k_acc, k_res = jax.random.split(it_key)
                p_draft = jnp.take_along_axis(
                    probs[:D], draft[:, None], axis=1
                )[:, 0]                                  # [D]
                u = jax.random.uniform(k_acc, (D,))
                na = jnp.sum(jnp.cumprod(
                    (u < p_draft).astype(jnp.int32)
                ))
                # residual/bonus distribution at the commit position
                res_logits = flogits[na]
                res_logits = jnp.where(
                    (na < D)
                    & (jnp.arange(res_logits.shape[0])
                       == draft[jnp.minimum(na, D - 1)]),
                    -jnp.inf, res_logits,
                )
                fresh = jax.random.categorical(
                    k_res, res_logits
                ).astype(jnp.int32)
                # write vector: accepted draft prefix, then the fresh
                # token at position na; beyond is junk (invisible via
                # the pos_index rewind, overwritten next round)
                pos = jnp.arange(D + 1)
                write = jnp.where(
                    pos < na,
                    jnp.concatenate([draft, draft[-1:]]),
                    fresh,
                )
            # a stop token inside the committed prefix truncates the
            # commit there (drafts PAST a stop are rejected — VERDICT
            # r4 missing #1); tokens beyond stay junk in the buffer,
            # invisible via the pos_index rewind and masked by the
            # caller
            c0 = na + 1
            cpos = jnp.arange(D + 1)
            hit = _isin(write, stops) & (cpos < c0)
            any_hit = jnp.any(hit)
            c = jnp.where(any_hit, jnp.argmax(hit) + 1, c0)
            toks = lax.dynamic_update_slice(toks, write, (n,))
            new_cache = dict(vs["cache"])
            new_cache["pos_index"] = n + c - 1
            return (toks, n + c, iters + 1, new_cache, done | any_hit)

        toks, n, iters, cache, _ = lax.while_loop(
            cond, body, (toks, n, jnp.int32(0), cache, done0)
        )
        return toks, n, iters

    return run


@functools.lru_cache(maxsize=32)
def _prefill_fresh(model, total: int):
    """Compiled (zero cache build + prompt prefill) pair per (model,
    cache length): one dispatch where ``fresh_cache`` + ``prefill``
    was ~50 (the per-request serving hot path). Batch size
    specializes by trace like any other jit dimension."""

    from ..parallel.tp import constrain_kv_tree

    mesh = getattr(model, "mesh", None)

    @jax.jit
    def go(params, prompt, pad_lens=None):
        b = prompt.shape[0]
        shapes = jax.eval_shape(
            lambda p: model.apply(
                {"params": p}, jnp.zeros((b, total), jnp.int32),
                train=False, decode=True, mutable=["cache"],
            ),
            params,
        )[1]["cache"]
        cache = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes
        )
        # TP serving: pin the fresh cache's K/V leaves to the head
        # sharding before the prefill writes land (without this GSPMD
        # may replicate the zeros and all-gather heads every step)
        cache = constrain_kv_tree(cache, mesh)
        extra = {} if pad_lens is None else {"pad_lens": pad_lens}
        logits, vs = model.apply(
            {"params": params, "cache": cache}, prompt,
            train=False, decode=True, prefill=True, mutable=["cache"],
            **extra,
        )
        return logits[:, -1], vs["cache"]

    return go


@functools.lru_cache(maxsize=32)
def _decode_fns(model, temperature: float, top_k: int, top_p: float = 0.0):
    """Compiled (prefill, step) pair per (model, sampling) combination.

    Module-level cache so repeated ``generate()`` calls with the same
    model reuse the XLA executables instead of recompiling per call
    (flax modules are frozen dataclasses — hashable as long as their
    fields are, which holds for the in-tree model zoo).
    """

    @jax.jit
    def prefill(params, cache, tokens):
        # prefill=True (static): fresh cache at position 0, so attention
        # routes through the flash kernel instead of the cached-einsum
        # path — the [T0, cache_len] f32 score tensor never materializes
        logits, vs = model.apply(
            {"params": params, "cache": cache}, tokens,
            train=False, decode=True, prefill=True, mutable=["cache"],
        )
        return logits[:, -1], vs["cache"]

    @jax.jit
    def step(params, cache, token, keys, pad_lens=None):
        # keys: [B] per-row streams (generate._fold_all_rows) — sampling
        # is row-independent, so batching requests never changes a row
        extra = {} if pad_lens is None else {"pad_lens": pad_lens}
        logits, vs = model.apply(
            {"params": params, "cache": cache}, token[:, None],
            train=False, decode=True, mutable=["cache"], **extra,
        )
        nxt = _sample_rows(keys, logits[:, -1], temperature, top_k, top_p)
        return nxt, vs["cache"]

    return prefill, step
