"""Autoregressive generation with KV-cached incremental decoding.

The reference has no inference path beyond batch evaluation (its
``test.py`` computes metrics, /root/reference/test.py:64-101); a framework
with a GPT-2 family needs actual sampling. TPU-shaped design:

- ONE compiled step function reused for every generated token (static
  shapes: the KV cache is pre-allocated at ``prompt + max_new_tokens`` and
  written in place via ``dynamic_update_slice`` — no growing arrays, no
  per-step recompiles);
- prefill processes the whole prompt in a single call (big matmuls for the
  MXU), then the loop feeds one token at a time;
- sampling (temperature / top-k / greedy) runs in-graph on the logits.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def sample_logits(key, logits, temperature: float = 1.0, top_k: int = 0,
                  top_p: float = 0.0):
    """Sample token ids from ``[B, V]`` logits (in-graph).

    ``temperature <= 0`` means greedy argmax. ``top_k > 0`` restricts
    sampling to the k highest-probability tokens. ``top_p`` in (0, 1)
    applies nucleus sampling: the smallest set of tokens whose cumulative
    probability reaches ``top_p`` (the top token always survives).
    ``top_k`` and ``top_p`` compose (k-filter first, as in HF).
    """
    if temperature <= 0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if 0.0 < top_p < 1.0:
        # sort descending; keep tokens while the cumulative probability of
        # STRICTLY-higher-ranked tokens is < top_p (so the boundary token
        # that crosses the threshold is kept, like HF's implementation)
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1) - probs
        keep = cum < top_p                       # [B, V] in sorted order
        # threshold logit = smallest kept logit per row
        thresh = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < thresh, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def generate(model, params, prompt: jnp.ndarray, max_new_tokens: int,
             temperature: float = 1.0, top_k: int = 0, top_p: float = 0.0,
             rng: Optional[jax.Array] = None) -> jnp.ndarray:
    """Generate ``max_new_tokens`` continuations for each prompt row.

    :param model: a TransformerLM-family module (``decode=True`` support).
    :param params: trained params pytree (e.g. ``state.params`` or
        ``state.ema_params``).
    :param prompt: ``[B, T0]`` int32 token ids (T0 >= 1).
    :param rng: PRNG key for sampling (defaults to key(0); unused when
        greedy).
    :returns: ``[B, T0 + max_new_tokens]`` tokens (prompt included).
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    b, t0 = prompt.shape
    max_new_tokens = int(max_new_tokens)
    if max_new_tokens <= 0:
        return prompt
    total = t0 + max_new_tokens
    if total > model.max_len:
        raise ValueError(
            f"prompt + max_new_tokens = {total} exceeds model.max_len "
            f"= {model.max_len}"
        )
    rng = rng if rng is not None else jax.random.key(0)

    # 1) allocate the [B, total] KV caches from SHAPES only (eval_shape:
    # no FLOPs run); all cache variables initialize to zeros, so a zeros
    # pytree of the right shapes/dtypes is exactly the fresh cache
    shapes = jax.eval_shape(
        lambda p: model.apply(
            {"params": p}, jnp.zeros((b, total), jnp.int32),
            train=False, decode=True, mutable=["cache"],
        ),
        params,
    )
    cache = jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes[1]["cache"]
    )

    prefill, step = _decode_fns(model, float(temperature), int(top_k),
                                float(top_p))
    last_logits, cache = prefill(params, cache, prompt)
    keys = jax.random.split(rng, max_new_tokens)
    token = sample_logits(keys[0], last_logits, temperature, top_k, top_p)
    # tokens stay on device through the loop (no per-step host sync);
    # async dispatch pipelines the steps
    out = [prompt, token[:, None]]
    for i in range(1, max_new_tokens):
        token, cache = step(params, cache, token, keys[i])
        out.append(token[:, None])
    return jnp.concatenate(out, axis=1)


@functools.lru_cache(maxsize=32)
def _decode_fns(model, temperature: float, top_k: int, top_p: float = 0.0):
    """Compiled (prefill, step) pair per (model, sampling) combination.

    Module-level cache so repeated ``generate()`` calls with the same
    model reuse the XLA executables instead of recompiling per call
    (flax modules are frozen dataclasses — hashable as long as their
    fields are, which holds for the in-tree model zoo).
    """

    @jax.jit
    def prefill(params, cache, tokens):
        # prefill=True (static): fresh cache at position 0, so attention
        # routes through the flash kernel instead of the cached-einsum
        # path — the [T0, cache_len] f32 score tensor never materializes
        logits, vs = model.apply(
            {"params": params, "cache": cache}, tokens,
            train=False, decode=True, prefill=True, mutable=["cache"],
        )
        return logits[:, -1], vs["cache"]

    @jax.jit
    def step(params, cache, token, key):
        logits, vs = model.apply(
            {"params": params, "cache": cache}, token[:, None],
            train=False, decode=True, mutable=["cache"],
        )
        nxt = sample_logits(key, logits[:, -1], temperature, top_k, top_p)
        return nxt, vs["cache"]

    return prefill, step
