"""Metric functions.

Reference: ``model/metric.py`` — ``accuracy`` and ``top_k_acc``
(/root/reference/model/metric.py:4-20), computed there on the full gathered
prediction set on rank 0. Here metrics are per-example indicator functions
``(output, target) -> [B]`` reduced **in-graph** as masked sufficient
statistics (sum, count) — numerically identical to gathering everything, but
the data never leaves the devices (SURVEY.md §3.5 "TPU equivalent").
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..config.registry import METRICS
from .losses import chunk_shifted_sequence


@METRICS.register("accuracy")
def accuracy(output, target):
    pred = jnp.argmax(output, axis=-1)
    return (pred == target).astype(jnp.float32)


@METRICS.register("top_k_acc")
def top_k_acc(output, target, k: int = 3):
    _, topk = jax.lax.top_k(output, k)
    hit = (topk == target[..., None]).any(axis=-1)
    return hit.astype(jnp.float32)


@METRICS.register("lm_token_accuracy")
def lm_token_accuracy(output, target):
    """Next-token accuracy for LM heads: output [B,T,V], target [B,T].

    Also accepts the ``fused_head`` model's ``(hidden [B,T,D], head_w
    [D,V])`` tuple, computing argmax per 256-token chunk so the full
    logits tensor stays unmaterialized here too."""
    if isinstance(output, tuple):
        h, w = output
        tm1 = h.shape[1] - 1
        b = h.shape[0]
        # pad_label=-1 never matches an argmax, so padding rows count 0
        h_c, l_c, _ = chunk_shifted_sequence(
            h[:, :-1], target[:, 1:], chunk=256, pad_label=-1
        )

        def body(carry, inp):
            hc, lc = inp
            pred = jnp.argmax((hc @ w).astype(jnp.float32), axis=-1)
            return carry + jnp.sum((pred == lc).astype(jnp.float32), -1), None

        hits, _ = jax.lax.scan(
            body, jnp.zeros((b,), jnp.float32), (h_c, l_c)
        )
        return hits / tm1
    pred = jnp.argmax(output[:, :-1], axis=-1)
    hit = (pred == target[:, 1:]).astype(jnp.float32)
    return hit.mean(axis=-1)


@METRICS.register("lm_bits_per_byte")
def lm_bits_per_byte(output, target):
    """Per-example next-token cross entropy in BITS — the standard
    byte-LM quality number when tokens are raw bytes (vocab 256), e.g.
    the real-corpus runs behind BASELINE.md's learning evidence
    (8.0 = uniform random, lower is better). Accepts the same plain
    [B,T,V] or fused-head ``(hidden, head_w)`` outputs as
    ``lm_token_accuracy``, delegating the CE math to the loss
    implementations so the two can never drift."""
    from .losses import fused_lm_cross_entropy, lm_cross_entropy

    ln2 = 0.6931471805599453
    if isinstance(output, tuple):
        return fused_lm_cross_entropy(chunk=256)(output, target) / ln2
    return lm_cross_entropy(output, target) / ln2


@METRICS.register("lm_nll")
def lm_nll(output, target):
    """Per-example next-token negative log likelihood in NATS/token —
    the subword-vocab counterpart of ``lm_bits_per_byte`` (whose
    per-BYTE interpretation only holds at vocab 256). Reported as NLL
    rather than perplexity because mean-of-per-example-perplexities is
    not corpus perplexity; ``ppl = exp(lm_nll)`` is the right reading
    of the aggregated value. Same plain/[B,T,V]-or-fused dispatch as
    the other LM metrics, delegated to the loss implementations."""
    from .losses import fused_lm_cross_entropy, lm_cross_entropy

    if isinstance(output, tuple):
        return fused_lm_cross_entropy(chunk=256)(output, target)
    return lm_cross_entropy(output, target)


@METRICS.register("mlm_accuracy")
def mlm_accuracy(output, target):
    """Per-example accuracy at the MASKED positions of the BERT MLM
    pair ``(logits, mask)`` (models/bert.py) against the original
    tokens — the quality number for masked-LM pretraining."""
    logits, sel = output
    hit = (jnp.argmax(logits, axis=-1) == target).astype(jnp.float32)
    denom = jnp.maximum(sel.sum(axis=-1), 1.0)
    return (hit * sel).sum(axis=-1) / denom
