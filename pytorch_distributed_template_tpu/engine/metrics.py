"""Metric functions.

Reference: ``model/metric.py`` — ``accuracy`` and ``top_k_acc``
(/root/reference/model/metric.py:4-20), computed there on the full gathered
prediction set on rank 0. Here metrics are per-example indicator functions
``(output, target) -> [B]`` reduced **in-graph** as masked sufficient
statistics (sum, count) — numerically identical to gathering everything, but
the data never leaves the devices (SURVEY.md §3.5 "TPU equivalent").
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..config.registry import METRICS


@METRICS.register("accuracy")
def accuracy(output, target):
    pred = jnp.argmax(output, axis=-1)
    return (pred == target).astype(jnp.float32)


@METRICS.register("top_k_acc")
def top_k_acc(output, target, k: int = 3):
    _, topk = jax.lax.top_k(output, k)
    hit = (topk == target[..., None]).any(axis=-1)
    return hit.astype(jnp.float32)


@METRICS.register("lm_token_accuracy")
def lm_token_accuracy(output, target):
    """Next-token accuracy for LM heads: output [B,T,V], target [B,T]."""
    pred = jnp.argmax(output[:, :-1], axis=-1)
    hit = (pred == target[:, 1:]).astype(jnp.float32)
    return hit.mean(axis=-1)
