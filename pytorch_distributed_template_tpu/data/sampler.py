"""Deterministic sharded index sampling.

Replicates ``torch.utils.data.DistributedSampler`` semantics — the
reference's data-sharding mechanism (/root/reference/data_loader/
data_loaders.py:23-26, base/base_data_loader.py:11-19) — without torch:

- the index set is padded **by duplication** up to a multiple of the shard
  count (parity with DistributedSampler's wraparound padding; SURVEY.md §7
  hard-part (c)),
- shard ``i`` takes indices ``i::num_shards`` (strided assignment),
- shuffling permutes globally with a seed derived from ``(seed, epoch)`` so
  every shard sees the same permutation (``set_epoch`` parity).

In the TPU framework shards are **hosts** (process_index), not devices: a
single process feeds its whole local mesh slice and ``jit`` shards the batch
over devices. ``pad_mask()`` additionally exposes which indices are
duplicates so evaluation can compute exact (unpadded) metrics — an option the
reference lacks.
"""
from __future__ import annotations

import numpy as np


def epoch_permutation(seed: int, epoch: int, n: int) -> np.ndarray:
    """The framework's canonical per-epoch permutation: Philox keyed from
    ``SeedSequence((seed, epoch))`` so (a) every host derives the same global
    order from the same ``(seed, epoch)`` and (b) different epochs draw from
    *independent* streams (a raw counter offset of ``epoch`` would only shift
    the stream by one block, leaving consecutive epochs correlated)."""
    rng = np.random.Generator(
        np.random.Philox(np.random.SeedSequence((seed, epoch)))
    )
    return rng.permutation(n)


class ShardedSampler:
    def __init__(self, num_samples: int, num_shards: int = 1,
                 shard_index: int = 0, shuffle: bool = True, seed: int = 0):
        if not 0 <= shard_index < num_shards:
            raise ValueError(
                f"shard_index {shard_index} out of range for {num_shards} shards"
            )
        if num_samples <= 0:
            raise ValueError("num_samples must be positive")
        self.num_samples = num_samples
        self.num_shards = num_shards
        self.shard_index = shard_index
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        # ceil(n / shards) * shards, like DistributedSampler
        self.total_size = -(-num_samples // num_shards) * num_shards
        self.shard_size = self.total_size // num_shards

    def set_epoch(self, epoch: int) -> None:
        """Reseed the per-epoch permutation (DistributedSampler.set_epoch)."""
        self.epoch = epoch

    def _global_indices(self) -> np.ndarray:
        if self.shuffle:
            idx = epoch_permutation(self.seed, self.epoch, self.num_samples)
        else:
            idx = np.arange(self.num_samples)
        pad = self.total_size - self.num_samples
        if pad:
            # duplicate-padding, cycling when pad > n (e.g. 1 sample over
            # 3 shards needs the sample repeated twice) — same wraparound
            # as DistributedSampler's repeated-indices padding
            idx = np.resize(idx, self.total_size)
        return idx

    def indices(self) -> np.ndarray:
        """This shard's indices for the current epoch."""
        return self._global_indices()[self.shard_index :: self.num_shards]

    def pad_mask(self) -> np.ndarray:
        """True where this shard's index is real data (not duplicate padding).

        Padding occupies the tail of the *global* order, so positions
        >= num_samples in the global array are flagged.
        """
        positions = np.arange(self.shard_index, self.total_size, self.num_shards)
        return positions < self.num_samples

    def state(self) -> dict:
        """Serializable shard cursor for the checkpoint ``data_state``
        sidecar (resilience subsystem): everything needed to prove a
        resumed run reconstructs this shard's exact order — the order
        itself is a pure function of ``(seed, epoch)``, so no index
        arrays travel, only the knobs that derive them."""
        return {
            "num_samples": self.num_samples,
            "num_shards": self.num_shards,
            "shard_index": self.shard_index,
            "shuffle": self.shuffle,
            "seed": self.seed,
            "epoch": self.epoch,
        }

    def __iter__(self):
        return iter(self.indices())

    def __len__(self) -> int:
        return self.shard_size
