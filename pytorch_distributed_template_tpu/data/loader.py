"""Batched loading and host->device prefetch.

TPU-native replacement for the reference's data layer
(/root/reference/base/base_data_loader.py + torch DataLoader workers):

- ``ArrayDataLoader`` batches in-memory numpy arrays with the reference's
  sampler contract: a sampler forces ``shuffle=False`` and owns the order
  (base_data_loader.py:11-19); otherwise a plain seeded shuffle.
- ``prefetch_to_device`` replaces torch's pinned-memory H2D copies
  (trainer/trainer.py:46 does a blocking ``.to(device)`` per step) with a
  double-buffered pipeline: batch N+k is already being transferred (and, on
  multi-host, assembled into a globally-sharded ``jax.Array``) while the TPU
  computes step N. Transfers land directly in each device's HBM slice
  according to the batch sharding.

Heavy per-sample decode (ImageNet-scale) belongs in a grain pipeline; for the
array-backed datasets in-tree this loader is already IO-free after startup.
"""
from __future__ import annotations

import collections
import queue
import threading
from typing import Iterable, Iterator, Optional

import jax
import numpy as np

from . import native
from ..observability.trace import span
from ..resilience import faults
from .sampler import ShardedSampler, epoch_permutation


class ArrayDataLoader:
    """Iterate dict-of-array datasets in batches.

    :param arrays: dict of same-leading-dim numpy arrays, e.g.
        ``{"image": [N,H,W,C], "label": [N]}``.
    :param batch_size: per-host batch size (the global batch when
        single-host; ``jit`` further shards it over local devices).
    :param shuffle: seeded reshuffle each epoch (ignored when sampler given).
    :param sampler: optional ShardedSampler owning the index order.
    :param drop_last: drop the trailing partial batch. When False the last
        batch is padded by wraparound duplication and ``batch["mask"]`` marks
        real rows — static shapes for XLA, exact metrics for eval.
    :param normalize: optional ``{"key": "image", "mean": [...],
        "std": [...]}``. When the named array is uint8 with a trailing
        channel dim, batches come out float32 ``(x/255 - mean)/std`` via the
        fused native gather (one pass) — uint8 on-disk datasets are 4x
        smaller than float32 with no extra host traversals.
        ``"on_device": true`` defers the conversion past the host->device
        copy instead: batches keep the image uint8 (4x less transfer
        traffic — the PCIe/link bandwidth win) and ``device_transform``
        normalizes on the accelerator, where XLA fuses it into the first
        consumer op. The trainer/evaluator apply it automatically via
        ``prefetch_to_device(..., transform=...)``.
    """

    def __init__(self, arrays: dict, batch_size: int, shuffle: bool = True,
                 sampler: Optional[ShardedSampler] = None,
                 drop_last: bool = False, seed: int = 0,
                 normalize: Optional[dict] = None):
        if not arrays:
            raise ValueError("arrays must be a non-empty dict")
        lens = {k: len(v) for k, v in arrays.items()}
        if len(set(lens.values())) != 1:
            raise ValueError(f"all arrays must share the leading dim, got {lens}")
        self.arrays = arrays
        self.n_samples = next(iter(lens.values()))
        self.batch_size = int(batch_size)
        self.sampler = sampler
        # Reference parity: an explicit sampler owns ordering, shuffle off
        # (base_data_loader.py:11-15).
        self.shuffle = bool(shuffle) and sampler is None
        self.drop_last = bool(drop_last)
        self.seed = seed
        self.epoch = 0
        self.normalize = dict(normalize) if normalize else None
        self._norm_on_device = False
        if self.normalize:
            if not ("mean" in self.normalize and "std" in self.normalize):
                raise ValueError("normalize needs 'mean' and 'std'")
            nkey = self.normalize.get("key", "image")
            if nkey not in arrays:
                raise ValueError(
                    f"normalize key {nkey!r} not in arrays "
                    f"{sorted(arrays)}"
                )
            if arrays[nkey].dtype != np.uint8:
                raise ValueError(
                    f"normalize targets uint8 storage; array {nkey!r} is "
                    f"{arrays[nkey].dtype} — pre-normalized data should "
                    "drop the normalize option"
                )
            self._norm_on_device = bool(self.normalize.get("on_device"))

    @property
    def device_transform(self):
        """Post-H2D batch transform (jitted, cached), or None.

        With ``normalize.on_device`` the uint8 image crosses the link
        raw; this function does ``(x/255 - mean)/std`` on the
        accelerator (fused by XLA into the first consumer). Cached on
        the loader so epochs reuse one compiled program. Batches without
        the normalize key (e.g. an init template dict holding a
        different input key) pass through unchanged.
        """
        if not self._norm_on_device:
            return None
        if getattr(self, "_device_transform_fn", None) is None:
            import jax
            import jax.numpy as jnp

            key = self.normalize.get("key", "image")
            mean = jnp.asarray(self.normalize["mean"], jnp.float32)
            std = jnp.asarray(self.normalize["std"], jnp.float32)

            def transform(batch: dict) -> dict:
                if key not in batch:
                    return batch
                x = batch[key].astype(jnp.float32) / 255.0
                return {**batch, key: (x - mean) / std}

            self._device_transform_fn = jax.jit(transform)
        return self._device_transform_fn

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        if self.sampler is not None:
            self.sampler.set_epoch(epoch)

    def _epoch_indices(self):
        if self.sampler is not None:
            return self.sampler.indices(), self.sampler.pad_mask()
        if self.shuffle:
            idx = epoch_permutation(self.seed, self.epoch, self.n_samples)
        else:
            idx = np.arange(self.n_samples)
        return idx, np.ones(len(idx), dtype=bool)

    def __iter__(self) -> Iterator[dict]:
        return self.iter_batches()

    def iter_batches(self, start_batch: int = 0) -> Iterator[dict]:
        """Iterate the epoch's batches, optionally from batch ordinal
        ``start_batch`` (step-accurate mid-epoch resume: the trainer
        fast-forwards to the ``data_state`` sidecar's next batch
        WITHOUT gathering the skipped batches — the permutation is a
        pure function of ``(seed, epoch)``, so skipping index ranges
        is exact). Also hosts the ``loader_raise`` fault hook
        (resilience/faults), keyed by the epoch-absolute batch
        ordinal."""
        idx, mask = self._epoch_indices()
        n = len(idx)
        end = (n // self.batch_size) * self.batch_size if self.drop_last else n
        for bi, start in enumerate(range(0, end, self.batch_size)):
            if bi < start_batch:
                continue  # cheap: no gather for fast-forwarded batches
            faults.on_loader_batch(bi, loader=self)
            stop = min(start + self.batch_size, end)
            batch_idx = idx[start:stop]
            batch_mask = mask[start:stop]
            if len(batch_idx) < self.batch_size:
                # Pad to the static batch size by wraparound (np.resize tiles
                # cyclically, so even pad > n works); mask the pads.
                pad = self.batch_size - len(batch_idx)
                batch_idx = np.concatenate([batch_idx, np.resize(idx, pad)])
                batch_mask = np.concatenate(
                    [batch_mask, np.zeros(pad, dtype=bool)]
                )
            # native multithreaded gather (data/native, the torch-C++-
            # dataloader equivalent); falls back to numpy per array.
            # Virtual arrays (e.g. data/sharded.ShardedU8Array: out-of-core
            # mmap shard sets) bring their own gather methods.
            batch = {}
            for k, v in self.arrays.items():
                is_norm_key = (
                    self.normalize is not None
                    and not self._norm_on_device
                    and k == self.normalize.get("key", "image")
                    and v.dtype == np.uint8
                )
                if is_norm_key and hasattr(v, "gather_normalize"):
                    batch[k] = v.gather_normalize(
                        batch_idx,
                        np.asarray(self.normalize["mean"], np.float32),
                        np.asarray(self.normalize["std"], np.float32),
                    )
                elif is_norm_key:
                    batch[k] = native.gather_normalize_u8(
                        v, batch_idx,
                        np.asarray(self.normalize["mean"], np.float32),
                        np.asarray(self.normalize["std"], np.float32),
                    )
                elif hasattr(v, "gather"):
                    batch[k] = v.gather(batch_idx)
                else:
                    batch[k] = native.gather(v, batch_idx)
            batch["mask"] = batch_mask
            yield batch

    def __len__(self) -> int:
        idx_len = len(self.sampler) if self.sampler is not None else self.n_samples
        if self.drop_last:
            return idx_len // self.batch_size
        return -(-idx_len // self.batch_size)


def host_prefetch(iterable: Iterable, depth: int = 2) -> Iterator:
    """Assemble batches on a background thread (bounded queue).

    The role of the reference's DataLoader worker processes
    (base_data_loader.py:19 ``num_workers``): host-side batch gathering
    overlaps device compute instead of serializing with it. One thread is
    enough here because gathering is itself multithreaded (data/native).
    Worker exceptions re-raise at the consuming site.
    """
    q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
    done = object()
    stop = threading.Event()

    def worker():
        try:
            it = iter(iterable)
            while True:
                # spanned per batch: the host-gather cost is THE number
                # that says whether prefetch depth is hiding it
                with span("data/host_gather"):
                    try:
                        item = next(it)
                    except StopIteration:
                        break
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        pass
                if stop.is_set():
                    return
            q.put(done)
        except BaseException as e:  # propagate into the consumer
            if not stop.is_set():
                q.put(e)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is done:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        # abandoned early (consumer raised / generator closed): unblock the
        # worker so buffered batches don't stay pinned for the process life
        stop.set()
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break


def prefetch_to_device(iterator: Iterable[dict], sharding,
                       size: int = 2, transform=None) -> Iterator[dict]:
    """Double-buffered host->device transfer.

    Keeps ``size`` batches in flight: ``jax.device_put`` is async, so the
    transfer of batch N+1 overlaps the computation consuming batch N —
    the role torch's pinned-memory + worker prefetch plays in the reference.
    ``sharding`` is typically ``batch_sharding(mesh)``; on multi-host, use
    a sharding built from the global mesh and per-host data (the put then
    assembles a global array from each host's local shard).

    ``transform``: optional dict->dict function applied AFTER the device
    transfer — e.g. a loader's ``device_transform`` normalizing uint8
    images on the accelerator so only 1/4 of the bytes cross the link.
    Jit it at the provider (``ArrayDataLoader.device_transform`` is
    pre-jitted and cached) so repeated ``prefetch_to_device`` calls —
    one per epoch — reuse one compiled program.
    """
    queue = collections.deque()
    multihost = jax.process_count() > 1

    def _put(batch: dict) -> dict:
        with span("data/device_put"):
            if multihost:
                # Each host holds its sampler shard; assemble the global
                # array.
                out = {
                    k: jax.make_array_from_process_local_data(sharding, v)
                    for k, v in batch.items()
                }
            else:
                out = {
                    k: jax.device_put(v, sharding) for k, v in batch.items()
                }
            return transform(out) if transform is not None else out

    it = iter(iterator)
    try:
        for _ in range(size):
            queue.append(_put(next(it)))
    except StopIteration:
        pass
    while queue:
        out = queue.popleft()
        try:
            queue.append(_put(next(it)))
        except StopIteration:
            pass
        yield out
