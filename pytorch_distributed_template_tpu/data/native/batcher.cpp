// Native batch assembly: multithreaded row gather.
//
// The reference delegates per-batch sample collation to torch's C++
// DataLoader worker pool (SURVEY.md §2.2, base_data_loader.py:19). Here the
// equivalent hot operation — assembling a batch by gathering rows from a
// large contiguous array — is a parallel memcpy implemented natively and
// driven from Python via ctypes (data/native/__init__.py). At ImageNet
// shapes a batch is tens of MB; single-threaded numpy fancy indexing is
// memcpy-bound on one core, while this spreads rows across threads.
//
// Build: g++ -O3 -shared -fPIC -pthread batcher.cpp -o libbatcher.so
// (compiled on demand by data/native/__init__.py, cached in .build/).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// dst[i, :] = src[idx[i], :] for i in [0, n_idx); rows are row_bytes wide.
// idx values must be valid row numbers of src (caller-checked).
void gather_rows(const char* src, const int64_t* idx, int64_t n_idx,
                 int64_t row_bytes, char* dst, int32_t n_threads) {
  auto work = [=](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      std::memcpy(dst + i * row_bytes, src + idx[i] * row_bytes, row_bytes);
    }
  };
  if (n_threads < 1) n_threads = 1;
  // Threading only pays off past ~1 MiB of total copy.
  if (n_threads == 1 || n_idx * row_bytes < (1 << 20)) {
    work(0, n_idx);
    return;
  }
  std::vector<std::thread> ts;
  ts.reserve(n_threads);
  const int64_t chunk = (n_idx + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    const int64_t lo = t * chunk;
    const int64_t hi = std::min(n_idx, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back(work, lo, hi);
  }
  for (auto& th : ts) th.join();
}

// Fused gather + uint8->float32 + per-channel normalize:
//   dst[i, p, c] = (src[idx[i], p, c] / 255 - mean[c]) / std[c]
// for i in [0, n_idx), p pixels, c in [0, n_chan). One pass over the
// gathered bytes instead of gather-then-cast-then-normalize (three
// full-batch traversals in numpy), enabling uint8 on-disk datasets (4x
// smaller than float32) at full pipeline speed. row_elems counts uint8
// elements per row; n_chan must divide row_elems (trailing channel dim).
void gather_rows_norm_u8(const uint8_t* src, const int64_t* idx,
                         int64_t n_idx, int64_t row_elems, int64_t n_chan,
                         const float* mean, const float* stddev, float* dst,
                         int32_t n_threads) {
  // Precompute per-channel affine: x * a[c] + b[c].
  std::vector<float> a(n_chan), b(n_chan);
  for (int64_t c = 0; c < n_chan; ++c) {
    a[c] = 1.0f / (255.0f * stddev[c]);
    b[c] = -mean[c] / stddev[c];
  }
  const int64_t n_pix = row_elems / n_chan;
  auto work = [=, &a, &b](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const uint8_t* s = src + idx[i] * row_elems;
      float* d = dst + i * row_elems;
      for (int64_t p = 0; p < n_pix; ++p) {
        for (int64_t c = 0; c < n_chan; ++c) {
          d[p * n_chan + c] =
              static_cast<float>(s[p * n_chan + c]) * a[c] + b[c];
        }
      }
    }
  };
  if (n_threads < 1) n_threads = 1;
  if (n_threads == 1 || n_idx * row_elems < (1 << 20)) {
    work(0, n_idx);
    return;
  }
  std::vector<std::thread> ts;
  ts.reserve(n_threads);
  const int64_t chunk = (n_idx + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    const int64_t lo = t * chunk;
    const int64_t hi = std::min(n_idx, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back(work, lo, hi);
  }
  for (auto& th : ts) th.join();
}

}  // extern "C"
