"""ctypes binding for the native batch assembler (batcher.cpp).

Compiles the C++ on first use with the system g++ (cached in
``<repo>/.build/``), loads it via ctypes, and exposes ``gather``: a
multithreaded row-gather used by ``ArrayDataLoader`` as a drop-in fast path
for numpy fancy indexing. Degrades gracefully: any failure (no compiler,
unusual platform, non-contiguous arrays) falls back to numpy — mirroring
the reference's ability to run with ``num_workers: 0``
(/root/reference/config/debug.json).
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

_SRC = Path(__file__).parent / "batcher.cpp"
_BUILD_DIR = Path(__file__).resolve().parents[3] / ".build"
_LIB_PATH = _BUILD_DIR / "libbatcher.so"

_lock = threading.Lock()
_lib = None
_tried = False
_threads = min(8, os.cpu_count() or 1)


def _load() -> Optional[ctypes.CDLL]:
    """Compile (if stale) and load the shared library; None on failure."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if (not _LIB_PATH.exists()
                    or _LIB_PATH.stat().st_mtime < _SRC.stat().st_mtime):
                _BUILD_DIR.mkdir(parents=True, exist_ok=True)
                # per-process tmp: concurrent builders must not interleave
                # writes into one file (os.replace keeps the install atomic)
                tmp = _LIB_PATH.with_suffix(f".so.tmp{os.getpid()}")
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-pthread",
                     str(_SRC), "-o", str(tmp)],
                    check=True, capture_output=True, timeout=120,
                )
                os.replace(tmp, _LIB_PATH)
            lib = ctypes.CDLL(str(_LIB_PATH))
            lib.gather_rows.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_int32,
            ]
            lib.gather_rows.restype = None
            lib.gather_rows_norm_u8.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
            ]
            lib.gather_rows_norm_u8.restype = None
            _lib = lib
        except Exception as e:  # no g++, sandboxed exec, etc.
            logger.info("native batcher unavailable (%s); using numpy", e)
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def gather(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """``src[idx]`` through the native multithreaded gather.

    Falls back to numpy when the library is unavailable or the array
    layout doesn't qualify (non-contiguous rows).
    """
    lib = _load()
    idx = np.asarray(idx)
    if (lib is None or not src.flags.c_contiguous or src.ndim < 1
            or src.itemsize == 0 or src.dtype.hasobject
            or idx.ndim != 1 or len(idx) == 0
            or idx.dtype.kind not in "iu"):
        # numpy handles every non-fast-path case: object arrays (memcpy of
        # PyObject* would corrupt refcounts), boolean masks and float
        # indices (an int64 cast would silently select the WRONG rows)
        return src[idx]
    idx64 = np.ascontiguousarray(idx, dtype=np.int64)
    if int(idx64.min()) < 0:
        idx64 = idx64.copy()
        idx64[idx64 < 0] += len(src)  # numpy negative-index semantics
    if int(idx64.min()) < 0 or int(idx64.max()) >= len(src):
        raise IndexError("gather index out of range")
    out = np.empty((len(idx64),) + src.shape[1:], dtype=src.dtype)
    row_bytes = int(np.prod(src.shape[1:], dtype=np.int64)) * src.itemsize
    if row_bytes == 0:
        return src[idx]
    lib.gather_rows(
        src.ctypes.data, idx64.ctypes.data, len(idx64), row_bytes,
        out.ctypes.data, _threads,
    )
    return out


def gather_normalize_u8(src: np.ndarray, idx: np.ndarray,
                        mean: np.ndarray, std: np.ndarray) -> np.ndarray:
    """Fused ``(src[idx] / 255 - mean) / std`` for uint8 image arrays with
    a trailing channel dim: one pass over the gathered bytes instead of
    numpy's gather -> cast -> subtract -> divide (four full-batch
    traversals). Enables uint8 on-disk datasets (4x smaller than float32).
    Falls back to the numpy expression when the native path is out.
    """
    idx = np.asarray(idx)
    mean = np.ascontiguousarray(mean, dtype=np.float32).ravel()
    std = np.ascontiguousarray(std, dtype=np.float32).ravel()
    n_chan = len(mean)
    lib = _load()

    def fallback():
        x = src[idx].astype(np.float32) / 255.0
        return (x - mean) / std

    if (lib is None or src.dtype != np.uint8 or not src.flags.c_contiguous
            or src.ndim < 2 or src.shape[-1] != n_chan or len(std) != n_chan
            or idx.ndim != 1 or len(idx) == 0 or idx.dtype.kind not in "iu"):
        return fallback()
    idx64 = np.ascontiguousarray(idx, dtype=np.int64)
    if int(idx64.min()) < 0:
        idx64 = idx64.copy()
        idx64[idx64 < 0] += len(src)
    if int(idx64.min()) < 0 or int(idx64.max()) >= len(src):
        raise IndexError("gather index out of range")
    row_elems = int(np.prod(src.shape[1:], dtype=np.int64))
    if row_elems == 0 or row_elems % n_chan != 0:
        return fallback()
    out = np.empty((len(idx64),) + src.shape[1:], dtype=np.float32)
    lib.gather_rows_norm_u8(
        src.ctypes.data, idx64.ctypes.data, len(idx64), row_elems, n_chan,
        mean.ctypes.data, std.ctypes.data, out.ctypes.data, _threads,
    )
    return out
