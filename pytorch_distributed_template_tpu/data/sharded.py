"""Out-of-core sharded image datasets (uint8 mmap shards).

The image analogue of ``ByteLMLoader``'s beyond-RAM contract
(datasets.py): a dataset too big for host memory (real ImageNet is
~150 GB as uint8 224^2) lives on disk as N aligned ``.npy`` shards

    <data_dir>/<split>_images_0000.npy   uint8 [n_i, H, W, C]
    <data_dir>/<split>_labels_0000.npy   int   [n_i]
    ...

each memory-mapped, never materialized. ``ShardedU8Array`` presents the
shard set as one virtual [N, H, W, C] array whose ``gather`` /
``gather_normalize`` group a batch's global indices by shard and copy
rows straight out of the mapped pages with the C++ multithreaded
batcher (data/native) — the OS page cache is the working set, so
sequential epochs over a dataset larger than RAM stream at disk/cache
speed while the fused uint8 -> normalized-float32 conversion still
happens in one pass. Composes unchanged with ``ShardedSampler``
(per-host index shards), ``host_prefetch`` (gather on a background
thread) and ``prefetch_to_device`` (async H2D) — the full SURVEY §7
hard-part (b) overlap story.

``write_image_shards`` is the converter (also exposed as
``scripts/make_image_shards.py``); it streams, so the source can be a
generator and never needs to fit in memory either.
"""
from __future__ import annotations

import re
from pathlib import Path
from typing import Iterable, Optional, Sequence, Tuple

import numpy as np

from . import native


class ShardedU8Array:
    """Read-only virtual concatenation of aligned uint8 ``.npy`` shards.

    Supports exactly what ``ArrayDataLoader`` needs: ``len``, ``shape``,
    ``dtype``, and batched row ``gather``/``gather_normalize`` by global
    index. Shards are memory-mapped lazily at construction and stay
    mapped (cheap: address space, not RAM).
    """

    def __init__(self, paths: Sequence[Path]):
        if not paths:
            raise ValueError("ShardedU8Array needs at least one shard")
        self.shards = [np.load(p, mmap_mode="r") for p in paths]
        base = self.shards[0]
        if base.dtype != np.uint8:
            raise ValueError(
                f"image shards must be uint8, got {base.dtype} ({paths[0]})"
            )
        for p, s in zip(paths, self.shards):
            if s.shape[1:] != base.shape[1:] or s.dtype != base.dtype:
                raise ValueError(
                    f"shard {p} shape {s.shape}/{s.dtype} mismatches "
                    f"{base.shape}/{base.dtype}"
                )
        # offsets[i] = first global index of shard i; searchsorted maps
        # global index -> shard
        counts = np.asarray([len(s) for s in self.shards], np.int64)
        self.offsets = np.concatenate([[0], np.cumsum(counts)])
        self.shape = (int(self.offsets[-1]),) + base.shape[1:]
        self.dtype = base.dtype

    def __len__(self) -> int:
        return self.shape[0]

    def __getitem__(self, key):
        """Row slicing/fancy indexing, materialized via ``gather`` — the
        trainer/evaluator take ``arrays[key][:1]`` as the model-init
        template, and numpy-style access keeps the virtual array a
        drop-in for a real one in any read-only use."""
        if isinstance(key, slice):
            return self.gather(np.arange(*key.indices(len(self))))
        if isinstance(key, (int, np.integer)):
            return self.gather(np.asarray([key]))[0]
        return self.gather(np.asarray(key))

    def _per_shard(self, idx: np.ndarray):
        """Yield (shard_array, local_indices, dest_positions) groups."""
        idx = np.asarray(idx, np.int64)
        if len(idx) and idx.min() < 0:
            idx = np.where(idx < 0, idx + len(self), idx)  # numpy semantics
        if len(idx) and (idx.min() < 0 or idx.max() >= len(self)):
            raise IndexError("sharded gather index out of range")
        shard_of = np.searchsorted(self.offsets, idx, side="right") - 1
        for s in np.unique(shard_of):
            pos = np.nonzero(shard_of == s)[0]
            yield self.shards[s], idx[pos] - self.offsets[s], pos

    def gather(self, idx: np.ndarray) -> np.ndarray:
        out = np.empty((len(idx),) + self.shape[1:], self.dtype)
        for shard, local, pos in self._per_shard(idx):
            out[pos] = native.gather(shard, local)
        return out

    def gather_normalize(self, idx: np.ndarray, mean: np.ndarray,
                         std: np.ndarray) -> np.ndarray:
        out = np.empty((len(idx),) + self.shape[1:], np.float32)
        for shard, local, pos in self._per_shard(idx):
            out[pos] = native.gather_normalize_u8(shard, local, mean, std)
        return out


def find_shards(data_dir, split: str,
                kind: str = "images") -> list:
    """Sorted shard paths ``<split>_<kind>_<NNNN>.npy`` under ``data_dir``."""
    pat = re.compile(rf"{split}_{kind}_(\d+)\.npy$")
    hits = []
    for p in Path(data_dir).glob(f"{split}_{kind}_*.npy"):
        m = pat.search(p.name)
        if m:
            hits.append((int(m.group(1)), p))
    return [p for _, p in sorted(hits)]


def load_sharded_labels(paths: Sequence[Path]) -> np.ndarray:
    """Concatenate label shards, materialized as int32 (labels are ~4 B
    per sample — resident even at ImageNet scale)."""
    return np.concatenate(
        [np.asarray(np.load(p, mmap_mode="r"), np.int32) for p in paths]
    )


def write_image_shards(samples: Iterable[Tuple[np.ndarray, int]],
                       out_dir, split: str = "train",
                       shard_size: int = 8192) -> int:
    """Stream ``(uint8 image, int label)`` samples into aligned shards.

    Returns the number of samples written. Only one shard's images are
    ever buffered (shard_size * image bytes), so arbitrarily large
    datasets convert in bounded memory.
    """
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    buf_x: list = []
    buf_y: list = []
    shard = 0
    total = 0

    def flush():
        nonlocal shard, buf_x, buf_y
        if not buf_x:
            return
        x = np.stack(buf_x).astype(np.uint8)
        y = np.asarray(buf_y, np.int32)
        np.save(out / f"{split}_images_{shard:04d}.npy", x)
        np.save(out / f"{split}_labels_{shard:04d}.npy", y)
        shard += 1
        buf_x, buf_y = [], []

    for img, label in samples:
        buf_x.append(np.asarray(img, np.uint8))
        buf_y.append(int(label))
        total += 1
        if len(buf_x) >= shard_size:
            flush()
    flush()
    return total


def open_sharded_split(data_dir, training: bool
                       ) -> Optional[Tuple[ShardedU8Array, np.ndarray]]:
    """(images, labels) for a split's shard set, or None when absent."""
    split = "train" if training else "val"
    img_paths = find_shards(data_dir, split, "images")
    lbl_paths = find_shards(data_dir, split, "labels")
    if not img_paths and not lbl_paths:
        return None  # genuinely no shards: caller may fall back
    if len(img_paths) != len(lbl_paths):
        # shards EXIST but are unpaired (interrupted converter run):
        # silent synthetic fallback would train on the wrong data
        raise ValueError(
            f"sharded split {split} under {data_dir} is corrupt: "
            f"{len(img_paths)} image shards vs {len(lbl_paths)} label "
            "shards — re-run scripts/make_image_shards.py"
        )
    images = ShardedU8Array(img_paths)
    labels = load_sharded_labels(lbl_paths)
    if len(images) != len(labels):
        raise ValueError(
            f"sharded split {split}: {len(images)} images vs "
            f"{len(labels)} labels"
        )
    return images, labels
