"""Byte-level BPE tokenizer: train / encode / decode / save, in-tree.

Closes the "bring your own ids" gap in the LM workflow (the reference
has no text pipeline at all — its data layer is torchvision MNIST,
/root/reference/data_loader/data_loaders.py): ``ByteLMLoader`` covers
vocab<=256 tokenizer-free training, and this module covers real
subword vocabularies without any network or external tooling.

Design: classic byte-level BPE (GPT-2 family's scheme, minus the regex
pre-tokenizer — merges may cross whitespace, which is simpler and
slightly better for code/structured text). Ids 0..255 are the raw
bytes, so ANY input encodes (no <unk>) and any id sequence decodes.
Training is numpy-vectorized: each merge is one pass over the corpus
array (pair counting via a packed-key ``np.unique``), so a few hundred
merges over a multi-MB sample take seconds on one core.

Usage:
    tok = BpeTokenizer.train(Path("corpus.txt").read_bytes(), 1024)
    ids = tok.encode("hello world")
    tok.save("tok.json"); tok = BpeTokenizer.load("tok.json")

``BpeLMLoader`` (data/datasets.py) trains+caches one of these next to
the corpus and feeds the LM families; ``generate.py`` finds it back
through the run config for --prompt round-tripping.
"""
from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Sequence, Union

import numpy as np

logger = logging.getLogger(__name__)


def _sample_bytes(source, max_train_bytes: int) -> bytes:
    """<= ``max_train_bytes`` of evenly-spaced slices from a sliceable
    byte source (bytes or a uint8 memmap) — the whole file's
    distribution, not just its head, without materializing it."""
    if len(source) <= max_train_bytes:
        return bytes(source[:])
    k = 16
    step = len(source) // k
    take = max_train_bytes // k
    return b"".join(
        bytes(source[i * step: i * step + take]) for i in range(k)
    )


def _pair_counts(ids: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Packed (a, b) adjacent-pair keys and their counts."""
    key = ids[:-1].astype(np.int64) << 21 | ids[1:].astype(np.int64)
    return np.unique(key, return_counts=True)


def _merge_once(ids: np.ndarray, a: int, b: int, new_id: int) -> np.ndarray:
    """Replace non-overlapping occurrences of (a, b) with ``new_id``.

    For a != b matches can never overlap (an overlap at i, i+1 would
    need ids[i+1] == b == a). For a == b, runs like ``aaa`` must merge
    greedily left-to-right — resolved with a short loop over the match
    positions only (rare case, tiny index arrays).
    """
    m = (ids[:-1] == a) & (ids[1:] == b)
    idx = np.flatnonzero(m)
    if idx.size == 0:
        return ids
    if a == b:
        # vectorized greedy: within each run of consecutive matches keep
        # every other one starting at the run head (a Python loop here
        # is hot-path — (space, space) dominates code corpora)
        order = np.arange(idx.size)
        is_start = np.empty(idx.size, bool)
        is_start[0] = True
        is_start[1:] = np.diff(idx) > 1
        run_head = idx[np.maximum.accumulate(np.where(is_start, order, 0))]
        idx = idx[((idx - run_head) % 2) == 0]
    out = ids.copy()
    out[idx] = new_id
    return np.delete(out, idx + 1)


class BpeTokenizer:
    """Ordered byte-level BPE merges + the derived id->bytes vocab."""

    def __init__(self, merges: Sequence[tuple[int, int]]):
        self.merges = [tuple(m) for m in merges]
        self.vocab: list[bytes] = [bytes([i]) for i in range(256)]
        for a, b in self.merges:
            self.vocab.append(self.vocab[a] + self.vocab[b])

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    # -- training ------------------------------------------------------------

    @classmethod
    def train(cls, data: Union[bytes, str], vocab_size: int,
              max_train_bytes: int = 4 << 20,
              max_token_bytes: int = 16) -> "BpeTokenizer":
        """Learn ``vocab_size - 256`` merges from ``data``.

        ``max_train_bytes`` caps the training sample (evenly-spaced
        slices across the corpus, so the sample sees the whole file's
        distribution, not just its head) — merge quality saturates long
        before corpus size on natural text/code, and training cost is
        linear in the sample.

        ``max_token_bytes`` bounds merged token length (SentencePiece's
        default bound): without it, a corpus with long verbatim repeats
        (boilerplate, repeated phrases) collapses whole sentences into
        single giant tokens — each merge can double token length, so a
        phrase repeated N times degenerates the id stream toward one
        token and generalizes to nothing.
        """
        if isinstance(data, str):
            data = data.encode("utf-8")
        if vocab_size < 256:
            raise ValueError(f"vocab_size {vocab_size} < 256 (the byte "
                             "alphabet is the floor)")
        data = _sample_bytes(data, max_train_bytes)
        ids = np.frombuffer(data, dtype=np.uint8).astype(np.int32)
        lens = [1] * 256                   # id -> token byte length
        merges: list[tuple[int, int]] = []
        for new_id in range(256, vocab_size):
            if len(ids) < 2:
                break
            keys, counts = _pair_counts(ids)
            # most frequent pair whose merged token stays under the cap
            a = b = -1
            for j in np.argsort(-counts):
                if counts[j] < 2:
                    break                  # nothing left that repeats
                ka = int(keys[j]) >> 21
                kb = int(keys[j]) & ((1 << 21) - 1)
                if lens[ka] + lens[kb] <= max_token_bytes:
                    a, b = ka, kb
                    break
            if a < 0:
                break
            ids = _merge_once(ids, a, b, new_id)
            merges.append((a, b))
            lens.append(lens[a] + lens[b])
        return cls(merges)

    # -- inference -----------------------------------------------------------

    def encode(self, text: Union[str, bytes]) -> np.ndarray:
        """Text -> int32 ids (applies the merges in learned order)."""
        if isinstance(text, str):
            text = text.encode("utf-8")
        ids = np.frombuffer(text, dtype=np.uint8).astype(np.int32)
        for new_id, (a, b) in enumerate(self.merges, start=256):
            if len(ids) < 2:
                break
            ids = _merge_once(ids, a, b, new_id)
        return ids

    def decode(self, ids, errors: str = "strict") -> str:
        """Ids -> text (any id < vocab_size is valid; invalid UTF-8 from
        model sampling decodes with replacement characters).

        ``errors="replace"`` maps out-of-vocab ids to U+FFFD instead of
        raising — for sampling CLIs, where a model head larger than the
        learned vocab (BPE training can stop short of the requested
        size) must not crash after a full generation."""
        ids = np.asarray(ids).reshape(-1)
        bad = [int(i) for i in ids if not 0 <= int(i) < len(self.vocab)]
        if bad and errors != "replace":
            raise ValueError(f"ids outside vocab (size {len(self.vocab)}):"
                             f" {bad[:5]}")
        rep = "�".encode("utf-8")
        return b"".join(
            self.vocab[int(i)] if 0 <= int(i) < len(self.vocab) else rep
            for i in ids
        ).decode("utf-8", errors="replace")

    @classmethod
    def train_from_file(cls, path, vocab_size: int,
                        max_train_bytes: int = 4 << 20,
                        max_token_bytes: int = 16,
                        sample_until: float = 1.0) -> "BpeTokenizer":
        """``train`` over a file WITHOUT loading it whole: the <=
        ``max_train_bytes`` evenly-spaced sample is assembled from
        memmap slices, so a multi-GB corpus touches only the sampled
        pages (same beyond-RAM contract as ByteLMLoader).

        ``sample_until`` restricts sampling to the first fraction of the
        file: loaders that split a held-out tail off the SAME file pass
        their train fraction here so the tokenizer never fits on eval
        text (fitting on the full file leaks the val tail into the
        merges, mildly flattering held-out nats/token)."""
        if not 0.0 < sample_until <= 1.0:
            raise ValueError(f"sample_until {sample_until} not in (0, 1]")
        raw = np.memmap(Path(path), dtype=np.uint8, mode="r")
        end = max(int(len(raw) * sample_until), 1)
        return cls.train(_sample_bytes(raw[:end], max_train_bytes),
                         vocab_size,
                         max_train_bytes=max_train_bytes,
                         max_token_bytes=max_token_bytes)

    def encode_file(self, path, chunk_bytes: int = 4 << 20) -> np.ndarray:
        """Tokenize a whole file in bounded memory: memmap the source
        and encode ``chunk_bytes`` slices independently (a merge that
        would span a chunk boundary is skipped — on multi-MB chunks the
        effect on the id stream is a few tokens per chunk, and training
        data does not need boundary-exact tokenization)."""
        raw = np.memmap(Path(path), dtype=np.uint8, mode="r")
        parts = [
            self.encode(raw[i: i + chunk_bytes].tobytes())
            for i in range(0, len(raw), chunk_bytes)
        ]
        return np.concatenate(parts) if parts else np.zeros(0, np.int32)

    # -- persistence ---------------------------------------------------------

    def save(self, path) -> None:
        # atomic (tmp + rename): concurrent readers — other hosts of a
        # multi-process run — never see a partial file
        import os

        path = Path(path)
        tmp = path.with_name(path.name + f".tmp{os.getpid()}")
        tmp.write_text(json.dumps({
            "format": "bpe-bytelevel-v1",
            "merges": [list(m) for m in self.merges],
        }))
        os.replace(tmp, path)

    @classmethod
    def load(cls, path) -> "BpeTokenizer":
        spec = json.loads(Path(path).read_text())
        if spec.get("format") != "bpe-bytelevel-v1":
            raise ValueError(f"{path}: not a bpe-bytelevel-v1 tokenizer")
        return cls([tuple(m) for m in spec["merges"]])


def token_index_at_byte(tok: BpeTokenizer, ids, byte_cut: int) -> int:
    """Index of the first token whose bytes start at or after
    ``byte_cut`` in the original file.

    Token byte lengths are exact (chunked encoding never merges across
    chunk bounds, so summed token lengths reproduce file offsets).
    Lets a loader place its train/val split at the SAME byte position
    the tokenizer's fit stopped at — a fraction of the id stream only
    approximates it, because bytes-per-token differs between head and
    tail (ADVICE r3 leakage fix, exact-boundary form)."""
    lens = np.array([len(v) for v in tok.vocab], np.int64)
    total, chunk = 0, 1 << 22
    for i in range(0, len(ids), chunk):
        seg = lens[np.asarray(ids[i: i + chunk])]
        s = int(seg.sum())
        if total + s < byte_cut:
            total += s
            continue
        # boundary in this chunk: the straddling token goes to TRAIN
        # (its bytes begin before the cut), so the split is after the
        # first token whose cumulative coverage reaches the cut
        cum = total + np.cumsum(seg)
        return i + int(np.searchsorted(cum, byte_cut, side="left")) + 1
    return len(ids)


def tokenizer_from_config(config) -> "BpeTokenizer | None":
    """Recover the run's tokenizer from its config, if the experiment
    trained through ``BpeLMLoader`` (the loader caches the tokenizer
    next to the corpus — same derivation as the loader's own path).
    Used by generate.py to round-trip ``--prompt`` text for subword
    models.

    Resolution order: (1) the run-pinned ``tokenizer.json`` next
    to/above the checkpoint — authoritative, because the corpus-side
    cache is shared mutable state a later run can rewrite with
    different merges; (2) the corpus-side keyed cache; (3) the legacy
    (pre-train-fraction-key) cache name."""
    resume = getattr(config, "resume", None)
    if resume is not None:
        d = Path(resume)
        for _ in range(3):   # ckpt dir -> run dir -> artifact nesting
            pinned = d / "tokenizer.json"
            if pinned.exists():
                return BpeTokenizer.load(pinned)
            d = d.parent
    for block in ("train_loader", "valid_loader", "test_loader"):
        spec = config.get(block, None)
        if spec and spec.get("type") == "BpeLMLoader":
            args = spec.get("args", {})
            keyed = bpe_cache_path(
                args.get("data_dir", "data/"),
                args.get("file", "input.txt"),
                int(args.get("vocab_size", 1024)),
                val_fraction=float(args.get("val_fraction", 0.1)),
            )
            # legacy fallback: caches written before the train-fraction
            # key (fitted on the full file) keep round-tripping old runs
            legacy = (
                Path(args.get("data_dir", "data/"))
                / f"{args.get('file', 'input.txt')}"
                  f".bpe{int(args.get('vocab_size', 1024))}.json"
            )
            for path in (keyed, legacy):
                if path.exists():
                    return BpeTokenizer.load(path)
            logger.warning("BpeLMLoader tokenizer %s not found", keyed)
    return None


def bpe_cache_path(data_dir, file: str, vocab_size: int,
                   val_fraction: float = 0.1) -> Path:
    """Where ``BpeLMLoader`` persists the tokenizer for a corpus.

    The name carries the TRAIN fraction (in percent) the merges were
    fitted on (``t90`` for the default 10% held-out tail): a
    ``val_fraction`` change must refit, not silently reuse merges
    fitted at the old cut — reusing them can leak eval text into the
    tokenizer."""
    # "p" stands in for the decimal point (t90, t90p5) so the keyed
    # stem stays a single path suffix and ``with_suffix`` derives the
    # sibling id-stream cache. %g keeps 6 significant digits: cuts
    # that differ only beyond that collide on one cache — accepted,
    # val fractions are human-chosen round numbers
    pct = f"{(1.0 - float(val_fraction)) * 100:g}".replace(".", "p")
    return Path(data_dir) / f"{file}.bpe{vocab_size}.t{pct}.json"
