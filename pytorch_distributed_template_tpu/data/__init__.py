from .sampler import ShardedSampler
from .loader import ArrayDataLoader, prefetch_to_device
from . import datasets  # registers DATASETS / LOADERS entries
