"""Datasets and the config-facing data loaders.

The reference's data layer is ``MnistDataLoader`` — torchvision MNIST with a
Normalize transform and an auto-attached ``DistributedSampler`` when
``world_size > 1`` (/root/reference/data_loader/data_loaders.py:8-27). Here:

- Real MNIST/CIFAR-10 are loaded **from disk** when the standard files exist
  under ``data_dir`` (torch CPU is available in-image for parsing, never in
  the compute path). This container has no network egress, so missing files
  fall back to a *deterministic, learnable* synthetic surrogate of identical
  shapes: class-conditional templates + noise. A model can actually fit it,
  so end-to-end loss-decrease tests are meaningful.
- Every loader auto-attaches a ``ShardedSampler`` over **hosts** when
  ``process_count > 1`` (the analogue of the reference's world_size check);
  device-level batch sharding is jit's job, not the loader's.

All loaders are registered in ``LOADERS`` with the reference's config
signature ``(data_dir, batch_size, shuffle, num_workers, training)``;
``num_workers`` is accepted and ignored (no torch worker pool — arrays are
memory-resident and prefetch is async DMA).
"""
from __future__ import annotations

import logging
import os
import time
from pathlib import Path
from typing import Optional

import numpy as np

from ..config.registry import DATASETS, LOADERS
from ..parallel import dist
from .loader import ArrayDataLoader
from .sampler import ShardedSampler

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# synthetic class-conditional image data (deterministic, learnable)
# ---------------------------------------------------------------------------

def _synthetic_image_classification(n: int, shape, num_classes: int,
                                    seed: int, noise: float = 0.3,
                                    split: int = 0):
    """Images = per-class smooth template + Gaussian noise; labels uniform.

    The class templates depend only on ``seed``; ``split`` (0=train, 1=eval)
    offsets the sample stream so train/val draw disjoint samples from the
    SAME class distribution — otherwise validation would be unlearnable.
    """
    tmpl_rng = np.random.Generator(np.random.Philox(key=seed))
    # Sample stream keyed by (seed, split): templates depend only on seed
    # (shared across splits), while train/val sample streams are independent.
    rng = np.random.Generator(
        np.random.Philox(np.random.SeedSequence((seed, split + 1)))
    )
    templates = tmpl_rng.normal(0.0, 1.0, size=(num_classes, *shape)).astype(np.float32)
    # Smooth templates along spatial dims so convs have structure to find.
    for _ in range(2):
        templates = (
            templates
            + np.roll(templates, 1, axis=1)
            + np.roll(templates, -1, axis=1)
            + np.roll(templates, 1, axis=2)
            + np.roll(templates, -1, axis=2)
        ) / 5.0
    labels = rng.integers(0, num_classes, size=n).astype(np.int32)
    images = templates[labels] + noise * rng.normal(size=(n, *shape)).astype(
        np.float32
    )
    return images.astype(np.float32), labels


@DATASETS.register("synthetic_mnist")
def synthetic_mnist(n: int = 4096, seed: int = 0, training: bool = True):
    images, labels = _synthetic_image_classification(
        n, (28, 28, 1), 10, seed=seed, split=0 if training else 1
    )
    return {"image": images, "label": labels}


@DATASETS.register("synthetic_cifar10")
def synthetic_cifar10(n: int = 4096, seed: int = 0, training: bool = True):
    images, labels = _synthetic_image_classification(
        n, (32, 32, 3), 10, seed=seed, split=0 if training else 1
    )
    return {"image": images, "label": labels}


@DATASETS.register("synthetic_imagenet")
def synthetic_imagenet(n: int = 1024, image_size: int = 224, seed: int = 0,
                       training: bool = True, num_classes: int = 1000):
    split = 0 if training else 1
    tmpl_rng = np.random.Generator(np.random.Philox(key=seed))
    rng = np.random.Generator(
        np.random.Philox(np.random.SeedSequence((seed, split + 1)))
    )
    # Templates at full ImageNet size would be 1000*224*224*3 floats (~600MB);
    # generate low-res templates and upsample per-sample instead.
    small = 16
    if image_size % small != 0 or image_size < small:
        raise ValueError(
            f"image_size must be a positive multiple of {small}, got {image_size}"
        )
    templates = tmpl_rng.normal(0, 1, size=(num_classes, small, small, 3)).astype(
        np.float32
    )
    labels = rng.integers(0, num_classes, size=n).astype(np.int32)
    factor = image_size // small
    images = np.repeat(np.repeat(templates[labels], factor, 1), factor, 2)
    images += 0.3 * rng.normal(size=images.shape).astype(np.float32)
    return {"image": images.astype(np.float32), "label": labels}


@DATASETS.register("synthetic_lm")
def synthetic_lm(n: int = 2048, seq_len: int = 128, vocab_size: int = 50257,
                 seed: int = 0, training: bool = True):
    """Token sequences from a sparse bigram chain — learnable structure.

    The bigram table depends only on ``seed``; the sample stream is offset
    by split so train/val sequences differ but share the distribution.
    """
    tmpl_rng = np.random.Generator(np.random.Philox(key=seed))
    split = 0 if training else 1
    rng = np.random.Generator(
        np.random.Philox(np.random.SeedSequence((seed, split + 1)))
    )
    # Each token deterministically prefers a few successors.
    successors = tmpl_rng.integers(0, vocab_size, size=(vocab_size, 4))
    tokens = np.empty((n, seq_len), dtype=np.int32)
    tokens[:, 0] = rng.integers(0, vocab_size, size=n)
    choices = rng.integers(0, 4, size=(n, seq_len))
    noise = rng.random((n, seq_len)) < 0.1
    random_tok = rng.integers(0, vocab_size, size=(n, seq_len))
    for t in range(1, seq_len):
        nxt = successors[tokens[:, t - 1], choices[:, t]]
        tokens[:, t] = np.where(noise[:, t], random_tok[:, t], nxt)
    return {"tokens": tokens}


# ---------------------------------------------------------------------------
# real data from disk (no egress: never downloads)
# ---------------------------------------------------------------------------

def _try_load_mnist(data_dir: Path, training: bool):
    """Parse raw MNIST idx files if present under data_dir (any layout)."""
    names = (
        ("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
        if training
        else ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")
    )
    import gzip

    def find(stem):
        for cand in list(data_dir.rglob(stem)) + list(data_dir.rglob(stem + ".gz")):
            return cand
        return None

    img_f, lbl_f = find(names[0]), find(names[1])
    if img_f is None or lbl_f is None:
        return None

    def read(fp):
        op = gzip.open if fp.suffix == ".gz" else open
        with op(fp, "rb") as f:
            return f.read()

    raw = read(img_f)
    images = np.frombuffer(raw, dtype=np.uint8, offset=16).reshape(-1, 28, 28, 1)
    raw = read(lbl_f)
    labels = np.frombuffer(raw, dtype=np.uint8, offset=8).astype(np.int32)
    # Reference normalization: Normalize((0.1307,), (0.3081,)) over [0,1]
    # pixels (data_loader/data_loaders.py:13-16).
    images = (images.astype(np.float32) / 255.0 - 0.1307) / 0.3081
    return {"image": images, "label": labels}


def _make_image_loader(dataset: dict, batch_size: int, shuffle: bool,
                       drop_last: bool = False, seed: int = 0,
                       normalize=None):
    sampler = None
    if dist.process_count() > 1:
        sampler = ShardedSampler(
            num_samples=len(next(iter(dataset.values()))),
            num_shards=dist.process_count(),
            shard_index=dist.process_index(),
            shuffle=shuffle,
            seed=seed,
        )
    return ArrayDataLoader(
        dataset, batch_size=batch_size, shuffle=shuffle, sampler=sampler,
        drop_last=drop_last, seed=seed, normalize=normalize,
    )


@LOADERS.register("MnistDataLoader")
def mnist_loader(data_dir: str = "data/", batch_size: int = 128,
                 shuffle: bool = True, num_workers: int = 0,
                 training: bool = True, synthetic_n: int = 4096,
                 seed: int = 0):
    """MNIST loader with the reference's signature; synthetic fallback."""
    del num_workers  # no worker pool: arrays are memory-resident
    data = _try_load_mnist(Path(data_dir), training)
    if data is None:
        logger.warning(
            "MNIST files not found under %s and this environment has no "
            "network egress; using deterministic synthetic MNIST "
            "(n=%d). Provide raw idx files to train on real data.",
            data_dir, synthetic_n,
        )
        data = synthetic_mnist(n=synthetic_n, seed=seed, training=training)
    return _make_image_loader(data, batch_size, shuffle, seed=seed)


def _load_real_digits(training: bool, val_fraction: float, seed: int):
    """The UCI handwritten-digits test set bundled with scikit-learn
    (1,797 REAL 8x8 grayscale digit images — ``sklearn.datasets
    .load_digits``) — the only real image-classification data available
    with zero network egress. Returns images in LeNet's native 28x28
    geometry: 3x nearest-neighbor upsample (8->24) + 2px zero pad, with
    per-dataset mean/std normalization (the reference's MNIST recipe,
    data_loader/data_loaders.py:13-16, applied to this dataset's own
    statistics). The pixel CONTENT is untouched real data; only the
    canvas is resized.
    """
    from sklearn.datasets import load_digits

    d = load_digits()
    images = d.images.astype(np.float32) / 16.0  # [N, 8, 8] in [0, 1]
    labels = d.target.astype(np.int32)
    # Deterministic shuffled split: the raw ordering is stratified runs of
    # each class, so a tail split would skew the label distribution.
    perm = np.random.Generator(np.random.Philox(key=seed)).permutation(
        len(images)
    )
    n_train = len(images) - int(len(images) * val_fraction)
    idx = perm[:n_train] if training else perm[n_train:]
    x = images[idx][..., None]                      # [n, 8, 8, 1]
    x = np.repeat(np.repeat(x, 3, axis=1), 3, axis=2)   # [n, 24, 24, 1]
    x = np.pad(x, ((0, 0), (2, 2), (2, 2), (0, 0)))     # [n, 28, 28, 1]
    # Normalization constants computed over the full upsampled dataset
    # (train+val, label-free so no leakage), frozen here for determinism.
    x = (x - 0.2243) / 0.3494
    return {"image": x.astype(np.float32), "label": labels[idx]}


@LOADERS.register("DigitsDataLoader")
def digits_loader(data_dir: str = "data/", batch_size: int = 128,
                  shuffle: bool = True, num_workers: int = 0,
                  training: bool = True, val_fraction: float = 0.2,
                  seed: int = 0):
    """REAL handwritten-digit classification with no files and no egress.

    Drop-in for ``MnistDataLoader`` (same signature, same 28x28x1 batch
    shapes, same LeNet) over the sklearn-bundled UCI digits. This is the
    loader behind the committed real-data learning evidence
    (BASELINE.md): unlike the synthetic fallbacks, val_accuracy here is
    measured on genuinely held-out real images. ``data_dir`` is accepted
    and ignored (the data ships inside scikit-learn).
    """
    del num_workers, data_dir
    data = _load_real_digits(training, val_fraction, seed=seed)
    return _make_image_loader(data, batch_size, shuffle, seed=seed)


@LOADERS.register("Cifar10DataLoader")
def cifar10_loader(data_dir: str = "data/", batch_size: int = 128,
                   shuffle: bool = True, num_workers: int = 0,
                   training: bool = True, synthetic_n: int = 4096,
                   seed: int = 0):
    data = _try_load_cifar10(Path(data_dir), training)
    if data is None:
        logger.warning(
            "CIFAR-10 files not found under %s; using synthetic CIFAR-10.",
            data_dir,
        )
        data = synthetic_cifar10(n=synthetic_n, seed=seed, training=training)
    return _make_image_loader(data, batch_size, shuffle, seed=seed)


def _try_load_cifar10(data_dir: Path, training: bool):
    """Parse the python-pickle CIFAR-10 batches if present."""
    import pickle

    base = None
    for cand in data_dir.rglob("data_batch_1"):
        base = cand.parent
        break
    if base is None:
        return None
    files = (
        [base / f"data_batch_{i}" for i in range(1, 6)]
        if training
        else [base / "test_batch"]
    )
    xs, ys = [], []
    for f in files:
        with open(f, "rb") as fh:
            d = pickle.load(fh, encoding="bytes")
        xs.append(np.asarray(d[b"data"], dtype=np.uint8))
        ys.append(np.asarray(d[b"labels"], dtype=np.int32))
    x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    mean = np.array([0.4914, 0.4822, 0.4465], np.float32)
    std = np.array([0.2470, 0.2435, 0.2616], np.float32)
    x = (x.astype(np.float32) / 255.0 - mean) / std
    return {"image": x, "label": np.concatenate(ys)}


@LOADERS.register("NpyDataLoader")
def npy_loader(data_dir: str = "data/", batch_size: int = 128,
               shuffle: bool = True, num_workers: int = 0,
               training: bool = True, files: Optional[dict] = None,
               mmap: bool = True, seed: int = 0,
               normalize: Optional[dict] = None):
    """Generic real-data loader over ``.npy`` arrays (the escape hatch for
    any dataset: preprocess once into aligned arrays, train from disk).

    :param files: mapping of batch key -> filename relative to ``data_dir``;
        ``{split}`` in a filename expands to ``train``/``val``. Default:
        ``{"image": "{split}_images.npy", "label": "{split}_labels.npy"}``.
    :param mmap: memory-map the arrays (``np.load mmap_mode='r'``) so
        datasets larger than host RAM stream pages on demand; the native
        row-gather (data/native) copies straight out of the mapped pages.

    All arrays must share their leading (sample) dimension. Labels are cast
    to int32; floating images are used as stored (preprocess/normalize at
    conversion time). For uint8 image arrays pass
    ``normalize: {"mean": [...], "std": [...]}`` — batches come out
    float32 via the fused native gather+cast+normalize (one pass), so
    storing uint8 (4x smaller on disk and in page cache) costs nothing.
    """
    del num_workers
    split = "train" if training else "val"
    files = files or {"image": "{split}_images.npy",
                      "label": "{split}_labels.npy"}
    arrays = {}
    for key, fname in files.items():
        path = Path(data_dir) / fname.format(split=split)
        if not path.exists():
            raise FileNotFoundError(
                f"NpyDataLoader: {path} not found (key '{key}')"
            )
        arr = np.load(path, mmap_mode="r" if mmap else None)
        if key == "label":
            arr = np.asarray(arr, dtype=np.int32)  # small; materialize
        arrays[key] = arr
    # mismatched sample counts raise in ArrayDataLoader.__init__
    return _make_image_loader(arrays, batch_size, shuffle, seed=seed,
                              normalize=normalize)


@LOADERS.register("ShardedImageNetLoader")
def sharded_imagenet_loader(data_dir: str = "data/imagenet_shards/",
                            batch_size: int = 128, shuffle: bool = True,
                            num_workers: int = 0, training: bool = True,
                            normalize: Optional[dict] = None,
                            synthetic_n: int = 1024,
                            image_size: int = 224, num_classes: int = 1000,
                            seed: int = 0):
    """Out-of-core ImageNet-scale loader over uint8 mmap shards.

    Expects ``{split}_images_NNNN.npy`` / ``{split}_labels_NNNN.npy``
    under ``data_dir`` (write them with ``scripts/make_image_shards.py``
    or ``data.sharded.write_image_shards``). The shard set is presented
    as one virtual array (``data/sharded.ShardedU8Array``): batches are
    gathered straight out of the memory-mapped pages by the C++ batcher
    with the fused uint8 -> normalized float32 conversion, so a dataset
    bigger than host RAM trains from disk with the OS page cache as the
    working set. Composes with ShardedSampler (multi-host),
    host_prefetch and prefetch_to_device unchanged. Falls back to the
    synthetic in-memory ImageNet when no shards exist (the degradation
    contract every loader here follows).

    Default ``normalize`` is the standard ImageNet mean/std.
    """
    del num_workers
    from .sharded import open_sharded_split

    if normalize is None:
        # on_device: uint8 crosses the host->device link (4x less
        # traffic) and the normalize fuses into the first conv under jit
        normalize = {"mean": [0.485, 0.456, 0.406],
                     "std": [0.229, 0.224, 0.225], "on_device": True}
    pair = open_sharded_split(data_dir, training)
    if pair is None:
        logger.warning(
            "ShardedImageNetLoader: no shards under %s; using synthetic "
            "ImageNet (n=%d). Convert real data with "
            "scripts/make_image_shards.py.", data_dir, synthetic_n,
        )
        data = synthetic_imagenet(
            n=synthetic_n, image_size=image_size, seed=seed,
            training=training, num_classes=num_classes,
        )
        return _make_image_loader(data, batch_size, shuffle, seed=seed)
    images, labels = pair
    return _make_image_loader(
        {"image": images, "label": labels}, batch_size, shuffle,
        seed=seed, normalize=normalize,
    )


@LOADERS.register("SyntheticImageNetLoader")
def imagenet_loader(data_dir: str = "data/", batch_size: int = 128,
                    shuffle: bool = True, num_workers: int = 0,
                    training: bool = True, n: int = 1024,
                    image_size: int = 224, num_classes: int = 1000,
                    seed: int = 0):
    del num_workers
    data = synthetic_imagenet(
        n=n, image_size=image_size, seed=seed, training=training,
        num_classes=num_classes,
    )
    return _make_image_loader(data, batch_size, shuffle, seed=seed)


@LOADERS.register("ByteLMLoader")
def byte_lm_loader(data_dir: str = "data/", batch_size: int = 8,
                   shuffle: bool = True, num_workers: int = 0,
                   training: bool = True, file: str = "input.txt",
                   seq_len: int = 256, val_fraction: float = 0.1,
                   seed: int = 0):
    """Byte-level LM over any local text/binary file (vocab = 256).

    The tokenizer-free path to real-text training for the GPT-2 family:
    no vocab files, no network, UTF-8 agnostic. The file is split into
    train/val by ``val_fraction`` (tail split, so val is held-out text),
    then chunked into non-overlapping ``seq_len`` windows. Falls back to
    the synthetic bigram stream when the file is absent (same degradation
    contract as the image loaders).
    """
    del num_workers
    path = Path(data_dir) / file
    if not path.exists():
        logger.warning(
            "ByteLMLoader: %s not found; using synthetic byte-LM data.",
            path,
        )
        data = synthetic_lm(n=2048, seq_len=seq_len, vocab_size=256,
                            seed=seed, training=training)
        return _make_image_loader(data, batch_size, shuffle, seed=seed)
    # memory-map and keep uint8: a multi-GB corpus stays on disk (pages
    # stream on demand through the native gather) instead of 4x-expanding
    # into resident int32 — same beyond-RAM contract as NpyDataLoader.
    # uint8 tokens flow through embed/CE unchanged (integer ops cast).
    raw = np.memmap(path, dtype=np.uint8, mode="r")
    split = int(len(raw) * (1.0 - val_fraction))
    part = raw[:split] if training else raw[split:]
    n_chunks = len(part) // seq_len
    if n_chunks == 0:
        raise ValueError(
            f"ByteLMLoader: {path} too small for one {seq_len}-byte "
            f"{'train' if training else 'val'} sequence"
        )
    tokens = part[: n_chunks * seq_len].reshape(n_chunks, seq_len)
    return _make_image_loader({"tokens": tokens}, batch_size, shuffle,
                              seed=seed)


@LOADERS.register("BpeLMLoader")
def bpe_lm_loader(data_dir: str = "data/", batch_size: int = 8,
                  shuffle: bool = True, num_workers: int = 0,
                  training: bool = True, file: str = "input.txt",
                  seq_len: int = 256, vocab_size: int = 1024,
                  val_fraction: float = 0.1, seed: int = 0):
    """Subword LM over any local text file: a byte-level BPE tokenizer
    (data/tokenizer.py) is trained ONCE per (corpus, vocab_size) and
    cached next to the file, along with the tokenized id stream, so
    repeat runs skip straight to chunking. The real-vocab counterpart
    of ``ByteLMLoader`` — same tail train/val split, same synthetic
    fallback when the corpus is absent. ``generate.py`` recovers the
    cached tokenizer through the run config to round-trip ``--prompt``
    text (data/tokenizer.tokenizer_from_config).

    The tokenizer fits on the TRAIN fraction of the file only (bytes
    before the ``1 - val_fraction`` cut), so held-out nats/token is
    never computed with merges fitted on eval text. The cache is keyed
    by (file, vocab_size, train fraction) and invalidated by source
    mtime — changing ``val_fraction`` refits rather than silently
    reusing merges fitted at the old cut.

    Multi-host: ``data_dir`` must be a filesystem shared with host 0 —
    host 0 builds the tokenizer/id caches and every other host polls
    for the files to appear (below).
    """
    del num_workers
    from .tokenizer import BpeTokenizer, bpe_cache_path

    path = Path(data_dir) / file
    if not path.exists():
        logger.warning(
            "BpeLMLoader: %s not found; using synthetic LM data.", path
        )
        data = synthetic_lm(n=2048, seq_len=seq_len,
                            vocab_size=vocab_size, seed=seed,
                            training=training)
        return _make_image_loader(data, batch_size, shuffle, seed=seed)
    tok_path = bpe_cache_path(data_dir, file, vocab_size,
                              val_fraction=val_fraction)
    # id stream is tokenizer-dependent, so it shares the keyed stem
    ids_path = tok_path.with_suffix(".npy")
    src_mtime = path.stat().st_mtime

    def caches_fresh():
        return (tok_path.exists() and ids_path.exists()
                and tok_path.stat().st_mtime >= src_mtime
                and ids_path.stat().st_mtime >= tok_path.stat().st_mtime)

    if not caches_fresh():
        if dist.is_main_process():
            # one builder; writes are atomic (tmp + os.replace), so the
            # waiters below never read a partial file
            logger.info("BpeLMLoader: training %d-vocab BPE on %s ...",
                        vocab_size, path)
            tok = BpeTokenizer.train_from_file(
                path, vocab_size, sample_until=1.0 - val_fraction
            )
            tok.save(tok_path)
            logger.info("BpeLMLoader: tokenizing %s ...", path)
            # memmapped chunked encode: bounded memory on multi-GB
            # corpora (ByteLMLoader's beyond-RAM contract)
            ids = tok.encode_file(path)
            dtype = np.uint16 if tok.vocab_size <= 65536 else np.int32
            tmp = ids_path.with_name(ids_path.name + f".tmp{os.getpid()}")
            with open(tmp, "wb") as f:  # file handle: no .npy suffixing
                np.save(f, ids.astype(dtype))
            os.replace(tmp, ids_path)
        else:
            # non-zero hosts wait for host 0's atomic writes to land
            deadline = time.time() + 1800
            while not caches_fresh():
                if time.time() > deadline:
                    raise TimeoutError(
                        f"BpeLMLoader: timed out waiting for host 0 to "
                        f"build {tok_path} / {ids_path} — multi-host "
                        "runs require data_dir on a filesystem shared "
                        "with host 0 (each host polls for host 0's "
                        "atomic cache writes; there is no network "
                        "broadcast of the tokenizer)"
                    )
                time.sleep(2.0)
    from .tokenizer import token_index_at_byte

    tok = BpeTokenizer.load(tok_path)
    ids = np.load(ids_path, mmap_mode="r")
    # split at the token covering the SAME byte position the tokenizer
    # fit stopped at — a plain id-stream fraction only approximates the
    # byte cut (bytes/token differs head vs tail), and when the tail
    # compresses better the fractional split would hand val some
    # tokenizer-seen bytes
    split = token_index_at_byte(
        tok, ids, int(path.stat().st_size * (1.0 - val_fraction))
    )
    part = ids[:split] if training else ids[split:]
    n_chunks = len(part) // seq_len
    if n_chunks == 0:
        raise ValueError(
            f"BpeLMLoader: {path} too small for one {seq_len}-token "
            f"{'train' if training else 'val'} sequence"
        )
    tokens = np.asarray(part[: n_chunks * seq_len]).reshape(
        n_chunks, seq_len
    )
    loader = _make_image_loader({"tokens": tokens}, batch_size, shuffle,
                                seed=seed)
    # advertised so the trainer can pin a copy of the tokenizer in the
    # run dir (the corpus-side cache can be rewritten by later runs)
    loader.tokenizer_path = tok_path
    return loader


@LOADERS.register("PyModuleClsLoader")
def py_module_cls_loader(data_dir: str = "data/", batch_size: int = 64,
                         shuffle: bool = True, num_workers: int = 0,
                         training: bool = True,
                         modules: tuple = ("asyncio", "email", "unittest",
                                           "xml", "multiprocessing",
                                           "importlib", "encodings",
                                           "http"),
                         seq_len: int = 128, vocab_size: int = 1024,
                         corpus_file: str = "pystdlib.txt",
                         val_fraction: float = 0.2,
                         max_chunks_per_module: int = 1000,
                         seed: int = 0):
    """Real downstream classification: which stdlib package does a
    token window come from?

    The labeled companion to the unlabeled ``pystdlib.txt`` pretraining
    corpus (scripts/make_text_corpus.py): windows of ``seq_len`` BPE
    tokens drawn from the named top-level stdlib packages in THIS
    image, labeled by package. Tokenized with the SAME cached BPE
    tokenizer the ``BpeLMLoader`` pretraining run fits (so a
    pretrained encoder's embeddings line up with the fine-tune ids).
    The val split holds out whole FILES (deterministic md5 of the
    file's package-relative name), so val windows come from source
    files the classifier never saw — a generalization split, not a
    shuffled-window split. Honest caveat for transfer experiments: the
    *unlabeled* text of val files does appear in the pretraining
    corpus (the standard SSL setup); the labels do not.

    The reference's data layer is MNIST-only (reference
    data_loader/data_loaders.py); this loader is the text-domain
    real-data analogue, with the same synthetic fallback contract.
    """
    del num_workers
    import hashlib
    import sysconfig

    from .tokenizer import BpeTokenizer, bpe_cache_path

    modules = tuple(modules)
    stdlib = Path(sysconfig.get_paths()["stdlib"])
    tok_path = bpe_cache_path(data_dir, corpus_file, vocab_size)
    legacy_tok = Path(data_dir) / f"{corpus_file}.bpe{vocab_size}.json"
    corpus = Path(data_dir) / corpus_file

    if tok_path.exists():
        tok = BpeTokenizer.load(tok_path)
    elif legacy_tok.exists():
        tok = BpeTokenizer.load(legacy_tok)
    elif corpus.exists():
        # no pretraining run cached a tokenizer yet: fit one exactly
        # like BpeLMLoader would (train split only) and cache it there
        tok = BpeTokenizer.train_from_file(corpus, vocab_size,
                                           sample_until=0.9)
        tok.save(tok_path)
    else:
        tok = None

    if tok is None or not stdlib.exists():
        logger.warning(
            "PyModuleClsLoader: %s missing; using synthetic labeled "
            "data.", tok_path if tok is None else stdlib,
        )
        rng = np.random.default_rng(seed + (0 if training else 1))
        n = 512 if training else 128
        labels = rng.integers(0, len(modules), n)
        # class-dependent token distributions so learning is possible
        tokens = (rng.integers(0, vocab_size // 2, (n, seq_len))
                  + labels[:, None] * (vocab_size // (2 * len(modules))))
        return _make_image_loader(
            {"tokens": tokens.astype(np.int32),
             "label": labels.astype(np.int32)},
            batch_size, shuffle, seed=seed)

    # window cache: encoding ~10 MB of source is seconds of numpy work
    # per process; four loader builds per experiment ask for a cache.
    # The key folds in EVERYTHING the window content depends on,
    # including the tokenizer's actual bytes (a refit BPE with different
    # merges must not reuse windows encoded with the stale merges — the
    # fine-tune ids would silently misalign with pretrained embeddings)
    # and max_chunks_per_module (changes which windows survive thinning).
    tok_file = tok_path if tok_path.exists() else legacy_tok
    tok_fp = hashlib.md5(tok_file.read_bytes()).hexdigest()[:10]
    key = hashlib.md5(
        ("|".join(modules)
         + f"|{seq_len}|{vocab_size}|{val_fraction}"
         + f"|{max_chunks_per_module}|{tok_fp}|v3"
         ).encode()).hexdigest()[:10]
    cache = Path(data_dir) / f"pycls_{key}.npz"
    if not cache.exists():
        tok_rows, lab_rows, split_rows = [], [], []
        for li, mod in enumerate(modules):
            root = stdlib / mod
            files = (sorted(root.rglob("*.py")) if root.is_dir()
                     else [stdlib / f"{mod}.py"])
            files = [f for f in files if f.exists()
                     and "__pycache__" not in f.parts]
            encoded = []
            for f in files:
                rel = f.relative_to(stdlib).as_posix()
                ids = tok.encode(f.read_bytes()[: 256 << 10])
                k = len(ids) // seq_len
                if k == 0:
                    continue
                h = int(hashlib.md5(rel.encode()).hexdigest(), 16)
                encoded.append((h, ids[: k * seq_len].reshape(k, seq_len)))
            # stratified file holdout: walk files in deterministic hash
            # order, sending whole files to val until this MODULE's val
            # share is met — a plain per-file hash threshold can leave a
            # single-big-file class with zero val rows
            encoded.sort(key=lambda e: e[0])
            total_mod = sum(len(c) for _, c in encoded)
            chunks_per_file, val_seen = [], 0
            for _, c in encoded:
                is_val = val_seen < val_fraction * total_mod
                val_seen += len(c) if is_val else 0
                chunks_per_file.append((c, is_val))
            total = sum(len(c) for c, _ in chunks_per_file)
            keep = min(total, max_chunks_per_module)
            # proportional thinning keeps every file represented
            frac = keep / max(total, 1)
            for c, is_val in chunks_per_file:
                take = max(1, int(round(len(c) * frac)))
                c = c[:take]
                tok_rows.append(c)
                lab_rows.append(np.full(len(c), li, np.int32))
                split_rows.append(np.full(len(c), is_val, bool))
        tokens = np.concatenate(tok_rows).astype(np.int32)
        labels = np.concatenate(lab_rows)
        is_val = np.concatenate(split_rows)
        # cross-file duplicate text (encodings/* boilerplate, vendored
        # copies) can reproduce a train window bit-for-bit inside a
        # held-out file — drop those val windows so val measures
        # generalization, never recall
        train_keys = {r.tobytes() for r in tokens[~is_val]}
        dup = np.array([is_val[i] and tokens[i].tobytes() in train_keys
                        for i in range(len(tokens))])
        if dup.any():
            logger.info(
                "PyModuleClsLoader: dropping %d val windows duplicated "
                "in train files", int(dup.sum()),
            )
            tokens, labels, is_val = (
                tokens[~dup], labels[~dup], is_val[~dup]
            )
        tmp = cache.with_name(cache.name + f".tmp{os.getpid()}")
        with open(tmp, "wb") as fh:
            np.savez(fh, tokens=tokens, labels=labels, is_val=is_val)
        os.replace(tmp, cache)
        logger.info(
            "PyModuleClsLoader: cached %d windows (%d val) over %d "
            "classes to %s", len(labels), int(is_val.sum()),
            len(modules), cache,
        )
    data = np.load(cache)
    sel = ~data["is_val"] if training else data["is_val"]
    return _make_image_loader(
        {"tokens": data["tokens"][sel], "label": data["labels"][sel]},
        batch_size, shuffle, seed=seed)


@LOADERS.register("SyntheticLMLoader")
def lm_loader(data_dir: str = "data/", batch_size: int = 8,
              shuffle: bool = True, num_workers: int = 0,
              training: bool = True, n: int = 2048, seq_len: int = 128,
              vocab_size: int = 50257, seed: int = 0):
    del num_workers
    data = synthetic_lm(
        n=n, seq_len=seq_len, vocab_size=vocab_size, seed=seed,
        training=training,
    )
    return _make_image_loader(data, batch_size, shuffle, seed=seed)
