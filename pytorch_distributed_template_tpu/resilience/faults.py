"""Deterministic fault injection: a parsed plan + cheap hook points.

Nothing in a training framework's recovery story is real until a test
can *make* the failure happen: this module turns a one-line plan into
deterministic faults fired at exact steps/batches/epochs, so the
supervisor restart path, the emergency checkpoint, the health
monitor's NaN forensics, the non-finite guard, and the watchdog can
all be exercised on demand (tests, the bench ``chaos`` rung, the CI
``chaos-smoke`` job) instead of waiting for production to break.

Plan grammar (``;``-separated specs)::

    PDT_FAULTS="kill@step:120;nan_grad@step:40;slow_host@step:30:2.5s;\
loader_raise@batch:7;ckpt_write_fail@epoch:2"

    <kind>@<unit>:<at>[:<arg>][@attempt:<n|any>]

Kinds and their designated detectors/recovery (docs/RESILIENCE.md has
the full failure matrix):

===============  ======  ==========================================
kind             unit    effect at the hook point
===============  ======  ==========================================
``kill``         step    SIGKILL this process *before* dispatching
                         the step (hard crash / preemption without
                         notice; nothing flushes, by design)
``crash``        step    raise :class:`FaultInjected` before the
                         step (unhandled-exception path → emergency
                         checkpoint → supervisor restart)
``nan_grad``     step    poison every gradient leaf with NaN inside
                         the compiled step (health monitor +
                         ``skip_nonfinite`` guard path); injected at
                         trace time via ``state.step == at``
``slow_host``    step    ``time.sleep(arg)`` before the step (host
                         straggler / hang; arg like ``2.5s``/``250ms``,
                         default 1s) — trips the watchdog and, when
                         long enough, the supervisor's heartbeat
                         hang detection
``loader_raise`` batch   raise from the data loader at per-epoch
                         batch index ``at`` (input-pipeline failure)
``ckpt_write_fail`` epoch raise from ``CheckpointManager.save``/
                         ``save_interval`` at epoch ``at``; flagged
                         ``is_checkpoint_fault`` so the emergency
                         path knows NOT to re-enter the checkpointer
===============  ======  ==========================================

Serving-path kinds (ISSUE 9) — same grammar, attempt-gated and
once-per-process like the training kinds, but triggered on the
serving stack's own ordinals instead of training steps:

==================  ====  ==========================================
kind                unit  effect at the hook point
==================  ====  ==========================================
``slow_decode``     tick  ``time.sleep(arg)`` inside the continuous
                          scheduler's round when its chunk counter
                          reaches ``at`` (a slow replica: everything
                          in flight there stalls; hedging/deadlines
                          are the designated mitigation)
``hang``            tick  the scheduler round blocks FOREVER at
                          chunk ``at`` while ``/healthz`` and
                          ``/metrics`` keep answering — the wedge
                          the fleet poller's frozen-progress
                          detection exists to catch
``pool_exhaust``    tick  the paged prefix pool reports dry for
                          ``arg`` (default 1s) starting at chunk
                          ``at``: admissions defer, queues build,
                          brownout pressure rises
``stall_stream``    req   the ``at``-th ``/generate`` request of
                          this serve.py process stalls its SSE
                          stream after the first delta WITHOUT
                          closing (the router's deadline-bounded
                          read is what frees the client)
``proxy_latency``   req   ``time.sleep(arg)`` before proxying the
                          ``at``-th router request (a slow hop)
``proxy_blackhole`` req   the first proxy attempt of the ``at``-th
                          router request never reaches a replica
                          and never answers (hedge/timeout territory)
``ckpt_corrupt``    load  the ``at``-th serving-artifact load sees
                          a corrupted manifest digest: the loader
                          must refuse LOUDLY instead of serving
                          garbage weights
==================  ====  ==========================================

KV-tier kinds (ISSUE 13) — the spill hierarchy's fault surface.
``evt`` is the process-global tier-operation ordinal (every demote or
promote the spill tier performs advances it); ``pull`` is the
process-global peer-page-pull ordinal (the fleet manager's
miss-driven pulls and restart re-warm pulls both count):

===================  ====  =========================================
kind                 unit  effect at the hook point
===================  ====  =========================================
``slow_spill``       evt   ``time.sleep(arg)`` before the tier
                           operation (a slow host/disk tier; the
                           admission simply takes longer — nothing
                           may strand)
``corrupt_spill``    evt   flip one byte of the most recently
                           DEMOTED blob after its checksum was
                           recorded: the next read of that entry
                           must fail verification and recompute
                           cold, never serve the torn page
``tier_exhaust``     evt   the spill tier reports full for ``arg``
                           (default 1s): eviction degrades to the
                           classic destroy-on-evict, counted, with
                           zero correctness impact
``peer_pull_timeout`` pull the ``at``-th peer page pull times out
                           (sleeps ``arg``, then fails): the router
                           falls back to a cold prefill — a pull is
                           an optimization, never a dependency
===================  ====  =========================================

Token-integrity kind (ISSUE 18) — the shadow auditor's self-test.
``evt`` here is the pool's page-ADOPTION ordinal (every ``adopt()``
call that lands at least one block advances it — its own counter,
independent of the spill tier's operation ordinal above):

===================  ====  =========================================
kind                 unit  effect at the hook point
===================  ====  =========================================
``corrupt_page``     evt   overwrite the first block adopted by the
                           ``at``-th adoption event with a constant
                           pattern (applied at the pool's next safe
                           device point): warm consumers of that
                           cached page serve WRONG tokens while the
                           cold no-pool replay stays clean — exactly
                           the silent divergence the shadow-replay
                           auditor exists to catch
===================  ====  =========================================

Attempt gating: each spec fires only on one supervisor attempt
(default the first), so a ``kill@step:5`` chaos run dies once and the
restarted attempt — the supervisor exports ``PDT_ATTEMPT=n`` — sails
past the same step. ``@attempt:any`` disables the gate. Every spec
additionally fires at most once per process.

Stdlib-only on purpose: the supervisor and the loader hook import this
module, and neither should drag jax in. The one in-graph fault
(``nan_grad``) is compiled by ``engine/steps.py`` from the plain int
this module hands it.
"""
from __future__ import annotations

import logging
import os
import re
import signal
import sys
import time
from dataclasses import dataclass, field
from typing import List, Optional

logger = logging.getLogger(__name__)

# kind -> the trigger unit its hook point understands
KINDS = {
    "kill": "step",
    "crash": "step",
    "nan_grad": "step",
    "slow_host": "step",
    "loader_raise": "batch",
    "ckpt_write_fail": "epoch",
    # serving-path kinds (ISSUE 9): tick = the continuous scheduler's
    # chunk counter, req = this process's /generate ordinal (router or
    # replica — each counts its own), load = serving-artifact load
    # ordinal. Same attempt gating + once-per-process as the training
    # kinds; the supervisor's PDT_ATTEMPT export means a restarted
    # replica sails past the fault that killed/wedged attempt 1.
    "slow_decode": "tick",
    "hang": "tick",
    "pool_exhaust": "tick",
    "stall_stream": "req",
    "proxy_latency": "req",
    "proxy_blackhole": "req",
    "ckpt_corrupt": "load",
    # KV-tier kinds (ISSUE 13): evt = the spill tier's operation
    # ordinal (demotes + promotes), pull = the fleet manager's peer
    # page-pull ordinal. Same attempt gating + once-per-process rules.
    "slow_spill": "evt",
    "corrupt_spill": "evt",
    "tier_exhaust": "evt",
    "peer_pull_timeout": "pull",
    # token-integrity kind (ISSUE 18): evt = the pool's page-adoption
    # ordinal (separate counter from the spill tier's operation
    # ordinal — KINDS maps unit per kind, so the grammar token is the
    # same while each kind counts its own events)
    "corrupt_page": "evt",
}

#: kinds whose optional arg is a duration (validated at parse time)
_DURATION_KINDS = ("slow_host", "slow_decode", "pool_exhaust",
                   "stall_stream", "proxy_latency", "slow_spill",
                   "tier_exhaust", "peer_pull_timeout")

ENV_PLAN = "PDT_FAULTS"
ENV_ATTEMPT = "PDT_ATTEMPT"


class FaultInjected(RuntimeError):
    """An injected fault firing as an exception.

    ``is_checkpoint_fault`` marks faults raised from inside the
    checkpoint manager — the trainer's emergency-save path skips the
    save when the checkpointer itself is the thing that failed.
    """

    def __init__(self, spec: "FaultSpec", message: str):
        super().__init__(message)
        self.kind = spec.kind
        self.spec = spec
        self.is_checkpoint_fault = spec.kind == "ckpt_write_fail"


_DURATION = re.compile(r"^(\d+(?:\.\d+)?)(ms|s)?$")


def _parse_duration_s(text: str) -> float:
    m = _DURATION.match(text)
    if not m:
        raise ValueError(f"bad duration {text!r} (want e.g. '2.5s', '250ms')")
    value = float(m.group(1))
    return value / 1e3 if m.group(2) == "ms" else value


@dataclass
class FaultSpec:
    kind: str
    unit: str
    at: int
    arg: Optional[str] = None
    attempt: Optional[int] = 1     # None = any attempt
    fired: bool = field(default=False, compare=False)

    @property
    def duration_s(self) -> float:
        return _parse_duration_s(self.arg) if self.arg else 1.0

    def describe(self) -> str:
        out = f"{self.kind}@{self.unit}:{self.at}"
        if self.arg:
            out += f":{self.arg}"
        if self.attempt != 1:
            out += f"@attempt:{self.attempt if self.attempt else 'any'}"
        return out


@dataclass
class FaultPlan:
    specs: List[FaultSpec] = field(default_factory=list)

    @classmethod
    def parse(cls, text: Optional[str]) -> "FaultPlan":
        specs: List[FaultSpec] = []
        for token in (text or "").split(";"):
            token = token.strip()
            if not token:
                continue
            parts = token.split("@")
            if len(parts) < 2:
                raise ValueError(
                    f"bad fault spec {token!r}: want kind@unit:at[:arg]"
                )
            kind = parts[0].strip()
            if kind not in KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} (known: {sorted(KINDS)})"
                )
            attempt: Optional[int] = 1
            for extra in parts[2:]:
                key, _, val = extra.partition(":")
                if key.strip() != "attempt":
                    raise ValueError(
                        f"bad fault qualifier {extra!r} in {token!r} "
                        "(only @attempt:<n|any> is understood)"
                    )
                attempt = None if val.strip() == "any" else int(val)
            trigger = parts[1].split(":")
            unit = trigger[0].strip()
            if unit != KINDS[kind]:
                raise ValueError(
                    f"fault {kind!r} triggers on {KINDS[kind]!r}, "
                    f"not {unit!r}"
                )
            if len(trigger) < 2 or len(trigger) > 3:
                raise ValueError(
                    f"bad trigger {parts[1]!r} in {token!r}: "
                    "want unit:at[:arg]"
                )
            at = int(trigger[1])
            arg = trigger[2].strip() if len(trigger) == 3 else None
            if kind in _DURATION_KINDS and arg is not None:
                _parse_duration_s(arg)  # validate at parse time
            specs.append(FaultSpec(kind, unit, at, arg, attempt))
        return cls(specs)

    def active(self, attempt: int) -> List[FaultSpec]:
        return [s for s in self.specs
                if s.attempt is None or s.attempt == attempt]

    def __bool__(self) -> bool:
        return bool(self.specs)


# ---------------------------------------------------------------------------
# process-global plan + hook points
# ---------------------------------------------------------------------------

_plan: Optional[FaultPlan] = None
_attempt: int = 1
_active: List[FaultSpec] = []
# id() of the loader loader_raise targets; None = any loader. The
# trainer binds its TRAIN loader so a validation/eval pass sharing the
# same loader class cannot consume the one-shot spec at ITS batch 7.
_watched_loader_id: Optional[int] = None


def configure(text: Optional[str] = None,
              attempt: Optional[int] = None) -> FaultPlan:
    """(Re)install the process fault plan.

    ``PDT_FAULTS`` in the environment wins over ``text`` (the operator/
    supervisor-level injection path must be able to override a config
    file); both absent installs an empty plan. ``attempt`` defaults to
    ``PDT_ATTEMPT`` (exported by the supervisor), else 1.
    """
    global _plan, _attempt, _active
    env = os.environ.get(ENV_PLAN)
    _plan = FaultPlan.parse(env if env else text)
    if attempt is None:
        try:
            attempt = int(os.environ.get(ENV_ATTEMPT, "1"))
        except ValueError:
            attempt = 1
    _attempt = attempt
    _active = _plan.active(_attempt)
    if _active:
        logger.warning(
            "FAULT PLAN ACTIVE (attempt %d): %s", _attempt,
            "; ".join(s.describe() for s in _active),
        )
    return _plan


def reset() -> None:
    """Drop the plan entirely (tests)."""
    global _plan, _attempt, _active, _watched_loader_id, _load_ordinal
    global _tier_ordinal, _pull_ordinal, _page_ordinal
    _plan, _attempt, _active, _watched_loader_id = None, 1, [], None
    _load_ordinal = 0
    _tier_ordinal = 0
    _pull_ordinal = 0
    _page_ordinal = 0


def watch_loader(loader) -> None:
    """Bind ``loader_raise`` to one loader instance (the trainer binds
    its train loader). Unbound (the default, e.g. a bare loader in a
    test), the hook fires from any loader."""
    global _watched_loader_id
    _watched_loader_id = id(loader) if loader is not None else None


def _ensure_configured() -> None:
    if _plan is None:
        configure()


def _take(kind: str, value: int) -> Optional[FaultSpec]:
    """The not-yet-fired active spec of ``kind`` triggering at
    ``value``, marked fired; None otherwise. O(active specs) — the
    plan is empty in production, a handful of entries under chaos."""
    for s in _active:
        if s.kind == kind and not s.fired and s.at == int(value):
            s.fired = True
            return s
    return None


def on_step(step: int) -> None:
    """Trainer-loop hook, called once per batch with the global step,
    BEFORE the step is dispatched (``kill@step:N`` ⇒ exactly N steps
    completed). Order: slow_host (then continue), crash (raise),
    kill (never returns)."""
    if _plan is None:
        _ensure_configured()
    if not _active:
        return
    s = _take("slow_host", step)
    if s is not None:
        logger.warning("fault slow_host: sleeping %.3fs at step %d",
                       s.duration_s, step)
        time.sleep(s.duration_s)
    s = _take("crash", step)
    if s is not None:
        raise FaultInjected(
            s, f"injected crash at step {step} ({s.describe()})"
        )
    s = _take("kill", step)
    if s is not None:
        # raw write + SIGKILL: simulate a hard host loss — no flushes,
        # no atexit, no emergency checkpoint. The surviving evidence is
        # whatever was already durable, exactly like a real preemption
        # without notice.
        try:
            os.write(2, f"fault kill: SIGKILL at step {step}\n".encode())
        except OSError:
            pass
        os.kill(os.getpid(), signal.SIGKILL)


def on_loader_batch(batch_index: int, loader=None) -> None:
    """Data-loader hook (per-epoch batch ordinal, before the gather).

    ``loader``: the iterating loader instance, checked against
    :func:`watch_loader`'s binding so only the targeted (train) input
    pipeline can fire the one-shot spec."""
    if _plan is None:
        _ensure_configured()
    if not _active:
        return
    if (_watched_loader_id is not None and loader is not None
            and id(loader) != _watched_loader_id):
        return
    s = _take("loader_raise", batch_index)
    if s is not None:
        raise FaultInjected(
            s, f"injected loader failure at batch {batch_index} "
               f"({s.describe()})"
        )


def on_checkpoint_save(epoch: int) -> None:
    """Checkpoint-manager hook (save/save_interval entry)."""
    if _plan is None:
        _ensure_configured()
    if not _active:
        return
    s = _take("ckpt_write_fail", epoch)
    if s is not None:
        raise FaultInjected(
            s, f"injected checkpoint write failure at epoch {epoch} "
               f"({s.describe()})"
        )


def nan_grad_step() -> Optional[int]:
    """The global step whose gradients should be NaN-poisoned, or None.

    Read once at trainer build time and compiled into the train step
    (``engine/steps.make_train_step(inject_nan_grad_step=...)``) — the
    injection itself is a branchless in-graph select, so the fault
    fires at the exact step with zero host involvement.
    """
    _ensure_configured()
    for s in _active:
        if s.kind == "nan_grad":
            return s.at
    return None


# ---------------------------------------------------------------------------
# serving-path hook points (ISSUE 9)
# ---------------------------------------------------------------------------

#: serving-artifact load ordinal (1-based) for the ``load`` unit
_load_ordinal = 0


def on_serve_tick(tick: int):
    """Continuous-scheduler hook, called once per scheduler round with
    the engine's chunk counter. Handles ``slow_decode`` (sleep, then
    continue) and ``hang`` (block this thread FOREVER — ``/healthz``
    keeps answering from the HTTP threads, which is exactly the wedge
    the fleet poller's frozen-progress detection exists for) in place;
    returns the fired ``pool_exhaust`` spec (the engine owns the drain
    window) or None."""
    if _plan is None:
        _ensure_configured()
    if not _active:
        return None
    s = _take("slow_decode", tick)
    if s is not None:
        logger.warning("fault slow_decode: sleeping %.3fs at tick %d",
                       s.duration_s, tick)
        time.sleep(s.duration_s)
    s = _take("hang", tick)
    if s is not None:
        logger.warning("fault hang: wedging scheduler at tick %d "
                       "(healthz stays up)", tick)
        import threading

        threading.Event().wait()       # never set: wedged by design
    return _take("pool_exhaust", tick)


def on_serve_request(ordinal: int):
    """Replica request hook (serve.py ``/generate`` ordinal, 1-based):
    returns the fired ``stall_stream`` spec (the SSE handler owns the
    stall) or None."""
    if _plan is None:
        _ensure_configured()
    if not _active:
        return None
    return _take("stall_stream", ordinal)


def on_proxy_request(ordinal: int):
    """Router request hook (front-door ``/generate`` ordinal,
    1-based). Handles ``proxy_latency`` in place (sleep before the
    hop); returns the fired ``proxy_blackhole`` spec (the router's
    proxy attempt owns the blackhole) or None."""
    if _plan is None:
        _ensure_configured()
    if not _active:
        return None
    s = _take("proxy_latency", ordinal)
    if s is not None:
        logger.warning("fault proxy_latency: sleeping %.3fs before "
                       "request %d", s.duration_s, ordinal)
        time.sleep(s.duration_s)
    return _take("proxy_blackhole", ordinal)


def on_artifact_load():
    """Serving-artifact load hook (checkpoint/manager manifest
    verification): each call advances the process-global load ordinal;
    returns the fired ``ckpt_corrupt`` spec or None. The verifier
    perturbs its OBSERVED digest when the spec fires — deterministic
    corruption without destroying the artifact on disk."""
    global _load_ordinal
    if _plan is None:
        _ensure_configured()
    _load_ordinal += 1
    if not _active:
        return None
    return _take("ckpt_corrupt", _load_ordinal)


#: spill-tier operation ordinal (1-based) for the ``evt`` unit —
#: every demote or promote the tier performs advances it
_tier_ordinal = 0

#: peer page-pull ordinal (1-based) for the ``pull`` unit
_pull_ordinal = 0


def on_tier_event():
    """Spill-tier hook (engine/kvcache.SpillTier, ISSUE 13): each
    call advances the tier-operation ordinal. ``slow_spill`` sleeps
    in place (the tier is just slow; the caller proceeds); returns
    ``{"corrupt": spec|None, "exhaust": spec|None}`` — the tier owns
    the byte flip and the full-window — or None with no plan active."""
    global _tier_ordinal
    if _plan is None:
        _ensure_configured()
    _tier_ordinal += 1
    if not _active:
        return None
    s = _take("slow_spill", _tier_ordinal)
    if s is not None:
        logger.warning("fault slow_spill: sleeping %.3fs at tier op %d",
                       s.duration_s, _tier_ordinal)
        time.sleep(s.duration_s)
    return {"corrupt": _take("corrupt_spill", _tier_ordinal),
            "exhaust": _take("tier_exhaust", _tier_ordinal)}


#: pool page-adoption ordinal (1-based) for the ISSUE 18
#: ``corrupt_page`` kind — every adopt() landing >= 1 block advances it
_page_ordinal = 0


def on_page_adopt():
    """Pool page-adoption hook (engine/kvcache.PrefixCache.adopt,
    ISSUE 18): each adoption event advances the page ordinal; returns
    the fired ``corrupt_page`` spec (the pool owns the overwrite —
    deferred to its next safe device point so a mid-tick pool donation
    can never invalidate a live engine cache) or None."""
    global _page_ordinal
    if _plan is None:
        _ensure_configured()
    _page_ordinal += 1
    if not _active:
        return None
    return _take("corrupt_page", _page_ordinal)


def on_peer_pull():
    """Peer page-pull hook (fleet/replicas.FleetManager, ISSUE 13):
    each call advances the pull ordinal; returns the fired
    ``peer_pull_timeout`` spec (the caller sleeps its duration and
    then treats the pull as timed out — cold-prefill fallback) or
    None."""
    global _pull_ordinal
    if _plan is None:
        _ensure_configured()
    _pull_ordinal += 1
    if not _active:
        return None
    return _take("peer_pull_timeout", _pull_ordinal)


def install_from_env_or_config(config_text: Optional[str]) -> None:
    """Trainer-entry helper: (re)configure from PDT_FAULTS / the
    ``trainer.faults`` config string. Called once per Trainer build so
    a fresh trainer in the same process gets fresh one-shot flags."""
    configure(config_text)


def main(argv=None) -> int:
    """``python -m ...resilience.faults 'PLAN'`` — parse + describe a
    plan (CI/operator sanity check; exit 2 on a malformed plan)."""
    text = (argv or sys.argv[1:] or [os.environ.get(ENV_PLAN, "")])[0]
    try:
        plan = FaultPlan.parse(text)
    except ValueError as e:
        print(f"invalid fault plan: {e}", file=sys.stderr)
        return 2
    for s in plan.specs:
        print(s.describe())
    return 0


if __name__ == "__main__":
    sys.exit(main())
