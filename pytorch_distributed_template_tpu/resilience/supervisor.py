"""In-process training supervisor: spawn, classify, back off, resume.

Replaces the bash retry loop (``scripts/run_resilient.sh``) with a
process manager that actually understands what happened to its child:

- **exit classification** — ``clean`` (rc 0), ``preemption`` (the
  trainer's SIGTERM-drain exit code :data:`EXIT_PREEMPTED`, or the
  child dying to an external SIGTERM), ``crash`` (any other nonzero
  exit or signal), ``hang`` (heartbeat went stale and the supervisor
  had to SIGTERM-drain then SIGKILL the child);
- **restart policy** — crashes and hangs burn a bounded restart
  budget with exponential backoff + jitter; preemptions restart at
  the base delay without burning budget (they are routine fleet
  events, not bugs); a rolling crash-loop window gives up early when
  restarts cluster (the classic mis-config loop that a plain
  ``MAX_RESTARTS=10`` would grind through for an hour);
- **hang detection** — the trainer touches a heartbeat file every
  step (``utils/watchdog.StepWatchdog``, wired off the same beat the
  in-process watchdog uses; the supervisor exports
  ``PDT_HEARTBEAT_FILE``). A stale heartbeat ⇒ SIGTERM (grace period
  for the preemption checkpoint path) ⇒ SIGKILL ⇒ restart;
- **drain** — SIGTERM/SIGINT to the supervisor forwards SIGTERM to
  the child (its preemption handler checkpoints and exits), waits,
  and exits without restarting — so preempting the supervisor
  preempts the training, cleanly;
- **evidence** — every lifecycle event is one JSONL line in
  ``supervisor.jsonl`` (FlightRecorder-style: ``v``/``t``/``event``
  plus event fields), which ``scripts/telemetry_report.py`` folds
  into its report and ``serve.py`` surfaces as ``restarts_total`` /
  ``last_restart_cause``.

Stdlib-only: this module must import in milliseconds and never touch
jax — it manages jax processes, it is not one.
"""
from __future__ import annotations

import collections
import json
import os
import random
import shlex
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import List, Optional

# The trainer exits with this code when it stopped because of a
# preemption notice (checkpointed + drained, work NOT finished): 75 is
# BSD EX_TEMPFAIL — "try again later", which is exactly the semantic.
# The supervisor restarts these without burning the crash budget.
EXIT_PREEMPTED = 75

SCHEMA_VERSION = 1

ENV_EVENTS = "PDT_SUPERVISOR_EVENTS"
ENV_HEARTBEAT = "PDT_HEARTBEAT_FILE"
ENV_ATTEMPT = "PDT_ATTEMPT"


def classify_exit(returncode: int, hang: bool = False) -> str:
    """Map a child's exit to ``clean|preemption|crash|hang``.

    ``hang=True`` (the supervisor killed the child after a stale
    heartbeat) wins over the resulting signal code. A child dying to
    SIGTERM (rc ``-15``) counts as preemption: cloud maintenance
    SIGTERMs the process directly, and the trainer's graceful path
    exits :data:`EXIT_PREEMPTED` instead.
    """
    if hang:
        return "hang"
    if returncode == 0:
        return "clean"
    if returncode == EXIT_PREEMPTED or returncode == -signal.SIGTERM:
        return "preemption"
    return "crash"


def compute_backoff(failures: int, base_s: float, max_s: float,
                    jitter: float, rand=random.random) -> float:
    """Delay before restart ``failures`` (1-based consecutive crash
    count): ``min(base * 2^(n-1), max)`` stretched by up to
    ``jitter`` fraction — the jitter decorrelates a fleet of
    supervisors restarting into the same shared service."""
    if base_s <= 0:
        return 0.0
    delay = min(base_s * (2.0 ** max(failures - 1, 0)), max_s)
    return delay * (1.0 + max(jitter, 0.0) * rand())


def _exit_code(returncode: int) -> int:
    """Shell-safe supervisor exit code for a child rc (signals map to
    the conventional 128+N)."""
    return 128 - returncode if returncode < 0 else returncode


class EventLog:
    """Append-only JSONL lifecycle log (``supervisor.jsonl``).

    Line-buffered + per-line flush: the log is the post-mortem record,
    and the supervisor itself can be killed at any point."""

    def __init__(self, path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._file = open(self.path, "a", buffering=1)

    def log(self, event: str, **fields) -> dict:
        rec = {"v": SCHEMA_VERSION, "t": round(time.time(), 3),
               "event": event}
        rec.update({k: v for k, v in fields.items() if v is not None})
        try:
            self._file.write(json.dumps(rec, default=repr) + "\n")
            self._file.flush()
        except (OSError, ValueError):
            pass  # a full disk must not take the supervisor down too
        return rec

    def close(self) -> None:
        try:
            self._file.close()
        except OSError:
            pass


def read_supervisor_stats(path) -> dict:
    """Fold a ``supervisor.jsonl`` into the counters the serving
    endpoints and the telemetry analyzer expose."""
    restarts = 0
    causes: collections.Counter = collections.Counter()
    last_cause = None
    attempts = 0
    gave_up = clean = False
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail line
            ev = rec.get("event")
            attempts = max(attempts, int(rec.get("attempt", 0) or 0))
            if ev == "restart":
                restarts += 1
                last_cause = rec.get("cause")
                causes[rec.get("cause", "?")] += 1
            elif ev == "give_up":
                gave_up = True
            elif ev == "clean":
                clean = True
    return {
        "restarts_total": restarts,
        "last_restart_cause": last_cause,
        "causes": dict(causes),
        "attempts": attempts,
        "gave_up": gave_up,
        "clean": clean,
    }


@dataclass
class SupervisorConfig:
    max_restarts: int = 10          # consecutive crash/hang budget
    #                                 (preemptions free; a stable run
    #                                 resets the streak)
    restart_delay_s: float = 10.0   # backoff base
    max_delay_s: float = 300.0      # backoff cap
    jitter: float = 0.25            # fractional jitter on the delay
    hang_timeout_s: float = 0.0     # heartbeat staleness; 0 disables
    term_grace_s: float = 10.0      # SIGTERM→SIGKILL grace on a hang
    crash_loop_window_s: float = 600.0
    crash_loop_max: int = 5         # crash/hang restarts in window ⇒ give up
    stable_runtime_s: float = 600.0  # a run this long resets the
    #                                  consecutive-crash counter/backoff
    poll_s: float = 0.5
    events_path: str = "supervisor.jsonl"
    heartbeat_path: Optional[str] = None  # default: next to events_path
    child_output_path: Optional[str] = None  # append child stdout+stderr
    #                                 here (fleet replicas get one log
    #                                 file each); None inherits ours
    child_env: Optional[dict] = None  # extra env for the child, merged
    #                                 over ours (fleet chaos: one
    #                                 replica gets its own PDT_FAULTS
    #                                 plan while its siblings run clean)
    rand: object = field(default=random.random, repr=False)


class Supervisor:
    """Run ``cmd`` until it exits cleanly or the budget is spent.

    :param cmd: full child argv (``scripts/supervise.py`` builds the
        ``python train.py --auto-resume ...`` default).
    :param cfg: :class:`SupervisorConfig`.
    """

    def __init__(self, cmd: List[str], cfg: SupervisorConfig):
        self.cmd = list(cmd)
        self.cfg = cfg
        self.events = EventLog(cfg.events_path)
        hb = cfg.heartbeat_path or str(
            Path(cfg.events_path).with_name("heartbeat")
        )
        self.heartbeat_path = Path(hb)
        self.restarts_total = 0          # every relaunch
        self.crash_restarts = 0          # budget-burning relaunches
        self._restart_times: collections.deque = collections.deque()
        self._child: Optional[subprocess.Popen] = None
        self._drain = False

    # -- signal forwarding --------------------------------------------------

    def _install_signals(self) -> None:
        def handler(signum, frame):  # noqa: ARG001
            self._drain = True

        try:
            signal.signal(signal.SIGTERM, handler)
            signal.signal(signal.SIGINT, handler)
        except ValueError:
            pass  # not the main thread (tests)

    # -- child lifecycle ----------------------------------------------------

    def _spawn(self, attempt: int) -> subprocess.Popen:
        env = dict(os.environ)
        if self.cfg.child_env:
            env.update({str(k): str(v)
                        for k, v in self.cfg.child_env.items()})
        env[ENV_ATTEMPT] = str(attempt)
        env[ENV_EVENTS] = str(self.events.path)
        env[ENV_HEARTBEAT] = str(self.heartbeat_path)
        # a stale heartbeat from the previous attempt must not mask a
        # child that hangs before its first beat
        try:
            self.heartbeat_path.unlink()
        except OSError:
            pass
        if self.cfg.child_output_path:
            # per-child log file (fleet replicas): APPEND so restarts
            # extend one history; the fd is the child's after spawn
            out = open(self.cfg.child_output_path, "ab", buffering=0)
            try:
                child = subprocess.Popen(self.cmd, env=env, stdout=out,
                                         stderr=subprocess.STDOUT)
            finally:
                out.close()
        else:
            child = subprocess.Popen(self.cmd, env=env)
        self.events.log("spawn", attempt=attempt, pid=child.pid,
                        cmd=shlex.join(self.cmd) if attempt == 1 else None)
        return child

    # -- external control (fleet manager) -----------------------------------

    def request_drain(self) -> None:
        """Ask the supervisor to stop: same effect as SIGTERM to it —
        the current child is SIGTERM-drained (its preemption handler
        runs) and the run loop exits without restarting. Thread-safe
        and callable from embedders (the fleet manager runs one
        supervisor per replica in a thread, where POSIX signals cannot
        be delivered per-instance)."""
        self._drain = True

    def signal_child(self, sig: int) -> bool:
        """Deliver ``sig`` to the CURRENT child, if one is running
        (chaos injection / rolling restarts: SIGKILL ⇒ classified
        crash, SIGTERM ⇒ the child's own drain path ⇒ preemption —
        either way the run loop restarts it within policy). Returns
        whether a live child was signalled."""
        child = self._child
        if child is None or child.poll() is not None:
            return False
        try:
            child.send_signal(sig)
        except OSError:
            return False
        return True

    def _heartbeat_age_s(self, spawned_at: float) -> float:
        try:
            mtime = self.heartbeat_path.stat().st_mtime
        except OSError:
            mtime = spawned_at  # no beat yet: age from spawn
        return time.time() - max(mtime, spawned_at)

    def _wait(self, child: subprocess.Popen, attempt: int):
        """Block until the child exits; returns ``(rc, hang)``.

        Polls for exit, heartbeat staleness (⇒ SIGTERM-drain then
        SIGKILL) and the supervisor's own drain flag (⇒ forward
        SIGTERM, wait, no restart)."""
        spawned_at = time.time()
        term_sent_at = None
        while True:
            rc = child.poll()
            if rc is not None:
                return rc, False
            if self._drain and term_sent_at is None:
                self.events.log("drain", attempt=attempt, pid=child.pid)
                child.terminate()
                term_sent_at = time.time()
            if term_sent_at is not None:
                # draining (supervisor preempted): bounded wait, then kill
                if time.time() - term_sent_at > max(self.cfg.term_grace_s,
                                                    1.0) * 6:
                    child.kill()
                time.sleep(min(self.cfg.poll_s, 0.1))
                continue
            if (self.cfg.hang_timeout_s > 0
                    and self._heartbeat_age_s(spawned_at)
                    > self.cfg.hang_timeout_s):
                self.events.log(
                    "hang", attempt=attempt, pid=child.pid,
                    heartbeat_age_s=round(
                        self._heartbeat_age_s(spawned_at), 1),
                )
                child.terminate()          # drain: preemption handler may
                try:                       # still land a checkpoint
                    child.wait(timeout=max(self.cfg.term_grace_s, 0.1))
                except subprocess.TimeoutExpired:
                    child.kill()
                    child.wait()
                return child.returncode, True
            time.sleep(self.cfg.poll_s)

    # -- the loop -----------------------------------------------------------

    def run(self) -> int:
        cfg = self.cfg
        self._install_signals()
        self.events.log(
            "start", max_restarts=cfg.max_restarts,
            restart_delay_s=cfg.restart_delay_s,
            hang_timeout_s=cfg.hang_timeout_s,
            crash_loop=(f"{cfg.crash_loop_max}/"
                        f"{cfg.crash_loop_window_s:g}s"),
        )
        attempt = 0
        while True:
            attempt += 1
            child = self._child = self._spawn(attempt)
            t0 = time.monotonic()
            rc, hang = self._wait(child, attempt)
            runtime_s = round(time.monotonic() - t0, 3)
            cause = classify_exit(rc, hang=hang)
            self.events.log("exit", attempt=attempt, returncode=rc,
                            cause=cause, runtime_s=runtime_s)
            if self._drain:
                # supervisor was told to stop: report the child's state
                # and get out of the way — no restart
                self.events.log("stopped", attempt=attempt,
                                returncode=rc, cause=cause)
                return 0 if rc in (0, EXIT_PREEMPTED) else _exit_code(rc)
            if cause == "clean":
                self.events.log("clean", attempt=attempt,
                                restarts_total=self.restarts_total)
                return 0
            burns = cause in ("crash", "hang")
            if burns:
                if (cfg.stable_runtime_s > 0
                        and runtime_s >= cfg.stable_runtime_s
                        and self.crash_restarts):
                    # a long healthy run before this crash: treat it as
                    # a fresh failure, not the Nth of a streak — a
                    # multi-week job with a rare crash per day must not
                    # creep to max backoff and exhaust the budget
                    self.events.log(
                        "stable_reset", attempt=attempt,
                        runtime_s=runtime_s,
                        crash_restarts=self.crash_restarts,
                    )
                    self.crash_restarts = 0
                self.crash_restarts += 1
                if self.crash_restarts > cfg.max_restarts:
                    self.events.log(
                        "give_up", attempt=attempt, reason="budget",
                        returncode=rc, cause=cause,
                        restarts_total=self.restarts_total,
                    )
                    return _exit_code(rc)
                # crash-loop window counts ONLY budget-burning causes:
                # preemption churn is routine fleet weather and must
                # never trip the give-up heuristic
                now = time.monotonic()
                self._restart_times.append(now)
                while (self._restart_times
                       and now - self._restart_times[0]
                       > cfg.crash_loop_window_s):
                    self._restart_times.popleft()
                if len(self._restart_times) > cfg.crash_loop_max:
                    self.events.log(
                        "give_up", attempt=attempt, reason="crash_loop",
                        window_s=cfg.crash_loop_window_s,
                        restarts_in_window=len(self._restart_times),
                        returncode=rc, cause=cause,
                    )
                    return _exit_code(rc)
            delay = (
                compute_backoff(self.crash_restarts, cfg.restart_delay_s,
                                cfg.max_delay_s, cfg.jitter, cfg.rand)
                if burns else
                compute_backoff(1, cfg.restart_delay_s, cfg.max_delay_s,
                                cfg.jitter, cfg.rand)
            )
            self.restarts_total += 1
            self.events.log(
                "restart", attempt=attempt, cause=cause,
                delay_s=round(delay, 3),
                restarts_total=self.restarts_total,
                crash_restarts=self.crash_restarts,
                budget_left=max(cfg.max_restarts - self.crash_restarts, 0),
            )
            # sleep in poll_s slices so a drain signal during backoff
            # exits promptly instead of after a multi-minute delay
            end = time.monotonic() + delay
            while time.monotonic() < end:
                if self._drain:
                    self.events.log("stopped", attempt=attempt,
                                    cause="drain_during_backoff")
                    return 0
                time.sleep(min(cfg.poll_s, max(end - time.monotonic(), 0)))
