"""Resilience subsystem: supervisor, deterministic fault injection,
emergency checkpoints, step-accurate resume.

Three cooperating layers (docs/RESILIENCE.md):

- :mod:`.supervisor` — an in-process replacement for the old bash
  relaunch loop: spawns ``train.py``, classifies exits (clean /
  preemption / crash / hang), enforces a restart budget with
  exponential backoff + jitter and a rolling crash-loop window, detects
  hangs via the trainer's heartbeat file, and logs every lifecycle
  event as JSONL (``supervisor.jsonl``). Stdlib-only: importing it must
  never pull in jax (the supervisor process manages jax processes, it
  is not one).
- :mod:`.faults` — a config/env-driven deterministic fault plan
  (``PDT_FAULTS="kill@step:120;nan_grad@step:40;..."``) with hook
  points in the trainer loop, the compiled train step, the data
  loader, and the checkpoint manager, so every recovery path is
  exercisable on demand in tests, the bench ``chaos`` rung, and CI.
- step-accurate resume — checkpoints gain a ``data_state`` sidecar
  (next batch, sampler cursor, RNG fingerprint) written on interval,
  epoch, preemption, and emergency paths; the trainer fast-forwards
  the loader to the exact next batch on resume
  (``checkpoint/manager.py`` + ``engine/trainer.py``).
"""
from .supervisor import EXIT_PREEMPTED  # noqa: F401
