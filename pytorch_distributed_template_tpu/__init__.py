"""pytorch_distributed_template_tpu: a TPU-native distributed training framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
Yun-960/Pytorch-Distributed-Template (a PyTorch-DDP/NCCL experiment template):
config-driven experiments, distributed data-parallel training over a TPU
device mesh, checkpoint/resume, distributed evaluation, and a 3-tier
observability stack — plus the parallelism the reference lacks (tensor,
sequence/ring-attention, FSDP-style sharding) expressed SPMD-first with
`jax.sharding` + `jit` and Pallas kernels for the hot ops.

Layout (mirrors the reference's layer map, SURVEY.md §1, re-shaped for TPU):
  config/        JSON experiment specs -> objects (registry DI, CLI overrides)
  parallel/      mesh construction, sharding rules, collectives, host sync
  data/          per-host sharded sampling, loaders, device prefetch
  models/        flax model zoo (see models/__init__ for what is registered)
  engine/        TrainState, jitted steps, Trainer/Evaluator loops
  checkpoint/    orbax-backed save/resume with the reference's policy
  observability/ logging, TensorBoard writer, metric tracking, profiling
  utils/         small host-side helpers
"""

__version__ = "0.1.0"
