"""Fused flash attention: Pallas TPU forward kernel + blockwise backward.

The reference delegates all kernels to cuDNN (SURVEY.md §2.2); here the one
op XLA doesn't fuse perfectly at long sequence length — attention — gets an
in-tree Pallas kernel (see /opt/skills/guides/pallas_guide.md):

- **forward**: grid (batch*head, q-block, kv-block) with the KV dimension
  innermost — K/V blocks STREAM through VMEM (Pallas double-buffers the
  HBM→VMEM copies against compute), and the online-softmax state (m, l,
  accumulator) lives in VMEM scratch carried across the KV grid steps. Only
  a [BQ, BK] score tile ever exists, and VMEM use is independent of T, so
  sequence length is bounded by HBM, not VMEM. Causal programs predicate
  away tiles beyond the diagonal (~2× fewer FLOPs). Outputs carry the
  logsumexp rows (trailing unit lane axis: Mosaic tiling-legal).
- **backward**: the standard two-kernel flash backward, also Pallas and
  also fully streamed. A dk/dv kernel (grid over KV blocks × q blocks, q
  innermost, dk/dv accumulated in scratch) and a dq kernel (grid over q
  blocks × KV blocks, KV innermost), both recomputing the probability tile
  from the saved logsumexp in f32 so only [BQ, BK] tiles ever exist.
  ``_bwd_3d`` (plain-JAX blockwise) is kept as the oracle the Pallas
  kernels are tested against.

Accumulation is float32 throughout regardless of input dtype.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
# Measured on TPU v5e (d=64): 512x512 beats 128x128 by 2.4x at t=2048 —
# streaming K/V makes VMEM independent of T, so blocks this large are safe
# and amortize the per-grid-step overhead. Sequences shorter than a block
# fall back to one block. End-to-end vs XLA attention (in-jit chained
# scan, the honest timing on this platform — see bench.py): ~2x on full
# fwd+bwd (grads wrt q,k,v) at t=8192 (b=1, h=12), 1.6x on the full
# GPT-2-small train step at t=1024; XLA attention additionally OOMs
# where flash streams
# (b=4, t=8192 materializes a ~12.9 GB float32 score tensor — scores
# upcast to f32 for the softmax — plus a same-size probs tensor).
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512


def _tile_mask(i, j, block_q, block_k, causal, t_valid, t, window=0):
    """NEG_INF mask for score tile (q block i, kv block j); None if no-op.

    ``window > 0`` adds the sliding-window band ``q_pos - k_pos < window``
    (Mistral-style, combined with ``causal``)."""
    need = causal or t_valid < t or window > 0
    if not need:
        return None
    q_pos = i * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = j * block_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    ok = jnp.full((block_q, block_k), True)
    if causal:
        ok = q_pos >= k_pos
    if window > 0:
        ok = ok & (q_pos - k_pos < window)
    if t_valid < t:  # keys past t_valid are padding
        ok = ok & (k_pos < t_valid)
    return ok


def _band_start(i, block_q, block_k, window):
    """First KV tile that can intersect q block ``i``'s sliding band.
    Floor division of a possibly-negative numerator rounds toward -inf,
    which the max-with-0 absorbs."""
    return jnp.maximum(0, (i * block_q - (window - 1)) // block_k)


def _num_band_tiles(span_block, tile_block, window):
    """Tiles of size ``tile_block`` intersecting a band that spans
    ``span_block + window - 1`` positions, +1 slack for tile misalignment
    (static). Used for the KV band per q block (span=block_q,
    tile=block_k) and, with the roles swapped, the q band per KV block in
    the dkv backward."""
    return (span_block + window - 1 + tile_block - 1) // tile_block + 1


def _q_band_start(j, block_q, block_k):
    """First q block whose rows can (causally) see KV tile ``j`` — the
    diagonal block. Shared by the dkv kernel and its index map so data
    placement and predication cannot desync."""
    return (j * block_k) // block_q


def _banded_index(start_fn, num_blocks):
    """Index map for a banded grid axis: block = clip(start(outer) + off).
    The kernel predicates with the UNclipped index; the clip only keeps
    the prefetch legal at the edges."""

    def index(b, outer, off):
        return b, jnp.clip(start_fn(outer) + off, 0, num_blocks - 1), 0

    return index


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale: float, causal: bool, t_valid: int, t: int,
                num_kv: int, window: int = 0, banded: bool = False,
                nb: int = 0):
    # grid (BH, num_q, num_kv) — or (BH, num_q, nb) when ``banded`` (causal
    # sliding window: only the ~window-wide KV tile band per q block is in
    # the grid at all, so both the compute AND the HBM->VMEM K/V streaming
    # are O(T * window)). kv innermost. q_ref/o_ref: [1, BQ, D];
    # k_ref/v_ref: [1, BK, D] (streamed); lse_ref: [1, BQ, 1] (the trailing
    # unit lane axis keeps the block shape legal under Mosaic's
    # (8, 128)-or-equal tiling rule). Scratch m/l: [BQ, 1] f32, acc:
    # [BQ, D] f32 — the online-softmax state carried across the kv dim.
    i = pl.program_id(1)
    jb = pl.program_id(2)
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    if banded:
        j = _band_start(i, block_q, block_k, window) + jb
        last = nb - 1
    else:
        j = jb
        last = num_kv - 1

    @pl.when(jb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale       # [BQ, D]
        k_blk = k_ref[0].astype(jnp.float32)           # [BK, D]
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                              # [BQ, BK]
        ok = _tile_mask(i, j, block_q, block_k, causal, t_valid, t,
                        window)
        if ok is not None:
            s = jnp.where(ok, s, NEG_INF)
        m = m_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_scr[...] = l_scr[...] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = m_new

    pred = None
    if causal:
        # tiles strictly beyond the diagonal are predicated away entirely
        pred = j * block_k < (i + 1) * block_q
    if window > 0:
        # tiles entirely below the band contribute nothing
        in_band = (j + 1) * block_k > i * block_q - window + 1
        pred = in_band if pred is None else (pred & in_band)
    if banded:
        pred = pred & (j <= num_kv - 1)  # nb overshoot near the edges
    if pred is not None:
        pl.when(pred)(_compute)
    else:
        _compute()

    @pl.when(jb == last)
    def _finalize():
        l_safe = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = m_scr[...] + jnp.log(l_safe)


def _flash_fwd_3d(q, k, v, *, causal: bool, block_q: int, block_k: int,
                  t_valid: int, interpret: bool, window: int = 0):
    """q,k,v: [BH, T, D] (T block-padded) -> (out, lse [BH, T])."""
    bh, t, d = q.shape
    scale = d ** -0.5
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    assert t % block_q == 0 and t % block_k == 0, (t, block_q, block_k)
    num_kv = t // block_k
    banded = causal and 0 < window < t
    nb = min(_num_band_tiles(block_q, block_k, window), num_kv)
    if banded and nb >= num_kv:
        banded = False  # band covers everything: plain grid is simpler
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, t_valid=t_valid, t=t,
        num_kv=num_kv, window=window, banded=banded, nb=nb,
    )
    if banded:
        kv_grid = nb
        kv_index = _banded_index(
            lambda i: _band_start(i, block_q, block_k, window), num_kv
        )
    else:
        kv_grid, kv_index = num_kv, (lambda b, i, j: (b, j, 0))
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, t // block_q, kv_grid),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse[..., 0]


def _bwd_3d(causal, block_k, t_valid, residuals, g, window: int = 0):
    """Blockwise flash backward over KV blocks (plain JAX, O(T*BK) memory)."""
    q, k, v, out, lse = residuals
    bh, t, d = q.shape
    scale = d ** -0.5
    block_k = min(block_k, t)
    num_kv = t // block_k

    qf = q.astype(jnp.float32)
    g = g.astype(jnp.float32)
    out = out.astype(jnp.float32)
    delta = jnp.sum(g * out, axis=-1)                 # [BH, T]
    q_pos = jnp.arange(t)

    def per_block(j):
        sl = lambda x: lax.dynamic_slice_in_dim(x, j * block_k, block_k, 1)
        k_blk = sl(k).astype(jnp.float32)             # [BH, BK, D]
        v_blk = sl(v).astype(jnp.float32)
        s = jnp.einsum("bqd,bkd->bqk", qf, k_blk) * scale
        k_pos = j * block_k + jnp.arange(block_k)
        if causal:
            s = jnp.where((q_pos[:, None] >= k_pos[None, :])[None], s, NEG_INF)
        if window > 0:
            band = q_pos[:, None] - k_pos[None, :] < window
            s = jnp.where(band[None], s, NEG_INF)
        if t_valid < t:
            s = jnp.where((k_pos < t_valid)[None, None], s, NEG_INF)
        p = jnp.exp(s - lse[..., None])               # [BH, T, BK]
        dv = jnp.einsum("bqk,bqd->bkd", p, g)
        dp = jnp.einsum("bqd,bkd->bqk", g, v_blk)
        ds = p * (dp - delta[..., None]) * scale
        dq_j = jnp.einsum("bqk,bkd->bqd", ds, k_blk)
        dk = jnp.einsum("bqk,bqd->bkd", ds, qf)
        return dq_j, dk, dv

    def body(dq, j):
        dq_j, dk_j, dv_j = per_block(j)
        return dq + dq_j, (dk_j, dv_j)

    dq, (dk_blocks, dv_blocks) = lax.scan(
        body, jnp.zeros_like(qf), jnp.arange(num_kv)
    )
    dk = jnp.moveaxis(dk_blocks, 0, 1).reshape(bh, t, d)
    dv = jnp.moveaxis(dv_blocks, 0, 1).reshape(bh, t, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _bwd_dkv_kernel(q_ref, g_ref, lse_ref, delta_ref, k_ref, v_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                    causal: bool, t_valid: int, t: int, num_q: int,
                    window: int = 0, banded: bool = False, nqb: int = 0):
    # grid (BH, num_kv, num_q) — or (BH, num_kv, nqb) when ``banded``
    # (sliding window: only q blocks within ``window`` above this KV block
    # are visited). q innermost (streamed). k/v/dk/dv refs:
    # [1, BK, D] (this program's KV block); q_ref/g_ref: [1, BQ, D];
    # lse_ref/delta_ref: [1, BQ, 1]. Scratch dk/dv: [BK, D] f32.
    j = pl.program_id(1)
    ib = pl.program_id(2)
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    if banded:
        i = _q_band_start(j, block_q, block_k) + ib
        last = nqb - 1
    else:
        i = ib
        last = num_q - 1

    @pl.when(ib == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _compute():
        q_blk = q_ref[0].astype(jnp.float32)           # [BQ, D]
        g_blk = g_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                               # [BQ, 1]
        delta = delta_ref[0]
        k_blk = k_ref[0].astype(jnp.float32)           # [BK, D]
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                      # [BQ, BK]
        ok = _tile_mask(i, j, block_q, block_k, causal, t_valid, t,
                        window)
        if ok is not None:
            s = jnp.where(ok, s, NEG_INF)
        p = jnp.exp(s - lse)                           # [BQ, BK]
        dv_scr[...] += jax.lax.dot_general(
            p, g_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            g_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        dk_scr[...] += jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    pred = None
    if causal:
        # q blocks strictly above this KV block's first row see none of it
        pred = (i + 1) * block_q > j * block_k
    if window > 0:
        in_band = (j + 1) * block_k > i * block_q - window + 1
        pred = in_band if pred is None else (pred & in_band)
    if banded:
        pred = pred & (i <= num_q - 1)
    if pred is not None:
        pl.when(pred)(_compute)
    else:
        _compute()

    @pl.when(ib == last)
    def _finalize():
        dk = dk_scr[...]
        dv = dv_scr[...]
        if t_valid < t:  # padded keys: their grads must be exactly 0
            kv_valid = (
                j * block_k
                + lax.broadcasted_iota(jnp.int32, (block_k, 1), 0)
                < t_valid
            )
            dk = jnp.where(kv_valid, dk, 0.0)
            dv = jnp.where(kv_valid, dv, 0.0)
        dk_ref[0] = dk.astype(dk_ref.dtype)
        dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, g_ref, lse_ref, delta_ref, k_ref, v_ref, dq_ref,
                   dq_scr, *, scale: float, causal: bool, t_valid: int,
                   t: int, num_kv: int, window: int = 0,
                   banded: bool = False, nb: int = 0):
    # grid (BH, num_q, num_kv) — or (BH, num_q, nb) when ``banded``
    # (sliding window: only the band's KV tiles are visited). kv innermost
    # (streamed). q/g/dq refs: [1, BQ, D]; k_ref/v_ref: [1, BK, D];
    # lse_ref/delta_ref: [1, BQ, 1]. Scratch dq: [BQ, D] f32.
    i = pl.program_id(1)
    jb = pl.program_id(2)
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    if banded:
        j = _band_start(i, block_q, block_k, window) + jb
        last = nb - 1
    else:
        j = jb
        last = num_kv - 1

    @pl.when(jb == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def _compute():
        q_blk = q_ref[0].astype(jnp.float32)
        g_blk = g_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        delta = delta_ref[0]
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        ok = _tile_mask(i, j, block_q, block_k, causal, t_valid, t,
                        window)
        if ok is not None:
            s = jnp.where(ok, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            g_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        dq_scr[...] += jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    pred = None
    if causal:
        pred = j * block_k < (i + 1) * block_q
    if window > 0:
        in_band = (j + 1) * block_k > i * block_q - window + 1
        pred = in_band if pred is None else (pred & in_band)
    if banded:
        pred = pred & (j <= num_kv - 1)
    if pred is not None:
        pl.when(pred)(_compute)
    else:
        _compute()

    @pl.when(jb == last)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_pallas_3d(causal, block_q, block_k, t_valid, interpret,
                   residuals, g, g_lse=None, window: int = 0):
    """Pallas two-kernel flash backward. Same signature/result as _bwd_3d.

    ``g_lse`` ([BH, T] or None): cotangent of the logsumexp output when the
    caller consumed it (flash_attention_lse — e.g. the ring-merge weights).
    d(lse)/ds is the normalized probability tile p, so its contribution is
    ``ds += p * g_lse`` — which folds into the existing ``ds = p*(dp-delta)``
    as ``delta' = delta - g_lse``. The kernels are unchanged.
    """
    q, k, v, out, lse = residuals
    bh, t, d = q.shape
    scale = d ** -0.5
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    num_q = t // block_q
    num_kv = t // block_k
    # delta_i = g_i . out_i (rowwise) — cheap, XLA-fused outside the kernels.
    # Both row-stat tensors carry a trailing unit lane axis (see _fwd_kernel).
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1,
        keepdims=True,
    )
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32)[..., None]
    lse = lse.astype(jnp.float32)[..., None]

    banded = causal and 0 < window < t
    nqb = min(_num_band_tiles(block_k, block_q, window), num_q)
    nb = min(_num_band_tiles(block_q, block_k, window), num_kv)
    if banded and (nqb >= num_q and nb >= num_kv):
        banded = False

    if banded:
        q_grid = nqb
        q_index = _banded_index(
            lambda j: _q_band_start(j, block_q, block_k), num_q
        )
    else:
        q_grid, q_index = num_q, (lambda b, j, i: (b, i, 0))

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal, t_valid=t_valid,
            t=t, num_q=num_q, window=window, banded=banded, nqb=nqb,
        ),
        grid=(bh, num_kv, q_grid),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_index),                    # q
            pl.BlockSpec((1, block_q, d), q_index),                    # g
            pl.BlockSpec((1, block_q, 1), q_index),                    # lse
            pl.BlockSpec((1, block_q, 1), q_index),                    # delta
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),  # k
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),  # v
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, g, lse, delta, k, v)

    if banded:
        kv_grid = nb
        kv_index = _banded_index(
            lambda i: _band_start(i, block_q, block_k, window), num_kv
        )
    else:
        kv_grid, kv_index = num_kv, (lambda b, i, j: (b, j, 0))

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal, t_valid=t_valid,
            t=t, num_kv=num_kv, window=window, banded=banded, nb=nb,
        ),
        grid=(bh, num_q, kv_grid),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),  # q
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),  # g
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),  # lse
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),  # delta
            pl.BlockSpec((1, block_k, d), kv_index),                   # k
            pl.BlockSpec((1, block_k, d), kv_index),                   # v
        ],
        out_specs=[pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))],
        out_shape=[jax.ShapeDtypeStruct((bh, t, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, g, lse, delta, k, v)[0]
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_3d(q, k, v, causal, block_q, block_k, t_valid, interpret,
              window=0):
    out, _ = _flash_fwd_3d(q, k, v, causal=causal, block_q=block_q,
                           block_k=block_k, t_valid=t_valid,
                           interpret=interpret, window=window)
    return out


def _flash_3d_fwd(q, k, v, causal, block_q, block_k, t_valid, interpret,
                  window=0):
    out, lse = _flash_fwd_3d(q, k, v, causal=causal, block_q=block_q,
                             block_k=block_k, t_valid=t_valid,
                             interpret=interpret, window=window)
    return out, (q, k, v, out, lse)


def _flash_3d_bwd(causal, block_q, block_k, t_valid, interpret, window,
                  residuals, g):
    return _bwd_pallas_3d(causal, block_q, block_k, t_valid, interpret,
                          residuals, g, window=window)


_flash_3d.defvjp(_flash_3d_fwd, _flash_3d_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_3d_lse(q, k, v, causal, block_q, block_k, t_valid, interpret,
                  window=0):
    """Like ``_flash_3d`` but also returns the logsumexp rows [BH, T] —
    the composition primitive: softmaxes over disjoint key blocks merge
    exactly from (out, lse) pairs (ops/attention.py ring 'flash' bodies)."""
    return _flash_fwd_3d(q, k, v, causal=causal, block_q=block_q,
                         block_k=block_k, t_valid=t_valid,
                         interpret=interpret, window=window)


def _flash_3d_lse_fwd(q, k, v, causal, block_q, block_k, t_valid, interpret,
                      window=0):
    out, lse = _flash_fwd_3d(q, k, v, causal=causal, block_q=block_q,
                             block_k=block_k, t_valid=t_valid,
                             interpret=interpret, window=window)
    return (out, lse), (q, k, v, out, lse)


def _flash_3d_lse_bwd(causal, block_q, block_k, t_valid, interpret, window,
                      residuals, cotangents):
    g, g_lse = cotangents
    return _bwd_pallas_3d(causal, block_q, block_k, t_valid, interpret,
                          residuals, g, g_lse=g_lse, window=window)


_flash_3d_lse.defvjp(_flash_3d_lse_fwd, _flash_3d_lse_bwd)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Paged attention: decode directly from the KV block pool (ISSUE 7 tentpole)
# ---------------------------------------------------------------------------
#
# The serving-path KV cache lives in a bounded block pool
# (engine/kvcache.py): one ``[pool_blocks, block_tokens, H, D]`` leaf per
# cache leaf, with each request's logical token positions mapped to pool
# blocks through a per-row BLOCK TABLE (vLLM/PagedAttention, Kwon et al.
# SOSP 2023 — the TPU shape of it). This kernel consumes that layout
# IN PLACE: grid (batch, q-head, kv-block) with the kv dimension
# innermost, and the KV tile for (row b, block j) fetched straight from
# the pool page ``tables[b, j]`` via Pallas scalar prefetch — the block
# table drives the HBM->VMEM DMA index map, so a warm prefix admit is a
# pointer update instead of the HBM scatter copy the round-5 path paid
# per admit. Online-softmax state streams across the kv grid exactly
# like ``_fwd_kernel``.
#
# Positions are ROW-LOCAL (canonical): row ``b``'s token at logical
# position p lives at ``pool[tables[b, p // bt], p % bt]`` and its RoPE
# angle is p itself — block content is therefore position- and
# era-independent, which is what lets the radix index share pages
# between requests with zero copies (engine/kvcache.py).

PAGED_MIN_Q = 8      # q lanes padded up to this (Mosaic sublane tile)


def _paged_kernel(tables_ref, starts_ref, pads_ref, *refs, scale: float,
                  bt: int, nb: int, window: int = 0,
                  quant: bool = False):
    # grid (B, Hq, NB), kv innermost. q_ref/o_ref: [1, T, 1, D];
    # k_ref/v_ref: [1, bt, 1, D] — the pool page ``tables[b, j]`` for
    # this row's j-th logical block (scalar-prefetched index map; -1
    # lanes clip to the scratch page and are predicated away here).
    # Scratch m/l: [T, 1] f32, acc: [T, D] f32.
    #
    # ``quant`` (int8-KV pool layout, ISSUE 15): k/v pages are int8 and
    # two extra scale refs ``[1, bt, 1]`` f32 ride along — the DEQUANT
    # EPILOGUE multiplies each fetched tile by its per-(token, head)
    # scale right after the HBM->VMEM DMA, so only half the KV bytes
    # ever cross HBM (decode's binding constraint, BASELINE.md).
    #
    # ``window > 0`` (sliding-window ring layout, ISSUE 15): the block
    # table is a RING — table slot ``s`` holds the newest logical block
    # ``j ≡ s (mod nb)`` the row has written. k positions are derived
    # from the query's own block (``j_log = jq - (jq - s) mod nb``);
    # slots holding content newer than the query's block resolve to an
    # out-of-band j_log and are masked (see engine/kvcache.py ring
    # geometry: the +1/slack pages guarantee in-band content is never
    # clobbered mid-dispatch).
    if quant:
        (q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
         m_scr, l_scr, acc_scr) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = refs
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    j = pl.program_id(2)
    t = q_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    start = starts_ref[b]
    pad = pads_ref[b]
    page = tables_ref[b, j]

    def _compute():
        q = q_ref[0, :, 0].astype(jnp.float32) * scale     # [T, D]
        k_blk = k_ref[0, :, 0].astype(jnp.float32)         # [bt, D]
        v_blk = v_ref[0, :, 0].astype(jnp.float32)
        if quant:
            k_blk = k_blk * ks_ref[0]                      # [bt, 1]
            v_blk = v_blk * vs_ref[0]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                  # [T, bt]
        lane = lax.broadcasted_iota(jnp.int32, (t, bt), 0)
        q_pos = start + lane
        k_off = lax.broadcasted_iota(jnp.int32, (t, bt), 1)
        if window > 0:
            jq = q_pos // bt
            j_log = jq - jnp.mod(jq - j, nb)
            k_pos = j_log * bt + k_off
            # causal band over ROW-LOCAL positions; k_pos < 0 marks a
            # slot this row has not written yet
            ok = ((k_pos >= 0) & (k_pos <= q_pos)
                  & (q_pos - k_pos < window) & (lane >= pad))
        else:
            k_pos = j * bt + k_off
            # causal over ROW-LOCAL positions + leading pad lanes
            # invalid
            ok = (k_pos <= q_pos) & (lane >= pad)
        s = jnp.where(ok, s, NEG_INF)
        m = m_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_scr[...] = l_scr[...] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = m_new

    # unused table lanes (-1: past the row's allocation) and blocks
    # entirely beyond the last query position contribute nothing. In
    # ring mode any slot may hold in-band content, so only the
    # unallocated-lane predicate applies.
    pred = page >= 0
    if window <= 0:
        pred = pred & (j * bt <= start + t - 1)
    pl.when(pred)(_compute)

    @pl.when(j == nb - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0, :, 0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)


def paged_attention_ref(q, k_pool, v_pool, tables, row_starts, pad_lens,
                        window: int = 0, k_scale=None, v_scale=None):
    """Plain-JAX oracle for :func:`paged_attention` (same contract):
    gather every row's pages, mask, and run the grouped-query einsum.
    Materializes the ``[B, NB*bt, KVH, D]`` gather — the HBM cost the
    Pallas kernel exists to avoid — so it is the CPU/test path and the
    allclose reference, not the TPU path. ``k_scale``/``v_scale``
    dequantize int8 pages on the gather; ``window > 0`` applies the
    ring-table position mapping + sliding band (see ``_paged_kernel``).
    """
    from .attention import grouped_query_attention

    b, t, hq, d = q.shape
    bt = k_pool.shape[1]
    nb = tables.shape[1]
    safe = jnp.maximum(tables, 0)

    def gather(pool, pscale):
        arr = pool[safe].reshape(b, nb * bt, *pool.shape[2:])
        if pscale is not None:
            s = pscale[safe].reshape(b, nb * bt, *pscale.shape[2:])
            arr = (arr.astype(jnp.float32) * s[..., None]).astype(
                q.dtype)
        return arr

    k_all, v_all = gather(k_pool, k_scale), gather(v_pool, v_scale)
    lane = jnp.arange(t)
    q_pos = row_starts[:, None] + lane[None, :]                 # [B, T]
    used = jnp.repeat(tables >= 0, bt, axis=1)                  # [B, L]
    valid = lane[None, :, None] >= pad_lens[:, None, None]
    if window > 0:
        # ring layout: table slot s holds the newest logical block
        # j ≡ s (mod nb) at or below the query's own block
        jq = q_pos // bt                                        # [B, T]
        slot = jnp.arange(nb)
        j_log = jq[:, :, None] - jnp.mod(
            jq[:, :, None] - slot[None, None, :], nb)       # [B, T, NB]
        k_pos = (j_log[..., None] * bt
                 + jnp.arange(bt)).reshape(b, t, nb * bt)
        ok = ((k_pos >= 0) & (k_pos <= q_pos[:, :, None])
              & (q_pos[:, :, None] - k_pos < window)
              & valid & used[:, None, :])
    else:
        k_pos = jnp.arange(nb * bt)
        ok = (
            (k_pos[None, None, :] <= q_pos[:, :, None])
            & valid & used[:, None, :]
        )                                                       # [B, T, L]
    return grouped_query_attention(q, k_all, v_all, mask=ok[:, None])


def paged_attention(q, k_pool, v_pool, tables, row_starts, pad_lens,
                    impl: str = "auto", interpret: bool | None = None,
                    window: int = 0, k_scale=None, v_scale=None):
    """Paged decode attention over the KV block pool.

    :param q: ``[B, T, Hq, D]`` query rows (RoPE already applied at
        their row-local positions), T = this call's token window.
    :param k_pool / v_pool: ``[P, bt, KVH, D]`` pool leaves (page 0 is
        the reserved scratch page).
    :param tables: ``[B, NB]`` int32 block table — row ``b``'s logical
        block ``j`` lives in pool page ``tables[b, j]``; ``-1`` =
        unallocated (masked, fetch clipped to the scratch page).
    :param row_starts: ``[B]`` int32 — row-local position of q lane 0
        (may be negative when leading lanes are padding).
    :param pad_lens: ``[B]`` int32 — number of leading INVALID q lanes
        (their output rows are garbage; callers ignore them).
    :param impl: ``"auto"`` (Pallas on TPU, oracle elsewhere),
        ``"pallas"``, or ``"ref"``.
    :param window: sliding-window size (ISSUE 15). ``> 0`` switches the
        block table to RING semantics — logical block ``j`` lives in
        table slot ``j % NB`` — and masks keys outside the band
        ``q_pos - k_pos < window``; the table width bounds decode reads
        at O(window), independent of sequence length.
    :param k_scale / v_scale: ``[P, bt, KVH]`` f32 per-(token, head)
        scales for int8 pools (ISSUE 15): pages dequantize in the
        kernel's tile fetch (the decode-bandwidth win — half the KV
        bytes cross HBM), or on the gather in the oracle.
    :returns: ``[B, T, Hq, D]`` attention output.

    Query lane ``i`` of row ``b`` (valid iff ``i >= pad_lens[b]``)
    attends key positions ``0 .. row_starts[b] + i`` through the block
    table — the call's own tokens must already be written into the pool
    (models/llama.py writes before attending, same as the contiguous
    DUS path).

    TP serving (ISSUE 10): this kernel is HEAD-RANGE OBLIVIOUS — every
    shape it reads is local (``groups = hq // kvh`` holds per shard
    because both counts divide by the same tp), so under a tensor mesh
    it runs inside ``ops/attention.paged_gqa_attention``'s shard_map
    with each shard's instance walking only its local ``KVH/tp`` slice
    of the pool; nothing here needs to know the mesh exists.
    """
    if impl == "auto":
        impl = "pallas" if _on_tpu() else "ref"
    if impl == "ref":
        return paged_attention_ref(q, k_pool, v_pool, tables, row_starts,
                                   pad_lens, window=window,
                                   k_scale=k_scale, v_scale=v_scale)
    if interpret is None:
        interpret = not _on_tpu()
    b, t, hq, d = q.shape
    p, bt, kvh, _ = k_pool.shape
    nb = tables.shape[1]
    groups = hq // kvh
    quant = k_scale is not None
    t_pad = max(t, PAGED_MIN_Q)
    if t_pad != t:
        # LEFT-pad the q window (the last lane must stay last): the new
        # lanes are invalid by construction
        q = jnp.pad(q, ((0, 0), (t_pad - t, 0), (0, 0), (0, 0)))
        row_starts = row_starts - (t_pad - t)
        pad_lens = pad_lens + (t_pad - t)
    page_index = lambda bb, h, j, tbl, st, pd: (       # noqa: E731
        jnp.maximum(tbl[bb, j], 0), 0, h // groups, 0)
    scale_index = lambda bb, h, j, tbl, st, pd: (      # noqa: E731
        jnp.maximum(tbl[bb, j], 0), 0, h // groups)
    in_specs = [
        pl.BlockSpec((1, t_pad, 1, d),
                     lambda bb, h, j, tbl, st, pd: (bb, 0, h, 0)),
        pl.BlockSpec((1, bt, 1, d), page_index),
        pl.BlockSpec((1, bt, 1, d), page_index),
    ]
    args = [q, k_pool, v_pool]
    if quant:
        # dequant epilogue inputs: per-(token, head) f32 scales, same
        # page-table-driven DMA as the int8 tiles they rescale
        in_specs += [pl.BlockSpec((1, bt, 1), scale_index),
                     pl.BlockSpec((1, bt, 1), scale_index)]
        args += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, hq, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, t_pad, 1, d),
                               lambda bb, h, j, tbl, st, pd: (bb, 0, h, 0)),
        scratch_shapes=[
            pltpu.VMEM((t_pad, 1), jnp.float32),
            pltpu.VMEM((t_pad, 1), jnp.float32),
            pltpu.VMEM((t_pad, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_kernel, scale=d ** -0.5, bt=bt, nb=nb,
                          window=window, quant=quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, t_pad, hq, d), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), row_starts.astype(jnp.int32),
      pad_lens.astype(jnp.int32), *args)
    return out[:, t_pad - t:]


def pick_block_sizes(t: int, d: int) -> tuple:
    """(block_q, block_k) for a [*, t, *, d] attention, from the round-3
    measurement sweep on TPU v5e (full fwd+bwd through ``jax.grad``,
    in-jit chained scan timing — the 7-point (bq, bk) grid at each of
    (t, d) in {1024, 4096, 8192}x64 and 2048x128, causal):

    - **(512, 1024)** is fastest or tied-fastest at every measured point
      up to t=4096 — 30% over the old 512x512 default at t=1024
      (11.7 vs 16.9 ms) and 16% at t=4096. Wide KV tiles suit the
      KV-innermost forward stream; 1024x1024 gives the gain back.
    - **(1024, 512)** wins at t=8192 with small batch (17.4 vs 21.5 ms):
      once b*h programs no longer fill the chip, coarser q-grids put
      more work in each program.

    Sequences shorter than a block fall back to one block (the ``min``
    in the caller). Lengths that don't divide the asymmetric pair's
    lcm (1024) keep the old square 512x512 — the caller pads to the
    block lcm, and taxing a t=1536 call with 512 columns of masked
    padding would cost more than the block win."""
    del d  # same winner at d=64 and d=128 everywhere measured
    if t % 1024:
        return DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K
    if t >= 8192:
        return 1024, 512
    return 512, 1024


def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = 0,
                    block_k: int = 0,
                    interpret: bool | None = None,
                    window: int = 0):
    """Fused attention. q,k,v: [B, T, H, D] -> [B, T, H, D].

    ``interpret=None`` auto-selects: compiled on TPU, interpreter elsewhere
    (CPU tests). ``block_q/block_k = 0`` (the default) auto-picks via
    ``pick_block_sizes(t, d)``. Any sequence length works: lengths that
    don't divide the block sizes are zero-padded to the next block multiple
    and the padded keys are masked out inside the kernel (padded query rows
    are sliced off, and ``jnp.pad``'s VJP zeroes their gradients).

    ``window > 0`` (with ``causal``): sliding-window banding. The grid
    itself is banded — only the ~window-wide KV tile strip per q block is
    visited in forward and both backward kernels, so compute and K/V
    streaming are O(T * window).
    """
    if interpret is None:
        interpret = not _on_tpu()
    b, t, h, d = q.shape
    if not block_q or not block_k:
        auto_q, auto_k = pick_block_sizes(t, d)
        block_q = block_q or auto_q
        block_k = block_k or auto_k
    bq, bk = min(block_q, t), min(block_k, t)
    t_pad = t
    if t % bq or t % bk:
        lcm = block_q * block_k // math.gcd(block_q, block_k)
        t_pad = -(-t // lcm) * lcm
    fold = lambda x: jnp.moveaxis(x, 2, 1).reshape(b * h, t, d)
    q, k, v = fold(q), fold(k), fold(v)
    if t_pad != t:
        pad = ((0, 0), (0, t_pad - t), (0, 0))
        q, k, v = jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad)
    out = _flash_3d(q, k, v, causal, block_q, block_k, t, interpret,
                    window)
    out = out[:, :t]
    return jnp.moveaxis(out.reshape(b, h, t, d), 1, 2)


def flash_attention_lse(q, k, v, causal: bool = False,
                        block_q: int = DEFAULT_BLOCK_Q,
                        block_k: int = DEFAULT_BLOCK_K,
                        interpret: bool | None = None,
                        window: int = 0):
    """Fused attention returning ``(out, lse)``.

    ``window > 0`` (with ``causal``) applies the same-origin sliding-window
    band ``q_pos - k_pos < window`` with the banded grid of
    ``flash_attention`` — used by the ring bodies for the DIAGONAL block
    (off-diagonal ring blocks have shifted position origins and are
    handled by the callers: fully-visible blocks need no mask, band-edge
    blocks go through a masked einsum merge).

    q, k, v: [B, T, H, D]; out: [B, T, H, D]; lse: [B, H, T] float32 —
    ``logsumexp_k(q·k/sqrt(d))`` per query row. Disjoint-key-block results
    combine exactly:

        lse = logaddexp(lse_a, lse_b)
        out = exp(lse_a - lse)·out_a + exp(lse_b - lse)·out_b

    which is how the ring bodies (ops/attention.py) chain this kernel over
    K/V blocks arriving via ppermute (ring blocks are always square, so
    Tq == Tk is required). Gradients flow through BOTH outputs (the lse
    cotangent folds into the backward kernels' delta term).
    """
    if interpret is None:
        interpret = not _on_tpu()
    b, t, h, d = q.shape
    if k.shape[1] != t:
        raise ValueError(f"flash_attention_lse needs Tq == Tk; "
                         f"{t} vs {k.shape[1]}")
    bq, bk = min(block_q, t), min(block_k, t)
    t_pad = t
    if t % bq or t % bk:
        lcm = block_q * block_k // math.gcd(block_q, block_k)
        t_pad = -(-t // lcm) * lcm
    fold = lambda x: jnp.moveaxis(x, 2, 1).reshape(b * h, t, d)
    qf, kf, vf = fold(q), fold(k), fold(v)
    if t_pad != t:
        pad = ((0, 0), (0, t_pad - t), (0, 0))
        qf, kf, vf = jnp.pad(qf, pad), jnp.pad(kf, pad), jnp.pad(vf, pad)
    out, lse = _flash_3d_lse(qf, kf, vf, causal, block_q, block_k,
                             t, interpret, window)
    out = out[:, :t]
    lse = lse[:, :t]
    return (jnp.moveaxis(out.reshape(b, h, t, d), 1, 2),
            lse.reshape(b, h, t))
