"""Fused flash attention: Pallas TPU forward kernel + blockwise backward.

The reference delegates all kernels to cuDNN (SURVEY.md §2.2); here the one
op XLA doesn't fuse perfectly at long sequence length — attention — gets an
in-tree Pallas kernel (see /opt/skills/guides/pallas_guide.md):

- **forward**: one grid program per (batch*head, q-block); K/V live in VMEM
  and are consumed in BK-sized blocks with the online-softmax recurrence, so
  the T×T score matrix never leaves VMEM (only a [BQ, BK] tile exists at a
  time). Causal programs skip KV blocks beyond the diagonal entirely —
  ~2× fewer FLOPs, not just masking. Outputs carry the logsumexp rows.
- **backward**: flash-style blockwise recomputation (scan over KV blocks)
  in plain JAX using the saved logsumexp — O(T·BK) memory, XLA-fused; a
  Pallas backward kernel is a later optimization, the math and memory
  behavior are already right.

Accumulation is float32 throughout regardless of input dtype.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = -1e30
DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale: float,
                causal: bool, block_k: int, t_valid: int):
    # q_ref: [1, BQ, D]; k_ref/v_ref: [1, T, D]; o_ref: [1, BQ, D];
    # lse_ref: [1, BQ]
    qi = pl.program_id(1)
    block_q = q_ref.shape[1]
    t_kv = k_ref.shape[1]
    d = q_ref.shape[2]

    q = q_ref[0].astype(jnp.float32) * scale          # [BQ, D]
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    num_kv = t_kv // block_k
    if causal:
        # KV blocks strictly beyond this q block's last row are invisible.
        num_kv = jnp.minimum(
            num_kv, ((qi + 1) * block_q + block_k - 1) // block_k
        )

    q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                              # [BQ, BK]
        if causal or t_valid < t_kv:
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            if causal:
                s = jnp.where(q_pos >= k_pos, s, NEG_INF)
            if t_valid < t_kv:  # keys past t_valid are padding
                s = jnp.where(k_pos < t_valid, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m, l, acc = lax.fori_loop(0, num_kv, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)


def _flash_fwd_3d(q, k, v, *, causal: bool, block_q: int, block_k: int,
                  t_valid: int, interpret: bool):
    """q,k,v: [BH, T, D] (T block-padded) -> (out, lse [BH, T])."""
    bh, t, d = q.shape
    scale = d ** -0.5
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    assert t % block_q == 0 and t % block_k == 0, (t, block_q, block_k)
    grid = (bh, t // block_q)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_k=block_k,
        t_valid=t_valid,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


def _bwd_3d(causal, block_k, t_valid, residuals, g):
    """Blockwise flash backward over KV blocks (plain JAX, O(T*BK) memory)."""
    q, k, v, out, lse = residuals
    bh, t, d = q.shape
    scale = d ** -0.5
    block_k = min(block_k, t)
    num_kv = t // block_k

    qf = q.astype(jnp.float32)
    g = g.astype(jnp.float32)
    out = out.astype(jnp.float32)
    delta = jnp.sum(g * out, axis=-1)                 # [BH, T]
    q_pos = jnp.arange(t)

    def per_block(j):
        sl = lambda x: lax.dynamic_slice_in_dim(x, j * block_k, block_k, 1)
        k_blk = sl(k).astype(jnp.float32)             # [BH, BK, D]
        v_blk = sl(v).astype(jnp.float32)
        s = jnp.einsum("bqd,bkd->bqk", qf, k_blk) * scale
        k_pos = j * block_k + jnp.arange(block_k)
        if causal:
            s = jnp.where((q_pos[:, None] >= k_pos[None, :])[None], s, NEG_INF)
        if t_valid < t:
            s = jnp.where((k_pos < t_valid)[None, None], s, NEG_INF)
        p = jnp.exp(s - lse[..., None])               # [BH, T, BK]
        dv = jnp.einsum("bqk,bqd->bkd", p, g)
        dp = jnp.einsum("bqd,bkd->bqk", g, v_blk)
        ds = p * (dp - delta[..., None]) * scale
        dq_j = jnp.einsum("bqk,bkd->bqd", ds, k_blk)
        dk = jnp.einsum("bqk,bqd->bkd", ds, qf)
        return dq_j, dk, dv

    def body(dq, j):
        dq_j, dk_j, dv_j = per_block(j)
        return dq + dq_j, (dk_j, dv_j)

    dq, (dk_blocks, dv_blocks) = lax.scan(
        body, jnp.zeros_like(qf), jnp.arange(num_kv)
    )
    dk = jnp.moveaxis(dk_blocks, 0, 1).reshape(bh, t, d)
    dv = jnp.moveaxis(dv_blocks, 0, 1).reshape(bh, t, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_3d(q, k, v, causal, block_q, block_k, t_valid, interpret):
    out, _ = _flash_fwd_3d(q, k, v, causal=causal, block_q=block_q,
                           block_k=block_k, t_valid=t_valid,
                           interpret=interpret)
    return out


def _flash_3d_fwd(q, k, v, causal, block_q, block_k, t_valid, interpret):
    out, lse = _flash_fwd_3d(q, k, v, causal=causal, block_q=block_q,
                             block_k=block_k, t_valid=t_valid,
                             interpret=interpret)
    return out, (q, k, v, out, lse)


def _flash_3d_bwd(causal, block_q, block_k, t_valid, interpret, residuals, g):
    del block_q, interpret
    return _bwd_3d(causal, block_k, t_valid, residuals, g)


_flash_3d.defvjp(_flash_3d_fwd, _flash_3d_bwd)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool | None = None):
    """Fused attention. q,k,v: [B, T, H, D] -> [B, T, H, D].

    ``interpret=None`` auto-selects: compiled on TPU, interpreter elsewhere
    (CPU tests). Any sequence length works: lengths that don't divide the
    block sizes are zero-padded to the next block multiple and the padded
    keys are masked out inside the kernel (padded query rows are sliced off,
    and ``jnp.pad``'s VJP zeroes their gradients).
    """
    if interpret is None:
        interpret = not _on_tpu()
    b, t, h, d = q.shape
    bq, bk = min(block_q, t), min(block_k, t)
    t_pad = t
    if t % bq or t % bk:
        lcm = block_q * block_k // math.gcd(block_q, block_k)
        t_pad = -(-t // lcm) * lcm
    fold = lambda x: jnp.moveaxis(x, 2, 1).reshape(b * h, t, d)
    q, k, v = fold(q), fold(k), fold(v)
    if t_pad != t:
        pad = ((0, 0), (0, t_pad - t), (0, 0))
        q, k, v = jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad)
    out = _flash_3d(q, k, v, causal, block_q, block_k, t, interpret)
    out = out[:, :t]
    return jnp.moveaxis(out.reshape(b, h, t, d), 1, 2)
