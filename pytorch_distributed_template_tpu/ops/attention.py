"""Attention implementations.

The reference has no attention anywhere (its model zoo is one MNIST CNN,
SURVEY.md §2.3) — but the BASELINE.json ladder (ViT, GPT-2) and the
long-context mandate require it, so attention is a first-class op family
here with three interchangeable implementations:

- ``multihead_attention``: plain XLA einsum-softmax-einsum. XLA:TPU fuses
  the mask+softmax chain; fine up to moderate T.
- ``ring_attention``: sequence/context parallelism over a ``seq`` mesh axis
  via ``shard_map`` + ``lax.ppermute`` — each device holds a T/s slice of
  Q/K/V and K/V blocks rotate around the ring while partial attention
  accumulates with an online (flash-style) softmax. Memory per chip is
  O(T/s · d) instead of O(T · d) and the T×T score matrix never
  materializes globally. KV transfers ride ICI concurrently with the local
  block's compute (XLA's latency-hiding scheduler overlaps the ppermute).
- ``flash_attention`` (ops/flash.py): fused Pallas TPU kernel for the
  single-device block-streaming case.

All take/return ``[B, T, H, D]`` ("BTHD") and accumulate in float32
regardless of input dtype (bf16-safe).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def multihead_attention(q, k, v, causal: bool = True,
                        mask: Optional[jax.Array] = None):
    """Reference XLA attention. q,k,v: [B, T, H, D] -> [B, T, H, D]."""
    dtype = q.dtype
    depth = q.shape[-1]
    q = q.astype(jnp.float32) * (depth ** -0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k.astype(jnp.float32))
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        cm = jnp.tril(jnp.ones((tq, tk), bool))
        scores = jnp.where(cm[None, None], scores, NEG_INF)
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(dtype)


def _ring_attention_local(q, k, v, *, axis_name: str, axis_size: int,
                          causal: bool, vary_axes: tuple = ()):
    """Per-shard ring attention body (runs inside shard_map).

    q,k,v: local [B, Tl, H, D] slices of the global [B, T, H, D] arrays,
    sharded along T over ``axis_name``. Rotates K/V blocks around the ring
    with an online-softmax accumulator: after ``axis_size`` steps every query
    has attended to every (visible) key.
    """
    dtype = q.dtype
    b, tl, h, d = q.shape
    my = lax.axis_index(axis_name)
    qf = q.astype(jnp.float32) * (d ** -0.5)
    q_pos = my * tl + jnp.arange(tl)  # global query positions

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, t):
        kb, vb, m, l, o = carry
        src = (my - t) % axis_size  # origin shard of the current K/V block
        k_pos = src * tl + jnp.arange(tl)
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kb.astype(jnp.float32))
        if causal:
            visible = q_pos[:, None] >= k_pos[None, :]  # [Tl_q, Tl_k]
            scores = jnp.where(visible[None, None], scores, NEG_INF)
        blk_max = jnp.max(scores, axis=-1)            # [B, H, Tq]
        m_new = jnp.maximum(m, blk_max)
        p = jnp.exp(scores - m_new[..., None])        # [B, H, Tq, Tk]
        scale = jnp.exp(m - m_new)                    # [B, H, Tq]
        l_new = l * scale + jnp.sum(p, axis=-1)
        o_new = o * scale[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vb.astype(jnp.float32)
        )
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (kb, vb, m_new, l_new, o_new), None

    m0 = jnp.full((b, h, tl), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, tl), jnp.float32)
    o0 = jnp.zeros((b, h, tl, d), jnp.float32)
    # The accumulators depend on device-varying data from step 1 on; mark
    # them varying over the sharded mesh axes up front so the scan carry
    # type is stable (JAX's varying-manual-axes check under shard_map).
    if vary_axes:
        vary = lambda x: lax.pcast(x, vary_axes, to="varying")
        m0, l0, o0 = vary(m0), vary(l0), vary(o0)
    (kb, vb, m, l, o), _ = lax.scan(
        step, (k, v, m0, l0, o0), jnp.arange(axis_size)
    )
    out = o / jnp.maximum(l, 1e-30)[..., None]        # [B, H, Tq, D]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(dtype)


def ring_attention(q, k, v, mesh: Mesh, causal: bool = True,
                   seq_axis: str = "seq", data_axes=("data", "fsdp"),
                   head_axis: str = "tensor"):
    """Sequence-parallel attention over the mesh's ``seq`` axis.

    q,k,v are global ``[B, T, H, D]`` arrays (T sharded over ``seq``); the
    TxT score matrix never exists — only [Tl x Tl] blocks per device per
    ring step. Composes with DP (batch over data axes) and TP (heads over
    ``tensor``) in one shard_map.
    """
    if seq_axis not in mesh.axis_names or mesh.shape[seq_axis] == 1:
        return multihead_attention(q, k, v, causal=causal)
    axis_size = mesh.shape[seq_axis]
    if q.shape[1] % axis_size != 0:
        # Sequence not evenly shardable (e.g. a probe batch at init time):
        # the dense path is always correct, just not sequence-parallel.
        return multihead_attention(q, k, v, causal=causal)

    dp = tuple(a for a in data_axes if a in mesh.axis_names)
    dp_total = int(np.prod([mesh.shape[a] for a in dp])) if dp else 1
    if dp and q.shape[0] % dp_total != 0:
        dp = ()  # batch too small to shard (init probes); replicate it
    hp = head_axis if head_axis in mesh.axis_names else None
    if hp is not None and q.shape[2] % mesh.shape[hp] != 0:
        hp = None
    spec = P(dp if dp else None, seq_axis, hp, None)

    vary_axes = tuple(dp) + (seq_axis,) + ((hp,) if hp else ())
    fn = functools.partial(
        _ring_attention_local, axis_name=seq_axis, axis_size=axis_size,
        causal=causal, vary_axes=vary_axes,
    )
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )(q, k, v)
