"""Attention implementations.

The reference has no attention anywhere (its model zoo is one MNIST CNN,
SURVEY.md §2.3) — but the BASELINE.json ladder (ViT, GPT-2) and the
long-context mandate require it, so attention is a first-class op family
here as a family of interchangeable implementations:

- ``multihead_attention``: plain XLA einsum-softmax-einsum. XLA:TPU fuses
  the mask+softmax chain; fine up to moderate T.
- ``ring_attention``: sequence/context parallelism over a ``seq`` mesh axis
  via ``shard_map`` + ``lax.ppermute`` — each device holds a T/s slice of
  Q/K/V and K/V blocks rotate around the ring while partial attention
  accumulates with an online (flash-style) softmax. Memory per chip is
  O(T/s · d) instead of O(T · d) and the T×T score matrix never
  materializes globally. KV transfers ride ICI concurrently with the local
  block's compute (XLA's latency-hiding scheduler overlaps the ppermute).
- ``ring_attention(..., layout="zigzag")``: causal load-balanced variant.
  With the contiguous layout, causal masking makes ring shard i skip every
  K/V block originating from shard j > i — half the ring steps are fully
  masked yet still paid for (utilization (s+1)/2s). In the zigzag layout
  each device holds sequence chunks ``(i, 2s-1-i)`` of 2s chunks, so every
  device sees the same visible-key count and each post-local ring step
  needs only two quarter-block matmuls, all fully visible (no masks at
  all): half the attention FLOPs and no stragglers. Callers permute the
  sequence with ``zigzag_perm`` once at the input and invert once at the
  output (models/transformer.py does this around the whole block stack —
  two cheap all-to-alls per step, amortized over all layers).
- ``ulysses_attention``: the all-to-all SP alternative — one tiled
  all-to-all turns the sequence shard into a head shard, full-sequence
  attention runs locally, one all-to-all converts back (two collectives
  per call vs the ring's s ppermutes).
- ``flash_attention`` (ops/flash.py): fused Pallas TPU kernel for the
  single-device block-streaming case; also the per-block kernel inside
  ``ring_attention(block_impl="flash")`` via ``flash_attention_lse``.

Sliding-window banding (``window > 0``) threads through the XLA, flash
(banded grids), Ulysses, and contiguous-ring paths — the ring adds the
banded-skip schedule (stop after ~window/Tl hops; see ``ring_attention``).
All take/return ``[B, T, H, D]`` ("BTHD") and accumulate in float32
regardless of input dtype (bf16-safe).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.compat import shard_map

NEG_INF = -1e30


def multihead_attention(q, k, v, causal: bool = True,
                        mask: Optional[jax.Array] = None,
                        window: int = 0):
    """Reference XLA attention. q,k,v: [B, T, H, D] -> [B, T, H, D].

    ``window > 0``: sliding-window (Mistral-style) banding — query t sees
    keys in ``(t - window, t]`` (combined with ``causal``).
    """
    dtype = q.dtype
    depth = q.shape[-1]
    q = q.astype(jnp.float32) * (depth ** -0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k.astype(jnp.float32))
    tq, tk = scores.shape[-2], scores.shape[-1]
    if causal:
        cm = jnp.tril(jnp.ones((tq, tk), bool))
        scores = jnp.where(cm[None, None], scores, NEG_INF)
    if window > 0:
        q_pos = jnp.arange(tq)[:, None]
        k_pos = jnp.arange(tk)[None, :]
        band = q_pos - k_pos < window
        scores = jnp.where(band[None, None], scores, NEG_INF)
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(dtype)


def grouped_query_attention(q, k, v, mask=None):
    """Decode-path GQA attention that never materializes the head
    expansion. q: [B, T, H, D]; k/v: [B, L, KVH, D] with H = KVH * g.
    ``mask`` follows the :func:`multihead_attention` convention
    (broadcastable to [B, 1, T, L]); the group axis is inserted here.

    Why this exists: ``jnp.repeat(k, groups, axis=2)`` before
    ``multihead_attention`` materializes a groups-x copy of the K/V
    cache on every decode step once the batch is large enough that XLA
    stops fusing the broadcast — measured on v5e at [B, W]=[32, 1024]:
    2.2x step time, and 6x at [64, 1024] (the round-4 "batch-32 cliff";
    scripts/debug_batch32_cliff.py). Grouping the query heads instead
    ([B,T,KVH,g,D] x [B,L,KVH,D] -> [B,KVH,g,T,L]) reads the cache once
    at its stored width. Scores/probs accumulate in f32 exactly like
    ``multihead_attention``; the bf16 K/V upcasts fuse into the dots
    (measured free).
    """
    dtype = q.dtype
    b, t, h, d = q.shape
    g = _gqa_groups(q, k, v)
    if mask is not None:       # normalize to [B|1, 1, T, L] like the
        if mask.ndim == 2:     # multihead_attention contract allows
            mask = mask[None, None]
        elif mask.ndim == 3:
            mask = mask[:, None]
    if g == 1:
        return multihead_attention(q, k, v, causal=False, mask=mask)
    kvh = h // g
    # q head i attends kv head i // g — the same pairing jnp.repeat
    # (..., groups, axis=2) induces, so this is a drop-in replacement
    qg = q.reshape(b, t, kvh, g, d).astype(jnp.float32) * (d ** -0.5)
    scores = jnp.einsum("btkgd,blkd->bkgtl", qg, k,
                        preferred_element_type=jnp.float32)
    if mask is not None:
        scores = jnp.where(mask[:, :, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgtl,blkd->btkgd", probs, v,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, t, h, d).astype(dtype)


def paged_gqa_attention(q, k_pool, v_pool, tables, row_starts, pad_lens,
                        impl: str = "auto", mesh=None, window: int = 0,
                        k_scale=None, v_scale=None):
    """Decode attention straight from the paged KV block pool
    (ops/flash.paged_attention): row ``b``'s keys/values are gathered
    through its block table instead of a contiguous per-row cache, so a
    warm prefix admit is a block-table pointer update, not an HBM
    scatter (ISSUE 7). q: ``[B, T, Hq, D]``; pools: ``[P, bt, KVH, D]``
    with ``Hq = KVH * g`` (the kernel pairs q head ``i`` with kv head
    ``i // g``, same as :func:`grouped_query_attention`).

    ``impl="auto"`` runs the Pallas kernel on TPU and the plain-JAX
    gather oracle elsewhere (the oracle materializes the page gather —
    fine for CPU tests, the exact HBM traffic the kernel avoids on
    TPU).

    ``mesh`` with a ``tensor`` axis > 1 (ISSUE 10, TP serving): the
    call runs under ``shard_map`` with PER-SHARD HEAD RANGES — each
    tensor shard's kernel instance sees only its local ``KVH/tp`` pool
    slice and the matching ``Hq/tp`` q heads (the q-to-kv pairing
    ``i // g`` is shard-local because both counts divide by the same
    tp), while block tables / row starts / pad lens stay replicated.
    Attention is embarrassingly parallel over heads, so the body needs
    no collectives; on TPU each shard's Pallas kernel DMA-walks only
    its own head slice of the pool.

    ``window``/``k_scale``/``v_scale`` (ISSUE 15): the sliding-window
    ring-table mapping and the int8-pool dequant scales, passed through
    to :func:`ops.flash.paged_attention`; scale leaves shard on their
    own head axis (axis 2 of 3) under TP, like the pages they rescale."""
    from .flash import paged_attention

    if mesh is not None and "tensor" in mesh.axis_names \
            and mesh.shape["tensor"] > 1:
        hs = P(None, None, "tensor", None)
        ss = P(None, None, "tensor")
        rep = P(None)
        if k_scale is not None:
            def local_q(q_, k_, v_, t_, rs_, pl_, ks_, vs_):
                return paged_attention(q_, k_, v_, t_, rs_, pl_,
                                       impl=impl, window=window,
                                       k_scale=ks_, v_scale=vs_)

            return shard_map(
                local_q, mesh=mesh,
                in_specs=(hs, hs, hs, P(None, None), rep, rep, ss, ss),
                out_specs=hs, check_vma=False,
            )(q, k_pool, v_pool, tables, row_starts, pad_lens,
              k_scale, v_scale)

        def local(q_, k_, v_, t_, rs_, pl_):
            return paged_attention(q_, k_, v_, t_, rs_, pl_, impl=impl,
                                   window=window)

        return shard_map(
            local, mesh=mesh,
            in_specs=(hs, hs, hs, P(None, None), rep, rep),
            out_specs=hs, check_vma=False,
        )(q, k_pool, v_pool, tables, row_starts, pad_lens)
    return paged_attention(q, k_pool, v_pool, tables, row_starts,
                           pad_lens, impl=impl, window=window,
                           k_scale=k_scale, v_scale=v_scale)


def _online_update(m, l, o, scores, vb):
    """Flash-style online-softmax accumulator update for one key block.

    m/l/o: running max [B,H,Tq], normalizer [B,H,Tq], output [B,H,Tq,D];
    scores: [B,H,Tq,Tk] for the new block; vb: [B,Tk,H,D] values.
    Shared by both ring bodies so numerics changes stay in one place.
    """
    blk_max = jnp.max(scores, axis=-1)
    m_new = jnp.maximum(m, blk_max)
    p = jnp.exp(scores - m_new[..., None])
    scale = jnp.exp(m - m_new)
    l_new = l * scale + jnp.sum(p, axis=-1)
    o_new = o * scale[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, vb.astype(jnp.float32)
    )
    return m_new, l_new, o_new


def zigzag_perm(t: int, s: int) -> np.ndarray:
    """Natural→zigzag sequence permutation for ``s`` ring shards.

    The sequence splits into ``2s`` chunks of ``t // (2s)``; ring shard i
    holds chunks ``(i, 2s-1-i)`` concatenated. Returns ``perm`` such that
    ``x[:, perm]`` is the zigzag layout; invert with ``np.argsort(perm)``.
    """
    if t % (2 * s) != 0:
        raise ValueError(f"t={t} not divisible by 2*s={2 * s}")
    c = t // (2 * s)
    parts = []
    for i in range(s):
        parts.append(np.arange(i * c, (i + 1) * c))
        j = 2 * s - 1 - i
        parts.append(np.arange(j * c, (j + 1) * c))
    return np.concatenate(parts)


def _merge_blocks(o, lse, o_b, lse_b):
    """Merge two attention partials over disjoint key blocks.

    o/o_b: [B, T, H, D] (o in float32); lse/lse_b: [B, H, T]. Exact:
    softmax over the union of key sets = lse-weighted combination of the
    per-block softmaxes. A fully-masked partial (lse_b == NEG_INF) merges
    as a no-op (weight exp(NEG_INF - lse) == 0).
    """
    lse_new = jnp.logaddexp(lse, lse_b)
    w = jnp.moveaxis(jnp.exp(lse - lse_new), 1, 2)[..., None]
    w_b = jnp.moveaxis(jnp.exp(lse_b - lse_new), 1, 2)[..., None]
    return o * w + o_b.astype(jnp.float32) * w_b, lse_new


def _expand_kv(x, groups: int):
    """GQA: broadcast compact [B, T, Hkv, D] K/V to the query head count
    for one block's compute. The ring bodies carry the COMPACT tensors
    around the ring (groups x less ICI traffic) and expand per hop."""
    return x if groups == 1 else jnp.repeat(x, groups, axis=2)


def _gqa_groups(q, k, v) -> int:
    """Validated q-to-kv head ratio (1 when heads match)."""
    if k.shape[2] == q.shape[2]:
        return 1
    if q.shape[2] % k.shape[2] or v.shape[2] != k.shape[2]:
        raise ValueError(
            f"GQA head counts must divide: q has {q.shape[2]}, "
            f"k/v have {k.shape[2]}/{v.shape[2]}"
        )
    return q.shape[2] // k.shape[2]


def _einsum_block_lse(q, kb, vb, visible):
    """(out, lse) of one attention block with an explicit [Tq, Tk] mask.

    The band-edge fallback for the windowed flash ring: Pallas banding
    assumes same-origin positions, so the O(1) ring blocks straddling the
    window edge run as a masked einsum instead (their [Tl x Tl] scores DO
    materialize — acceptable for the one or two such blocks). Fully-masked
    rows get lse = NEG_INF, making the subsequent merge a no-op there.
    """
    d = q.shape[-1]
    qf = q.astype(jnp.float32) * (d ** -0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kb.astype(jnp.float32))
    scores = jnp.where(visible[None, None], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)
    p = jnp.exp(scores - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bhqd", p, vb.astype(jnp.float32))
    o = o / jnp.maximum(l, 1e-30)[..., None]
    lse = jnp.where(m <= NEG_INF / 2, NEG_INF,
                    m + jnp.log(jnp.maximum(l, 1e-30)))
    return jnp.transpose(o, (0, 2, 1, 3)), lse  # [B,T,H,D], [B,H,T]


def _ring_attention_local_flash(q, k, v, *, axis_name: str, axis_size: int,
                                causal: bool, window: int = 0,
                                kv_groups: int = 1):
    """Contiguous-layout ring body with the Pallas flash kernel per block.

    Same ring schedule as ``_ring_attention_local``, but each [Tl x Tl]
    block runs through ``flash_attention_lse`` (scores stream through VMEM
    — nothing Tl x Tl ever materializes in HBM, so per-device sequence
    slices can be long) and partials chain via ``_merge_blocks``. Step 0 is
    the local (diagonal) block — the only one needing causal masking;
    every later block is fully visible or fully masked (gated by
    lse = NEG_INF, which also zeroes its gradient).

    ``window > 0`` (causal): three-tier banded-skip schedule —
    1. the diagonal block runs banded INSIDE the flash kernel;
    2. ring distances fully inside the band run maskless flash exactly as
       the unwindowed path;
    3. the O(1) distances straddling the band edge run as masked einsum
       blocks (``_einsum_block_lse``);
    4. distances beyond the band don't run — the ring stops early
       (``_ring_steps_needed``), so K/V hops, compute and the scan length
       are all O(window / Tl), not O(s).
    """
    from .flash import flash_attention_lse

    dtype = q.dtype
    s = axis_size
    tl = q.shape[1]
    my = lax.axis_index(axis_name)
    out0, lse0 = flash_attention_lse(
        q, _expand_kv(k, kv_groups), _expand_kv(v, kv_groups),
        causal=causal, window=window,
    )
    carry0 = (k, v, out0.astype(jnp.float32), lse0)
    perm = [(i, (i + 1) % s) for i in range(s)]

    def step(carry, t):
        kb, vb, o, lse = carry
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        out_b, lse_b = flash_attention_lse(
            q, _expand_kv(kb, kv_groups), _expand_kv(vb, kv_groups),
            causal=False,
        )
        if causal:
            src = (my - t) % s
            lse_b = jnp.where(src < my, lse_b, NEG_INF)
        o, lse = _merge_blocks(o, lse, out_b, lse_b)
        return (kb, vb, o, lse), None

    if window <= 0 or not causal:
        (_, _, o, _), _ = lax.scan(step, carry0, jnp.arange(1, s))
        return o.astype(dtype)

    # causal sliding window: distance-t keys span offsets
    # [t*tl - (tl-1), t*tl + (tl-1)] behind the query
    n = _ring_steps_needed(tl, s, window)
    full = [t for t in range(1, n) if t * tl + tl - 1 < window]
    edge = [t for t in range(1, n) if t * tl + tl - 1 >= window]
    assert full == list(range(1, len(full) + 1)) and len(edge) <= 2

    carry = carry0
    if full:
        carry, _ = lax.scan(step, carry, jnp.arange(1, len(full) + 1))
    kb, vb, o, lse = carry
    q_pos = my * tl + jnp.arange(tl)
    for t in edge:
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        src = (my - t) % s
        k_pos = src * tl + jnp.arange(tl)
        visible = (q_pos[:, None] >= k_pos[None, :]) & (
            q_pos[:, None] - k_pos[None, :] < window
        )  # wrapped sources (src > my) mask out entirely via positions
        out_b, lse_b = _einsum_block_lse(
            q, _expand_kv(kb, kv_groups), _expand_kv(vb, kv_groups),
            visible,
        )
        o, lse = _merge_blocks(o, lse, out_b, lse_b)
    return o.astype(dtype)


def _ring_attention_zigzag_local_flash(q, k, v, *, axis_name: str,
                                       axis_size: int, kv_groups: int = 1):
    """Zigzag ring body with the Pallas flash kernel per quarter block.

    The balanced schedule of ``_ring_attention_zigzag_local`` (same chunk
    visibility proof), with each quarter block as one flash call and
    lse-merges instead of the inline online-softmax accumulator. Step 0 is
    three quarter blocks (the two intra-chunk diagonals + the always-
    visible hi×lo); later steps are exactly two maskless quarter calls.
    """
    from .flash import flash_attention_lse

    dtype = q.dtype
    b, tl, h, d = q.shape
    c = tl // 2
    s = axis_size
    my = lax.axis_index(axis_name)
    q_lo, q_hi = q[:, :c], q[:, c:]
    kx, vx = _expand_kv(k, kv_groups), _expand_kv(v, kv_groups)

    o_ll, l_ll = flash_attention_lse(q_lo, kx[:, :c], vx[:, :c],
                                     causal=True)
    o_hl, l_hl = flash_attention_lse(q_hi, kx[:, :c], vx[:, :c],
                                     causal=False)
    o_hh, l_hh = flash_attention_lse(q_hi, kx[:, c:], vx[:, c:],
                                     causal=True)
    o_lo, l_lo = o_ll.astype(jnp.float32), l_ll
    o_hi, l_hi = _merge_blocks(o_hl.astype(jnp.float32), l_hl, o_hh, l_hh)

    perm = [(i, (i + 1) % s) for i in range(s)]

    def step(carry, t):
        kb, vb, o_lo, l_lo, o_hi, l_hi = carry
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        src = (my - t) % s
        pred = src < my
        kbx = _expand_kv(kb, kv_groups)
        vbx = _expand_kv(vb, kv_groups)
        k_lo, k_hi = kbx[:, :c], kbx[:, c:]
        v_lo, v_hi = vbx[:, :c], vbx[:, c:]
        sel_q = jnp.where(pred, q_lo, q_hi)
        sel_k = jnp.where(pred, k_lo, k_hi)
        sel_v = jnp.where(pred, v_lo, v_hi)
        e1_o, e1_l = flash_attention_lse(q_hi, k_lo, v_lo, causal=False)
        e2_o, e2_l = flash_attention_lse(sel_q, sel_k, sel_v, causal=False)
        o_hi, l_hi = _merge_blocks(o_hi, l_hi, e1_o, e1_l)
        # e2 routes to the lo rows when pred, else to the (post-e1) hi rows
        o_b = jnp.where(pred, o_lo, o_hi)
        l_b = jnp.where(pred, l_lo, l_hi)
        o_b, l_b = _merge_blocks(o_b, l_b, e2_o, e2_l)
        o_lo = jnp.where(pred, o_b, o_lo)
        l_lo = jnp.where(pred, l_b, l_lo)
        o_hi = jnp.where(pred, o_hi, o_b)
        l_hi = jnp.where(pred, l_hi, l_b)
        return (kb, vb, o_lo, l_lo, o_hi, l_hi), None

    carry0 = (k, v, o_lo, l_lo, o_hi, l_hi)
    (_, _, o_lo, _, o_hi, _), _ = lax.scan(step, carry0, jnp.arange(1, s))
    return jnp.concatenate([o_lo, o_hi], axis=1).astype(dtype)


def _ring_attention_zigzag_local(q, k, v, *, axis_name: str, axis_size: int,
                                 kv_groups: int = 1):
    """Causal zigzag ring attention body (runs inside shard_map).

    Local ``[B, Tl, H, D]`` slices are in zigzag layout: the first half is
    global chunk ``my`` ("lo"), the second half chunk ``2s-1-my`` ("hi"),
    of 2s chunks of ``c = Tl/2`` tokens. Key property (for ring step
    t >= 1, K/V arriving from shard ``src = (my - t) % s != my``):

    - ``q_hi × k_lo`` is ALWAYS fully visible (chunk 2s-1-my >= s > src);
    - exactly one of ``q_lo × k_lo`` (iff src < my) or ``q_hi × k_hi``
      (iff src > my) is fully visible; the other three pairings are fully
      masked.

    So every device does two fully-visible quarter-block matmuls per step —
    balanced, maskless — instead of one full (often fully-masked) block.
    Step 0 (the local block, the only one with intra-chunk diagonals) runs
    once with an explicit position mask before the scan.
    """
    dtype = q.dtype
    b, tl, h, d = q.shape
    c = tl // 2
    s = axis_size
    my = lax.axis_index(axis_name)
    qf = q.astype(jnp.float32) * (d ** -0.5)

    lo_pos = my * c + jnp.arange(c)                # chunk my
    hi_pos = (2 * s - 1 - my) * c + jnp.arange(c)  # chunk 2s-1-my
    q_pos = jnp.concatenate([lo_pos, hi_pos])

    # ---- step 0: local block, position-masked (the only diagonals) ------
    scores0 = jnp.einsum("bqhd,bkhd->bhqk", qf,
                         _expand_kv(k, kv_groups).astype(jnp.float32))
    visible0 = q_pos[:, None] >= q_pos[None, :]
    scores0 = jnp.where(visible0[None, None], scores0, NEG_INF)
    m0 = jnp.max(scores0, axis=-1)                 # [B, H, Tl]
    p0 = jnp.exp(scores0 - m0[..., None])
    l0 = jnp.sum(p0, axis=-1)
    o0 = jnp.einsum("bhqk,bkhd->bhqd", p0,
                    _expand_kv(v, kv_groups).astype(jnp.float32))

    q_lo, q_hi = qf[:, :c], qf[:, c:]
    # Unlike the contiguous body, every carry derives from device-varying
    # data (scores from q/k, positions from axis_index), so no pcast is
    # needed to stabilize the scan carry type.
    carry0 = (k, v, m0, l0, o0)

    perm = [(i, (i + 1) % s) for i in range(s)]

    def step(carry, t):
        kb, vb, m, l, o = carry
        # rotate FIRST: at scan iteration t (1-based below) the local block
        # holds K/V originating from shard (my - t) % s
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        src = (my - t) % s
        pred = src < my
        kbx = _expand_kv(kb, kv_groups)
        vbx = _expand_kv(vb, kv_groups)
        k_lo, k_hi = kbx[:, :c], kbx[:, c:]
        v_lo, v_hi = vbx[:, :c], vbx[:, c:]
        # E2: the step's second visible quarter — lo×lo below the ring
        # diagonal, hi×hi above it. Selects are on inputs (cheap); both
        # cases are FULLY visible so no mask is ever applied.
        sel_q = jnp.where(pred, q_lo, q_hi)
        sel_k = jnp.where(pred, k_lo, k_hi)
        sel_v = jnp.where(pred, v_lo, v_hi)
        e1 = jnp.einsum("bqhd,bkhd->bhqk", q_hi, k_lo.astype(jnp.float32))
        e2 = jnp.einsum("bqhd,bkhd->bhqk", sel_q, sel_k.astype(jnp.float32))
        m_lo, l_lo, o_lo = m[..., :c], l[..., :c], o[..., :c, :]
        m_hi, l_hi, o_hi = m[..., c:], l[..., c:], o[..., c:, :]
        # update 1: hi rows absorb e1 (always visible)
        m_hi, l_hi, o_hi = _online_update(m_hi, l_hi, o_hi, e1, v_lo)
        # update 2: e2 belongs to the lo rows when pred, else to the
        # (post-e1) hi rows — select the accumulator halves in, update,
        # and scatter back. Two quarter-block updates per step, nothing
        # inert: exactly half the contiguous body's per-step FLOPs.
        m_b = jnp.where(pred, m_lo, m_hi)
        l_b = jnp.where(pred, l_lo, l_hi)
        o_b = jnp.where(pred, o_lo, o_hi)
        m_b, l_b, o_b = _online_update(m_b, l_b, o_b, e2, sel_v)
        m_lo = jnp.where(pred, m_b, m_lo)
        l_lo = jnp.where(pred, l_b, l_lo)
        o_lo = jnp.where(pred, o_b, o_lo)
        m_hi = jnp.where(pred, m_hi, m_b)
        l_hi = jnp.where(pred, l_hi, l_b)
        o_hi = jnp.where(pred, o_hi, o_b)
        m = jnp.concatenate([m_lo, m_hi], axis=-1)
        l = jnp.concatenate([l_lo, l_hi], axis=-1)
        o = jnp.concatenate([o_lo, o_hi], axis=-2)
        return (kb, vb, m, l, o), None

    (kb, vb, m, l, o), _ = lax.scan(step, carry0, jnp.arange(1, s))
    out = o / jnp.maximum(l, 1e-30)[..., None]     # [B, H, Tl, D]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(dtype)


def _sp_partition(mesh: Mesh, q, seq_axis: str, data_axes, head_axis):
    """Shared sequence-parallel partition plan: which mesh axes shard the
    batch (dp) and heads (hp) for this array, and the resulting spec.
    Probe shapes that don't divide an axis simply drop that axis (the
    caller's shard_map then replicates that dimension)."""
    dp = tuple(a for a in data_axes if a in mesh.axis_names)
    dp_total = int(np.prod([mesh.shape[a] for a in dp])) if dp else 1
    if dp and q.shape[0] % dp_total != 0:
        dp = ()  # batch too small to shard (init probes); replicate it
    hp = head_axis if head_axis in mesh.axis_names else None
    if hp is not None and q.shape[2] % mesh.shape[hp] != 0:
        hp = None
    return dp, hp, P(dp if dp else None, seq_axis, hp, None)


def _ulysses_local(q, k, v, *, axis_name: str, axis_size: int,
                   causal: bool, inner: str, window: int = 0):
    """Per-shard Ulysses body (runs inside shard_map).

    q,k,v: local [B, T/s, H, D] sequence slices. One tiled all-to-all
    re-shards each to [B, T, H/s, D] (full sequence, 1/s of the heads),
    attention runs LOCALLY over the whole sequence — heads are
    embarrassingly parallel — and a second all-to-all restores the
    sequence layout. Two collectives total per attention call (vs the
    ring's s ppermutes), and the local compute is plain full-T attention,
    so the causal 2x comes from the flash kernel's diagonal predication
    rather than a schedule. Positions stay natural — no zigzag needed.
    """
    a2a = functools.partial(lax.all_to_all, axis_name=axis_name, tiled=True)
    q = a2a(q, split_axis=2, concat_axis=1)        # [B, T, H/s, D]
    # GQA: compact K/V cross the all-to-all at n_kv heads (groups x less
    # traffic) and broadcast locally after — shard j's q heads
    # [j*Hq/s, (j+1)*Hq/s) pair with kv heads [j*Hkv/s, ...): the repeat
    # mapping i -> i // groups preserves contiguous-block alignment.
    k = a2a(k, split_axis=2, concat_axis=1)
    v = a2a(v, split_axis=2, concat_axis=1)
    k, v = (_expand_kv(k, q.shape[2] // k.shape[2]),
            _expand_kv(v, q.shape[2] // v.shape[2]))
    if inner == "flash":
        from .flash import flash_attention

        out = flash_attention(q, k, v, causal=causal, window=window)
    else:
        out = multihead_attention(q, k, v, causal=causal, window=window)
    return a2a(out, split_axis=1, concat_axis=2)   # [B, T/s, H, D]


def ulysses_attention(q, k, v, mesh: Mesh, causal: bool = True,
                      seq_axis: str = "seq", data_axes=("data", "fsdp"),
                      head_axis: str = "tensor", inner: str = "xla",
                      window: int = 0):
    """All-to-all (DeepSpeed-Ulysses-style) sequence parallelism.

    The alternative SP strategy to ``ring_attention``: instead of rotating
    K/V blocks s times around the ring, ONE all-to-all converts the
    sequence sharding into a head sharding (heads are independent in
    attention), full-sequence attention runs locally, and one all-to-all
    converts back. Cheaper in collective count for moderate T; the ring
    wins when T is so long that even [B, T, H/s, D] per device is too big.
    Local head count (after any ``tensor`` sharding) must divide by the
    seq-axis size; otherwise — and for probe shapes — falls back dense.

    GQA: ``k``/``v`` may carry FEWER heads than ``q`` — the compact K/V
    cross the all-to-alls (``groups``× less traffic) and broadcast
    locally after, provided the KV head count also splits over the
    involved axes; otherwise they pre-expand.

    ``inner`` selects the local kernel: "xla" einsum or "flash" (Pallas).
    """
    kv_groups = _gqa_groups(q, k, v)

    def dense():
        return multihead_attention(q, _expand_kv(k, kv_groups),
                                   _expand_kv(v, kv_groups),
                                   causal=causal, window=window)

    if seq_axis not in mesh.axis_names or mesh.shape[seq_axis] == 1:
        return dense()
    s = mesh.shape[seq_axis]
    if q.shape[1] % s != 0:
        return dense()

    dp, hp, spec = _sp_partition(mesh, q, seq_axis, data_axes, head_axis)
    local_heads = q.shape[2] // (mesh.shape[hp] if hp else 1)
    if local_heads % s != 0:
        # not enough heads per device to split across the seq axis
        return dense()
    if kv_groups > 1:
        # the compact KV heads must split over the SAME axes as q's
        # (tensor sharding, then the a2a's seq split); else pre-expand
        hp_size = mesh.shape[hp] if hp else 1
        if k.shape[2] % hp_size or (k.shape[2] // hp_size) % s:
            k = _expand_kv(k, kv_groups)
            v = _expand_kv(v, kv_groups)
            kv_groups = 1

    fn = functools.partial(
        _ulysses_local, axis_name=seq_axis, axis_size=s, causal=causal,
        inner=inner, window=window,
    )
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=inner != "flash",
    )(q, k, v)


def _ring_steps_needed(tl: int, axis_size: int, window: int) -> int:
    """Ring steps with any in-band key for sliding window ``window``.

    Block at ring distance ``t`` holds keys ``t*tl`` to ``t*tl - (tl-1)``
    positions behind the nearest query, so it is fully out of the band
    once ``t*tl - (tl-1) >= window``. Static — the scan just gets shorter
    (the banded-skip optimization: a narrow window stops the ring after
    ``~window/tl`` hops instead of circling all ``s`` shards).
    """
    if window <= 0:
        return axis_size
    return min(axis_size, (window + tl - 2) // tl + 1)


def _ring_attention_local(q, k, v, *, axis_name: str, axis_size: int,
                          causal: bool, vary_axes: tuple = (),
                          window: int = 0, kv_groups: int = 1):
    """Per-shard ring attention body (runs inside shard_map).

    q,k,v: local [B, Tl, H, D] slices of the global [B, T, H, D] arrays,
    sharded along T over ``axis_name``. Rotates K/V blocks around the ring
    with an online-softmax accumulator: after ``axis_size`` steps every query
    has attended to every (visible) key. ``window > 0`` adds the
    sliding-window band to the position mask and shortens the scan to the
    in-band ring distance (``_ring_steps_needed``).
    """
    dtype = q.dtype
    b, tl, h, d = q.shape
    my = lax.axis_index(axis_name)
    qf = q.astype(jnp.float32) * (d ** -0.5)
    q_pos = my * tl + jnp.arange(tl)  # global query positions

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, t):
        kb, vb, m, l, o = carry
        src = (my - t) % axis_size  # origin shard of the current K/V block
        k_pos = src * tl + jnp.arange(tl)
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf,
                            _expand_kv(kb, kv_groups).astype(jnp.float32))
        visible = None
        if causal:
            visible = q_pos[:, None] >= k_pos[None, :]  # [Tl_q, Tl_k]
        if window > 0:
            band = q_pos[:, None] - k_pos[None, :] < window
            visible = band if visible is None else visible & band
        if visible is not None:
            scores = jnp.where(visible[None, None], scores, NEG_INF)
        m_new, l_new, o_new = _online_update(
            m, l, o, scores, _expand_kv(vb, kv_groups)
        )
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (kb, vb, m_new, l_new, o_new), None

    m0 = jnp.full((b, h, tl), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, tl), jnp.float32)
    o0 = jnp.zeros((b, h, tl, d), jnp.float32)
    # The accumulators depend on device-varying data from step 1 on; mark
    # them varying over the sharded mesh axes up front so the scan carry
    # type is stable (JAX's varying-manual-axes check under shard_map).
    if vary_axes:
        vary = lambda x: lax.pcast(x, vary_axes, to="varying")
        m0, l0, o0 = vary(m0), vary(l0), vary(o0)
    # banded-skip is only sound under causal masking: without it the band
    # q_pos - k_pos < window keeps every FUTURE block visible
    n_steps = (_ring_steps_needed(tl, axis_size, window) if causal
               else axis_size)
    (kb, vb, m, l, o), _ = lax.scan(
        step, (k, v, m0, l0, o0), jnp.arange(n_steps)
    )
    out = o / jnp.maximum(l, 1e-30)[..., None]        # [B, H, Tq, D]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(dtype)


def ring_attention(q, k, v, mesh: Mesh, causal: bool = True,
                   seq_axis: str = "seq", data_axes=("data", "fsdp"),
                   head_axis: str = "tensor", layout: str = "contig",
                   block_impl: str = "einsum", window: int = 0):
    """Sequence-parallel attention over the mesh's ``seq`` axis.

    q,k,v are global ``[B, T, H, D]`` arrays (T sharded over ``seq``); the
    TxT score matrix never exists — only [Tl x Tl] blocks per device per
    ring step. Composes with DP (batch over data axes) and TP (heads over
    ``tensor``) in one shard_map.

    ``layout="zigzag"`` (causal only, T divisible by 2s): inputs must be in
    ``zigzag_perm(T, s)`` order; the balanced maskless body cuts attention
    FLOPs 2× (module docstring). Output stays in zigzag order.

    ``block_impl="flash"`` runs each ring block through the Pallas flash
    kernel (``ops/flash.flash_attention_lse``) and merges partials by
    logsumexp — per-device score tiles stream through VMEM instead of
    materializing [Tl x Tl], so long per-device slices stay HBM-light.
    ``"einsum"`` (default) is the plain-XLA body, best for short slices.

    ``window > 0`` (with ``causal``): sliding-window banding with the
    banded-skip schedule — the ring stops after ``~window/Tl`` hops
    because farther blocks are fully out of band (``_ring_steps_needed``),
    so a narrow window makes ring cost O(T·window / s) per device.
    Contiguous layout only: zigzag exists to balance the full causal
    triangle, which a band already balances (and a banded zigzag would
    put BOTH of each device's chunks on the band edge — strictly more
    masked work than contiguous).

    GQA: ``k``/``v`` may carry FEWER heads than ``q`` (``Hq % Hkv == 0``)
    — the compact K/V rotates around the ring (``groups``× less ICI
    traffic than pre-repeating) and each hop broadcasts locally for its
    block compute. When a ``tensor`` head sharding doesn't divide the KV
    head count, K/V are pre-expanded instead (a sharded-q/replicated-kv
    split would mis-pair heads).
    """
    kv_groups = _gqa_groups(q, k, v)

    def dense():
        return multihead_attention(q, _expand_kv(k, kv_groups),
                                   _expand_kv(v, kv_groups),
                                   causal=causal, window=window)

    if seq_axis not in mesh.axis_names or mesh.shape[seq_axis] == 1:
        return dense()
    axis_size = mesh.shape[seq_axis]
    zigzag = layout == "zigzag"
    if zigzag and (not causal or q.shape[1] % (2 * axis_size) != 0):
        raise ValueError(
            "layout='zigzag' needs causal=True and T divisible by "
            f"2*seq ({2 * axis_size}); got causal={causal}, T={q.shape[1]}"
        )
    if zigzag and window > 0:
        raise ValueError(
            "layout='zigzag' does not compose with window (sliding-window "
            "attention): the band already load-balances the causal "
            "triangle, so use layout='contig', which also enables the "
            "banded-skip early ring exit"
        )
    if q.shape[1] % axis_size != 0:
        # Sequence not evenly shardable (e.g. a probe batch at init time):
        # the dense path is always correct, just not sequence-parallel.
        return dense()

    dp, hp, spec = _sp_partition(mesh, q, seq_axis, data_axes, head_axis)

    if kv_groups > 1 and hp is not None and (
        k.shape[2] % mesh.shape[hp] != 0
    ):
        # head-sharded q with a KV head count the tensor axis doesn't
        # divide would mis-pair local q heads with kv heads: pre-expand
        k, v = _expand_kv(k, kv_groups), _expand_kv(v, kv_groups)
        kv_groups = 1
    # The KV spec equals q's (same dp/seq/head axes — only the head
    # COUNT differs); each shard's local q:kv ratio stays kv_groups
    # because both shard heads over the same axis.
    spec_kv = spec

    if block_impl not in ("einsum", "flash"):
        raise ValueError(
            f"block_impl={block_impl!r}; expected 'einsum' or 'flash'"
        )
    flash_blocks = block_impl == "flash"
    if flash_blocks and window > 0 and not causal:
        # the flash body's banded-skip schedule is causal-only (a
        # non-causal band keeps every future block visible); the einsum
        # body applies the band independently of causal, so use it
        flash_blocks = False
    if zigzag:
        fn = functools.partial(
            _ring_attention_zigzag_local_flash if flash_blocks
            else _ring_attention_zigzag_local,
            axis_name=seq_axis, axis_size=axis_size, kv_groups=kv_groups,
        )
    elif flash_blocks:
        fn = functools.partial(
            _ring_attention_local_flash, axis_name=seq_axis,
            axis_size=axis_size, causal=causal, window=window,
            kv_groups=kv_groups,
        )
    else:
        vary_axes = tuple(dp) + (seq_axis,) + ((hp,) if hp else ())
        fn = functools.partial(
            _ring_attention_local, axis_name=seq_axis, axis_size=axis_size,
            causal=causal, vary_axes=vary_axes, window=window,
            kv_groups=kv_groups,
        )
    # Pallas calls don't annotate varying-mesh-axes metadata on their
    # outputs, so the flash bodies run with the vma check off (the einsum
    # bodies keep it, with explicit pcasts where carries start replicated).
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec_kv, spec_kv), out_specs=spec,
        check_vma=not flash_blocks,
    )(q, k, v)
