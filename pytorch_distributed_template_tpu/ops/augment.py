"""On-device data augmentation, fused into the jitted train step.

The reference's only input transform is a host-side Normalize
(/root/reference/data_loader/data_loaders.py:13-16); anything heavier
(random crop/flip for CIFAR/ImageNet) would run in torch's CPU worker pool.
TPU-natively the augmentations run *in-graph* on the accelerator: they are
a handful of elementwise/gather ops XLA fuses into the step, keyed by the
step's PRNG — so they cost ~nothing, stay reproducible (pure function of
the seed), and need no host worker pool at all.

All functions take ``[B, H, W, C]`` batches and a key; each example draws
its own randomness. Static shapes throughout (pad + dynamic_slice via
gather indices), so one compiled program serves every step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def random_flip(key: jax.Array, x: jax.Array) -> jax.Array:
    """Horizontal flip, per-example coin toss."""
    flip = jax.random.bernoulli(key, 0.5, (x.shape[0],))
    return jnp.where(flip[:, None, None, None], x[:, :, ::-1, :], x)


def random_crop(key: jax.Array, x: jax.Array, padding: int) -> jax.Array:
    """Pad-and-crop (the standard CIFAR augmentation), per-example offsets.

    Pads spatially by ``padding`` (reflect) then takes a random HxW window
    per example. Implemented with per-example gather indices instead of
    ``dynamic_slice`` so the whole batch is one vectorized op.
    """
    b, h, w, c = x.shape
    xp = jnp.pad(
        x, ((0, 0), (padding, padding), (padding, padding), (0, 0)),
        mode="reflect",
    )
    ky, kx = jax.random.split(key)
    oy = jax.random.randint(ky, (b,), 0, 2 * padding + 1)
    ox = jax.random.randint(kx, (b,), 0, 2 * padding + 1)
    rows = oy[:, None] + jnp.arange(h)[None, :]          # [B, H]
    cols = ox[:, None] + jnp.arange(w)[None, :]          # [B, W]
    batch_idx = jnp.arange(b)[:, None, None]
    return xp[batch_idx, rows[:, :, None], cols[:, None, :], :]


def random_cutout(key: jax.Array, x: jax.Array, size: int) -> jax.Array:
    """Zero one random ``size x size`` square per example (DeVries &
    Taylor 2017) — a cheap regularizer that is pure elementwise masking on
    TPU. The window is placed fully inside the image (corner-sampled), so
    exactly ``min(size, H) x min(size, W)`` pixels are zeroed."""
    b, h, w, _ = x.shape
    ky, kx = jax.random.split(key)
    oy = jax.random.randint(ky, (b,), 0, max(h - size + 1, 1))
    ox = jax.random.randint(kx, (b,), 0, max(w - size + 1, 1))
    ys = jnp.arange(h)[None, :, None]
    xs = jnp.arange(w)[None, None, :]
    oy = oy[:, None, None]
    ox = ox[:, None, None]
    mask = (ys >= oy) & (ys < oy + size) & (xs >= ox) & (xs < ox + size)
    return jnp.where(mask[..., None], 0.0, x).astype(x.dtype)


def build_augment(cfg: dict | None):
    """Compose the configured augmentations into one ``(key, x) -> x`` fn.

    Config schema (the ``trainer.augment`` block):
    ``{"flip": true, "crop_padding": 4, "cutout": 8}`` — all optional;
    returns None when nothing is enabled so callers can skip the key
    split entirely.
    """
    if not cfg:
        return None
    unknown = set(cfg) - {"flip", "crop_padding", "cutout"}
    if unknown:
        # fail loudly like the rest of the config system (a misspelled key
        # silently disabling augmentation would only show up as accuracy)
        raise ValueError(
            f"unknown trainer.augment keys {sorted(unknown)}; "
            "valid: flip, crop_padding, cutout"
        )
    steps = []
    if cfg.get("flip"):
        steps.append(random_flip)
    pad = int(cfg.get("crop_padding", 0))
    if pad > 0:
        steps.append(lambda k, x: random_crop(k, x, pad))
    cut = int(cfg.get("cutout", 0))
    if cut > 0:
        steps.append(lambda k, x: random_cutout(k, x, cut))
    if not steps:
        return None

    def apply(key, x):
        for i, fn in enumerate(steps):
            key_i = jax.random.fold_in(key, i)
            x = fn(key_i, x)
        return x

    return apply
