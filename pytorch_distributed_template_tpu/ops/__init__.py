"""Hot-path ops: attention implementations (XLA, ring/SP, Pallas flash)."""
from .attention import multihead_attention, ring_attention
from .flash import flash_attention

__all__ = ["multihead_attention", "ring_attention", "flash_attention"]
