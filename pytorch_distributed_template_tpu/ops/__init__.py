"""Hot-path ops: attention implementations (XLA, ring/SP, Pallas flash)."""
from .attention import (
    grouped_query_attention, multihead_attention, ring_attention,
    ulysses_attention, zigzag_perm,
)
from .flash import flash_attention, flash_attention_lse

__all__ = [
    "grouped_query_attention", "multihead_attention", "ring_attention",
    "ulysses_attention", "zigzag_perm", "flash_attention",
    "flash_attention_lse",
]
