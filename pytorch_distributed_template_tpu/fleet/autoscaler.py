"""Fleet autoscaler: one deterministic policy, two worlds (ISSUE 19).

The control plane the measurement substrate (PR 14) was built for.
This module owns the POLICY — a pure, deterministic state machine from
scraped fleet signals to scale actions — and the LIVE actuator that
runs it against a real :class:`fleet.replicas.FleetManager`. The
offline twin, :mod:`fleet.simulator`, runs the *same policy class*
against virtual replicas at time compression; that shared interface is
the point: a policy validated in the simulator at request scales this
container can't run live is the policy the live fleet executes.

Design notes:

- **Signals** (:class:`FleetSignals`) are exactly what the poller
  already scrapes: queue depth, brownout level, SLO-breach and
  deadline-miss rates, plus the arrival-rate trend the tracker
  derives. The policy never reaches into a manager — both worlds
  build the same dataclass.
- **Never flap**: scale-ups are immediate under pressure but gated by
  an up-cooldown; scale-downs require the pressure to stay below the
  low watermark for a dwell AND a separate (longer) down-cooldown —
  the :class:`utils.brownout.BrownoutController` hysteresis idiom
  (enter fast, exit slow, strictly separated watermarks).
- **Predictive scale-ahead**: Little's law on the projected arrival
  rate (EWMA + trend x horizon) x the measured mean service time
  gives the concurrency the fleet is ABOUT to need; the policy scales
  on ``max(reactive, predicted)`` so the spawn cost is paid before
  the queue builds, not after.
- **Beyond replica count**: the policy flips prefill<->decode roles as
  the traffic mixture shifts (PR 12's geometry, now actuated), and
  the live actuator pre-loads every spawning replica's re-warm plan
  with the fleet's hottest prefixes (PR 13's pull path) so scale-ups
  join warm.

Stdlib-only, like the rest of ``fleet/``.
"""
from __future__ import annotations

import dataclasses
import math
import threading
import time
from typing import Dict, List, Optional

from .replicas import HEALTHY

__all__ = ["AutoscaleConfig", "FleetSignals", "SignalTracker",
           "AutoscalePolicy", "StaticPolicy", "Autoscaler"]


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Policy knobs (docs/FLEET.md has the reference table)."""
    min_replicas: int = 1
    max_replicas: int = 4
    #: scale up when effective pressure >= this (pressure ~1.0 means
    #: demand equals healthy serving slots)
    up_pressure: float = 0.85
    #: scale down only while pressure <= this — strictly below
    #: up_pressure, the hysteresis gap that prevents flapping
    down_pressure: float = 0.40
    #: minimum seconds between consecutive scale actions (up / down)
    up_cooldown_s: float = 5.0
    down_cooldown_s: float = 20.0
    #: pressure must stay <= down_pressure this long before a drain
    down_dwell_s: float = 10.0
    #: predictive scale-ahead: project the arrival rate this far out
    horizon_s: float = 20.0
    #: fallback mean service time before the fleet has measured one
    service_s_hint: float = 0.5
    #: brownout level that counts as full pressure (level/this)
    brownout_full_level: int = 2
    #: SLO-breach fraction (breaches/arrivals) that counts as full
    #: pressure on its own
    slo_full_frac: float = 0.25
    #: predictive projection cap: the trend term may at most multiply
    #: the CURRENT arrival rate by this. A derivative over sparse
    #: arrivals is noise — uncapped, one request after a quiet spell
    #: projects phantom rps that flap a small fleet up and reset the
    #: scale-down dwell all through a valley. A genuine ramp carries
    #: its own rising rate, so the cap never blocks real scale-ahead.
    predict_max_factor: float = 3.0
    #: prefill<->decode role flips (off by default — an all-"both"
    #: fleet stays all-"both")
    role_flip: bool = False
    #: flip a replica TO prefill when the prefill share of arriving
    #: work exceeds this...
    prefill_share_high: float = 0.55
    #: ...and back to "both" when it falls below this
    prefill_share_low: float = 0.25
    role_cooldown_s: float = 30.0

    def __post_init__(self):
        if self.down_pressure >= self.up_pressure:
            raise ValueError("down_pressure must be strictly below "
                             "up_pressure (the hysteresis gap)")
        if self.min_replicas < 1 or self.max_replicas < self.min_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")


@dataclasses.dataclass
class FleetSignals:
    """One policy tick's worth of scraped state. Both worlds build
    exactly this — the live tracker from poller counters, the
    simulator from virtual state."""
    t: float                       #: seconds (monotonic or virtual)
    replicas: int                  #: current membership (incl. starting)
    healthy: int
    slots: float                   #: healthy serving slots, fleet-wide
    queue_depth: float = 0.0       #: accepted-but-unslotted requests
    inflight: float = 0.0
    brownout_level: int = 0
    slo_breach_rate: float = 0.0   #: breaches/s (EWMA)
    deadline_miss_rate: float = 0.0
    arrival_rate: float = 0.0      #: requests/s (EWMA)
    arrival_trend: float = 0.0     #: d(arrival_rate)/dt
    avg_service_s: float = 0.0     #: measured mean request service time
    prefill_share: float = 0.0     #: fraction of arriving work that is
                                   #: prefill-heavy (0 = unknown)
    replica_loads: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    replica_roles: Dict[str, str] = dataclasses.field(
        default_factory=dict)


class SignalTracker:
    """Derives the rate/trend signals the policy wants from raw
    monotonic counters — shared by the live actuator and the
    simulator so the two worlds see the same smoothing."""

    def __init__(self, alpha: float = 0.35):
        #: PER-SECOND smoothing coefficient. The effective per-update
        #: weight is 1-(1-alpha)^dt, so a 0.5 s live tick and a 1 s
        #: simulator tick converge to the SAME smoothed signal — a
        #: fixed per-update alpha at a faster cadence would smooth
        #: less, and sparse single arrivals would spike the rate (and
        #: its trend) into phantom pressure.
        self.alpha = float(alpha)
        self._last_t: Optional[float] = None
        self._last_counts: Dict[str, float] = {}
        self.rates: Dict[str, float] = {}
        self._last_rates: Dict[str, float] = {}
        self.trends: Dict[str, float] = {}

    def update(self, t: float, counts: Dict[str, float]) -> None:
        """Feed one observation of monotonic counters at time ``t``;
        EWMA rates and rate trends update in place."""
        if self._last_t is None or t <= self._last_t:
            self._last_t = t
            self._last_counts = dict(counts)
            return
        dt = t - self._last_t
        a = (1.0 if self.alpha >= 1.0
             else 1.0 - (1.0 - self.alpha) ** dt)
        for key, val in counts.items():
            delta = max(val - self._last_counts.get(key, 0.0), 0.0)
            inst = delta / dt
            prev = self.rates.get(key)
            new = (inst if prev is None
                   else prev + a * (inst - prev))
            self.rates[key] = new
            if prev is not None:
                inst_tr = (new - prev) / dt
                ptr = self.trends.get(key, 0.0)
                self.trends[key] = ptr + a * (inst_tr - ptr)
            self._last_counts[key] = val
        self._last_t = t

    def rate(self, key: str) -> float:
        return float(self.rates.get(key, 0.0))

    def trend(self, key: str) -> float:
        return float(self.trends.get(key, 0.0))


def pick_drain_victim(loads: Dict[str, float],
                      roles: Optional[Dict[str, str]] = None
                      ) -> Optional[str]:
    """The emptiest replica, deterministically (load, then rid).
    Dedicated prefill replicas are spared when any "both"/decode
    candidate exists — shrinking should not silently undo a role
    split the mixture still wants."""
    if not loads:
        return None
    roles = roles or {}
    pool = {rid: ld for rid, ld in loads.items()
            if roles.get(rid, "both") != "prefill"}
    if not pool:
        pool = dict(loads)
    return min(pool.items(), key=lambda kv: (kv[1], kv[0]))[0]


class AutoscalePolicy:
    """The deterministic scaling state machine. ``decide()`` maps one
    :class:`FleetSignals` tick to a list of action dicts:

    - ``{"op": "scale_up", "n": 1, "reason": ...}``
    - ``{"op": "scale_down", "rid": ..., "reason": ...}``
    - ``{"op": "role_flip", "rid": ..., "role": ..., "reason": ...}``

    Same signal sequence => same action sequence, byte for byte —
    that is what lets the simulator validate the exact policy the
    live fleet runs (tests/test_autoscale.py pins it).
    """

    def __init__(self, cfg: AutoscaleConfig = AutoscaleConfig()):
        self.cfg = cfg
        self._last_scale_t: Optional[float] = None
        self._last_flip_t: Optional[float] = None
        self._low_since: Optional[float] = None
        self.last_pressure = 0.0
        self.last_predicted = 0.0
        self.last_target = 0

    # -- pressure model ------------------------------------------------------

    def pressure(self, sig: FleetSignals) -> float:
        """Reactive pressure: demand over capacity, on whichever
        signal screams loudest. ~1.0 = the healthy slots are exactly
        consumed."""
        cfg = self.cfg
        slots = max(sig.slots, 1.0)
        util = (sig.queue_depth + sig.inflight) / slots
        brown = (sig.brownout_level
                 / max(cfg.brownout_full_level, 1))
        breach_frac = ((sig.slo_breach_rate + sig.deadline_miss_rate)
                       / max(sig.arrival_rate, 1e-9)
                       if sig.arrival_rate > 0 else 0.0)
        slo = breach_frac / max(cfg.slo_full_frac, 1e-9)
        return max(util, brown, slo)

    def predicted_pressure(self, sig: FleetSignals) -> float:
        """Scale-ahead pressure: Little's law on the projected
        arrival rate at the horizon. Trends only push UP — a falling
        trend must not mask real present load (scale-down has its own
        dwell) — and the projection is capped at
        ``predict_max_factor`` x the current rate so derivative noise
        from sparse arrivals cannot invent demand."""
        cfg = self.cfg
        proj = sig.arrival_rate + max(sig.arrival_trend, 0.0) \
            * cfg.horizon_s
        proj = min(proj, cfg.predict_max_factor * sig.arrival_rate)
        service = sig.avg_service_s or cfg.service_s_hint
        demand = proj * service          # concurrent requests needed
        return demand / max(sig.slots, 1.0)

    # -- the decision --------------------------------------------------------

    def decide(self, sig: FleetSignals) -> List[dict]:
        cfg = self.cfg
        actions: List[dict] = []
        pressure = self.pressure(sig)
        predicted = self.predicted_pressure(sig)
        eff = max(pressure, predicted)
        self.last_pressure = round(pressure, 4)
        self.last_predicted = round(predicted, 4)
        since_scale = (math.inf if self._last_scale_t is None
                       else sig.t - self._last_scale_t)

        if eff >= cfg.up_pressure:
            self._low_since = None
            if (sig.replicas < cfg.max_replicas
                    and since_scale >= cfg.up_cooldown_s):
                # jump more than one step when demand is far past
                # capacity — predictive ticks during a steep ramp
                # should not pay one cooldown per replica
                want = math.ceil(sig.replicas * eff / cfg.up_pressure)
                n = max(1, min(want - sig.replicas,
                               cfg.max_replicas - sig.replicas))
                actions.append({
                    "op": "scale_up", "n": int(n),
                    "reason": ("predicted" if predicted > pressure
                               else "pressure"),
                    "pressure": round(eff, 4)})
                self._last_scale_t = sig.t
        elif eff <= cfg.down_pressure and sig.replicas > cfg.min_replicas:
            if self._low_since is None:
                self._low_since = sig.t
            elif (sig.t - self._low_since >= cfg.down_dwell_s
                    and since_scale >= cfg.down_cooldown_s):
                victim = pick_drain_victim(sig.replica_loads,
                                           sig.replica_roles)
                if victim is not None:
                    actions.append({
                        "op": "scale_down", "rid": victim,
                        "reason": "idle",
                        "pressure": round(eff, 4)})
                    self._last_scale_t = sig.t
                    self._low_since = sig.t
        else:
            # mid-band: neither watermark — reset the low dwell so a
            # brief dip never banks toward a drain
            self._low_since = None

        if cfg.role_flip:
            actions.extend(self._decide_roles(sig))
        self.last_target = sig.replicas + sum(
            a.get("n", 0) for a in actions if a["op"] == "scale_up"
        ) - sum(1 for a in actions if a["op"] == "scale_down")
        return actions

    def _decide_roles(self, sig: FleetSignals) -> List[dict]:
        """Mixture tracking (PR 12's geometry as an actuator): when
        the arriving work turns prefill-heavy, dedicate a replica to
        prefill; when it turns decode-heavy again, fold it back to
        "both". Never flips below 2 healthy (a 1-replica fleet must
        stay role-complete) and respects its own cooldown."""
        cfg = self.cfg
        since_flip = (math.inf if self._last_flip_t is None
                      else sig.t - self._last_flip_t)
        if sig.healthy < 2 or since_flip < cfg.role_cooldown_s:
            return []
        roles = sig.replica_roles
        prefills = [rid for rid, role in roles.items()
                    if role == "prefill"]
        if sig.prefill_share >= cfg.prefill_share_high and not prefills:
            flex = {rid: sig.replica_loads.get(rid, 0.0)
                    for rid, role in roles.items() if role == "both"}
            rid = pick_drain_victim(flex)
            if rid is not None:
                self._last_flip_t = sig.t
                return [{"op": "role_flip", "rid": rid,
                         "role": "prefill",
                         "reason": "prefill_heavy",
                         "share": round(sig.prefill_share, 4)}]
        elif sig.prefill_share <= cfg.prefill_share_low and prefills:
            rid = min(prefills)
            self._last_flip_t = sig.t
            return [{"op": "role_flip", "rid": rid, "role": "both",
                     "reason": "decode_heavy",
                     "share": round(sig.prefill_share, 4)}]
        return []


class StaticPolicy:
    """The peak-provisioned control arm: never scales. Shares the
    interface so the simulator/bench run both arms through one code
    path."""

    def __init__(self, cfg: AutoscaleConfig = AutoscaleConfig()):
        self.cfg = cfg
        self.last_pressure = 0.0
        self.last_predicted = 0.0
        self.last_target = 0

    def decide(self, sig: FleetSignals) -> List[dict]:
        self.last_target = sig.replicas
        return []


class Autoscaler:
    """The LIVE actuator: ticks the policy against a running
    :class:`FleetManager` and actuates through the first-class
    membership API — spawn via ``make_replica`` + ``add_replica``
    (supervised start + warm-signature ladder, PR 9), drain via
    ``remove_replica`` (drain-on-SIGTERM, zero dropped requests),
    role flips as replace-then-retire, and every spawn pre-loaded
    with the fleet's hot prefixes (PR 13's re-warm pull path) so it
    joins warm."""

    def __init__(self, manager, policy, make_replica,
                 interval_s: float = 1.0,
                 prefill_share_fn=None,
                 rewarm_top_k: int = 8,
                 drain_grace_s: float = 30.0):
        self.manager = manager
        self.policy = policy
        self.make_replica = make_replica
        self.interval_s = float(interval_s)
        self.prefill_share_fn = prefill_share_fn
        self.rewarm_top_k = int(rewarm_top_k)
        self.drain_grace_s = float(drain_grace_s)
        self.tracker = SignalTracker()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._next_idx = 0
        #: (new_rid, old_rid) role replacements waiting on the new
        #: replica's health before the old one retires
        self._pending_flips: List[tuple] = []
        self._lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="fleet-autoscale")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the control loop must
                pass           # survive any one bad tick
            self._stop.wait(self.interval_s)

    # -- one tick ------------------------------------------------------------

    def signals(self, t: Optional[float] = None) -> FleetSignals:
        """Scrape the live fleet into the policy's input dataclass.
        Everything here is what the poller already collects — the
        autoscaler adds no new probes."""
        m = self.manager
        t = time.monotonic() if t is None else t
        with m._lock:
            reps = list(m.replicas.values())
            counts = {
                "arrivals": float(sum(r.cum["requests_total"]
                                      for r in reps)),
                "breaches": float(sum(r.cum["slo_breach_total"]
                                      for r in reps)),
                "misses": float(m.stats.get("deadline_expired_total",
                                            0)),
            }
            healthy = [r for r in reps if r.state == HEALTHY]
            queue_depth = sum(
                float(r.polled.get("queue_depth", 0) or 0)
                for r in healthy)
            inflight = sum(r.inflight for r in reps)
            slots = sum(r.slots(m.slots_hint) for r in healthy)
            brown = m._brownout_level_locked()
            loads = {r.rid: r.load_estimate() for r in healthy}
            roles = {r.rid: r.role for r in healthy}
            n = len(reps)
        self.tracker.update(t, counts)
        share = 0.0
        if self.prefill_share_fn is not None:
            try:
                share = float(self.prefill_share_fn() or 0.0)
            except Exception:  # noqa: BLE001
                share = 0.0
        arrival = self.tracker.rate("arrivals")
        return FleetSignals(
            t=t, replicas=n, healthy=len(healthy), slots=float(slots),
            queue_depth=queue_depth, inflight=float(inflight),
            brownout_level=int(brown),
            slo_breach_rate=self.tracker.rate("breaches"),
            deadline_miss_rate=self.tracker.rate("misses"),
            arrival_rate=arrival,
            arrival_trend=self.tracker.trend("arrivals"),
            avg_service_s=0.0,
            prefill_share=share,
            replica_loads=loads, replica_roles=roles)

    def tick(self) -> List[dict]:
        self._settle_flips()
        sig = self.signals()
        actions = self.policy.decide(sig)
        for act in actions:
            self._apply(act)
        return actions

    # -- actuation -----------------------------------------------------------

    def _fresh_rid(self) -> str:
        with self._lock:
            while True:
                rid = f"as{self._next_idx}"
                self._next_idx += 1
                if rid not in self.manager.replicas:
                    return rid

    def _spawn(self, role: str = "both") -> Optional[str]:
        rid = self._fresh_rid()
        replica = self.make_replica(rid, role)
        if replica is None:
            return None
        # proactive hot-prefix replication (ISSUE 19 via PR 13): the
        # spawn's re-warm plan is the FLEET's hottest chains, pulled
        # from peers before the poller readmits it — first request
        # lands warm, not cold
        with self.manager._lock:
            plan = self.manager.radix.hot_prefixes(self.rewarm_top_k)
        if plan:
            replica.rewarm_prefixes = plan
            replica.rewarm_state = "pending"
        if not self.manager.add_replica(replica):
            return None
        return rid

    def _apply(self, act: dict) -> None:
        m = self.manager
        op = act.get("op")
        if op == "scale_up":
            spawned = []
            for _ in range(int(act.get("n", 1))):
                rid = self._spawn()
                if rid is not None:
                    spawned.append(rid)
            if spawned:
                with m._lock:
                    m.stats["autoscale_scale_up_total"] += len(spawned)
                m.events.log("scale_up", replicas=spawned,
                             reason=act.get("reason"),
                             pressure=act.get("pressure"))
        elif op == "scale_down":
            rid = act.get("rid")
            if rid is not None and m.remove_replica(
                    rid, grace_s=self.drain_grace_s):
                with m._lock:
                    m.stats["autoscale_scale_down_total"] += 1
                m.events.log("scale_down", replica=rid,
                             reason=act.get("reason"),
                             pressure=act.get("pressure"))
        elif op == "role_flip":
            # replace-then-retire: spawn the new-role replica first,
            # retire the old one only once the spawn is HEALTHY — the
            # fleet never dips below its serving capacity mid-flip
            old = act.get("rid")
            new_rid = self._spawn(role=act.get("role", "both"))
            if new_rid is not None:
                with self._lock:
                    self._pending_flips.append((new_rid, old))
                m.events.log("role_flip", replica=old,
                             replacement=new_rid,
                             role=act.get("role"),
                             reason=act.get("reason"),
                             share=act.get("share"))

    def scale_to(self, n: int) -> dict:
        """Operator override (``POST /admin/scale?replicas=N``): walk
        the fleet to ``n`` replicas through the SAME actuators the
        policy uses — supervised spawns with hot-prefix re-warm plans,
        emptiest-first drains — clamped to the policy's bounds."""
        cfg = getattr(self.policy, "cfg", None)
        lo = getattr(cfg, "min_replicas", 1)
        hi = getattr(cfg, "max_replicas", 64)
        n = max(lo, min(int(n), hi))
        sig = self.signals()
        delta = n - sig.replicas
        if delta > 0:
            self._apply({"op": "scale_up", "n": delta,
                         "reason": "admin"})
        else:
            loads = dict(sig.replica_loads)
            roles = dict(sig.replica_roles)
            for _ in range(-delta):
                rid = pick_drain_victim(loads, roles)
                if rid is None:
                    break
                self._apply({"op": "scale_down", "rid": rid,
                             "reason": "admin"})
                loads.pop(rid, None)
                roles.pop(rid, None)
        return {"target": n, "was": sig.replicas,
                "delta": delta}

    def _settle_flips(self) -> None:
        """Retire the old half of any role flip whose replacement has
        come up healthy."""
        m = self.manager
        with self._lock:
            pending = list(self._pending_flips)
        for new_rid, old_rid in pending:
            rep = m.replicas.get(new_rid)
            if rep is None:
                # replacement died permanently: abandon the flip, the
                # old replica stays
                with self._lock:
                    self._pending_flips.remove((new_rid, old_rid))
                continue
            if rep.state == HEALTHY:
                m.remove_replica(old_rid, grace_s=self.drain_grace_s)
                with m._lock:
                    m.stats["autoscale_role_flip_total"] += 1
                with self._lock:
                    self._pending_flips.remove((new_rid, old_rid))

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        """Flat gauges merged onto the router's /metrics via the
        manager's ``extra_counters_fn`` hook (promlint: gauges carry
        no ``_total`` suffix)."""
        with self.manager._lock:
            n = len(self.manager.replicas)
            healthy = sum(1 for r in self.manager.replicas.values()
                          if r.state == HEALTHY)
        return {
            "autoscale_target_replicas": int(
                getattr(self.policy, "last_target", 0) or n),
            "autoscale_actual_replicas": n,
            "autoscale_healthy_replicas": healthy,
            "autoscale_pressure": float(
                getattr(self.policy, "last_pressure", 0.0)),
            "autoscale_predicted_pressure": float(
                getattr(self.policy, "last_predicted", 0.0)),
            "autoscale_arrival_rate": round(
                self.tracker.rate("arrivals"), 4),
        }
