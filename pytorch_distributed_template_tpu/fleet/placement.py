"""Cache-aware request placement: a fleet-level radix over prompt ids.

The serving replicas each run a paged KV prefix cache whose radix index
is block-granular — one edge per FULL ``block_tokens``-id chunk, no
partial-edge splits (``engine/kvcache.RadixIndex``, the vLLM
hash-per-block contract). Routing can only exploit that cache if the
router's own view of "who holds which prefix" uses the SAME chunking:
:class:`FleetRadix` mirrors the trie host-side, but instead of pool
block ids its nodes carry the set of replicas that were last routed a
prompt through that prefix. A match therefore predicts, per replica,
how many prompt tokens would be served from its pool instead of
recomputed — the exact quantity the replicas report back as
``prefix_hit_tokens_total``.

The router cannot see the replicas' evictions, so the index is a
best-effort *prediction*, kept honest three ways: it is bounded
(LRU-evicting leaves past ``max_nodes``, like the device pool it
mirrors), a replica's entries are dropped wholesale when the replica
dies (its pool restarts empty), and a stale prediction costs only a
cold prefill on the chosen replica — correctness never depends on it.

Placement (:func:`choose_replica`) is SGLang-style cache-aware
scheduling: send the request to the replica with the deepest cached
prefix, UNLESS that replica is overloaded relative to the least-loaded
candidate (``load_spread``) — affinity must never turn one hot prefix
into a hotspot that queues while other replicas idle. No match (or the
``least_loaded`` policy) falls back to least-loaded; ``round_robin``
ignores both and is the bench's control arm.

Text prompts (no ids on the wire) key the trie on their UTF-8 bytes:
the affinity signal — "these two requests share a long literal prefix"
— is the same one the replica's tokenizer would produce, and the
router must not load a tokenizer (stdlib-only, model-agnostic).
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

#: routing decision labels (router metrics count requests per reason)
REASON_PREFIX = "prefix"
REASON_LEAST_LOADED = "least_loaded"
REASON_ROUND_ROBIN = "round_robin"

POLICIES = ("cache_aware", "least_loaded", "round_robin")

#: replica roles (disaggregated prefill/decode serving, ISSUE 12):
#: "prefill" replicas compute prompt KV and ship pool pages, "decode"
#: replicas ingest pages and serve decode, "both" does everything (the
#: classic colocated replica — every pre-disaggregation fleet is all
#: "both" and routes exactly as before)
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_BOTH = "both"
ROLES = (ROLE_BOTH, ROLE_PREFILL, ROLE_DECODE)


def role_serves(replica_role: str, stage: Optional[str]) -> bool:
    """Can a replica with ``replica_role`` serve ``stage``?
    ``stage=None`` (no role constraint — the colocated path) matches
    everything; ``"prefill"`` matches prefill/both; ``"decode"``
    matches decode/both. One owner for the stage→role matrix — the
    manager's role-filtered routing and the two-queue capacity split
    both consult it."""
    if stage is None:
        return True
    role = replica_role or ROLE_BOTH
    return role == ROLE_BOTH or role == stage


def affinity_ids(body: dict) -> list:
    """Wire request body -> the id sequence the radix keys on:
    ``prompt_ids`` verbatim when present, else the UTF-8 bytes of
    ``prompt``. Malformed payloads (the replica will 400 them anyway)
    key as empty — they route least-loaded and never touch the trie."""
    ids = body.get("prompt_ids")
    if isinstance(ids, (list, tuple)):
        try:
            return [int(i) for i in ids]
        except (TypeError, ValueError):
            return []
    prompt = body.get("prompt")
    if prompt is None:
        return []
    return list(str(prompt).encode("utf-8"))


class FleetRadix:
    """Block-granular trie over prompt ids -> replicas that hold them.

    One edge per full ``block_tokens``-id chunk; matching walks whole
    blocks, so two prompts diverging mid-block share nothing for that
    block — byte-for-byte the contract of the replica-side index this
    predicts. Nodes carry the replica ids routed through them and an
    LRU clock; the node count is bounded by evicting the least-
    recently-used leaf (children keep ancestors alive by construction,
    exactly like the device pool's eviction)."""

    def __init__(self, block_tokens: int = 32, max_nodes: int = 4096):
        if int(block_tokens) < 1:
            raise ValueError("block_tokens must be >= 1")
        self.block = int(block_tokens)
        self.max_nodes = int(max_nodes)
        self.root: dict = {"children": {}, "replicas": set(),
                           "parent": None, "chunk": None, "last_use": 0}
        self.nodes = 0
        self._clock = 0

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _chunks(self, ids) -> list:
        ids = list(ids)
        n = len(ids) // self.block
        return [tuple(ids[i * self.block:(i + 1) * self.block])
                for i in range(n)]

    def match(self, ids) -> Dict[str, int]:
        """Longest cached prefix per replica: ``{replica_id: predicted
        hit tokens}`` (deepest node containing the replica wins). Like
        the replica's own lookup, the match is PROPER — the final
        prompt token is never served from cache — so the walk is capped
        at ``(len(ids) - 1) // block`` full blocks."""
        now = self._tick()
        out: Dict[str, int] = {}
        node = self.root
        limit = max((len(list(ids)) - 1) // self.block, 0)
        for depth, chunk in enumerate(self._chunks(ids)[:limit], 1):
            node = node["children"].get(chunk)
            if node is None:
                break
            node["last_use"] = now
            for rid in node["replicas"]:
                out[rid] = depth * self.block
        return out

    def record(self, ids, replica_id: str) -> int:
        """Note that ``replica_id`` was just routed a prompt: after the
        admit, its pool holds every FULL block of ``ids``. Creates
        missing nodes, stamps the replica down the whole path, and
        LRU-evicts past ``max_nodes``. Returns blocks walked."""
        now = self._tick()
        node = self.root
        walked = 0
        for chunk in self._chunks(ids):
            nxt = node["children"].get(chunk)
            if nxt is None:
                nxt = {"children": {}, "replicas": set(), "parent": node,
                       "chunk": chunk, "last_use": now}
                node["children"][chunk] = nxt
                self.nodes += 1
            nxt["replicas"].add(replica_id)
            nxt["last_use"] = now
            node = nxt
            walked += 1
        if self.nodes > self.max_nodes:
            self._evict_batch(protect_from=now)
        return walked

    def replica_prefixes(self, replica_id: str,
                         top_k: int = 8) -> List[list]:
        """The DEEPEST id-chains ``replica_id`` is recorded to hold,
        hottest (most recently used) first, at most ``top_k`` — the
        restart re-warm plan (ISSUE 13): captured at ejection time,
        BEFORE :meth:`drop_replica` erases the dead replica's
        entries, and replayed from peers once the replica comes back.
        A chain is "deepest" when no child node also names the
        replica (shallower prefixes ride along for free on a pull of
        the deep one)."""
        out: List[tuple] = []
        # record() stamps a replica down the WHOLE path, so a node
        # whose replicas lack the id has no claiming descendants —
        # the walk prunes there
        stack: List[tuple] = [(self.root, [])]
        while stack:
            node, ids = stack.pop()
            deeper = False
            for child in node["children"].values():
                if replica_id in child["replicas"]:
                    stack.append((child, ids + list(child["chunk"])))
                    deeper = True
            if (node is not self.root and not deeper
                    and replica_id in node["replicas"]):
                out.append((node["last_use"], ids))
        out.sort(key=lambda t: -t[0])
        return [ids for _, ids in out[:max(int(top_k), 0)]]

    def hot_prefixes(self, top_k: int = 8) -> List[list]:
        """The FLEET's hottest deepest id-chains regardless of owner —
        the proactive spawn re-warm plan (ISSUE 19). A scale-up
        replica has no eviction history to replay (the ISSUE 13 plan
        is per-dead-replica), so it pre-warms with whatever the whole
        fleet is serving hottest right now; each chain is pulled from
        whichever healthy peer holds it via the same peer-pull path."""
        out: List[tuple] = []
        stack: List[tuple] = [(self.root, [])]
        while stack:
            node, ids = stack.pop()
            for child in node["children"].values():
                stack.append((child, ids + list(child["chunk"])))
            if node is not self.root and not node["children"]:
                out.append((node["last_use"], ids))
        out.sort(key=lambda t: -t[0])
        return [ids for _, ids in out[:max(int(top_k), 0)]]

    def drop_replica(self, replica_id: str) -> int:
        """A replica died or restarted: its pool is empty, so every
        prediction naming it is stale. Removes it everywhere and prunes
        the subtrees no replica claims anymore; returns nodes pruned."""
        pruned = 0
        stack = [self.root]
        leaves: List[dict] = []
        while stack:
            node = stack.pop()
            node["replicas"].discard(replica_id)
            for child in node["children"].values():
                stack.append(child)
            if node is not self.root and not node["children"]:
                leaves.append(node)
        for node in leaves:
            # walk up from each leaf deleting replica-less chains
            while (node is not None and node is not self.root
                   and not node["children"] and not node["replicas"]):
                parent = node["parent"]
                del parent["children"][node["chunk"]]
                node["parent"] = None
                self.nodes -= 1
                pruned += 1
                node = parent
        return pruned

    def _evict_batch(self, protect_from: int) -> None:
        """Prune back toward ~90% of ``max_nodes`` in ONE trie walk:
        collect every leaf, evict least-recently-used first, never
        touching nodes stamped at the current clock (``protect_from``
        — the chain being recorded must survive its own insertion).
        record() runs under the router's placement lock on every
        request, so eviction must be amortized-cheap — one O(trie)
        sweep per ~0.1*max_nodes insertions, not one per node."""
        target = max(int(self.max_nodes * 0.9), 1)
        leaves: List[dict] = []
        stack = [self.root]
        while stack:
            node = stack.pop()
            for child in node["children"].values():
                if child["children"]:
                    stack.append(child)
                elif child["last_use"] < protect_from:
                    leaves.append(child)
        leaves.sort(key=lambda n: n["last_use"])
        for node in leaves:
            if self.nodes <= target:
                break
            del node["parent"]["children"][node["chunk"]]
            node["parent"] = None
            self.nodes -= 1


def choose_replica(candidates: Iterable[Tuple[str, float]],
                   matches: Dict[str, int],
                   policy: str = "cache_aware",
                   rr_counter: int = 0,
                   min_match_tokens: int = 1,
                   load_spread: float = 4.0
                   ) -> Optional[Tuple[str, str]]:
    """Pick a replica for one request -> ``(replica_id, reason)``.

    ``candidates``: ``(replica_id, load)`` pairs for the HEALTHY
    replicas (load = the router's per-replica queue estimate: its own
    in-flight accounting plus the replica's last-polled queue depth).
    ``matches``: :meth:`FleetRadix.match` for the request's ids.

    ``cache_aware``: the deepest-match replica wins (ties break toward
    lighter load) unless its load exceeds the least-loaded candidate
    by more than ``load_spread`` — a popular prefix must never queue
    behind itself while the rest of the fleet idles; past the spread
    the request goes least-loaded (and the radix will record the
    prefix THERE, so the hot prefix naturally replicates). Returns
    None when ``candidates`` is empty (caller answers 503)."""
    cands = sorted(candidates)          # stable: by (rid, load)
    if not cands:
        return None
    if policy == "round_robin":
        rid, _ = cands[rr_counter % len(cands)]
        return rid, REASON_ROUND_ROBIN
    least_load = min(load for _, load in cands)
    # rotate among the equally-least-loaded (an idle fleet would
    # otherwise pile every new prefix onto the lexicographically
    # first replica until load breaks the tie)
    tied = [rid for rid, load in cands if load <= least_load]
    least_rid = tied[rr_counter % len(tied)]
    if policy != "least_loaded":
        scored = [(matches.get(rid, 0), rid, load)
                  for rid, load in cands
                  if matches.get(rid, 0) >= max(min_match_tokens, 1)]
        if scored:
            best_tokens = max(s[0] for s in scored)
            hit_rid, hit_load = min(
                ((rid, load) for tok, rid, load in scored
                 if tok == best_tokens),
                key=lambda c: (c[1], c[0]))
            if hit_load - least_load <= load_spread:
                return hit_rid, REASON_PREFIX
    return least_rid, REASON_LEAST_LOADED
