"""Serving fleet front door: cache-aware router, admission control,
and a trace-replay load harness.

One ``serve.py`` process cannot be "heavy traffic from millions of
users" — this package composes the pieces the repo already has
(``resilience.supervisor`` lifecycle, ``/healthz`` + ``/metrics``,
the paged KV prefix cache's per-replica hit counters) into a fleet:

- :mod:`.placement` — a host-side block-granular radix index over
  prompt ids (mirroring ``engine/kvcache.RadixIndex``'s one-edge-per-
  full-block contract) that remembers which replica last served each
  prefix, plus the placement policy: shared-prefix traffic steers to
  the replica already holding the blocks (SGLang-style cache-aware
  scheduling), falling back to least-loaded.
- :mod:`.admission` — admission control and backpressure: a bounded
  waiting room with per-tenant weighted fair queueing (``X-Tenant``
  header), 429 + ``Retry-After`` shedding past the watermark.
- :mod:`.replicas` — replica lifecycle: N supervised ``serve.py``
  children (one :class:`resilience.supervisor.Supervisor` each, so
  exit classification / backoff / crash budget / drain are shared with
  training), READY-line URL discovery, health polling with ejection +
  re-admission, rolling drain-restarts, and reset-corrected
  aggregation of the replicas' prefix-cache counters.
- :mod:`.router` — the HTTP front door itself: request proxying
  (including SSE streaming passthrough with disconnect-propagating
  cancellation), ``/healthz`` + ``/metrics`` on the router, and the
  flag-gated ``/admin`` kill/drain endpoints the chaos paths use.
- :mod:`.loadgen` — a deterministic trace-replay load generator
  (Poisson and bursty multi-tenant arrivals, shared-prefix mixture,
  SSE + non-streaming, cancellations) and its latency/shed summary.

Stdlib-only like the rest of the resilience layer: the router manages
jax processes, it is not one — importing this package must never pull
in jax. Entry point: ``scripts/serve_fleet.py``; docs: docs/FLEET.md.
"""
